"""Entry-point plugin discovery (capability parity:
mythril/plugin/discovery.py:8-21 PluginDiscovery).

Third-party packages publish detectors / engine plugins via the
`mythril_tpu.plugins` entry-point group:

    [project.entry-points."mythril_tpu.plugins"]
    my_detector = "my_package.module:MyDetector"

Discovery uses importlib.metadata (pkg_resources is deprecated)."""

from __future__ import annotations

import logging
from importlib.metadata import entry_points
from typing import Any, Dict, List, Optional

from .interface import MythrilPlugin

log = logging.getLogger(__name__)

ENTRY_POINT_GROUP = "mythril_tpu.plugins"


class PluginDiscovery:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance._installed_plugins = None
        return cls._instance

    def init_installed_plugins(self) -> None:
        found: Dict[str, Any] = {}
        try:
            group = entry_points(group=ENTRY_POINT_GROUP)
        except TypeError:  # Python < 3.10 signature
            group = entry_points().get(ENTRY_POINT_GROUP, [])
        for entry_point in group:
            try:
                found[entry_point.name] = entry_point.load()
            except Exception as error:
                log.warning("failed to load plugin entry point %s: %s",
                            entry_point.name, error)
        self._installed_plugins = found

    @property
    def installed_plugins(self) -> Dict[str, Any]:
        if self._installed_plugins is None:
            self.init_installed_plugins()
        return self._installed_plugins

    def is_installed(self, plugin_name: str) -> bool:
        return plugin_name in self.installed_plugins

    def build_plugin(self, plugin_name: str,
                     plugin_args: Optional[Dict] = None) -> MythrilPlugin:
        if not self.is_installed(plugin_name):
            raise ValueError(f"Plugin with name: `{plugin_name}` is not "
                             f"installed")
        plugin = self.installed_plugins.get(plugin_name)
        if plugin is None or not (isinstance(plugin, type)
                                  and issubclass(plugin, MythrilPlugin)):
            raise ValueError(f"No valid plugin was found for {plugin_name}")
        return plugin(**(plugin_args or {}))

    def get_plugins(self, default_enabled: Optional[bool] = None) -> List[str]:
        if default_enabled is None:
            return list(self.installed_plugins.keys())
        return [name for name, plugin_class in self.installed_plugins.items()
                if getattr(plugin_class, "plugin_default_enabled", False)
                == default_enabled]
