from .discovery import PluginDiscovery
from .interface import MythrilPlugin, MythrilLaserPlugin
from .loader import MythrilPluginLoader, UnsupportedPluginType

__all__ = ["PluginDiscovery", "MythrilPlugin", "MythrilLaserPlugin",
           "MythrilPluginLoader", "UnsupportedPluginType"]
