"""Third-party plugin interfaces (capability parity:
mythril/plugin/interface.py — MythrilPlugin / MythrilLaserPlugin)."""

from __future__ import annotations

from abc import ABC, abstractmethod


class MythrilPlugin:
    """Base class for discoverable plugins (detection modules subclass
    DetectionModule AND this marker; engine plugins use MythrilLaserPlugin).

    Packages expose plugins through the `mythril_tpu.plugins` entry-point
    group; `PluginDiscovery` finds them and `MythrilPluginLoader` activates
    them."""

    author = "unknown"
    name = "plugin"
    plugin_license = "All rights reserved."
    plugin_type = "Mythril Plugin"
    plugin_version = "0.0.1"
    plugin_description = ""
    plugin_default_enabled = False

    def __repr__(self):
        return f"{self.plugin_type}: {self.name} v{self.plugin_version} " \
               f"({self.author})"


class MythrilLaserPlugin(MythrilPlugin, ABC):
    """Engine-instrumentation plugin: must build a LaserPlugin
    (core/plugin/interface.py) when called."""

    @abstractmethod
    def __call__(self, *args, **kwargs):
        """Build the LaserPlugin instance."""
