"""Third-party plugin activation (capability parity:
mythril/plugin/loader.py:21 MythrilPluginLoader): dispatches discovered
plugins by kind — DetectionModule instances register with the analysis
ModuleLoader, MythrilLaserPlugin builders with the engine's
LaserPluginLoader."""

from __future__ import annotations

import logging
from typing import Dict, List

from ..analysis.module.base import DetectionModule
from ..analysis.module.loader import ModuleLoader
from ..core.plugin.builder import PluginBuilder
from ..core.plugin.loader import LaserPluginLoader
from .discovery import PluginDiscovery
from .interface import MythrilLaserPlugin, MythrilPlugin

log = logging.getLogger(__name__)


class UnsupportedPluginType(Exception):
    """Raised when a plugin of an unknown kind is loaded."""


class MythrilPluginLoader:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance.loaded_plugins = []
            cls._instance.plugin_args = {}
            cls._instance._defaults_loaded = False
        return cls._instance

    def set_args(self, plugin_name: str, **kwargs) -> None:
        self.plugin_args[plugin_name] = kwargs

    def load(self, plugin: MythrilPlugin) -> None:
        if not isinstance(plugin, MythrilPlugin):
            raise ValueError("Passed plugin is not of type MythrilPlugin")
        log.info("loading plugin: %s", plugin)
        if isinstance(plugin, DetectionModule):
            self._load_detection_module(plugin)
        elif isinstance(plugin, MythrilLaserPlugin):
            self._load_laser_plugin(plugin)
        else:
            raise UnsupportedPluginType(
                f"Passed plugin type is not yet supported: {type(plugin)}")
        self.loaded_plugins.append(plugin)

    @staticmethod
    def _load_detection_module(plugin: DetectionModule) -> None:
        ModuleLoader().register_module(plugin)

    @staticmethod
    def _load_laser_plugin(plugin: MythrilLaserPlugin) -> None:
        class _Adapter(PluginBuilder):
            name = plugin.name

            def __call__(self, *args, **kwargs):
                return plugin(*args, **kwargs)

        LaserPluginLoader().load(_Adapter())

    def load_default_enabled(self) -> List[str]:
        """Discover and activate every installed default-enabled plugin."""
        if self._defaults_loaded:
            return []
        self._defaults_loaded = True
        loaded = []
        discovery = PluginDiscovery()
        for name in discovery.get_plugins(default_enabled=True):
            try:
                plugin = discovery.build_plugin(name,
                                                self.plugin_args.get(name))
                self.load(plugin)
                loaded.append(name)
            except Exception as error:
                log.warning("failed to activate plugin %s: %s", name, error)
        return loaded
