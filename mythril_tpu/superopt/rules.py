"""Candidate-rewrite enumeration: the peephole catalog plus bounded
exhaustive stack-scheduling search.

Every rule proposes a *full replacement body* for a basic-block body
(terminator excluded); nothing here is trusted — each distinct candidate
becomes an equivalence obligation the engine discharges through the
solver stack. Rules therefore only have to be *plausible*, and the
catalog leans on a cheap concrete screen (a handful of seeded random
environments) to avoid wasting proof obligations on junk.

Two enumeration tiers:

* the **catalog** — windowed rewrites: generic constant folding (any
  entry-independent window collapses to pushes of its concrete result),
  identity/shuffle elision (PUSH 0 ADD, SWAPn SWAPn, PUSH/DUP POP,
  SWAP1 before a commutative op), strength reduction (MUL / SWAP1 DIV /
  SWAP1 MOD by a power of two into SHL / SHR / AND — these survive the
  term IR's constant folder, so they are the rules that generate *real*
  SAT queries for the batched prover), dead-store elision
  (back-to-back MSTORE/SSTORE to the same constant address), and PUSH0
  minimization (the only PUSH narrowing that changes static gas);
* **exhaustive search** — for short pure-stack bodies (length bounded by
  MYTHRIL_TPU_SUPEROPT_MAX_BLOCK_LEN), iterative-deepening enumeration
  of strictly shorter instruction sequences over an alphabet derived
  from the body, height-delta pruned, concretely screened, and capped
  by MYTHRIL_TPU_SUPEROPT_CANDIDATES total sequences tried.

Deterministic by construction: the screen RNG is fixed-seed and the
search order is the sorted alphabet, so repeat runs propose identical
candidates (the verdict cache then makes repeat proofs free).
"""

from __future__ import annotations

import random
from typing import Iterator, List, Optional, Sequence, Set, Tuple

from .encode import (BodyOp, MASK, concrete_run, differ_concretely,
                     is_encodable, random_env)

#: ops a constant-folding window may contain (entry-independent compute)
_FOLDABLE = frozenset(
    ["ADD", "SUB", "MUL", "DIV", "SDIV", "MOD", "SMOD",
     "LT", "GT", "SLT", "SGT", "EQ", "ISZERO",
     "AND", "OR", "XOR", "NOT", "SHL", "SHR", "SAR", "PUSH0"]
    + [f"PUSH{i}" for i in range(1, 33)]
)

_COMMUTATIVE = frozenset({"ADD", "MUL", "AND", "OR", "XOR", "EQ"})

#: x OP 0 == x with 0 on top of the stack (PUSH 0; OP)
_ZERO_IDENTITY = frozenset({"ADD", "OR", "XOR"})

_SCREEN_ENVS = 8
_SCREEN_DEPTH = 20
_SCREEN_SEED = 0x5EED


def push_of(value: int) -> BodyOp:
    """Cheapest PUSH encoding a constant: PUSH0 for zero (2 gas instead
    of 3), else the narrowest PUSHn."""
    value &= MASK
    if value == 0:
        return ("PUSH0", None)
    width = max(1, (value.bit_length() + 7) // 8)
    return (f"PUSH{width}", value)


def _push_value(op: BodyOp) -> Optional[int]:
    name, imm = op
    if name == "PUSH0":
        return 0
    if name.startswith("PUSH"):
        return (imm or 0) & MASK
    return None


def _is_pow2(value: int) -> Optional[int]:
    if value > 0 and value & (value - 1) == 0:
        return value.bit_length() - 1
    return None


def _cand(body: Sequence[BodyOp], rule: str) -> Tuple[Tuple[BodyOp, ...], str]:
    return (tuple(body), rule)


def _splice(body: Sequence[BodyOp], start: int, length: int,
            replacement: Sequence[BodyOp]) -> Tuple[BodyOp, ...]:
    return tuple(body[:start]) + tuple(replacement) + tuple(body[start + length:])


# ---------------------------------------------------------------------------------
# Catalog rules — each yields (replacement_window, rule_name, window_len) at `i`
# ---------------------------------------------------------------------------------

def _window_rewrites(body: Sequence[BodyOp], i: int
                     ) -> Iterator[Tuple[List[BodyOp], str, int]]:
    name, imm = body[i]
    nxt = body[i + 1][0] if i + 1 < len(body) else None

    # PUSH minimization: any wide encoding of zero drops to PUSH0
    if name.startswith("PUSH") and name != "PUSH0" and _push_value(body[i]) == 0:
        yield [("PUSH0", None)], "push0_min", 1

    value = _push_value(body[i])
    if value is not None and nxt is not None:
        # identity elision: PUSH 0; ADD/OR/XOR and PUSH 1; MUL vanish
        if value == 0 and nxt in _ZERO_IDENTITY:
            yield [], "identity", 2
        if value == 1 and nxt == "MUL":
            yield [], "identity", 2
        # PUSH x; POP is dead
        if nxt == "POP":
            yield [], "push_pop", 2
        # strength reduction: constant power-of-two multiply -> shift
        shift = _is_pow2(value)
        if shift is not None and nxt == "MUL":
            yield [push_of(shift), ("SHL", None)], "strength_mul", 2
        # ... and the compiled divide/modulo-by-constant idiom
        # (PUSH c; SWAP1 puts the dividend back on top before DIV/MOD)
        if shift is not None and i + 2 < len(body) and nxt == "SWAP1":
            third = body[i + 2][0]
            if third == "DIV":
                yield [push_of(shift), ("SHR", None)], "strength_div", 3
            if third == "MOD":
                yield [push_of(value - 1), ("AND", None)], "strength_mod", 3

    # shuffle elision
    if name.startswith("SWAP") and nxt == name:
        yield [], "swap_swap", 2
    if name.startswith("SWAP") and imm is None and name == "SWAP1" \
            and nxt in _COMMUTATIVE:
        yield [(nxt, None)], "swap_commutative", 2
    if name.startswith("DUP") and nxt == "POP":
        yield [], "dup_pop", 2

    # generic constant folding: the longest entry-independent window at i
    # that concretely executes from an empty stack collapses to pushes
    if name in _FOLDABLE and name.startswith("PUSH"):
        for length in range(2, min(6, len(body) - i) + 1):
            window = body[i:i + length]
            if any(op not in _FOLDABLE for op, _ in window):
                break
            try:
                stack, _, _ = concrete_run(list(window), [], {}, {})
            except IndexError:
                continue  # window reads the entry stack: not foldable
            folded = [push_of(v) for v in reversed(stack)]  # bottom-first
            if len(folded) < length:
                yield folded, "const_fold", length

    # dead store: PUSH v1; PUSH off; MSTORE; PUSH v2; PUSH off; MSTORE
    # (same constant offset, no intervening read) — first store is dead
    for store_op in ("MSTORE", "SSTORE"):
        if (i + 5 < len(body)
                and _push_value(body[i]) is not None
                and _push_value(body[i + 1]) is not None
                and body[i + 2][0] == store_op
                and _push_value(body[i + 3]) is not None
                and _push_value(body[i + 4]) == _push_value(body[i + 1])
                and body[i + 5][0] == store_op):
            yield list(body[i + 3:i + 6]), "dead_store", 6


def catalog_candidates(body: Sequence[BodyOp]
                       ) -> List[Tuple[Tuple[BodyOp, ...], str]]:
    """All single-window catalog rewrites of `body` (deduplicated)."""
    out: List[Tuple[Tuple[BodyOp, ...], str]] = []
    seen: Set[Tuple[BodyOp, ...]] = {tuple(body)}
    for i in range(len(body)):
        for replacement, rule, length in _window_rewrites(body, i):
            candidate = _splice(body, i, length, replacement)
            if candidate not in seen:
                seen.add(candidate)
                out.append(_cand(candidate, rule))
    return out


# ---------------------------------------------------------------------------------
# Bounded exhaustive stack-scheduling search
# ---------------------------------------------------------------------------------

_PURE_STACK = frozenset(
    ["ADD", "SUB", "MUL", "DIV", "SDIV", "MOD", "SMOD",
     "LT", "GT", "SLT", "SGT", "EQ", "ISZERO",
     "AND", "OR", "XOR", "NOT", "SHL", "SHR", "SAR",
     "POP", "PUSH0"]
    + [f"PUSH{i}" for i in range(1, 33)]
    + [f"DUP{i}" for i in range(1, 17)]
    + [f"SWAP{i}" for i in range(1, 17)]
)


def _height_delta(op: BodyOp) -> int:
    name, _ = op
    if name.startswith("PUSH"):
        return 1
    if name.startswith("DUP"):
        return 1
    if name.startswith("SWAP"):
        return 0
    if name in ("POP",):
        return -1
    if name in ("ISZERO", "NOT"):
        return 0
    return -1  # every binary op


def _search_alphabet(body: Sequence[BodyOp]) -> List[BodyOp]:
    """Instruction alphabet derived from the body: its constants, its
    operators, and small-depth stack plumbing."""
    alphabet: Set[BodyOp] = {("POP", None), ("PUSH0", None)}
    for op in body:
        value = _push_value(op)
        if value is not None:
            alphabet.add(push_of(value))
        else:
            alphabet.add(op)
    for depth in range(1, 4):
        alphabet.add((f"DUP{depth}", None))
        alphabet.add((f"SWAP{depth}", None))
    return sorted(alphabet)


def search_candidates(body: Sequence[BodyOp], max_block_len: int,
                      budget: int) -> Tuple[List[Tuple[Tuple[BodyOp, ...], str]], int]:
    """Iterative-deepening exhaustive search for strictly shorter
    equivalent-looking sequences. Returns (candidates, sequences_tried).

    Only pure-stack bodies are searched (a memory/storage write in the
    body makes the space explode and the catalog covers those), pruned
    by net-height reachability and screened on fixed-seed random
    environments; survivors still go through the full symbolic proof.
    """
    if len(body) > max_block_len or not body:
        return [], 0
    if any(name not in _PURE_STACK for name, _ in body):
        return [], 0

    rng = random.Random(_SCREEN_SEED)
    depth = max(_SCREEN_DEPTH, 17 + 2 * len(body))
    envs = [random_env(rng, depth,
                       tuple(v for v in (_push_value(op) for op in body)
                             if v is not None))
            for _ in range(_SCREEN_ENVS)]
    try:
        target_delta = _body_delta(body, envs[0])
    except IndexError:
        return [], 0

    alphabet = _search_alphabet(body)
    survivors: List[Tuple[Tuple[BodyOp, ...], str]] = []
    tried = 0
    body_t = tuple(body)

    for length in range(len(body)):
        prefix: List[BodyOp] = []

        def dfs(remaining: int, delta: int) -> bool:
            """Returns False when the budget ran out."""
            nonlocal tried
            if tried >= budget:
                return False
            if remaining == 0:
                tried += 1
                candidate = tuple(prefix)
                if candidate != body_t and not any(
                        differ_concretely(list(body), list(candidate), env)
                        for env in envs):
                    survivors.append(_cand(candidate, "search"))
                return True
            for op in alphabet:
                step = _height_delta(op)
                # net height must still be able to reach the target
                if abs(delta + step - target_delta) > remaining - 1:
                    continue
                prefix.append(op)
                ok = dfs(remaining - 1, delta + step)
                prefix.pop()
                if not ok:
                    return False
            return True

        if not dfs(length, 0):
            break

    return survivors, tried


def _body_delta(body: Sequence[BodyOp], env) -> int:
    entry, memory, storage = env
    stack, _, _ = concrete_run(list(body), entry, memory, storage)
    return len(stack) - len(entry)


# ---------------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------------

def enumerate_candidates(body: Sequence[BodyOp], max_block_len: int,
                         search_budget: int
                         ) -> Tuple[List[Tuple[Tuple[BodyOp, ...], str]], int]:
    """All screened candidate bodies for one block body, deduplicated,
    plus the exhaustive-search sequence count (for metrics)."""
    if not is_encodable(list(body)):
        return [], 0
    rng = random.Random(_SCREEN_SEED + 1)
    # 17 + 2*len bounds any body's entry-stack reach (SWAP16 peeks 17,
    # every op nets <= 2 pops), so the screen never underflows its envs
    depth = max(_SCREEN_DEPTH, 17 + 2 * len(body))
    envs = [random_env(rng, depth) for _ in range(_SCREEN_ENVS)]

    out: List[Tuple[Tuple[BodyOp, ...], str]] = []
    seen: Set[Tuple[BodyOp, ...]] = {tuple(body)}
    for candidate, rule in catalog_candidates(body):
        try:
            if any(differ_concretely(list(body), list(candidate), env)
                   for env in envs):
                continue  # a buggy rule application; screen it out
        except IndexError:
            continue
        if candidate not in seen:
            seen.add(candidate)
            out.append((candidate, rule))

    searched, tried = search_candidates(body, max_block_len, search_budget)
    for candidate, rule in searched:
        if candidate not in seen:
            seen.add(candidate)
            out.append((candidate, rule))
    return out, tried
