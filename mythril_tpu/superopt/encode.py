"""Symbolic block-transformer encoding for rewrite-equivalence proofs.

A basic-block body (terminator excluded) is summarized as a *transformer*
over an unknown entry state: the entry stack slots it consumes become
lazily-materialized 256-bit variables, memory is a byte array
(index 256 -> value 8, MSTORE = 32 byte stores MSB-first) and storage a
word array (256 -> 256). Two bodies simulated against the *same* entry
variables are compared by a miter: a disjunction of disagreement
predicates over the padded output stacks plus fresh probe indices into
the final memory/storage arrays. SAT means some entry state
distinguishes the bodies; UNSAT means the candidate is a drop-in
replacement. Because the term IR hash-conses and constant-folds, a
miter that folds to FALSE is a *syntactic* proof (no solver query) and
one that folds to TRUE is rejected without a query.

Only the whitelisted pure stack/memory/storage opcodes below are
encodable; anything observing the environment (GAS, PC, MSIZE, SHA3,
CALL*, LOG*, ...) or with blasting-hostile semantics (EXP, ADDMOD,
MULMOD, BYTE, SIGNEXTEND) makes a block ineligible.

The module also carries the concrete differential interpreter used to
screen exhaustive-search candidates, to self-check every accepted
rewrite, and by tests/test_superopt.py for the >=40-environment replay.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..smt import terms

WORD = 256
MASK = (1 << WORD) - 1

#: (name, immediate) — immediate is an int for PUSH1..32, else None
BodyOp = Tuple[str, Optional[int]]

# Opcodes whose effect is a pure function of (stack, memory, storage).
ENCODABLE = frozenset(
    ["ADD", "SUB", "MUL", "DIV", "SDIV", "MOD", "SMOD",
     "LT", "GT", "SLT", "SGT", "EQ", "ISZERO",
     "AND", "OR", "XOR", "NOT", "SHL", "SHR", "SAR",
     "POP", "JUMPDEST", "PUSH0",
     "MLOAD", "MSTORE", "MSTORE8", "SLOAD", "SSTORE"]
    + [f"PUSH{i}" for i in range(1, 33)]
    + [f"DUP{i}" for i in range(1, 17)]
    + [f"SWAP{i}" for i in range(1, 17)]
)


def is_encodable(body: List[BodyOp]) -> bool:
    return all(name in ENCODABLE for name, _ in body)


@dataclass(frozen=True)
class Transformer:
    """Symbolic summary of one body run against shared entry variables."""

    consumed: int            # entry slots materialized (depth read)
    outputs: Tuple[terms.Term, ...]   # residual stack, bottom..top
    memory: terms.Term
    storage: terms.Term
    max_growth: int          # max interim height relative to entry

    @property
    def delta(self) -> int:
        return len(self.outputs) - self.consumed


def entry_stack_var(tag: str, slot: int) -> terms.Term:
    """Entry stack slot `slot` (0 = top of stack at block entry)."""
    return terms.bv_var(f"{tag}_s{slot}", WORD)


def entry_memory(tag: str) -> terms.Term:
    return terms.array_var(f"{tag}_mem", WORD, 8)


def entry_storage(tag: str) -> terms.Term:
    return terms.array_var(f"{tag}_sto", WORD, WORD)


def _mem_store_word(memory: terms.Term, offset: terms.Term,
                    value: terms.Term) -> terms.Term:
    for i in range(32):
        addr = offset if i == 0 else terms.bv_binop(
            "bvadd", offset, terms.bv_const(i, WORD))
        byte = terms.extract(255 - 8 * i, 248 - 8 * i, value)
        memory = terms.store(memory, addr, byte)
    return memory


def _mem_load_word(memory: terms.Term, offset: terms.Term) -> terms.Term:
    parts = []
    for i in range(32):
        addr = offset if i == 0 else terms.bv_binop(
            "bvadd", offset, terms.bv_const(i, WORD))
        parts.append(terms.select(memory, addr))
    return terms.concat(*parts)


def _flag(cond: terms.Term) -> terms.Term:
    return terms.ite(cond, terms.bv_const(1, WORD), terms.bv_const(0, WORD))


def _guarded(op: str, a: terms.Term, b: terms.Term) -> terms.Term:
    # EVM defines x/0 == x%0 == 0; SMT bv division by zero does not.
    zero = terms.bv_const(0, WORD)
    return terms.ite(terms.bv_cmp("eq", b, zero), zero,
                     terms.bv_binop(op, a, b))


def simulate(body: List[BodyOp], tag: str) -> Transformer:
    """Run `body` symbolically against the shared `tag` entry state.

    Entry stack slots materialize lazily on underflow, so `consumed` is
    exactly the depth the body reads — the miter pads both sides to the
    deeper of the two.
    """
    stack: List[terms.Term] = []     # bottom..top
    consumed = 0
    max_growth = 0
    memory = entry_memory(tag)
    storage = entry_storage(tag)

    def ensure(n: int) -> None:
        nonlocal consumed
        while len(stack) < n:
            stack.insert(0, entry_stack_var(tag, consumed))
            consumed += 1

    def pop() -> terms.Term:
        ensure(1)
        return stack.pop()

    def push(value: terms.Term) -> None:
        nonlocal max_growth
        stack.append(value)
        max_growth = max(max_growth, len(stack) - consumed)

    for name, imm in body:
        if name not in ENCODABLE:
            raise ValueError(f"op {name} is not encodable")
        if name == "JUMPDEST":
            continue
        if name == "PUSH0":
            push(terms.bv_const(0, WORD))
        elif name.startswith("PUSH"):
            push(terms.bv_const((imm or 0) & MASK, WORD))
        elif name.startswith("DUP"):
            n = int(name[3:])
            ensure(n)
            push(stack[-n])
        elif name.startswith("SWAP"):
            n = int(name[4:])
            ensure(n + 1)
            stack[-1], stack[-1 - n] = stack[-1 - n], stack[-1]
        elif name == "POP":
            pop()
        elif name in ("ADD", "SUB", "MUL", "AND", "OR", "XOR"):
            a, b = pop(), pop()
            push(terms.bv_binop("bv" + name.lower(), a, b))
        elif name in ("DIV", "SDIV", "MOD", "SMOD"):
            a, b = pop(), pop()
            smt_op = {"DIV": "bvudiv", "SDIV": "bvsdiv",
                      "MOD": "bvurem", "SMOD": "bvsrem"}[name]
            push(_guarded(smt_op, a, b))
        elif name in ("SHL", "SHR", "SAR"):
            shift, value = pop(), pop()
            smt_op = {"SHL": "bvshl", "SHR": "bvlshr", "SAR": "bvashr"}[name]
            push(terms.bv_binop(smt_op, value, shift))
        elif name == "LT":
            a, b = pop(), pop()
            push(_flag(terms.bv_cmp("bvult", a, b)))
        elif name == "GT":
            a, b = pop(), pop()
            push(_flag(terms.bv_cmp("bvult", b, a)))
        elif name == "SLT":
            a, b = pop(), pop()
            push(_flag(terms.bv_cmp("bvslt", a, b)))
        elif name == "SGT":
            a, b = pop(), pop()
            push(_flag(terms.bv_cmp("bvslt", b, a)))
        elif name == "EQ":
            a, b = pop(), pop()
            push(_flag(terms.bv_cmp("eq", a, b)))
        elif name == "ISZERO":
            a = pop()
            push(_flag(terms.bv_cmp("eq", a, terms.bv_const(0, WORD))))
        elif name == "NOT":
            push(terms.bv_not(pop()))
        elif name == "MLOAD":
            push(_mem_load_word(memory, pop()))
        elif name == "MSTORE":
            offset, value = pop(), pop()
            memory = _mem_store_word(memory, offset, value)
        elif name == "MSTORE8":
            offset, value = pop(), pop()
            memory = terms.store(memory, offset, terms.extract(7, 0, value))
        elif name == "SLOAD":
            push(terms.select(storage, pop()))
        elif name == "SSTORE":
            key, value = pop(), pop()
            storage = terms.store(storage, key, value)
        else:  # pragma: no cover — whitelist and dispatch must agree
            raise ValueError(f"unhandled encodable op {name}")

    return Transformer(consumed=consumed, outputs=tuple(stack),
                       memory=memory, storage=storage,
                       max_growth=max_growth)


def build_miter(original: Transformer, candidate: Transformer,
                tag: str) -> Optional[terms.Term]:
    """Boolean term that is SAT iff some entry state distinguishes the
    two transformers. Returns None when the net stack deltas differ
    (never equivalent, no query worth making). FALSE means syntactic
    equivalence; TRUE means syntactic inequivalence.
    """
    if original.delta != candidate.delta:
        return None
    depth = max(original.consumed, candidate.consumed)
    disjuncts: List[terms.Term] = []
    for side_a, side_b in zip(_padded(original, tag, depth),
                              _padded(candidate, tag, depth)):
        if side_a is side_b:
            continue
        disjuncts.append(terms.bool_not(terms.bv_cmp("eq", side_a, side_b)))
    if original.memory is not candidate.memory:
        probe = terms.bv_var(f"{tag}_probe_mem", WORD)
        disjuncts.append(terms.bool_not(terms.bv_cmp(
            "eq", terms.select(original.memory, probe),
            terms.select(candidate.memory, probe))))
    if original.storage is not candidate.storage:
        probe = terms.bv_var(f"{tag}_probe_sto", WORD)
        disjuncts.append(terms.bool_not(terms.bv_cmp(
            "eq", terms.select(original.storage, probe),
            terms.select(candidate.storage, probe))))
    if not disjuncts:
        return terms.FALSE
    return terms.bool_or(*disjuncts)


def _padded(side: Transformer, tag: str, depth: int) -> List[terms.Term]:
    """Output stack top..bottom, padded with untouched deeper entry
    slots so both sides describe the same `depth` entry slots."""
    padded = list(reversed(side.outputs))
    for slot in range(side.consumed, depth):
        padded.append(entry_stack_var(tag, slot))
    return padded


# ---------------------------------------------------------------------------------
# Concrete differential interpreter
# ---------------------------------------------------------------------------------

def _c_signed(value: int) -> int:
    return value - (1 << WORD) if value >> (WORD - 1) else value


def concrete_run(body: List[BodyOp], entry_stack: List[int],
                 memory: Dict[int, int], storage: Dict[int, int]
                 ) -> Tuple[List[int], Dict[int, int], Dict[int, int]]:
    """Concretely execute `body` from an entry environment.

    `entry_stack` is top-first; `memory` maps byte address -> byte value
    (missing cells read 0); `storage` maps word key -> word value.
    Returns the final (stack top-first, memory, storage) without
    mutating the inputs. Raises IndexError if the body digs deeper than
    the provided entry stack — callers supply a stack at least as deep
    as the transformer's `consumed`.
    """
    stack = list(reversed(entry_stack))   # bottom..top
    mem = dict(memory)
    sto = dict(storage)

    for name, imm in body:
        if name == "JUMPDEST":
            continue
        if name == "PUSH0":
            stack.append(0)
        elif name.startswith("PUSH"):
            stack.append((imm or 0) & MASK)
        elif name.startswith("DUP"):
            stack.append(stack[-int(name[3:])])
        elif name.startswith("SWAP"):
            n = int(name[4:])
            if len(stack) < n + 1:
                raise IndexError("stack underflow")
            stack[-1], stack[-1 - n] = stack[-1 - n], stack[-1]
        elif name == "POP":
            stack.pop()
        elif name == "ADD":
            a, b = stack.pop(), stack.pop()
            stack.append((a + b) & MASK)
        elif name == "SUB":
            a, b = stack.pop(), stack.pop()
            stack.append((a - b) & MASK)
        elif name == "MUL":
            a, b = stack.pop(), stack.pop()
            stack.append((a * b) & MASK)
        elif name == "DIV":
            a, b = stack.pop(), stack.pop()
            stack.append(0 if b == 0 else a // b)
        elif name == "SDIV":
            a, b = stack.pop(), stack.pop()
            if b == 0:
                stack.append(0)
            else:
                sa, sb = _c_signed(a), _c_signed(b)
                quotient = abs(sa) // abs(sb)
                if (sa < 0) != (sb < 0):
                    quotient = -quotient
                stack.append(quotient & MASK)
        elif name == "MOD":
            a, b = stack.pop(), stack.pop()
            stack.append(0 if b == 0 else a % b)
        elif name == "SMOD":
            a, b = stack.pop(), stack.pop()
            if b == 0:
                stack.append(0)
            else:
                sa, sb = _c_signed(a), _c_signed(b)
                remainder = abs(sa) % abs(sb)
                if sa < 0:
                    remainder = -remainder
                stack.append(remainder & MASK)
        elif name == "LT":
            a, b = stack.pop(), stack.pop()
            stack.append(1 if a < b else 0)
        elif name == "GT":
            a, b = stack.pop(), stack.pop()
            stack.append(1 if a > b else 0)
        elif name == "SLT":
            a, b = stack.pop(), stack.pop()
            stack.append(1 if _c_signed(a) < _c_signed(b) else 0)
        elif name == "SGT":
            a, b = stack.pop(), stack.pop()
            stack.append(1 if _c_signed(a) > _c_signed(b) else 0)
        elif name == "EQ":
            a, b = stack.pop(), stack.pop()
            stack.append(1 if a == b else 0)
        elif name == "ISZERO":
            stack.append(1 if stack.pop() == 0 else 0)
        elif name == "AND":
            a, b = stack.pop(), stack.pop()
            stack.append(a & b)
        elif name == "OR":
            a, b = stack.pop(), stack.pop()
            stack.append(a | b)
        elif name == "XOR":
            a, b = stack.pop(), stack.pop()
            stack.append(a ^ b)
        elif name == "NOT":
            stack.append(stack.pop() ^ MASK)
        elif name == "SHL":
            shift, value = stack.pop(), stack.pop()
            stack.append((value << shift) & MASK if shift < WORD else 0)
        elif name == "SHR":
            shift, value = stack.pop(), stack.pop()
            stack.append(value >> shift if shift < WORD else 0)
        elif name == "SAR":
            shift, value = stack.pop(), stack.pop()
            signed = _c_signed(value)
            stack.append((signed >> min(shift, WORD - 1)) & MASK)
        elif name == "MLOAD":
            offset = stack.pop()
            word = 0
            for i in range(32):
                word = (word << 8) | mem.get((offset + i) & MASK, 0)
            stack.append(word)
        elif name == "MSTORE":
            offset, value = stack.pop(), stack.pop()
            for i in range(32):
                mem[(offset + i) & MASK] = (value >> (8 * (31 - i))) & 0xFF
        elif name == "MSTORE8":
            offset, value = stack.pop(), stack.pop()
            mem[offset] = value & 0xFF
        elif name == "SLOAD":
            stack.append(sto.get(stack.pop(), 0))
        elif name == "SSTORE":
            key, value = stack.pop(), stack.pop()
            sto[key] = value
        else:
            raise ValueError(f"concrete_run cannot execute {name}")

    return list(reversed(stack)), mem, sto


def differ_concretely(original: List[BodyOp], candidate: List[BodyOp],
                      env: Tuple[List[int], Dict[int, int], Dict[int, int]]
                      ) -> bool:
    """True when one concrete environment distinguishes the two bodies.

    Memory/storage comparison normalizes away explicitly-written default
    values so a rewrite that skips writing a cell the original sets to
    its implicit 0 still compares equal.
    """
    stack_a, mem_a, sto_a = concrete_run(original, *env)
    stack_b, mem_b, sto_b = concrete_run(candidate, *env)
    if stack_a != stack_b:
        return True
    if _nonzero(mem_a) != _nonzero(mem_b):
        return True
    return _nonzero(sto_a) != _nonzero(sto_b)


def _nonzero(cells: Dict[int, int]) -> Dict[int, int]:
    return {k: v for k, v in cells.items() if v != 0}


def random_env(rng, depth: int, interesting: Tuple[int, ...] = ()
               ) -> Tuple[List[int], Dict[int, int], Dict[int, int]]:
    """One random concrete entry environment for differential replay.

    Half the stack slots are drawn from a boundary-value pool (0, 1,
    small, MASK, sign bit, plus block constants) because uniform random
    256-bit words essentially never hit the x==0 / x==2^255 edges where
    DIV/SDIV/SMOD rewrites actually break.
    """
    pool = (0, 1, 2, 31, 32, 255, MASK, 1 << 255, (1 << 255) - 1) + interesting
    stack = [rng.choice(pool) if rng.random() < 0.5
             else rng.getrandbits(WORD) for _ in range(depth)]
    memory = {rng.randrange(0, 512): rng.getrandbits(8) for _ in range(8)}
    storage = {rng.choice(pool) & MASK: rng.getrandbits(WORD)
               for _ in range(4)}
    return stack, memory, storage
