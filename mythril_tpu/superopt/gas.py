"""Static per-opcode gas schedule for the superoptimizer's cost model.

This is the *ranking* table: a candidate rewrite is accepted when its
proven-equivalent body costs strictly less static gas than the original
(weighted by absint loop trip bounds where proven). It deliberately
prices every opcode at its **minimum** schedule cost — the warm-access /
zero-expansion floor — because a rewrite is only ever credited for the
gas component that is *certain*: dynamic components (memory expansion,
cold-access surcharges, per-byte copy costs, EXP exponent bytes) are
identical between a block and its transformer-equal rewrite whenever
they are identical in the floor, and crediting them would overstate
savings.

Kept in byte-for-byte parity with ``ops/opcodes.py`` — every mnemonic in
``OPCODES`` must appear here with exactly ``GAS[0]`` — enforced twice:
the tpu-lint rule R10 (tools/lint/rules/gas_parity.py) and
tests/test_superopt.py, so an EVM fork bump that edits the interpreter's
schedule cannot silently drift this cost model.

Stdlib-only, no in-package imports: the lint rule loads this module
standalone (importlib file-path load, the R4 pattern) without pulling
the mythril_tpu package tree.
"""

from __future__ import annotations

from typing import Dict, Iterable, Tuple

#: mnemonic -> static (minimum-schedule) gas cost
STATIC_GAS: Dict[str, int] = {
    "STOP": 0,
    "ADD": 3,
    "MUL": 5,
    "SUB": 3,
    "DIV": 5,
    "SDIV": 5,
    "MOD": 5,
    "SMOD": 5,
    "ADDMOD": 8,
    "MULMOD": 8,
    "EXP": 10,
    "SIGNEXTEND": 5,
    "LT": 3,
    "GT": 3,
    "SLT": 3,
    "SGT": 3,
    "EQ": 3,
    "ISZERO": 3,
    "AND": 3,
    "OR": 3,
    "XOR": 3,
    "NOT": 3,
    "BYTE": 3,
    "SHL": 3,
    "SHR": 3,
    "SAR": 3,
    "SHA3": 30,
    "ADDRESS": 2,
    "BALANCE": 100,
    "ORIGIN": 2,
    "CALLER": 2,
    "CALLVALUE": 2,
    "CALLDATALOAD": 3,
    "CALLDATASIZE": 2,
    "CALLDATACOPY": 3,
    "CODESIZE": 2,
    "CODECOPY": 3,
    "GASPRICE": 2,
    "EXTCODESIZE": 100,
    "EXTCODECOPY": 100,
    "RETURNDATASIZE": 2,
    "RETURNDATACOPY": 3,
    "EXTCODEHASH": 100,
    "BLOCKHASH": 20,
    "COINBASE": 2,
    "TIMESTAMP": 2,
    "NUMBER": 2,
    "PREVRANDAO": 2,
    "GASLIMIT": 2,
    "CHAINID": 2,
    "SELFBALANCE": 5,
    "BASEFEE": 2,
    "BLOBHASH": 3,
    "BLOBBASEFEE": 2,
    "POP": 2,
    "MLOAD": 3,
    "MSTORE": 3,
    "MSTORE8": 3,
    "SLOAD": 100,
    "SSTORE": 100,
    "JUMP": 8,
    "JUMPI": 10,
    "PC": 2,
    "MSIZE": 2,
    "GAS": 2,
    "JUMPDEST": 1,
    "TLOAD": 100,
    "TSTORE": 100,
    "MCOPY": 3,
    "PUSH0": 2,
    "LOG0": 375,
    "LOG1": 750,
    "LOG2": 1125,
    "LOG3": 1500,
    "LOG4": 1875,
    "CREATE": 32000,
    "CALL": 100,
    "CALLCODE": 100,
    "RETURN": 0,
    "DELEGATECALL": 100,
    "CREATE2": 32000,
    "STATICCALL": 100,
    "REVERT": 0,
    "INVALID": 0,
    "SELFDESTRUCT": 5000,
}

for _i in range(1, 33):  # PUSH1..PUSH32: G_verylow
    STATIC_GAS[f"PUSH{_i}"] = 3
for _i in range(1, 17):  # DUP1..DUP16 / SWAP1..SWAP16: G_verylow
    STATIC_GAS[f"DUP{_i}"] = 3
    STATIC_GAS[f"SWAP{_i}"] = 3
# pre-Merge alias, same cell as PREVRANDAO (mirrors ops/opcodes.py)
STATIC_GAS["DIFFICULTY"] = STATIC_GAS["PREVRANDAO"]


def static_gas(name: str) -> int:
    """Static gas for one mnemonic; raises KeyError on unknown names so
    a table gap fails loudly instead of pricing an opcode at zero."""
    return STATIC_GAS[name]


def sequence_gas(names: Iterable[str]) -> int:
    """Summed static gas of an opcode sequence (a block body)."""
    return sum(STATIC_GAS[name] for name in names)


def parity_errors(opcodes: Dict[str, dict], gas_key: str,
                  table: Dict[str, int] = None) -> Tuple[str, ...]:
    """Every parity violation between a gas table (this module's
    ``STATIC_GAS`` by default; the R10 lint rule also points it at
    fixture tables) and an ``ops/opcodes.py``-shaped ``OPCODES`` dict
    (mnemonic -> meta with a ``(min, max)`` gas tuple under `gas_key`).
    Shared by the R10 lint rule and the unit test so both enforce the
    identical contract: equal name sets, and
    ``table[name] == OPCODES[name][gas][0]`` for every name."""
    table = STATIC_GAS if table is None else table
    errors = []
    for name in sorted(set(opcodes) - set(table)):
        errors.append(f"missing from STATIC_GAS: {name}")
    for name in sorted(set(table) - set(opcodes)):
        errors.append(f"not an opcode: {name}")
    for name in sorted(set(opcodes) & set(table)):
        expected = opcodes[name][gas_key][0]
        if table[name] != expected:
            errors.append(f"{name}: STATIC_GAS says {table[name]}, "
                          f"opcode schedule says {expected}")
    return tuple(errors)
