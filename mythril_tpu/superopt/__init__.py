"""Gas superoptimization over the CFA + batched device SAT stack.

The first non-detection workload on the engine substrate (ROADMAP item
5(a)): per contract, walk the recovered basic blocks, enumerate
candidate rewrites (peephole catalog + bounded exhaustive
stack-scheduling search, :mod:`.rules`), encode original-vs-candidate
as symbolic transformer-equality miters (:mod:`.encode`), discharge all
obligations through the batched dispatch queue or the host CDCL oracle,
and re-emit the runtime bytecode with the proven-cheapest bodies
(:mod:`.engine`), ranked by the static gas table (:mod:`.gas`) weighted
by absint-proven loop trip bounds.

Surfaces: the `myth-tpu optimize` CLI subcommand, the serve-tier
`optimize` protocol op, `bench.py superopt_ab`, and
`tools/superopt_smoke.py` (jax-free check.sh fast path).
"""

from .engine import BlockRewrite, OptimizationReport, optimize_bytecode
from .gas import STATIC_GAS, sequence_gas, static_gas

__all__ = [
    "BlockRewrite",
    "OptimizationReport",
    "STATIC_GAS",
    "optimize_bytecode",
    "sequence_gas",
    "static_gas",
]
