"""The gas superoptimizer: CFA block walk -> candidate enumeration ->
batched equivalence proofs -> ranked, re-emitted runtime bytecode.

Per contract the engine walks the recovered basic blocks, asks
:mod:`.rules` for candidate rewrites of each eligible body, encodes
original-vs-candidate as a miter (:mod:`.encode`), and discharges every
obligation in one pass through the existing solver stack: with the jax
backend the blasted CNFs ride `smt/solver/dispatch.py` — one shared
flush, canonical-CNF verdict cache, breaker-gated ladder — and with the
host backend they run sequentially through `sat.solve_cnf` (that A/B is
exactly what `bench.py superopt_ab` measures). Accepted rewrites (UNSAT
miters only) are crosschecked on the host oracle at the sampled cadence,
self-checked on concrete random environments, ranked by static gas saved
weighted by absint-proven loop trip bounds, and patched back into the
runtime bytecode.

Emission is strictly in-place: total code length never changes. Blocks
ending in a no-fallthrough terminator (JUMP included) relocate the
terminator after the shorter body and pad the unreachable tail with
INVALID; JUMPI/fallthrough blocks must re-emit at the exact original
length (a PUSH immediate is zero-widened to restore it, or the rewrite
is rejected), so every byte address outside the block — jump targets
above all — keeps its meaning. Candidates never contain JUMPDEST, so no
new valid jump target can appear.

Eligibility is conservative: every body op whitelisted by the encoder,
a CFA-known entry height at least as deep as either side reads (a
shorter body must not mask a stack underflow the original would throw
on), and no increase in peak stack growth.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..frontends.disassembler import Disassembly, EvmInstruction
from ..observe import metrics, trace
from ..ops.opcodes import ADDRESS, OPCODES, push_width
from ..smt import terms
from ..smt.solver import dispatch, sat
from ..smt.solver.bitblast import Blaster
from ..smt.solver.preprocess import lower_constraints
from ..staticanalysis import TERMINATORS, get_absint, get_cfa
from ..staticanalysis.cfa import BasicBlock, push_immediate
from ..staticanalysis.summary import recover_loops
from ..support import tpu_config
from . import rules
from .encode import BodyOp, build_miter, differ_concretely, is_encodable, \
    random_env, simulate
from .gas import sequence_gas

_MAX_CONFLICTS = 2_000_000
_SELFCHECK_ENVS = 4
_SELFCHECK_SEED = 0xD1FF
_INVALID_BYTE = 0xFE


@dataclass
class BlockRewrite:
    """One accepted, proven, emitted rewrite."""

    block_id: int
    start_pc: int                #: first rewritten byte
    rule: str
    before: Tuple[str, ...]      #: original body disassembly
    after: Tuple[str, ...]       #: replacement body disassembly
    gas_before: int
    gas_after: int
    weight: int                  #: absint loop trip bound, 1 outside loops
    proof: str                   #: "syntactic" | "device" | "host"

    @property
    def gas_saved(self) -> int:
        return self.gas_before - self.gas_after

    @property
    def weighted_saved(self) -> int:
        return self.gas_saved * self.weight

    def to_json(self) -> dict:
        return {"block_id": self.block_id, "start_pc": self.start_pc,
                "rule": self.rule, "before": list(self.before),
                "after": list(self.after), "gas_before": self.gas_before,
                "gas_after": self.gas_after, "gas_saved": self.gas_saved,
                "weight": self.weight,
                "weighted_saved": self.weighted_saved, "proof": self.proof}


@dataclass
class OptimizationReport:
    """Everything `myth-tpu optimize` / the serve `optimize` op returns."""

    code_in: str                 #: input runtime bytecode, hex
    code_out: str                #: rewritten runtime bytecode, hex
    blocks_scanned: int = 0
    candidates: int = 0
    rewrites: List[BlockRewrite] = field(default_factory=list)
    proof_stats: Dict[str, int] = field(default_factory=dict)
    wall_ms: float = 0.0
    note: str = ""               #: why the run was empty, when it was

    @property
    def gas_saved(self) -> int:
        return sum(r.gas_saved for r in self.rewrites)

    @property
    def weighted_gas_saved(self) -> int:
        return sum(r.weighted_saved for r in self.rewrites)

    def to_json(self) -> dict:
        return {"code_in": self.code_in, "code_out": self.code_out,
                "blocks_scanned": self.blocks_scanned,
                "candidates": self.candidates,
                "rewrites": [r.to_json() for r in self.rewrites],
                "gas_saved": self.gas_saved,
                "weighted_gas_saved": self.weighted_gas_saved,
                "proof_stats": dict(self.proof_stats),
                "wall_ms": round(self.wall_ms, 3), "note": self.note}


# ---------------------------------------------------------------------------------
# Block layout: what byte region may be rewritten, and how
# ---------------------------------------------------------------------------------

@dataclass
class _Layout:
    body: List[BodyOp]
    region_start: int
    region_len: int
    relocatable: bool            #: terminator may move up (no fallthrough)
    term_byte: Optional[int]     #: terminator opcode byte when relocatable


def _instr_size(ins: EvmInstruction) -> int:
    width = push_width(ins.op_code) if ins.op_code.startswith("PUSH") else 0
    return 1 + width


def _body_op(ins: EvmInstruction) -> BodyOp:
    if ins.argument is not None:
        return (ins.op_code, push_immediate(ins))
    return (ins.op_code, None)


def _block_layout(disassembly: Disassembly,
                  block: BasicBlock) -> Optional[_Layout]:
    instrs = disassembly.instruction_list[block.first_index:
                                          block.last_index + 1]
    if not instrs:
        return None
    relocatable = block.terminator in TERMINATORS or \
        block.terminator == "JUMP"
    has_term = relocatable or block.terminator == "JUMPI"
    term_instr = instrs[-1] if has_term else None
    body_instrs = instrs[:-1] if has_term else instrs
    if body_instrs and body_instrs[0].op_code == "JUMPDEST":
        body_instrs = body_instrs[1:]  # the jump target byte stays put
    if not body_instrs:
        return None
    for ins in body_instrs:
        if ins.op_code.startswith("PUSH") and ins.argument is not None:
            # a PUSH immediate truncated by end-of-code is trailing
            # garbage, not a rewritable instruction
            if len(ins.argument[2:]) != 2 * push_width(ins.op_code):
                return None
    region_start = body_instrs[0].address
    if relocatable:
        region_end = term_instr.address + 1   # terminator byte included
        term_byte = OPCODES[block.terminator][ADDRESS]
    else:
        region_end = term_instr.address if term_instr else \
            body_instrs[-1].address + _instr_size(body_instrs[-1])
        term_byte = None
    return _Layout(body=[_body_op(ins) for ins in body_instrs],
                   region_start=region_start,
                   region_len=region_end - region_start,
                   relocatable=relocatable, term_byte=term_byte)


def _assemble(body: Sequence[BodyOp]) -> bytes:
    out = bytearray()
    for name, imm in body:
        out.append(OPCODES[name][ADDRESS])
        if name.startswith("PUSH") and name != "PUSH0":
            out += (imm or 0).to_bytes(push_width(name), "big")
    return bytes(out)


def _fit_region(candidate: Sequence[BodyOp], layout: _Layout
                ) -> Optional[Tuple[Tuple[BodyOp, ...], bytes]]:
    """Emit `candidate` into the block's byte region, preserving total
    code length. Returns (final_body, region_bytes) or None when the
    candidate cannot fit."""
    raw = _assemble(candidate)
    if layout.relocatable:
        used = len(raw) + 1
        if used > layout.region_len:
            return None
        padding = bytes([_INVALID_BYTE]) * (layout.region_len - used)
        return tuple(candidate), raw + bytes([layout.term_byte]) + padding

    deficit = layout.region_len - len(raw)
    if deficit < 0:
        return None
    if deficit == 0:
        return tuple(candidate), raw
    # fallthrough/JUMPI: restore the exact length by zero-widening PUSH
    # immediates (PUSHk -> PUSH(k+m), same value, same static gas)
    widened: List[BodyOp] = []
    for name, imm in candidate:
        if deficit > 0 and name.startswith("PUSH") and name != "PUSH0":
            width = push_width(name)
            grow = min(32 - width, deficit)
            if grow:
                deficit -= grow
                widened.append((f"PUSH{width + grow}", imm))
                continue
        widened.append((name, imm))
    if deficit > 0:
        return None
    return tuple(widened), _assemble(widened)


def _disasm(body: Sequence[BodyOp]) -> Tuple[str, ...]:
    return tuple(name if imm is None else f"{name} 0x{imm:x}"
                 for name, imm in body)


# ---------------------------------------------------------------------------------
# Proof obligations
# ---------------------------------------------------------------------------------

@dataclass
class _Obligation:
    block_id: int
    body: Tuple[BodyOp, ...]
    emitted: bytes
    rule: str
    gas_after: int
    clauses: Optional[List[List[int]]] = None   # None => syntactic proof
    n_vars: int = 0
    future: Optional[object] = None
    status: int = sat.UNKNOWN
    proof: str = ""


def _blast(miter: terms.Term) -> Optional[Tuple[List[List[int]], int, str]]:
    """Lower + bit-blast one miter. Returns (clauses, n_vars, "") for a
    real query, (None, 0, verdict) when lowering decided it: verdict
    "unsat" means proven equivalent, "sat" means proven distinguishable.
    """
    lowered, _info = lower_constraints([miter], simplify=True)
    pending = []
    for term in lowered:
        if term is terms.FALSE:
            return None, 0, "unsat"
        if term is terms.TRUE:
            continue
        pending.append(term)
    if not pending:
        return None, 0, "sat"
    blaster = Blaster()
    for term in pending:
        blaster.assert_true(term)
    return blaster.clauses, blaster.n_vars, ""


# ---------------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------------

def optimize_bytecode(code, *, solver: str = "cdcl",
                      max_block_len: Optional[int] = None,
                      candidates_budget: Optional[int] = None,
                      crosscheck: Optional[int] = None) -> OptimizationReport:
    """Superoptimize one runtime bytecode; returns the full report.

    `solver` selects the proof backend: "jax" batches every obligation
    through the dispatch queue (one flush, shared verdict cache,
    UNKNOWNs fall down the ladder to the host CDCL), anything else
    proves sequentially on the host oracle.
    """
    started = time.perf_counter()
    if max_block_len is None:
        max_block_len = tpu_config.get_int("MYTHRIL_TPU_SUPEROPT_MAX_BLOCK_LEN")
    if candidates_budget is None:
        candidates_budget = tpu_config.get_int("MYTHRIL_TPU_SUPEROPT_CANDIDATES")
    if crosscheck is None:
        crosscheck = tpu_config.get_int("MYTHRIL_TPU_SUPEROPT_CROSSCHECK")

    disassembly = code if isinstance(code, Disassembly) else Disassembly(code)
    code_hex = disassembly.raw_code.hex()
    report = OptimizationReport(code_in=code_hex, code_out=code_hex)

    cfa = get_cfa(disassembly)
    if cfa is None:
        report.note = "no CFA tables (MYTHRIL_TPU_CFA off or pass bailed)"
        report.wall_ms = (time.perf_counter() - started) * 1000.0
        return report
    absint = get_absint(disassembly)
    loops, loop_header_of = recover_loops(cfa, disassembly.instruction_list)

    def block_weight(block_id: int) -> int:
        header_pc = loop_header_of.get(block_id)
        if header_pc is None or absint is None:
            return 1
        bound = absint.loop_bound(header_pc)
        return bound if bound and bound > 0 else 1

    # -- enumerate: per block, every screened + fitted candidate ------------------
    pending: Dict[int, List[_Obligation]] = {}
    layouts: Dict[int, _Layout] = {}
    stats = {"obligations": 0, "syntactic": 0, "queries": 0, "sat": 0,
             "unsat": 0, "unknown": 0, "crosschecks": 0, "divergences": 0,
             "selfcheck_failures": 0, "batched": 0}

    for block in cfa.blocks:
        if block.block_id not in cfa.reachable:
            continue
        report.blocks_scanned += 1
        metrics.inc("superopt.blocks_scanned")
        layout = _block_layout(disassembly, block)
        if layout is None or not layout.body or not is_encodable(layout.body):
            continue
        if block.entry_height is None:
            continue
        tag = f"so{block.block_id}"
        original = simulate(layout.body, tag)
        if block.entry_height < original.consumed:
            continue  # the real machine underflows here; do not touch it
        gas_before = sequence_gas(name for name, _ in layout.body)

        candidates, tried = rules.enumerate_candidates(
            layout.body, max_block_len, candidates_budget)
        if tried:
            metrics.inc("superopt.search_sequences", tried)

        block_pending: List[_Obligation] = []
        seen_emitted = set()
        for cand_body, rule in candidates:
            fitted = _fit_region(cand_body, layout)
            if fitted is None:
                continue
            final_body, emitted = fitted
            gas_after = sequence_gas(name for name, _ in final_body)
            if gas_after >= gas_before:
                continue
            if emitted in seen_emitted:
                continue
            candidate = simulate(final_body, tag)
            if block.entry_height < candidate.consumed:
                continue
            if candidate.max_growth > original.max_growth:
                continue
            miter = build_miter(original, candidate, tag)
            if miter is None or miter is terms.TRUE:
                continue
            obligation = _Obligation(
                block_id=block.block_id, body=final_body, emitted=emitted,
                rule=rule, gas_after=gas_after)
            if miter is terms.FALSE:
                obligation.status = sat.UNSAT
                obligation.proof = "syntactic"
            else:
                clauses, n_vars, verdict = _blast(miter)
                if verdict == "unsat":
                    obligation.status = sat.UNSAT
                    obligation.proof = "syntactic"
                elif verdict == "sat":
                    obligation.status = sat.SAT
                else:
                    obligation.clauses = clauses
                    obligation.n_vars = n_vars
            if obligation.status == sat.SAT:
                stats["sat"] += 1
                metrics.inc("superopt.proofs_sat")
                continue
            seen_emitted.add(emitted)
            report.candidates += 1
            metrics.inc("superopt.candidates")
            stats["obligations"] += 1
            if obligation.proof == "syntactic":
                stats["syntactic"] += 1
                metrics.inc("superopt.proofs_syntactic")
            block_pending.append(obligation)
        if block_pending:
            pending[block.block_id] = block_pending
            layouts[block.block_id] = layout

    # -- discharge: one batched flush (jax) or sequential host proofs -------------
    queries = [ob for obs in pending.values() for ob in obs
               if ob.clauses is not None]
    stats["queries"] = len(queries)
    batched = solver == "jax" and dispatch.enabled()
    stats["batched"] = int(batched)
    with trace.span("superopt.prove", obligations=stats["obligations"],
                    queries=len(queries), batched=batched) as span:
        if batched and queries:
            dispatch.set_query_origin("superopt")
            try:
                for ob in queries:
                    ob.future = dispatch.submit(ob.clauses, ob.n_vars,
                                                _MAX_CONFLICTS)
                metrics.observe("superopt.proof_flush.occupancy",
                                len(queries))
                dispatch.flush()
            finally:
                dispatch.set_query_origin(None)
            for ob in queries:
                status, _model = ob.future.result()
                if status == sat.UNKNOWN:
                    # bottom of the ladder: the host CDCL decides
                    status, _model = sat.solve_cnf(ob.clauses, ob.n_vars,
                                                   max_conflicts=_MAX_CONFLICTS)
                    ob.proof = "host"
                else:
                    ob.proof = "device"
                ob.status = status
        else:
            for ob in queries:
                status, _model = sat.solve_cnf(ob.clauses, ob.n_vars,
                                               max_conflicts=_MAX_CONFLICTS)
                ob.status = status
                ob.proof = "host"

        accepted_queries = 0
        for ob in queries:
            if ob.status == sat.UNSAT:
                stats["unsat"] += 1
                metrics.inc("superopt.proofs_unsat")
                accepted_queries += 1
                # sampled crosscheck on the host oracle, divergence fatal
                # for the rewrite and loud in metrics
                if crosscheck and accepted_queries % crosscheck == 0:
                    stats["crosschecks"] += 1
                    metrics.inc("superopt.crosschecks")
                    host_status, _ = sat.solve_cnf(
                        ob.clauses, ob.n_vars, max_conflicts=_MAX_CONFLICTS)
                    if host_status == sat.SAT:
                        stats["divergences"] += 1
                        metrics.inc("superopt.crosscheck_divergence")
                        ob.status = sat.SAT
            elif ob.status == sat.SAT:
                stats["sat"] += 1
                metrics.inc("superopt.proofs_sat")
            else:
                stats["unknown"] += 1
                metrics.inc("superopt.proofs_unknown")
        span.set(unsat=stats["unsat"], sat=stats["sat"],
                 unknown=stats["unknown"])

    # -- rank, self-check, emit ---------------------------------------------------
    rng = random.Random(_SELFCHECK_SEED)
    out = bytearray(disassembly.raw_code)
    for block_id, obligations in sorted(pending.items()):
        layout = layouts[block_id]
        accepted = [ob for ob in obligations if ob.status == sat.UNSAT]
        accepted.sort(key=lambda ob: (ob.gas_after, ob.emitted))
        chosen = None
        depth = max(20, 17 + 2 * len(layout.body))
        for ob in accepted:
            envs = [random_env(rng, depth) for _ in range(_SELFCHECK_ENVS)]
            if any(differ_concretely(list(layout.body), list(ob.body), env)
                   for env in envs):
                # a proven rewrite failing concrete replay means the
                # encoding itself is wrong — refuse it and say so loudly
                stats["selfcheck_failures"] += 1
                continue
            chosen = ob
            break
        if chosen is None:
            continue
        out[layout.region_start:layout.region_start + layout.region_len] = \
            chosen.emitted
        gas_before = sequence_gas(name for name, _ in layout.body)
        report.rewrites.append(BlockRewrite(
            block_id=block_id, start_pc=layout.region_start,
            rule=chosen.rule, before=_disasm(layout.body),
            after=_disasm(chosen.body), gas_before=gas_before,
            gas_after=chosen.gas_after, weight=block_weight(block_id),
            proof=chosen.proof or "host"))

    if len(out) != len(disassembly.raw_code):  # pragma: no cover
        raise AssertionError("superopt emission changed the code length")
    report.code_out = bytes(out).hex()
    report.proof_stats = stats
    if report.weighted_gas_saved:
        metrics.inc("superopt.gas_saved", report.weighted_gas_saved)
    report.wall_ms = (time.perf_counter() - started) * 1000.0
    return report
