"""The single funnel for all satisfiability checks (API parity:
mythril/support/model.py — get_model:69 with global model LRU + ModelCache quick-sat
pre-check + timeout conversion to UnsatError/SolverTimeOutException).

Performance note: the quick-sat pre-check re-evaluates cached models against the new
constraint set with the term evaluator (cheap, pure Python) before paying for a
bit-blast + CDCL run; the overwhelming majority of engine-issued checks hit this
path. This is also where `--solver jax` batches sat-checks on TPU."""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Iterable, Optional, Tuple

from ..exceptions import SolverTimeOutException, UnsatError
from ..smt import Bool, Model, Optimize, Solver, terms
from ..smt.solver.solver_statistics import SolverStatistics
from ..core.time_handler import time_handler
from .support_args import args


class LRUCache:
    def __init__(self, size: int):
        self.size = size
        self._cache: OrderedDict = OrderedDict()

    def get(self, key):
        if key in self._cache:
            self._cache.move_to_end(key)
            return self._cache[key]
        return None

    def put(self, key, value) -> None:
        self._cache[key] = value
        self._cache.move_to_end(key)
        if len(self._cache) > self.size:
            self._cache.popitem(last=False)

    def __len__(self):
        return len(self._cache)


class ModelCache:
    """Keeps recent sat models; `check_quick_sat` re-evaluates them against a new
    constraint conjunction (reference support/support_utils.py:56-66)."""

    def __init__(self, size: int = 32):
        self.model_cache = LRUCache(size)

    def put(self, model: Model, weight: int = 1) -> None:
        self.model_cache.put(model, weight)

    def check_quick_sat(self, constraints: Iterable[terms.Term]) -> Optional[Model]:
        constraints = list(constraints)
        for model in list(self.model_cache._cache.keys()):
            try:
                if all(model.eval(c) for c in constraints):
                    self.model_cache.put(model, 1)
                    return model
            except (KeyError, ValueError, TypeError):
                # the probe is best-effort: KeyError = the model lacks a
                # variable this conjunction mentions; ValueError/TypeError =
                # the evaluator met a term it cannot fold. Anything else is
                # a real bug and must surface.
                continue
        return None


model_cache = ModelCache()

#: query-result cache keyed by the constraint tuple (terms are hash-consed)
_result_cache = LRUCache(2 ** 16)


def reset_model_caches() -> None:
    """Drop the sat-model reuse cache and the query-result cache (used by
    solver.reset_solver_backend; results cached against a now-discarded
    pipeline's models must not leak into a fresh one)."""
    global model_cache, _result_cache
    model_cache = ModelCache()
    _result_cache = LRUCache(2 ** 16)

#: zero model tried first: most path constraints are satisfied by all-zeros
_ZERO_MODEL = Model()


def prefetch_models(constraint_tuples: Iterable[Tuple]) -> int:
    """Speculatively queue device SAT work for several upcoming get_model
    calls (`--solver jax` + batching only; a cheap no-op otherwise).

    Mirrors get_model's fast paths — constant-false, simplification,
    result-cache, zero-model and quick-sat probes — so only the sets that
    WOULD reach the solver get queued, then hands them to
    solver.prefetch_formulas. The later real get_model over the same set
    dedups onto the in-flight batch entry or hits the dispatch verdict
    cache: N feasibility checks, one device launch. Returns the number of
    sets queued."""
    if args.solver != "jax" or not getattr(args, "batch_solve", True):
        return 0
    from ..smt.solver import solver as solver_service

    sets = []
    for constraints in constraint_tuples:
        raw_constraints = []
        constant_false = False
        for constraint in constraints:
            raw = constraint.raw if isinstance(constraint, Bool) else constraint
            if raw is terms.FALSE:
                constant_false = True
                break
            if raw is not terms.TRUE:
                raw_constraints.append(raw)
        if constant_false:
            continue
        if getattr(args, "simplify", True):
            from ..smt.solver.simplify import simplify_constraints

            outcome = simplify_constraints(raw_constraints)
            if outcome.is_false:
                continue
            raw_constraints = outcome.constraints
        if not raw_constraints:
            continue
        if _result_cache.get(tuple(raw_constraints)) is not None:
            continue
        try:
            if all(_ZERO_MODEL.eval(c) for c in raw_constraints):
                continue
        except (KeyError, ValueError, TypeError):
            pass  # zero probe failed to evaluate: the set stays a candidate
        if model_cache.check_quick_sat(raw_constraints) is not None:
            continue
        sets.append(raw_constraints)
    if not sets:
        return 0
    return solver_service.prefetch_formulas(sets)


def get_model(constraints, minimize: Tuple = (), maximize: Tuple = (),
              enforce_execution_time: bool = True,
              solver_timeout: Optional[int] = None) -> Model:
    """check-sat with caching; raises UnsatError / SolverTimeOutException."""
    constraints = tuple(constraints)
    simple = not minimize and not maximize

    raw_constraints = []
    for constraint in constraints:
        raw = constraint.raw if isinstance(constraint, Bool) else constraint
        if raw is terms.FALSE:
            raise UnsatError("constant-false constraint")
        if raw is not terms.TRUE:
            raw_constraints.append(raw)

    # cache on the word-level simplified form: syntactically different
    # constraint sets that rewrite to the same conjuncts (constant-prop,
    # keccak/ite/select collapse) share one result-cache entry, and the
    # simplifier's own memo makes the re-simplification in check_formulas
    # free. Quick-sat also evaluates the (usually much smaller) simplified
    # conjunction. Defining equalities are kept by the pass, so a cached
    # model still covers every variable the caller will ask about.
    if getattr(args, "simplify", True):
        from ..smt.solver.simplify import simplify_constraints

        outcome = simplify_constraints(raw_constraints)
        if outcome.is_false:
            raise UnsatError("simplified to false")
        raw_constraints = outcome.constraints

    cache_key = tuple(raw_constraints)
    if simple:
        cached = _result_cache.get(cache_key)
        if cached is not None:
            if cached == "unsat":
                raise UnsatError("cached unsat")
            return cached
        # quick-sat: all-zeros, then recently seen models
        try:
            if all(_ZERO_MODEL.eval(c) for c in raw_constraints):
                return _ZERO_MODEL
        except (KeyError, ValueError, TypeError):
            pass  # zero probe failed to evaluate: fall through to the solver
        hit = model_cache.check_quick_sat(raw_constraints)
        if hit is not None:
            return hit

    timeout = solver_timeout or args.solver_timeout
    if enforce_execution_time:
        timeout = min(timeout, time_handler.time_remaining() - 500)
        if timeout <= 0:
            raise SolverTimeOutException("global execution budget exhausted")

    if simple:
        solver = Solver(timeout=timeout)
    else:
        solver = Optimize(timeout=timeout)
        for expression in minimize:
            solver.minimize(expression)
        for expression in maximize:
            solver.maximize(expression)

    wrapped = [c if isinstance(c, Bool) else Bool(c) for c in raw_constraints]
    solver.add(*wrapped)
    _dump_query(wrapped)
    status = solver.check()
    if status == "sat":
        model = solver.model()
        if simple:
            _result_cache.put(cache_key, model)
            model_cache.put(model)
        return model
    if status == "unknown":
        raise SolverTimeOutException("solver query exceeded budget")
    if simple:
        _result_cache.put(cache_key, "unsat")
    raise UnsatError("unsat")


_query_counter = [0]


def _dump_query(constraints) -> None:
    """--solver-log: dump each query as .smt2 (reference support/model.py:51-61)."""
    if not args.solver_log:
        return
    from ..smt.smtlib import to_smt2

    os.makedirs(args.solver_log, exist_ok=True)
    _query_counter[0] += 1
    # pid-namespaced so successive runs into one directory never overwrite
    path = os.path.join(args.solver_log,
                        f"{os.getpid()}-{_query_counter[0]}.smt2")
    with open(path, "w") as handle:
        handle.write(to_smt2([c.raw for c in constraints]))
