"""DynLoader: on-chain world-state fault-in (capability parity:
mythril/support/loader.py:15 — lru-cached read_storage / read_balance / dynld
that disassembles on-chain code). Consumed by core/call.py:57-66 and
core/state/account.py:38-44."""

from __future__ import annotations

import functools
import logging
from typing import Optional

from ..frontends.disassembler import Disassembly

log = logging.getLogger(__name__)


class DynLoader:
    def __init__(self, eth, active: bool = True):
        """eth: an EthJsonRpc-compatible client (ethereum/rpc.py)."""
        self.eth = eth
        self.active = active

    @functools.lru_cache(maxsize=2 ** 10)
    def read_storage(self, contract_address: str, index: int) -> str:
        if not self.active:
            raise ValueError("loader is disabled")
        if self.eth is None:
            raise ValueError("no RPC client configured")
        return self.eth.eth_getStorageAt(contract_address, index)

    @functools.lru_cache(maxsize=2 ** 10)
    def read_balance(self, address: str) -> int:
        if not self.active:
            raise ValueError("loader is disabled")
        if self.eth is None:
            raise ValueError("no RPC client configured")
        return self.eth.eth_getBalance(address)

    @functools.lru_cache(maxsize=2 ** 6)
    def dynld(self, dependency_address: str) -> Optional[Disassembly]:
        """Fetch and disassemble on-chain code at `dependency_address`."""
        if not self.active:
            return None
        if self.eth is None:
            raise ValueError("no RPC client configured")
        log.debug("fetching on-chain code for %s", dependency_address)
        code = self.eth.eth_getCode(dependency_address)
        if code in (None, "", "0x", "0x0"):
            return None
        return Disassembly(code[2:] if code.startswith("0x") else code)
