"""Failure domains, backend circuit breaker, and deterministic fault injection.

The solver stack degrades along a fixed ladder — device DPLL (`--solver jax`)
-> native CDCL -> pure-Python DPLL — and every rung decides the same
sat/unsat question, so a degraded run produces the same issues as a healthy
one, just slower (the DTVM determinism argument, PAPERS.md). This module
gives that ladder real failure domains instead of one blanket
`except Exception` counter:

- **Failure taxonomy**: every backend failure is classified (`classify_failure`)
  into one of `FAILURE_CLASSES` — device OOM, compile/trace error, wall-clock
  overrun, worker crash, verdict divergence, native crash — and counted
  per (backend, class) in `SolverStatistics.failure_counts`.
- **Circuit breaker** (`BackendHealth`): a backend that fails
  `trip_after` consecutive times is OPEN — skipped entirely, so a sick
  device stops paying minutes of XLA recompile per query. After
  `recovery_after` skipped queries one probe is let through (half-open);
  a probe success CLOSEs the breaker, a probe failure re-arms the skip
  window. A `DIVERGENCE` failure QUARANTINEs the backend for the rest of
  the process (no recovery probes): a backend that returned a *wrong*
  verdict can never be trusted again this run.
- **Fault injection** (`configure` / `fire` / `take`): the
  `--inject-fault CLASS[:NTH]` CLI flag (or `MYTHRIL_TPU_INJECT_FAULT`)
  raises the typed exception for CLASS at the NTH visit of its boundary —
  the device solve, the native solve, or the laser loop — so every ladder
  rung, the breaker thresholds, and the checkpoint/resume path are
  testable without real device failures.

State is process-global (like `SolverStatistics`); `reset()` restores a
pristine registry + plan and is called from
`smt.solver.solver.reset_solver_backend` so each fresh analysis (or test)
starts with healthy backends.
"""

from __future__ import annotations

import logging
import signal
from typing import Callable, Dict, List, Optional, Tuple

from . import tpu_config
from ..observe import trace

log = logging.getLogger(__name__)

# -- failure taxonomy -----------------------------------------------------------------

#: device ran out of HBM / host memory while solving
DEVICE_OOM = "device_oom"
#: XLA compile / trace / lowering error (bad shapes, tracer leaks, ...)
COMPILE_ERROR = "compile_error"
#: the solve exceeded its wall-clock budget (e.g. a recompile storm)
WALL_OVERRUN = "wall_overrun"
#: device/worker process died or any other unclassified backend error
WORKER_CRASH = "worker_crash"
#: backend returned a sat/unsat verdict the host oracle disproves
DIVERGENCE = "divergence"
#: native CDCL library failure (load error, session corruption, crash)
NATIVE_CRASH = "native_crash"
#: injection-only: simulated kill of the host laser loop (exercises the
#: checkpoint/resume path; never produced by classify_failure)
HOST_CRASH = "host_crash"
#: serve worker process died on a fatal signal (SIGSEGV/SIGBUS/SIGABRT)
WORKER_SEGV = "worker_segv"
#: serve worker process stopped heartbeating and had to be killed
WORKER_HANG = "worker_hang"
#: serve worker process was OOM-killed (SIGKILL) or raised MemoryError
WORKER_OOM = "worker_oom"

FAILURE_CLASSES = (DEVICE_OOM, COMPILE_ERROR, WALL_OVERRUN, WORKER_CRASH,
                   DIVERGENCE, NATIVE_CRASH, HOST_CRASH,
                   WORKER_SEGV, WORKER_HANG, WORKER_OOM)

#: backend names in ladder order (PYTHON is the floor: never gated)
DEVICE, NATIVE, PYTHON = "device", "native", "python"

# breaker states
CLOSED, OPEN, QUARANTINED = "closed", "open", "quarantined"


class BackendFailure(Exception):
    """Base of the typed failure exceptions (used by fault injection; real
    backend errors keep their original type and are mapped by
    classify_failure)."""

    failure_class = WORKER_CRASH


class DeviceOOM(BackendFailure):
    failure_class = DEVICE_OOM


class DeviceCompileError(BackendFailure):
    failure_class = COMPILE_ERROR


class DeviceWallOverrun(BackendFailure):
    failure_class = WALL_OVERRUN


class DeviceWorkerCrash(BackendFailure):
    failure_class = WORKER_CRASH


class NativeCrash(BackendFailure):
    failure_class = NATIVE_CRASH


class WorkerSegv(BackendFailure):
    failure_class = WORKER_SEGV


class WorkerHang(BackendFailure):
    failure_class = WORKER_HANG


class WorkerOOM(BackendFailure):
    failure_class = WORKER_OOM


class InjectedCrash(BaseException):
    """Simulated kill -9 of the analysis loop (`--inject-fault host_crash:N`).
    BaseException on purpose: it must sail through every `except Exception`
    (the analyzer's per-contract catch-all included) and unwind like a real
    death so the test can assert the run resumes from its last atomic
    checkpoint."""

    failure_class = HOST_CRASH


_EXCEPTION_FOR_CLASS = {
    DEVICE_OOM: DeviceOOM,
    COMPILE_ERROR: DeviceCompileError,
    WALL_OVERRUN: DeviceWallOverrun,
    WORKER_CRASH: DeviceWorkerCrash,
    NATIVE_CRASH: NativeCrash,
    HOST_CRASH: InjectedCrash,
    WORKER_SEGV: WorkerSegv,
    WORKER_HANG: WorkerHang,
    WORKER_OOM: WorkerOOM,
}

#: which injection boundary ("site") each failure class fires at
SITE_OF_CLASS = {
    DEVICE_OOM: DEVICE,
    COMPILE_ERROR: DEVICE,
    WALL_OVERRUN: DEVICE,
    WORKER_CRASH: DEVICE,
    DIVERGENCE: "divergence",
    NATIVE_CRASH: NATIVE,
    HOST_CRASH: "host",
    # worker classes fire at the serve supervisor's job-dispatch boundary
    # (serve/supervisor.py visits "worker" once per job handed to a
    # worker process; the worker then genuinely dies that way)
    WORKER_SEGV: "worker",
    WORKER_HANG: "worker",
    WORKER_OOM: "worker",
}

#: substrings of exception type names / messages that identify OOMs. XLA
#: surfaces device OOM as XlaRuntimeError("RESOURCE_EXHAUSTED: ...").
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "out of memory", "OOM",
                "Resource exhausted")
_COMPILE_TYPE_MARKERS = ("TracerError", "ConcretizationTypeError",
                         "UnexpectedTracerError", "JaxStackTraceBeforeTransformation",
                         "TypeError", "ShapeError")
_COMPILE_MSG_MARKERS = ("INVALID_ARGUMENT", "compilation", "lowering",
                        "abstract value", "jit")


def classify_failure(error: BaseException,
                     context: Optional[str] = None) -> str:
    """Map an arbitrary backend exception to a failure class. Typed
    injection exceptions carry their class; real errors classify by type
    and message shape, defaulting to WORKER_CRASH (the catch-all domain).

    ``context="worker"`` classifies on behalf of a serve worker process:
    memory exhaustion there is the worker's own failure domain
    (WORKER_OOM — the sandbox died, not the device), while the default
    context keeps the historical DEVICE_OOM mapping for in-process
    backend errors."""
    if isinstance(error, BackendFailure):
        return error.failure_class
    name = type(error).__name__
    text = f"{name}: {error}"
    if isinstance(error, MemoryError) or \
            any(marker in text for marker in _OOM_MARKERS):
        return WORKER_OOM if context == "worker" else DEVICE_OOM
    if isinstance(error, TimeoutError):
        return WALL_OVERRUN
    if any(marker in name for marker in _COMPILE_TYPE_MARKERS) or \
            any(marker in str(error) for marker in _COMPILE_MSG_MARKERS):
        return COMPILE_ERROR
    return WORKER_CRASH


#: fatal signals that mean "the process itself blew up" (not a kill)
_SEGV_SIGNALS = frozenset(
    getattr(signal, sig_name)
    for sig_name in ("SIGSEGV", "SIGBUS", "SIGABRT", "SIGILL", "SIGFPE")
    if hasattr(signal, sig_name))


def classify_exit_status(returncode: Optional[int]) -> Optional[str]:
    """Map a child process's ``Popen.returncode`` to a worker failure
    class, or None for a clean (or still-running) exit.

    Negative return codes are ``-signum`` (POSIX): SIGSEGV/SIGBUS/
    SIGABRT/SIGILL/SIGFPE classify as WORKER_SEGV (the process's own
    fault), SIGKILL as WORKER_OOM (the kernel OOM killer is the only
    expected uninvited SIGKILL source), anything else signal-ish or a
    non-zero exit as WORKER_CRASH."""
    if returncode is None or returncode == 0:
        return None
    if returncode < 0:
        signum = -returncode
        if signum in _SEGV_SIGNALS:
            return WORKER_SEGV
        if signum == getattr(signal, "SIGKILL", 9):
            return WORKER_OOM
        return WORKER_CRASH
    return WORKER_CRASH


# -- circuit breaker ------------------------------------------------------------------

#: consecutive failures before a backend trips OPEN
DEFAULT_TRIP_AFTER = 3
#: queries skipped while OPEN before one half-open recovery probe
DEFAULT_RECOVERY_AFTER = 32


def _stats():
    from ..smt.solver.solver_statistics import SolverStatistics

    return SolverStatistics()


class BackendHealth:
    """Per-backend failure bookkeeping + circuit breaker.

    States: CLOSED (healthy, queries flow), OPEN (tripped: queries are
    skipped, with a half-open probe every `recovery_after` skips),
    QUARANTINED (divergence: permanently off for this run). Every
    transition is mirrored into SolverStatistics so the final report can
    show the full fault story."""

    def __init__(self, name: str, trip_after: int = DEFAULT_TRIP_AFTER,
                 recovery_after: int = DEFAULT_RECOVERY_AFTER):
        self.name = name
        self.trip_after = trip_after
        self.recovery_after = recovery_after
        self.state = CLOSED
        self.consecutive_failures = 0
        self.skipped_since_trip = 0
        self.failure_counts: Dict[str, int] = {}
        self.trips = 0
        self.recoveries = 0
        self.last_failure: Optional[Tuple[str, str]] = None  # (class, detail)

    def allow(self) -> bool:
        """May the next query attempt this backend? OPEN breakers skip
        queries but let one probe through per recovery window."""
        if self.state == QUARANTINED:
            return False
        if self.state == OPEN:
            self.skipped_since_trip += 1
            if self.skipped_since_trip >= self.recovery_after:
                log.info("backend %r half-open: letting a recovery probe "
                         "through after %d skipped queries", self.name,
                         self.skipped_since_trip)
                return True
            return False
        return True

    def record_success(self) -> None:
        self.consecutive_failures = 0
        if self.state == OPEN:
            # a successful half-open probe recovers the backend
            self.state = CLOSED
            self.skipped_since_trip = 0
            self.recoveries += 1
            _stats().breaker_recoveries += 1
            trace.instant("resilience.breaker_recovery", backend=self.name)
            log.warning("backend %r recovered: circuit breaker closed",
                        self.name)

    def record_failure(self, failure_class: str, detail: str = "") -> None:
        self.failure_counts[failure_class] = \
            self.failure_counts.get(failure_class, 0) + 1
        self.consecutive_failures += 1
        self.last_failure = (failure_class, detail)
        stats = _stats()
        key = f"{self.name}:{failure_class}"
        stats.failure_counts[key] = stats.failure_counts.get(key, 0) + 1
        if failure_class == DIVERGENCE:
            self.quarantine(detail)
            return
        if self.state == OPEN:
            # failed recovery probe: re-arm the skip window
            self.skipped_since_trip = 0
            return
        if self.state == CLOSED and \
                self.consecutive_failures >= self.trip_after:
            self.state = OPEN
            self.skipped_since_trip = 0
            self.trips += 1
            stats.breaker_trips += 1
            trace.instant("resilience.breaker_trip", backend=self.name,
                          failure_class=failure_class,
                          consecutive=self.consecutive_failures)
            log.error(
                "backend %r circuit breaker TRIPPED after %d consecutive "
                "failures (last: %s %s) — degrading to the next ladder rung",
                self.name, self.consecutive_failures, failure_class, detail)

    def quarantine(self, detail: str = "") -> None:
        """Permanently disable the backend for this run (divergence: a
        backend that produced a wrong verdict cannot be probed back)."""
        if self.state == QUARANTINED:
            return
        self.state = QUARANTINED
        stats = _stats()
        if self.name not in stats.backends_quarantined:
            stats.backends_quarantined.append(self.name)
        trace.instant("resilience.quarantine", backend=self.name,
                      detail=detail or "verdict divergence")
        log.critical(
            "backend %r QUARANTINED for the rest of this run: %s — all "
            "further queries use the host ladder", self.name,
            detail or "verdict divergence")


class HealthRegistry:
    """Process-wide registry of BackendHealth objects (DEVICE / NATIVE;
    PYTHON is the unconditional floor and is never registered)."""

    def __init__(self):
        self._backends: Dict[str, BackendHealth] = {}

    def backend(self, name: str) -> BackendHealth:
        health = self._backends.get(name)
        if health is None:
            trip = tpu_config.get_int("MYTHRIL_TPU_BREAKER_TRIP",
                                      DEFAULT_TRIP_AFTER)
            recover = tpu_config.get_int("MYTHRIL_TPU_BREAKER_RECOVERY",
                                         DEFAULT_RECOVERY_AFTER)
            health = BackendHealth(name, trip_after=trip,
                                   recovery_after=recover)
            self._backends[name] = health
        return health

    def states(self) -> Dict[str, str]:
        return {name: health.state
                for name, health in sorted(self._backends.items())}

    def reset(self) -> None:
        self._backends.clear()


registry = HealthRegistry()


# -- deterministic fault injection ----------------------------------------------------


def _parse_matcher(spec: str) -> Callable[[int], bool]:
    """"3" fires exactly at visit 3, "3+" from visit 3 on, "*" at every
    visit; an omitted NTH means "1"."""
    spec = spec.strip() or "1"
    if spec == "*":
        return lambda count: True
    if spec.endswith("+"):
        nth = int(spec[:-1])
        return lambda count: count >= nth
    nth = int(spec)
    return lambda count: count == nth


class FaultPlan:
    """Parsed `--inject-fault` spec: comma-separated CLASS[:NTH] entries.
    Each boundary visit increments a per-site counter; an entry fires when
    its matcher accepts the count — fully deterministic, no clocks."""

    def __init__(self, spec: Optional[str] = None):
        self.spec = spec
        #: (failure_class, site, matcher)
        self.entries: List[Tuple[str, str, Callable[[int], bool]]] = []
        self.site_counts: Dict[str, int] = {}
        self.fired: List[Tuple[str, int]] = []  # (class, visit) audit trail
        for raw_entry in (spec or "").split(","):
            raw_entry = raw_entry.strip()
            if not raw_entry:
                continue
            failure_class, _, nth = raw_entry.partition(":")
            failure_class = failure_class.strip()
            if failure_class not in SITE_OF_CLASS:
                raise ValueError(
                    f"unknown fault class {failure_class!r}; expected one of "
                    f"{sorted(SITE_OF_CLASS)}")
            self.entries.append((failure_class, SITE_OF_CLASS[failure_class],
                                 _parse_matcher(nth)))

    @property
    def active(self) -> bool:
        return bool(self.entries)

    def visit(self, site: str) -> Optional[str]:
        """Record a boundary visit; returns the failure class to fire (or
        None). At most one entry fires per visit (first match wins)."""
        if not self.entries:
            return None
        count = self.site_counts.get(site, 0) + 1
        self.site_counts[site] = count
        for failure_class, entry_site, matcher in self.entries:
            if entry_site == site and matcher(count):
                self.fired.append((failure_class, count))
                return failure_class
        return None


_plan: Optional[FaultPlan] = None


def configure(spec: Optional[str]) -> None:
    """Install a fault plan (None/empty disables injection). Also resets
    the plan's visit counters — each configure starts a fresh schedule."""
    global _plan
    _plan = FaultPlan(spec)
    if _plan.active:
        log.warning("fault injection ACTIVE: %s", spec)


def plan() -> FaultPlan:
    global _plan
    if _plan is None:
        _plan = FaultPlan(tpu_config.get_str("MYTHRIL_TPU_INJECT_FAULT"))
        if _plan.active:
            log.warning("fault injection ACTIVE (env): %s", _plan.spec)
    return _plan


def fire(site: str) -> None:
    """Raise the configured typed exception if an entry matches this visit
    of `site`. No-op (one dict lookup) when injection is inactive."""
    failure_class = plan().visit(site)
    if failure_class is not None:
        raise _EXCEPTION_FOR_CLASS[failure_class](
            f"injected {failure_class} (visit "
            f"{plan().site_counts[site]} of site {site!r})")


def take(site: str) -> bool:
    """Non-raising variant for verdict-mutation classes (divergence):
    True when this visit should fire."""
    return plan().visit(site) is not None


# -- knobs read by the solver stack ---------------------------------------------------


def device_wall_budget_ms() -> int:
    """Wall-clock budget for one device solve before it counts as a
    WALL_OVERRUN failure (0 disables the check). A sick backend often
    still answers — after minutes of recompile; overruns trip the breaker
    even when the verdict is usable."""
    return tpu_config.get_int("MYTHRIL_TPU_DEVICE_WALL_MS")


def crosscheck_every() -> int:
    """Sampling period for the divergence cross-check: every Nth device
    verdict is re-decided by the host CDCL oracle (0 = off, the default).
    Set by `--device-crosscheck N` or MYTHRIL_TPU_CROSSCHECK."""
    from .support_args import args

    configured = getattr(args, "device_crosscheck", 0)
    if configured:
        return int(configured)
    return tpu_config.get_int("MYTHRIL_TPU_CROSSCHECK")


def reset() -> None:
    """Fresh registry + disarmed plan (per-analysis / per-test isolation)."""
    global _plan
    registry.reset()
    _plan = FaultPlan(None)
