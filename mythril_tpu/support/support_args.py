"""Global engine flags (API parity: mythril/support/support_args.py:5).

The reference copies argparse values wholesale into this singleton and reads it from
arbitrary depths. Kept for CLI/capability parity, but engine components snapshot the
values they need at construction so nothing inside a jitted TPU step reads mutable
globals (SURVEY.md §5 config note)."""

from __future__ import annotations


class Args:
    """Singleton flag object."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance._init_defaults()
        return cls._instance

    def _init_defaults(self):
        self.solver_log = None
        self.transaction_sequences = None
        self.use_integer_module = True
        self.use_issue_annotations = False
        self.solver_timeout = 10000
        self.parallel_solving = False
        self.unconstrained_storage = False
        self.call_depth_limit = 3
        self.disable_iprof = True
        self.solc_args = None
        self.disable_coverage_strategy = False
        self.disable_mutation_pruner = False
        self.incremental_txs = True
        self.epic = False
        self.pruning_factor = None
        #: solver backend: "cdcl" (native host solver) or "jax" (batched TPU solver)
        self.solver = "cdcl"
        #: word-level simplification ahead of the bit-blaster (smt/solver/simplify.py);
        #: --no-simplify turns it off for A/B measurement
        self.simplify = True
        #: batched device SAT dispatch (smt/solver/dispatch.py): verdict
        #: cache + deferred-flush query batching on the jax lane;
        #: --no-batch-solve turns it off for A/B measurement
        self.batch_solve = True
        #: static control-flow-analysis screen (staticanalysis/ +
        #: smt/solver/cfa_screen.py); --no-cfa turns all consumers off
        #: for A/B measurement
        self.cfa = True
        #: taint module screen (staticanalysis/taint.py +
        #: analysis/module_screen.py); --no-taint turns all consumers
        #: off for A/B measurement
        self.taint = True
        #: value-range / memory-region abstract interpretation
        #: (staticanalysis/absint.py): widened memory-plane merging,
        #: proven loop bounds, constant-JUMPI pruning; --no-absint turns
        #: all consumers off for A/B measurement
        self.absint = True
        #: device-resident frontier counter plane (parallel/symstep.py);
        #: --no-frontier-telemetry compiles it out for A/B measurement
        self.frontier_telemetry = True
        #: on-device state merging at post-dominator join points
        #: (parallel/symstep.py merge_pass); --no-state-merge turns it
        #: off for A/B measurement. Distinct from enable_state_merging
        #: below, which is the host post-transaction merge plugin.
        self.state_merge = True
        self.sparse_pruning = True
        self.enable_state_merging = False
        self.enable_summaries = False
        #: deterministic fault injection spec, `CLASS[:NTH],...`
        #: (support/resilience.py; --inject-fault / MYTHRIL_TPU_INJECT_FAULT)
        self.inject_fault = None
        #: cross-check every Nth device verdict against the host CDCL oracle
        #: (0 = off); a divergence quarantines the device backend for the run
        self.device_crosscheck = 0


args = Args()
