"""Function-signature database: 4-byte selector -> canonical text signature(s).

Capability parity: mythril/support/signatures.py:117 (SQLite DB at
~/.mythril/signatures.db, optional 4byte.directory online lookup, solidity-file
import). This build keeps the same surface but (a) seeds from a small built-in table of
ubiquitous signatures rather than a shipped binary DB, (b) supports learning signatures
from any ABI/signature list the user supplies, (c) gates online lookup behind a flag
(the build environment has no egress, so it fails soft).
"""

from __future__ import annotations

import json
import os
import re
import sqlite3
import threading
from typing import List

from ..utils.keccak import keccak256

from . import tpu_config

_COMMON_SIGNATURES = [
    "transfer(address,uint256)", "transferFrom(address,address,uint256)",
    "approve(address,uint256)", "balanceOf(address)", "totalSupply()",
    "allowance(address,address)", "owner()", "name()", "symbol()", "decimals()",
    "mint(address,uint256)", "burn(uint256)", "withdraw()", "withdraw(uint256)",
    "deposit()", "kill()", "destroy()", "transferOwnership(address)",
    "fallback()", "pause()", "unpause()", "setOwner(address)", "init()",
    "initialize()", "getBalance()", "sendTo(address,uint256)", "claim()",
    "killbilly()", "activatekillability()", "commencekilling()", "isKillable()",
    "batchTransfer(address[],uint256)", "safeTransferFrom(address,address,uint256)",
]


def _default_db_path() -> str:
    base = tpu_config.get_str("MYTHRIL_TPU_DIR",
                              os.path.expanduser("~/.mythril_tpu"))
    os.makedirs(base, exist_ok=True)
    return os.path.join(base, "signatures.db")


class SignatureDB:
    """Thread-safe selector<->signature store, shared per-process (singleton-ish)."""

    _instance = None
    _lock = threading.Lock()

    def __new__(cls, enable_online_lookup: bool | None = None, path: str | None = None):
        if path is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = super().__new__(cls)
                return cls._instance
        return super().__new__(cls)

    def __init__(self, enable_online_lookup: bool | None = None, path: str | None = None):
        if getattr(self, "_initialized", False) and path is None:
            # singleton re-construction: only an EXPLICIT flag changes the setting
            if enable_online_lookup is not None:
                self.enable_online_lookup = enable_online_lookup
            return
        self.enable_online_lookup = bool(enable_online_lookup)
        self.path = path or _default_db_path()
        self._local = threading.local()
        self._ensure_schema()
        self._initialized = True

    @property
    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self.path)
            self._local.conn = conn
        return conn

    def _ensure_schema(self) -> None:
        with self._conn as conn:
            conn.execute(
                "CREATE TABLE IF NOT EXISTS signatures "
                "(byte_sig VARCHAR(10), text_sig VARCHAR(255), "
                "PRIMARY KEY (byte_sig, text_sig))")
        if not self._conn.execute("SELECT 1 FROM signatures LIMIT 1").fetchone():
            for sig in _COMMON_SIGNATURES:
                self.add(self.get_sighash(sig), sig)

    @staticmethod
    def get_sighash(text_signature: str) -> str:
        return "0x" + keccak256(text_signature.encode())[:4].hex()

    def add(self, byte_sig: str, text_sig: str) -> None:
        with self._conn as conn:
            conn.execute("INSERT OR IGNORE INTO signatures VALUES (?, ?)",
                         (byte_sig.lower(), text_sig))

    def get(self, byte_sig: str) -> List[str]:
        byte_sig = byte_sig.lower()
        if not byte_sig.startswith("0x"):
            byte_sig = "0x" + byte_sig
        rows = self._conn.execute(
            "SELECT text_sig FROM signatures WHERE byte_sig = ?", (byte_sig,)).fetchall()
        results = [row[0] for row in rows]
        if not results and self.enable_online_lookup:
            results = self._online_lookup(byte_sig)
            for sig in results:
                self.add(byte_sig, sig)
        return results

    def __getitem__(self, item: str) -> List[str]:
        return self.get(item)

    def _online_lookup(self, byte_sig: str) -> List[str]:
        """4byte.directory lookup; fails soft (no egress in this environment)."""
        try:
            import urllib.request

            url = f"https://www.4byte.directory/api/v1/signatures/?hex_signature={byte_sig}"
            with urllib.request.urlopen(url, timeout=2) as response:
                payload = json.load(response)
            return [entry["text_signature"] for entry in payload.get("results", [])]
        except Exception:
            return []

    def import_solidity_file(self, file_path: str) -> None:
        """Harvest `function name(args)` declarations from a solidity source file."""
        pattern = re.compile(r"function\s+(\w+)\s*\(([^)]*)\)")
        with open(file_path, errors="ignore") as handle:
            source = handle.read()
        for name, args in pattern.findall(source):
            arg_types = []
            for arg in args.split(","):
                arg = arg.strip()
                if not arg:
                    continue
                base_type = arg.split()[0]
                base_type = {"uint": "uint256", "int": "int256", "byte": "bytes1"}.get(
                    base_type, base_type)
                arg_types.append(base_type)
            canonical = f"{name}({','.join(arg_types)})"
            self.add(self.get_sighash(canonical), canonical)

    def import_abi(self, abi: list) -> None:
        for entry in abi:
            if entry.get("type") != "function":
                continue
            types = ",".join(inp["type"] for inp in entry.get("inputs", []))
            canonical = f"{entry['name']}({types})"
            self.add(self.get_sighash(canonical), canonical)
