"""Host-phase analysis checkpoints.

The reference has NO engine-state serialization (SURVEY §5 assigns
checkpoint/resume to this build as a fresh design). The device frontier has
dense .npz snapshots (parallel/frontier.py save_checkpoint); this module
covers the phase where most analyses actually live: the host worklist.

What a checkpoint holds: the open world states, the pending worklist (plus
the in-flight state at a mid-transaction save), the transaction index, and
the CALLBACK detectors' accumulated issues/caches — everything needed for a
killed `analyze` to resume and emit the identical final report. GlobalStates
are plain Python object graphs and the term DAG re-interns on unpickle
(smt/terms.py Term.__reduce__), so pickle is sufficient and exact.

Writes are crash-safe (tmp + fsync + os.replace, then a best-effort
directory fsync): preemption or power loss mid-write never corrupts the
only checkpoint — either the old file or the complete new one survives.

Known limit: laser-plugin INTERNAL state (e.g. the dependency pruner's
per-iteration counters) is not serialized — a mid-transaction resume
re-fires the tx lifecycle hooks but plugin counters restart, so pruning
heuristics may explore slightly differently than the uninterrupted run;
detector issues and tx-boundary resumes are exact.
"""

from __future__ import annotations

import logging
import os
import pickle
import re
import sys
import time
from typing import Optional

from . import tpu_config
from ..observe import metrics, trace

log = logging.getLogger(__name__)

FORMAT_VERSION = 2  # v2: payloads are namespaced by contract id
#: seconds between periodic mid-transaction saves
SAVE_INTERVAL_S = 15.0
#: states executed between periodic mid-transaction saves (overridable via
#: MYTHRIL_TPU_CHECKPOINT_STATES; the time cadence still applies)
SAVE_INTERVAL_STATES = 2000

#: every key restore_into_laser dereferences — validated at load so a
#: truncated or foreign payload degrades to a fresh run instead of raising
#: a KeyError deep inside resume
REQUIRED_KEYS = ("version", "tx_index", "open_states", "work_list",
                 "executed_nodes", "total_states", "detectors",
                 "contract_id")


def fsync_replace(tmp: str, path: str) -> None:
    """Durably promote `tmp` to `path`: flush the file's bytes to disk
    before the rename, then best-effort fsync the directory so the rename
    itself survives power loss (not just process death)."""
    with open(tmp, "rb+") as handle:
        os.fsync(handle.fileno())
    os.replace(tmp, path)
    try:
        dir_fd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
    except OSError:
        pass  # non-POSIX/odd filesystems: rename atomicity still holds


def request_checkpoint_path(base_dir: str, request_key: str) -> str:
    """Request-scoped checkpoint path for a serve worker job: one file
    per in-flight request under the supervisor's scratch dir, so a
    worker cut down mid-analysis leaves a checkpoint its one retry can
    resume from — and two concurrent requests (even for the same
    contract) never share a file. The key is sanitized to a safe
    filename; the caller deletes the file after the request's final
    outcome."""
    safe = re.sub(r"[^A-Za-z0-9._-]", "_", request_key)[:80] or "req"
    return os.path.join(base_dir, f"req-{safe}.ckpt")


def checkpoint_state_interval() -> int:
    return tpu_config.get_int("MYTHRIL_TPU_CHECKPOINT_STATES",
                              SAVE_INTERVAL_STATES)


def _collect_detector_state():
    from ..analysis.module.loader import ModuleLoader

    state = {}
    for module in ModuleLoader().get_detection_modules():
        state[module.name] = {
            "issues": list(module.issues),
            "cache": set(getattr(module, "cache", ()) or ()),
        }
    return state


def _restore_detector_state(state) -> None:
    from ..analysis.module.loader import ModuleLoader

    for module in ModuleLoader().get_detection_modules():
        saved = state.get(module.name)
        if saved is None:
            continue
        module.issues = list(saved["issues"])
        if hasattr(module, "cache"):
            module.cache = set(saved["cache"])


def save_host_checkpoint(path: str, laser, tx_index: int,
                         in_flight=None) -> None:
    payload = {
        "version": FORMAT_VERSION,
        "contract_id": getattr(laser, "contract_id", ""),
        "tx_index": tx_index,
        "open_states": list(laser.open_states),
        "work_list": ([in_flight] if in_flight is not None else [])
        + list(laser.work_list),
        "executed_nodes": laser.executed_nodes,
        "total_states": laser.total_states,
        "detectors": _collect_detector_state(),
    }
    limit = sys.getrecursionlimit()
    sys.setrecursionlimit(max(limit, 200_000))  # deep store/constraint chains
    started = time.perf_counter()
    try:
        with trace.span("checkpoint.save", kind="host", tx_index=tx_index,
                        open_states=len(payload["open_states"]),
                        work_list=len(payload["work_list"])):
            tmp = f"{path}.tmp"
            with open(tmp, "wb") as handle:
                pickle.dump(payload, handle, protocol=4)
            fsync_replace(tmp, path)
    finally:
        sys.setrecursionlimit(limit)
    metrics.inc("checkpoint.saves")
    metrics.observe("checkpoint.write_ms",
                    (time.perf_counter() - started) * 1000.0)


def load_host_checkpoint(path: str,
                         expected_contract_id: Optional[str] = None
                         ) -> Optional[dict]:
    """Returns the payload, or None when the file is absent/corrupt/foreign
    (a bad checkpoint must degrade to a fresh run, never crash the run).

    `expected_contract_id` guards fleet resumes: a checkpoint written for
    another contract in the corpus must not restore into this one's laser."""
    if not os.path.exists(path):
        return None
    try:
        with trace.span("checkpoint.load", kind="host"), \
                open(path, "rb") as handle:
            payload = pickle.load(handle)
        if not isinstance(payload, dict):
            log.warning("checkpoint %s is not a payload dict (%s); ignoring",
                        path, type(payload).__name__)
            return None
        if payload.get("version") != FORMAT_VERSION:
            log.warning("checkpoint %s has format %s (want %s); ignoring",
                        path, payload.get("version"), FORMAT_VERSION)
            return None
        missing = [key for key in REQUIRED_KEYS if key not in payload]
        if missing:
            log.warning("checkpoint %s is missing required keys %s; ignoring",
                        path, missing)
            return None
        if expected_contract_id is not None and \
                payload["contract_id"] != expected_contract_id:
            log.warning(
                "checkpoint %s belongs to contract %r, not %r; ignoring",
                path, payload["contract_id"], expected_contract_id)
            return None
        return payload
    except Exception as error:
        log.warning("cannot load checkpoint %s (%s); starting fresh",
                    path, error)
        return None


def restore_into_laser(payload: dict, laser) -> tuple:
    """Apply a loaded payload onto a fresh LaserEVM. Returns
    (start_tx_index, pending_work_list)."""
    laser.open_states = payload["open_states"]
    laser.executed_nodes = payload["executed_nodes"]
    laser.total_states = payload["total_states"]
    _restore_detector_state(payload["detectors"])
    log.info("resumed host checkpoint: tx %d, %d open states, %d pending "
             "worklist states", payload["tx_index"],
             len(payload["open_states"]), len(payload["work_list"]))
    return payload["tx_index"], payload["work_list"]
