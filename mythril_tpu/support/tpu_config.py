"""Central registry for every ``MYTHRIL_TPU_*`` environment knob.

Every knob the engine reads must be declared here — name, type, default,
and a one-line docstring. The tpu-lint rule R5 (tools/lint/rules/env_knobs)
fails the build on any ``os.environ``/``os.getenv`` read of an undeclared
``MYTHRIL_TPU_*`` name, and on a README knob table that drifts from
:func:`render_markdown_table`. The accessors below are the runtime half of
the same contract: they raise ``KeyError`` for undeclared names, so a typo
in a knob name is loud instead of silently returning the default.

All accessors read ``os.environ`` at *call time* (never at import or
construction time): tests monkeypatch knobs in arbitrary order relative to
queue/frontier construction, and an import-time snapshot would make those
overrides order-dependent (see tests/test_batch_dispatch.py's autouse
fixture, which resets the dispatch queue *before* setting the env).

This module must stay dependency-free (stdlib only): the lint framework
loads it standalone, without importing jax or the rest of the package.
"""

from __future__ import annotations

import os
from typing import Dict, List, NamedTuple, Optional


class Knob(NamedTuple):
    """One declared environment knob."""

    name: str           #: full env-var name (MYTHRIL_TPU_*)
    type: str           #: "int" | "float" | "str" | "flag"
    default: object     #: static default, or None when unset/dynamic
    doc: str            #: one-line description (rendered into the README)


_KNOBS: List[Knob] = [
    # -- device frontier / lockstep engine ---------------------------------------
    Knob("MYTHRIL_TPU_LANES", "int", 128,
         "Device lane count: vmapped EVM lanes per frontier phase."),
    Knob("MYTHRIL_TPU_MAX_STEPS", "int", 4096,
         "Per-transaction device step budget before host hand-over."),
    Knob("MYTHRIL_TPU_CHUNK", "int", 64,
         "Fused lockstep steps per device dispatch (one jit call)."),
    Knob("MYTHRIL_TPU_DEVICE_FRAC", "float", 0.85,
         "Fraction of the remaining wall budget the device phase may "
         "consume; the rest is reserved for the host continuation."),
    Knob("MYTHRIL_TPU_SHARD", "str", None,
         "Lane-axis sharding: 1 forces on, 0 forces off; unset enables "
         "it only on real multi-device accelerator meshes."),
    Knob("MYTHRIL_TPU_SKIP_HOST_DRAIN", "flag", False,
         "Bench warm-up aid: drop materialized states instead of running "
         "the host continuation."),
    Knob("MYTHRIL_TPU_CHECK_ESCAPES", "flag", False,
         "Re-enable escape-time solver pruning (default off: feasibility "
         "is decided at issue time, matching the host engine)."),
    Knob("MYTHRIL_TPU_DRAIN_BATCH", "int", None,
         "Escape rows buffered on device before one bulk host drain "
         "(dynamic default: max(4 * n_lanes, 1024))."),
    Knob("MYTHRIL_TPU_STACK_BYTES", "int", 3 << 30,
         "HBM byte budget for the device DFS sibling stack pool."),
    Knob("MYTHRIL_TPU_ESC_BYTES", "int", 1 << 30,
         "HBM byte budget for the device escape-row buffer."),
    Knob("MYTHRIL_TPU_CHECKPOINT", "str", None,
         "Path for crash-safe device-phase checkpoints (.npz)."),
    Knob("MYTHRIL_TPU_RESUME", "str", None,
         "Checkpoint path to resume the device phase from; consumed once."),
    Knob("MYTHRIL_TPU_JAX_CACHE", "str", None,
         "Persistent XLA compilation cache directory (dynamic default: "
         "~/.cache/mythril_tpu_jax)."),
    Knob("MYTHRIL_TPU_STATE_MERGE", "flag", True,
         "On-device state merging (veritesting): collapse sibling lanes "
         "that reconverged after a fork into one lane with ITE-blended "
         "planes; --no-state-merge / 0 disables for A/B measurement."),
    Knob("MYTHRIL_TPU_MERGE_MIN_LANES", "int", 2,
         "Merge-tag occupancy (lane-visits per chunk at one merge point) "
         "that triggers a merge pass; with telemetry off the pass runs "
         "on a fixed chunk cadence instead."),
    # -- batched SAT dispatch ----------------------------------------------------
    Knob("MYTHRIL_TPU_BATCH_FLUSH", "int", 16,
         "Queued SAT queries that trigger a batched device flush."),
    Knob("MYTHRIL_TPU_BUCKET_SCHEME", "str", "coarse",
         "Clause-shape bucketing for the device SAT runners: 'coarse' "
         "(default) rounds tiles/vars/batch to powers of four with a "
         "variable-axis floor so the warm set stays small enough to "
         "pre-bake; 'fine' keeps the original per-pow2 buckets (A/B)."),
    Knob("MYTHRIL_TPU_BATCH_AGE_MS", "float", 50.0,
         "Max age (ms) a queued SAT query may wait before a flush."),
    Knob("MYTHRIL_TPU_VERDICT_CACHE", "int", 4096,
         "Entries in the canonical-CNF SAT/UNSAT verdict LRU cache."),
    Knob("MYTHRIL_TPU_DEVICE_CLAUSE_CAP", "int", 0,
         "Per-flush clause cap for device SAT solving; 0 uses the "
         "built-in per-device cap. CPU-backend gates shrink it so "
         "oversize queries fall back to native CDCL instead of grinding "
         "a host-emulated device solve."),
    # -- resilience / failure domains --------------------------------------------
    Knob("MYTHRIL_TPU_BREAKER_TRIP", "int", 3,
         "Consecutive backend failures that trip the circuit breaker."),
    Knob("MYTHRIL_TPU_BREAKER_RECOVERY", "int", 32,
         "Skipped calls before a tripped breaker half-opens for a retry."),
    Knob("MYTHRIL_TPU_INJECT_FAULT", "str", None,
         "Deterministic fault-injection plan CLASS[:NTH] (tests/debug)."),
    Knob("MYTHRIL_TPU_DEVICE_WALL_MS", "int", 120_000,
         "Wall budget (ms) for one device solve before it counts as a "
         "WALL_OVERRUN failure (0 disables)."),
    Knob("MYTHRIL_TPU_CROSSCHECK", "int", 0,
         "Re-decide every Nth device verdict on the host CDCL oracle "
         "(0 = off)."),
    # -- checkpoint / persistence -------------------------------------------------
    Knob("MYTHRIL_TPU_CHECKPOINT_STATES", "int", 2000,
         "Host-engine states executed between periodic checkpoint saves."),
    Knob("MYTHRIL_TPU_DIR", "str", None,
         "Data directory for the signature DB (dynamic default: "
         "~/.mythril_tpu)."),
    Knob("MYTHRIL_TPU_RPC", "str", None,
         "Default RPC endpoint preset for dynamic loading."),
    # -- fleet packing (parallel/frontier.py FleetDriver) -------------------------
    Knob("MYTHRIL_TPU_FLEET_LANES", "int", 0,
         "Device lane count for fleet (multi-contract) frontiers; 0 "
         "falls back to MYTHRIL_TPU_LANES."),
    Knob("MYTHRIL_TPU_FLEET_WINDOW_MS", "float", 50.0,
         "Micro-batching join window (ms): how long a serve fleet leader "
         "waits for more compatible `analyze` requests before running "
         "the shared fleet step."),
    Knob("MYTHRIL_TPU_FLEET_MAX_BATCH", "int", 8,
         "Max `analyze` requests packed into one serve fleet "
         "micro-batch."),
    Knob("MYTHRIL_TPU_FLEET_SERVE", "flag", False,
         "Enable the serve micro-batching admission path (concurrent "
         "compatible `analyze` requests join one fleet step instead of "
         "queueing on the engine lock); `serve --fleet` sets the same "
         "switch."),
    Knob("MYTHRIL_TPU_FLEET_SHARD", "int", 0,
         "Logical shard count for the fleet frontier (lane-axis blocks "
         "with per-block scheduler segments): 0 = auto (device count on "
         "real multi-device meshes, else 1), N forces N blocks (valid "
         "on a single device; must divide the lane count or falls back "
         "to 1 with a logged reason)."),
    Knob("MYTHRIL_TPU_STEAL_CADENCE", "int", 4,
         "Chunks between device-resident work-steal passes on a sharded "
         "frontier (0 disables stealing)."),
    Knob("MYTHRIL_TPU_STEAL_MIN_IMBALANCE", "int", 8,
         "Minimum per-shard load gap (running lanes + pending rows) "
         "before a rich/poor shard pair actually exchanges rows in a "
         "steal pass."),
    # -- analysis service (mythril_tpu/serve/) ------------------------------------
    Knob("MYTHRIL_TPU_SERVE_SOCKET", "str", None,
         "Unix-socket path for `myth-tpu serve` / `myth-tpu client` "
         "(dynamic default: ~/.mythril_tpu/serve.sock)."),
    Knob("MYTHRIL_TPU_SERVE_MANIFEST", "str", None,
         "Warm-set manifest path: clause-shape buckets observed in prior "
         "runs, pre-compiled at daemon startup (dynamic default: "
         "~/.mythril_tpu/warmset.json)."),
    Knob("MYTHRIL_TPU_SERVE_MAX_INFLIGHT", "int", 4,
         "Admitted-but-unfinished serve requests; beyond it the daemon "
         "answers `busy` instead of queueing unboundedly."),
    Knob("MYTHRIL_TPU_SERVE_WARMUP", "flag", True,
         "Run the AOT warmup phase (manifest-driven bucket pre-compile) "
         "at daemon startup; `serve --no-warmup` also disables it."),
    Knob("MYTHRIL_TPU_SERVE_MAX_DEADLINE_MS", "int", 86_400_000,
         "Ceiling applied to a request's `deadline_ms` before it becomes "
         "the analysis execution timeout; requests without a deadline "
         "get the full ceiling (default: one day)."),
    Knob("MYTHRIL_TPU_SERVE_WORKERS", "int", 0,
         "Worker-process pool size for `myth-tpu serve`: each analyze "
         "(or fleet micro-batch) executes in a supervised, manifest-"
         "warmed worker process so a crash kills only that request's "
         "sandbox; 0 (the default) keeps the legacy in-process engine; "
         "`serve --workers N` sets the same pool size."),
    Knob("MYTHRIL_TPU_SERVE_WORKER_HEARTBEAT_MS", "int", 30_000,
         "Supervisor heartbeat timeout (ms): a busy worker that writes "
         "neither a heartbeat nor a result for this long is killed and "
         "its death classified WORKER_HANG."),
    Knob("MYTHRIL_TPU_SERVE_WORKER_BACKOFF_MS", "int", 250,
         "Base delay (ms) before a dead worker slot respawns; doubles "
         "per consecutive death on the slot (capped at 30 s) and resets "
         "on a completed job."),
    Knob("MYTHRIL_TPU_SERVE_QUARANTINE_AFTER", "int", 2,
         "Worker deaths attributed to one bytecode hash before the "
         "contract lands in the poison-quarantine sidecar and further "
         "requests for it are refused with a `quarantined` error."),
    # -- overload resilience (serve/admission.py, serve/autoscale.py) -------------
    Knob("MYTHRIL_TPU_SERVE_QUEUE_MAX", "int", 16,
         "Bounded admission-queue capacity (waiting requests across both "
         "priority classes); past it the lowest-priority oldest waiter "
         "is shed with a typed `overloaded` error carrying "
         "retry_after_ms."),
    Knob("MYTHRIL_TPU_SERVE_RETRY_AFTER_MS", "int", 1000,
         "Base retry hint (ms) carried by `overloaded` shed replies; "
         "scaled up with observed p95 service time and queue depth."),
    Knob("MYTHRIL_TPU_SERVE_DRAIN_MS", "int", 5000,
         "Graceful-drain budget (ms) at shutdown/SIGTERM: in-flight and "
         "queued-interactive requests may finish within it; queued bulk "
         "is shed immediately and anything still running past it is "
         "preempted to its checkpoint."),
    Knob("MYTHRIL_TPU_SERVE_WORKERS_MIN", "int", 0,
         "Autoscale floor for the serve worker pool; 0 falls back to the "
         "configured MYTHRIL_TPU_SERVE_WORKERS size."),
    Knob("MYTHRIL_TPU_SERVE_WORKERS_MAX", "int", 0,
         "Autoscale ceiling for the serve worker pool; 0 (the default) "
         "disables autoscaling and keeps the pool fixed."),
    Knob("MYTHRIL_TPU_SERVE_AUTOSCALE_INTERVAL_MS", "int", 500,
         "Autoscaler sampling cadence (ms): each tick reads admission "
         "queue depth and pool occupancy."),
    Knob("MYTHRIL_TPU_SERVE_AUTOSCALE_UP_AFTER", "int", 2,
         "Consecutive backlogged autoscaler ticks (queued work with the "
         "whole pool busy) before one scale-up step."),
    Knob("MYTHRIL_TPU_SERVE_AUTOSCALE_DOWN_AFTER", "int", 8,
         "Consecutive idle autoscaler ticks (no queue, no busy worker) "
         "before one scale-down step — the hysteresis that keeps a "
         "bursty load from thrashing the pool."),
    Knob("MYTHRIL_TPU_RESULT_STORE", "flag", True,
         "Content-addressed result store: answer repeat (bytecode, "
         "config) analyze requests from a persisted sidecar at "
         "admission, without dispatching a worker; 0 disables."),
    Knob("MYTHRIL_TPU_RESULT_STORE_MAX", "int", 4096,
         "Max entries kept in the persisted result-store sidecar; "
         "beyond it the oldest entries are evicted at save time."),
    # -- durable warmth (parallel/exec_cache.py, serve/warmset.py) ----------------
    Knob("MYTHRIL_TPU_EXEC_CACHE", "flag", True,
         "Persistent executable cache: serialize compiled solver runners "
         "(JAX AOT) beside the warmset manifest so worker respawn "
         "deserializes instead of recompiling; 0 disables for A/B."),
    Knob("MYTHRIL_TPU_EXEC_CACHE_DIR", "str", None,
         "Directory for serialized solver executables (dynamic default: "
         "an `exec_cache/` directory beside the warmset manifest)."),
    Knob("MYTHRIL_TPU_VERDICT_SIDECAR", "flag", True,
         "Persist the canonical-CNF SAT/UNSAT verdict cache to a "
         "union-merge sidecar beside the warmset manifest, loaded at "
         "worker spawn and merged at request end; 0 disables."),
    Knob("MYTHRIL_TPU_VERDICT_SIDECAR_MAX", "int", 65536,
         "Max entries kept in the persisted verdict sidecar; beyond it "
         "the oldest entries are evicted at save time."),
    # -- observability (mythril_tpu/observe/) -------------------------------------
    Knob("MYTHRIL_TPU_TRACE", "str", None,
         "Write a Chrome/Perfetto trace_event JSON to this path; setting "
         "it enables the span tracer (observe/trace.py)."),
    Knob("MYTHRIL_TPU_TRACE_BUFFER", "int", 65536,
         "Span-tracer ring-buffer capacity in events; beyond it the "
         "oldest events drop (counted in the export)."),
    Knob("MYTHRIL_TPU_FRONTIER_TELEMETRY", "flag", True,
         "Arm the device-resident frontier counter plane (opcode-class "
         "histogram, lane lifecycle, escape causes, tag occupancy) — "
         "decoded per chunk into metrics and Perfetto counter tracks; "
         "the --no-frontier-telemetry CLI flag also compiles it out for "
         "A/B runs."),
    Knob("MYTHRIL_TPU_METRICS", "str", None,
         "Write an fsync-atomic JSON metrics snapshot to this path when "
         "the analysis finishes; `analyze --metrics-out` sets the same "
         "path."),
    Knob("MYTHRIL_TPU_SLOG", "str", None,
         "Structured JSON log sink (observe/slog.py): a file path, or "
         "'stderr'; setting it enables correlated one-object-per-line "
         "log records carrying each request's correlation id."),
    Knob("MYTHRIL_TPU_METRICS_RING", "int", 256,
         "Snapshot entries kept by the in-process metrics time-series "
         "ring (observe/export.py); the `metrics` protocol op and GET "
         "/metrics serve its tail."),
    Knob("MYTHRIL_TPU_BENCH_TOLERANCE", "float", 0.2,
         "Relative regression tolerance for the tools/benchview.py perf "
         "sentinel: a tracked headline number that worsens by more than "
         "this fraction between consecutive comparable runs fails the "
         "gate."),
    # -- static control-flow analysis (mythril_tpu/staticanalysis/) ---------------
    Knob("MYTHRIL_TPU_CFA", "flag", True,
         "Build static CFA tables (CFG, post-dominator merge points, "
         "refined JUMPDEST bitmap) per contract and let consumers answer "
         "jump-validity queries from them; the --no-cfa CLI flag also "
         "turns the consumers off for A/B runs."),
    Knob("MYTHRIL_TPU_CFA_MAX_BLOCKS", "int", 16384,
         "Basic-block budget above which the cfa pass bails out and "
         "consumers keep their dynamic paths."),
    Knob("MYTHRIL_TPU_CFA_STACK_DEPTH", "int", 32,
         "Abstract-stack slots tracked per block entry by the cfa "
         "constant dataflow; deeper slots are treated as unknown."),
    # -- source->sink taint analysis (mythril_tpu/staticanalysis/taint.py) --------
    Knob("MYTHRIL_TPU_TAINT", "flag", True,
         "Build per-contract taint summaries (function partition, loop "
         "headers, source->sink taint verdicts) over the CFA tables and "
         "let the module screen skip unreachable modules and untainted "
         "hook sites; the --no-taint CLI flag also turns the consumers "
         "off for A/B runs."),
    Knob("MYTHRIL_TPU_TAINT_MAX_ITERS", "int", 4,
         "Cross-transaction storage rounds of the taint fixpoint; at the "
         "cap remaining storage cells saturate to fully-tainted so the "
         "summary stays sound."),
    Knob("MYTHRIL_TPU_TAINT_SLOTS", "int", 64,
         "Concrete storage slots tracked per contract by the taint "
         "dataflow; writes past the budget (or to unknown slots) collapse "
         "into one conservative summary cell."),
    # -- value-range / memory-region absint (staticanalysis/absint.py) ------------
    Knob("MYTHRIL_TPU_ABSINT", "flag", True,
         "Build per-contract value-range + memory write-region tables "
         "(stride-interval fixpoint over the CFA with loop-header "
         "widening) and let consumers blend diverged memory planes at "
         "proven join regions, apply proven loop bounds, and prune "
         "constant JUMPI sides; the --no-absint CLI flag also turns the "
         "consumers off for A/B runs."),
    Knob("MYTHRIL_TPU_ABSINT_MAX_ITERS", "int", 64,
         "Header-arrival cap for the absint loop trip-count prover; "
         "loops that do not provably exit within this many abstract "
         "iterations keep the flat unroll default."),
    Knob("MYTHRIL_TPU_ABSINT_MEM_REGIONS", "int", 8,
         "32-byte memory windows tracked per join point by the widened "
         "merge phase; joins whose proven write regions need more "
         "windows stay on the identical-memory gate."),
    # -- gas superoptimization (mythril_tpu/superopt/) ----------------------------
    Knob("MYTHRIL_TPU_SUPEROPT_MAX_BLOCK_LEN", "int", 8,
         "Longest pure-stack block body (instructions) eligible for the "
         "exhaustive stack-scheduling search; longer blocks only get the "
         "peephole catalog."),
    Knob("MYTHRIL_TPU_SUPEROPT_CANDIDATES", "int", 256,
         "Total candidate sequences the exhaustive search may try per "
         "block before giving up (catalog rewrites are not counted)."),
    Knob("MYTHRIL_TPU_SUPEROPT_CROSSCHECK", "int", 8,
         "Re-decide every Nth accepted superopt equivalence proof on the "
         "host CDCL oracle and count divergences (0 = off)."),
    # -- test corpora -------------------------------------------------------------
    Knob("MYTHRIL_TPU_VMTESTS", "str", None,
         "Root of the ethereum/tests VMTests corpus for parity suites."),
]

REGISTRY: Dict[str, Knob] = {knob.name: knob for knob in _KNOBS}

_UNSET = object()


def declared(name: str) -> bool:
    """True when `name` is a registered knob."""
    return name in REGISTRY


def _knob(name: str, expected_type: str) -> Knob:
    knob = REGISTRY[name]  # KeyError on undeclared names is the contract
    if knob.type != expected_type:
        raise TypeError(
            f"{name} is declared as {knob.type!r}, not {expected_type!r}")
    return knob


def get_raw(name: str) -> Optional[str]:
    """The raw env string for a declared knob (None when unset)."""
    REGISTRY[name]  # KeyError on undeclared names is the contract
    return os.environ.get(name)


def get_int(name: str, default: object = _UNSET) -> Optional[int]:
    """Call-time int read; `default` overrides the registry default
    (used for dynamic defaults like MYTHRIL_TPU_DRAIN_BATCH)."""
    knob = _knob(name, "int")
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return knob.default if default is _UNSET else default
    return int(raw)


def get_float(name: str, default: object = _UNSET) -> Optional[float]:
    knob = _knob(name, "float")
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return knob.default if default is _UNSET else default
    return float(raw)


def get_str(name: str, default: object = _UNSET) -> Optional[str]:
    knob = _knob(name, "str")
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return knob.default if default is _UNSET else default
    return raw


def get_flag(name: str, default: object = _UNSET) -> bool:
    """Boolean knob: unset -> default; "0"/""/"false"/"no"/"off" -> False;
    anything else -> True."""
    knob = _knob(name, "flag")
    raw = os.environ.get(name)
    if raw is None:
        return bool(knob.default if default is _UNSET else default)
    return raw.lower() not in ("", "0", "false", "no", "off")


def consume(name: str) -> Optional[str]:
    """Read a declared knob and remove it from the environment (pop-once
    semantics, e.g. MYTHRIL_TPU_RESUME)."""
    REGISTRY[name]  # KeyError on undeclared names is the contract
    return os.environ.pop(name, None)


def _fmt_default(knob: Knob) -> str:
    if knob.default is None:
        return "*(unset)*"
    if knob.type == "flag":
        return "`1`" if knob.default else "`0`"
    return f"`{knob.default}`"


def render_markdown_table() -> str:
    """The README env-knob table; lint R5 fails when the README section
    between the knob-table markers drifts from this rendering."""
    lines = [
        "| Knob | Type | Default | Meaning |",
        "| --- | --- | --- | --- |",
    ]
    for knob in _KNOBS:
        lines.append(
            f"| `{knob.name}` | {knob.type} | {_fmt_default(knob)} "
            f"| {knob.doc} |")
    return "\n".join(lines)


if __name__ == "__main__":
    print(render_markdown_table())
