"""Per-function feature extraction from the solc AST (capability parity:
mythril/solidity/features.py:4 SolidityFeatureExtractor).

Features feed the RF transaction prioritizer (core/tx_prioritiser.py): which
functions look dangerous (selfdestruct/delegatecall/call), which are payable,
which are owner-gated, and which variables their requires/modifiers guard."""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Set, Tuple

TRANSFER_METHODS = ("transfer", "send")


class SolidityFeatureExtractor:
    def __init__(self, ast: dict):
        self.ast = ast or {}

    def extract_features(self) -> Dict[str, Dict]:
        function_features: Dict[str, Dict] = {}
        modifier_vars: Dict[str, Set[str]] = {}
        for modifier_node in self._walk_nodes(self.ast, "ModifierDefinition"):
            guarded = self.find_variables_in_require(modifier_node)
            guarded |= set(self.find_variables_in_if(modifier_node))
            modifier_vars[modifier_node.get("name", "")] = guarded

        for node in self._walk_nodes(self.ast, "FunctionDefinition"):
            require_vars = self.find_variables_in_require(node)
            for modifier in node.get("modifiers", []):
                name = modifier.get("modifierName", {}).get("name")
                if name in modifier_vars:
                    require_vars |= modifier_vars[name]
            function_features[node.get("name", "")] = {
                "contains_selfdestruct": self._contains(node, "selfdestruct"),
                "contains_call": self._contains(node, "call"),
                "is_payable": node.get("stateMutability") == "payable",
                "has_owner_modifier": self.has_owner_modifier(node),
                "contains_assert": self._contains(node, "assert"),
                "contains_callcode": self._contains(node, "callcode"),
                "contains_delegatecall": self._contains(node, "delegatecall"),
                "contains_staticcall": self._contains(node, "staticcall"),
                "all_require_vars": require_vars,
                "transfer_vars": self.extract_address_variable(node),
            }
        return function_features

    # -- AST helpers -----------------------------------------------------------------

    @staticmethod
    def _walk_nodes(node, node_type: str) -> Iterator[dict]:
        stack = [node]
        while stack:
            current = stack.pop()
            if isinstance(current, dict):
                if current.get("nodeType") == node_type:
                    yield current
                stack.extend(v for v in current.values()
                             if isinstance(v, (dict, list)))
            elif isinstance(current, list):
                stack.extend(current)

    @staticmethod
    def _contains(node, command: str) -> bool:
        stack = [node]
        while stack:
            current = stack.pop()
            if isinstance(current, dict):
                if command in current.values():
                    return True
                stack.extend(v for v in current.values()
                             if isinstance(v, (dict, list)))
            elif isinstance(current, list):
                stack.extend(current)
        return False

    @staticmethod
    def has_owner_modifier(node) -> bool:
        for modifier in node.get("modifiers", []):
            name = modifier.get("modifierName", {}).get("name", "")
            if name.lower() in ("isowner", "onlyowner"):
                return True
        return False

    @classmethod
    def _nodes_with_value(cls, node, command: str, parent=None
                          ) -> List[Tuple[Optional[dict], dict]]:
        found = []
        if isinstance(node, dict):
            if command in node.values():
                found.append((parent, node))
            for value in node.values():
                if isinstance(value, (dict, list)):
                    found.extend(cls._nodes_with_value(value, command,
                                                       parent=node))
        elif isinstance(node, list):
            for item in node:
                found.extend(cls._nodes_with_value(item, command, parent=node))
        return found

    @classmethod
    def _identifiers(cls, node) -> Set[str]:
        names: Set[str] = set()
        stack = [node]
        while stack:
            current = stack.pop()
            if isinstance(current, dict):
                if current.get("nodeType") == "Identifier" and "name" in current:
                    names.add(current["name"])
                stack.extend(v for v in current.values()
                             if isinstance(v, (dict, list)))
            elif isinstance(current, list):
                stack.extend(current)
        return names

    def find_variables_in_require(self, node) -> Set[str]:
        variables: Set[str] = set()
        for parent, _ in self._nodes_with_value(node, "require"):
            if parent and "arguments" in parent:
                for argument in parent["arguments"]:
                    variables |= self._identifiers(argument)
        return variables

    def find_variables_in_if(self, node) -> List[str]:
        variables: List[str] = []
        stack = [node]
        while stack:
            current = stack.pop()
            if isinstance(current, dict):
                condition = current.get("condition")
                if isinstance(condition, dict):
                    for side in ("leftExpression", "rightExpression"):
                        expr = condition.get(side)
                        if isinstance(expr, dict) and \
                                expr.get("nodeType") == "Identifier":
                            variables.append(expr.get("name"))
                stack.extend(v for v in current.values()
                             if isinstance(v, (dict, list)))
            elif isinstance(current, list):
                stack.extend(current)
        return variables

    def extract_address_variable(self, node) -> Set[str]:
        """Variables receiving ether via .transfer(...) / .send(...)."""
        variables: Set[str] = set()
        for method in TRANSFER_METHODS:
            for _parent, member in self._nodes_with_value(node, method):
                if member.get("nodeType") != "MemberAccess":
                    continue
                expression = member.get("expression", {})
                variables |= self._identifiers(expression)
        return variables
