from .disassembler import Disassembly, EvmInstruction, disassemble
from .asm import assemble, Assembler

__all__ = ["Disassembly", "EvmInstruction", "disassemble", "assemble", "Assembler"]
