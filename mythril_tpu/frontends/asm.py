"""A small EVM assembler.

The build environment has no solc, so test and benchmark contracts are authored in EVM
assembly. This has no reference counterpart (the reference ships pre-compiled .sol.o
fixtures); it exists so the repo's fixtures are self-contained.

Syntax (one instruction per line, ';' comments):
    start:                 ; label definition
    PUSH1 0x60             ; explicit push
    PUSH 1234              ; auto-sized push (decimal or 0x hex)
    PUSH @start            ; label reference (assembled as PUSH2, patched)
    JUMPI

High-level helpers build solidity-ABI-style contracts: `dispatcher()` produces the
standard 4-byte selector jump table so the engine's selector recovery and per-function
symbolic transactions work exactly as they do on solc output.
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

from ..ops.opcodes import OPCODES, ADDRESS
from ..utils.keccak import keccak256


class AsmError(Exception):
    pass


def _encode_push(value: int, width: int | None = None) -> bytes:
    if value < 0:
        raise AsmError(f"push value must be non-negative: {value}")
    if value == 0 and width is None:
        width = 1  # PUSH1 0x00 (portable to pre-Shanghai; PUSH0 only when explicit)
    if width is None:
        width = max(1, (value.bit_length() + 7) // 8)
    if width > 32:
        raise AsmError(f"push value too wide: {value}")
    if value >= 1 << (8 * width):
        raise AsmError(f"value {value:#x} does not fit PUSH{width}")
    return bytes([0x5F + width]) + value.to_bytes(width, "big")


class Assembler:
    """Two-pass assembler with label patching (labels always use PUSH2)."""

    def __init__(self):
        self._chunks: List[bytes | Tuple[str, str]] = []  # bytes or ("label_ref", name)

    # -- programmatic API ----------------------------------------------------------
    def op(self, name: str) -> "Assembler":
        name = name.upper()
        if name not in OPCODES:
            raise AsmError(f"unknown opcode {name}")
        self._chunks.append(bytes([OPCODES[name][ADDRESS]]))
        return self

    def push(self, value: int, width: int | None = None) -> "Assembler":
        self._chunks.append(_encode_push(value, width))
        return self

    def push_label(self, label: str) -> "Assembler":
        self._chunks.append(("label_ref", label))
        return self

    def label(self, name: str) -> "Assembler":
        self._chunks.append(("label_def", name))
        return self

    def raw(self, data: bytes) -> "Assembler":
        self._chunks.append(bytes(data))
        return self

    # -- assembly ------------------------------------------------------------------
    def assemble(self) -> bytes:
        # pass 1: compute label addresses (label refs are fixed-width PUSH2)
        pc = 0
        labels: Dict[str, int] = {}
        for chunk in self._chunks:
            if isinstance(chunk, tuple):
                kind, name = chunk
                if kind == "label_def":
                    labels[name] = pc
                else:
                    pc += 3  # PUSH2 xx xx
            else:
                pc += len(chunk)
        # pass 2: emit
        out = bytearray()
        for chunk in self._chunks:
            if isinstance(chunk, tuple):
                kind, name = chunk
                if kind == "label_def":
                    continue
                if name not in labels:
                    raise AsmError(f"undefined label {name}")
                out += bytes([0x61]) + labels[name].to_bytes(2, "big")
            else:
                out += chunk
        return bytes(out)


_TOKEN_RE = re.compile(r"^(?P<label>\w+):$")


def assemble(source: str) -> bytes:
    """Assemble textual EVM assembly (see module docstring for syntax)."""
    asm = Assembler()
    for raw_line in source.splitlines():
        line = raw_line.split(";")[0].strip()
        if not line:
            continue
        label_match = _TOKEN_RE.match(line)
        if label_match:
            asm.label(label_match.group("label"))
            continue
        parts = line.split()
        mnemonic = parts[0].upper()
        if mnemonic.startswith("PUSH") and mnemonic != "PUSH0":
            if len(parts) < 2:
                raise AsmError(f"{mnemonic} needs an operand: {raw_line.strip()!r}")
            operand = parts[1]
            if operand.startswith("@"):
                asm.push_label(operand[1:])
            else:
                value = int(operand, 16) if operand.lower().startswith("0x") else int(operand)
                width = None if mnemonic == "PUSH" else int(mnemonic[4:])
                asm.push(value, width)
        elif mnemonic == "RAWHEX":
            asm.raw(bytes.fromhex(parts[1].removeprefix("0x")))
        else:
            asm.op(mnemonic)
    return asm.assemble()


def selector(signature: str) -> int:
    """4-byte function selector of a canonical signature like 'withdraw(uint256)'."""
    return int.from_bytes(keccak256(signature.encode())[:4], "big")


def dispatcher(functions: Dict[str, str], fallback: str = "STOP") -> str:
    """Build a full contract source with a solc-style selector dispatcher.

    `functions` maps canonical signatures to assembly bodies (each body should end in
    STOP/RETURN/REVERT). Produces the classic prelude:
    calldataload(0) >> 224, then PUSH4/EQ/JUMPI chains.
    """
    lines = [
        "PUSH1 0x00",
        "CALLDATALOAD",
        "PUSH1 0xe0",
        "SHR",
    ]
    names = list(functions)
    for sig in names:
        lines += [
            "DUP1",
            f"PUSH4 0x{selector(sig):08x}",
            "EQ",
            f"PUSH @fn_{selector(sig):08x}",
            "JUMPI",
        ]
    lines += [fallback]
    for sig in names:
        lines += [f"fn_{selector(sig):08x}:", "JUMPDEST", "POP"]
        lines += [functions[sig].strip()]
    return "\n".join(lines)


def creation_wrapper(runtime: bytes, constructor: str = "") -> bytes:
    """Wrap runtime code in standard init code (CODECOPY + RETURN), with an optional
    constructor body that runs first."""
    prefix = assemble(constructor) if constructor else b""
    # layout: [constructor][PUSH2 len][PUSH2 offset][PUSH1 0][CODECOPY][PUSH2 len][PUSH1 0][RETURN][runtime]
    # offset = len(prefix) + len(fixed tail)
    tail_len = 3 + 3 + 2 + 1 + 3 + 2 + 1  # computed below, fixed widths
    offset = len(prefix) + tail_len
    tail = bytearray()
    tail += bytes([0x61]) + len(runtime).to_bytes(2, "big")   # PUSH2 len
    tail += bytes([0x61]) + offset.to_bytes(2, "big")          # PUSH2 offset
    tail += bytes([0x60, 0x00])                                 # PUSH1 0
    tail += bytes([0x39])                                       # CODECOPY
    tail += bytes([0x61]) + len(runtime).to_bytes(2, "big")    # PUSH2 len
    tail += bytes([0x60, 0x00])                                 # PUSH1 0
    tail += bytes([0xF3])                                       # RETURN
    assert len(tail) == tail_len
    return bytes(prefix) + bytes(tail) + runtime
