"""EVM bytecode disassembler.

Capability parity: mythril/disassembler/asm.py (EvmInstruction, disassemble,
find_op_code_sequence) and mythril/disassembler/disassembly.py (Disassembly with
function-selector table recovery from the PUSHn;EQ dispatch pattern,
disassembly.py:42-54). Implementation is fresh: a single linear scan that also
precomputes the JUMPDEST set and the dense arrays the TPU lockstep interpreter consumes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Generator, List, Optional

from ..ops.opcodes import OPCODES, ADDRESS, opcode_name, push_width


@dataclass
class EvmInstruction:
    """One decoded instruction: absolute byte address, mnemonic, optional immediate."""

    address: int
    op_code: str
    argument: Optional[str] = None  # '0x..' hex immediate for PUSHn

    def to_dict(self) -> dict:
        result = {"address": self.address, "opcode": self.op_code}
        if self.argument is not None:
            result["argument"] = self.argument
        return result


import re as _re

# solc unlinked-library placeholders, both styles, are exactly 40 chars and must be
# zero-FILLED (not stripped) so byte offsets stay aligned:
#   0.5+:  __$<34 hex>$__      pre-0.5: __<36 chars of name/padding>__
_PLACEHOLDER_RE = _re.compile(r"__\$.{34}\$__|__.{36}__")


def _normalize(code: str | bytes) -> bytes:
    if isinstance(code, (bytes, bytearray)):
        return bytes(code)
    code = code.strip()
    if code.startswith("0x"):
        code = code[2:]
    if "_" in code:
        code = _PLACEHOLDER_RE.sub("0" * 40, code)
        code = code.replace("_", "0")  # stray underscores, length-preserving
    if len(code) % 2:
        code = code[:-1]  # tolerate trailing half-byte as the reference tooling does
    try:
        return bytes.fromhex(code)
    except ValueError:
        cleaned = "".join(ch for ch in code if ch in "0123456789abcdefABCDEF")
        return bytes.fromhex(cleaned if len(cleaned) % 2 == 0 else cleaned[:-1])


def disassemble(bytecode: str | bytes) -> List[EvmInstruction]:
    """Linear-sweep disassembly; PUSH immediates that overrun the code are truncated."""
    code = _normalize(bytecode)
    instructions: List[EvmInstruction] = []
    pc = 0
    length = len(code)
    while pc < length:
        byte = code[pc]
        name = opcode_name(byte)
        width = push_width(name) if name.startswith("PUSH") else 0
        if width:
            immediate = code[pc + 1:pc + 1 + width]
            instructions.append(EvmInstruction(pc, name, "0x" + immediate.hex()))
            pc += 1 + width
        else:
            instructions.append(EvmInstruction(pc, name))
            pc += 1
    return instructions


def find_op_code_sequence(pattern: List[List[str]],
                          instruction_list: List[EvmInstruction]) -> Generator[int, None, None]:
    """Yield indices where `pattern` matches; each pattern element is a list of
    acceptable mnemonics for that position (reference: disassembler/asm.py:66)."""
    for start in range(len(instruction_list) - len(pattern) + 1):
        if all(instruction_list[start + offset].op_code in alternatives
               for offset, alternatives in enumerate(pattern)):
            yield start


@dataclass
class Disassembly:
    """Decoded contract bytecode plus recovered metadata.

    Attributes mirror the reference surface (disassembler/disassembly.py:9): raw
    bytecode, instruction list, `func_hashes` / `function_name_to_address` /
    `address_to_function_name` recovered from the dispatcher pattern
    ``PUSH4 <selector>; EQ; PUSH2 <target>; JUMPI`` (and its DUP1/SWAP variants).
    """

    bytecode: str
    enable_online_lookup: Optional[bool] = None
    instruction_list: List[EvmInstruction] = field(default_factory=list)
    func_hashes: List[str] = field(default_factory=list)
    function_name_to_address: Dict[str, int] = field(default_factory=dict)
    address_to_function_name: Dict[int, str] = field(default_factory=dict)
    function_name_to_hash: Dict[str, str] = field(default_factory=dict)

    def __post_init__(self):
        code = _normalize(self.bytecode)
        self.raw_code: bytes = code
        self.bytecode = code.hex()
        self.instruction_list = disassemble(code)
        self._address_to_index = {ins.address: idx
                                  for idx, ins in enumerate(self.instruction_list)}
        self.valid_jump_destinations = {ins.address for ins in self.instruction_list
                                        if ins.op_code == "JUMPDEST"}
        self._recover_selector_table()

    # -- function selector recovery ------------------------------------------------
    # (pattern, selector_offset, inverted): selector pushes are PUSH1..PUSH4 (the solc
    # optimizer shortens selectors with leading zero bytes). When the comparison is
    # negated with ISZERO, JUMPI jumps on selector MISmatch, so the function entry is
    # the fall-through after JUMPI.
    _SELECTOR_PUSH = ["PUSH1", "PUSH2", "PUSH3", "PUSH4"]
    _TARGET_PUSH = ["PUSH1", "PUSH2", "PUSH3", "PUSH4"]
    _DISPATCH_PATTERNS = [
        ([_SELECTOR_PUSH, ["EQ"], _TARGET_PUSH, ["JUMPI"]], 0, False),
        ([["DUP1"], _SELECTOR_PUSH, ["EQ"], _TARGET_PUSH, ["JUMPI"]], 1, False),
        ([_SELECTOR_PUSH, ["EQ"], ["ISZERO"], _TARGET_PUSH, ["JUMPI"]], 0, True),
    ]

    def _recover_selector_table(self) -> None:
        from ..support.signatures import SignatureDB

        sig_db = SignatureDB(enable_online_lookup=self.enable_online_lookup)
        for pattern, selector_offset, inverted in self._DISPATCH_PATTERNS:
            for index in find_op_code_sequence(pattern, self.instruction_list):
                selector_push = self.instruction_list[index + selector_offset]
                selector = selector_push.argument
                if selector is None:
                    continue
                selector = "0x" + selector[2:].rjust(8, "0")
                if inverted:
                    after = index + len(pattern)
                    if after >= len(self.instruction_list):
                        continue
                    target = self.instruction_list[after].address
                else:
                    target_push = self.instruction_list[index + len(pattern) - 2]
                    try:
                        target = int(target_push.argument, 16)
                    except (TypeError, ValueError):
                        continue
                if selector in self.func_hashes:
                    continue
                self.func_hashes.append(selector)
                names = sig_db.get(selector)
                name = names[0] if names else f"_function_{selector}"
                self.function_name_to_address[name] = target
                self.address_to_function_name[target] = name
                self.function_name_to_hash[name] = selector

    # -- queries -------------------------------------------------------------------
    def get_instruction(self, address: int) -> Optional[EvmInstruction]:
        idx = self._address_to_index.get(address)
        return self.instruction_list[idx] if idx is not None else None

    def index_of_address(self, address: int) -> Optional[int]:
        return self._address_to_index.get(address)

    def get_function_info(self, index: int):
        """(function_name, selector) for a PUSH4 dispatcher entry at instruction index."""
        instruction = self.instruction_list[index]
        selector = "0x" + (instruction.argument or "0x")[2:].rjust(8, "0")
        if selector not in self.func_hashes:
            return None, selector
        for name, addr in self.function_name_to_address.items():
            entry = self.instruction_list[index + 2] if index + 2 < len(self.instruction_list) else None
            if entry is not None and entry.argument and int(entry.argument, 16) == addr:
                return name, selector
        return f"_function_{selector}", selector

    def get_easm(self) -> str:
        lines = []
        for ins in self.instruction_list:
            arg = f" {ins.argument}" if ins.argument else ""
            lines.append(f"{ins.address} {ins.op_code}{arg}")
        return "\n".join(lines) + "\n"

    def __str__(self) -> str:
        return self.get_easm()
