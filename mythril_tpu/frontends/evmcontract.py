"""EVMContract: bytecode container (capability parity:
mythril/ethereum/evmcontract.py:14 — creation + runtime code, disassembly
properties, `matches_expression` code search)."""

from __future__ import annotations

import re

from ..utils.helpers import sha3
from .disassembler import Disassembly


def _sha3_hex(data) -> str:
    if isinstance(data, str):
        data = bytes.fromhex(data[2:] if data.startswith("0x") else data or "")
    return sha3(data).hex()


class EVMContract:
    def __init__(self, code: str = "", creation_code: str = "",
                 name: str = "Unknown", enable_online_lookup: bool = False):
        self.creation_code = creation_code or ""
        self.name = name
        self.code = code or ""
        self.enable_online_lookup = enable_online_lookup

    @property
    def bytecode_hash(self) -> str:
        return "0x" + _sha3_hex(self.code)

    @property
    def creation_bytecode_hash(self) -> str:
        return "0x" + _sha3_hex(self.creation_code)

    @property
    def disassembly(self) -> Disassembly:
        # cached: per-contract static analyses (cfa, taint summary) memoize
        # on the Disassembly instance, and the serve daemon pre-seeds
        # persisted summaries onto it before the engine runs
        if getattr(self, "_disassembly", None) is None:
            self._disassembly = Disassembly(self.code)
        return self._disassembly

    @property
    def creation_disassembly(self) -> Disassembly:
        return Disassembly(self.creation_code)

    def as_dict(self) -> dict:
        return {"name": self.name, "code": self.code,
                "creation_code": self.creation_code}

    def get_easm(self) -> str:
        return self.disassembly.get_easm()

    def get_creation_easm(self) -> str:
        return self.creation_disassembly.get_easm()

    def matches_expression(self, expression: str) -> bool:
        """Code-search mini-language (reference evmcontract.py:51):
        `code#PUSH1#` opcode-sequence match and `func#transfer(address)#`
        function-selector match, combinable with `and` / `or`."""
        easm_code = None
        tokens = re.split(r"\s+(and|or)\s+", expression, flags=re.IGNORECASE)
        results = []
        for token in tokens:
            if token.lower() in ("and", "or"):
                results.append(token.lower())
                continue
            code_match = re.match(r"^code#([a-zA-Z0-9\s,\[\]]+)#$", token)
            if code_match:
                if easm_code is None:
                    easm_code = self.get_easm()
                pattern = code_match.group(1).replace(",", "\\n")
                results.append(bool(re.search(pattern, easm_code)))
                continue
            func_match = re.match(r"^func#(.+)#$", token)
            if func_match:
                selector = "0x" + sha3(func_match.group(1)).hex()[:8]
                results.append(selector in self.disassembly.func_hashes)
                continue
            raise ValueError(f"invalid expression term: {token}")
        # left-to-right evaluation
        value = results[0]
        for i in range(1, len(results), 2):
            if results[i] == "and":
                value = value and results[i + 1]
            else:
                value = value or results[i + 1]
        return bool(value)
