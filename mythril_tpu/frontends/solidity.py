"""Solidity frontend: solc standard-json driver + source-map decoding
(capability parity: mythril/solidity/soliditycontract.py:169 — compile,
creation+runtime srcmap decode, get_source_info; mythril/ethereum/util.py:43 —
the solc standard-json invocation).

Degrades gracefully: when no solc binary is on PATH (this build environment
ships none) `get_contracts_from_file` raises `SolcNotFound` with a clear
message, and `SolidityContract.from_standard_json` lets callers (and tests)
feed pre-compiled standard-json output directly."""

from __future__ import annotations

import json
import os
import shutil
import subprocess
from typing import Dict, Iterator, List, Optional

from .evmcontract import EVMContract

SOLC_SETTINGS = {
    "optimizer": {"enabled": False},
    "outputSelection": {
        "*": {"*": ["metadata", "evm.bytecode", "evm.deployedBytecode",
                    "evm.methodIdentifiers"],
              "": ["ast"]}},
}


class SolcError(Exception):
    pass


class SolcNotFound(SolcError):
    pass


def get_solc_json(file_path: str, solc_binary: str = "solc",
                  solc_settings_json: Optional[str] = None) -> Dict:
    """Compile with solc standard-json (reference ethereum/util.py:43)."""
    if shutil.which(solc_binary) is None:
        raise SolcNotFound(
            f"solc binary '{solc_binary}' not found on PATH; install solc or "
            "pass pre-compiled bytecode with -c / --bin")
    settings = dict(SOLC_SETTINGS)
    if solc_settings_json:
        with open(solc_settings_json) as handle:
            settings.update(json.load(handle))
    standard_input = {
        "language": "Solidity",
        "sources": {file_path: {"urls": [file_path]}},
        "settings": settings,
    }
    proc = subprocess.run(
        [solc_binary, "--standard-json", "--allow-paths", ".,/"],
        input=json.dumps(standard_input).encode(),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    if proc.returncode:
        raise SolcError(f"solc exited {proc.returncode}: "
                        f"{proc.stderr.decode()[:500]}")
    output = json.loads(proc.stdout)
    errors = [e for e in output.get("errors", [])
              if e.get("severity") == "error"]
    if errors:
        raise SolcError("\n".join(e.get("formattedMessage", str(e))
                                  for e in errors))
    return output


class SourceMapping:
    """One decoded srcmap entry: byte range + source file + line + snippet."""

    __slots__ = ("offset", "length", "file_index", "filename", "lineno", "code")

    def __init__(self, offset: int, length: int, file_index: int,
                 filename: str = "", lineno: Optional[int] = None,
                 code: str = ""):
        self.offset = offset
        self.length = length
        self.file_index = file_index
        self.filename = filename
        self.lineno = lineno
        self.code = code


def decode_srcmap(srcmap: str) -> List[List[int]]:
    """'s:l:f:j;;...' with empty-field inheritance -> [[s, l, f], ...]."""
    entries: List[List[int]] = []
    prev = [0, 0, 0]
    for chunk in srcmap.split(";"):
        fields = chunk.split(":")
        entry = list(prev)
        for i in range(min(3, len(fields))):
            if fields[i] != "":
                entry[i] = int(fields[i])
        entries.append(entry)
        prev = entry
    return entries


class SolidityContract(EVMContract):
    """A compiled contract with source mapping."""

    def __init__(self, input_file: str, name: str, code: str,
                 creation_code: str, srcmap_runtime: str, srcmap_creation: str,
                 sources: Dict[int, str], source_texts: Dict[int, str]):
        super().__init__(code=code, creation_code=creation_code, name=name)
        self.input_file = input_file
        self.sources = sources              # file index -> path
        self.source_texts = source_texts    # file index -> contents
        self.srcmap = decode_srcmap(srcmap_runtime) if srcmap_runtime else []
        self.creation_srcmap = \
            decode_srcmap(srcmap_creation) if srcmap_creation else []
        #: per-function AST features for the RF tx prioritizer
        #: (reference soliditycontract.py:195)
        self.features = None

    @classmethod
    def from_standard_json(cls, output: Dict, input_file: str,
                           contract_name: Optional[str] = None
                           ) -> Iterator["SolidityContract"]:
        # file index -> path, from the AST ids solc assigns
        sources: Dict[int, str] = {}
        source_texts: Dict[int, str] = {}
        for path, desc in output.get("sources", {}).items():
            index = desc.get("id", len(sources))
            sources[index] = path
            text = None
            if os.path.exists(path):
                with open(path, errors="replace") as handle:
                    text = handle.read()
            source_texts[index] = text or ""
        for path, contracts in output.get("contracts", {}).items():
            for name, desc in contracts.items():
                if contract_name and name != contract_name:
                    continue
                evm = desc.get("evm", {})
                runtime = evm.get("deployedBytecode", {})
                creation = evm.get("bytecode", {})
                code = _strip_unlinked(runtime.get("object", ""))
                creation_code = _strip_unlinked(creation.get("object", ""))
                if not code:
                    continue
                contract = cls(input_file=input_file, name=name, code=code,
                               creation_code=creation_code,
                               srcmap_runtime=runtime.get("sourceMap", ""),
                               srcmap_creation=creation.get("sourceMap", ""),
                               sources=sources, source_texts=source_texts)
                ast = output.get("sources", {}).get(path, {}).get("ast")
                if ast:
                    from .features import SolidityFeatureExtractor

                    contract.features = \
                        SolidityFeatureExtractor(ast).extract_features()
                yield contract

    # -- issue source mapping -----------------------------------------------------
    def get_source_info(self, address: int, constructor: bool = False):
        """bytecode address -> (filename, lineno, code snippet) or None."""
        disassembly = self.creation_disassembly if constructor \
            else self.disassembly
        srcmap = self.creation_srcmap if constructor else self.srcmap
        index = None
        for i, instruction in enumerate(disassembly.instruction_list):
            if instruction.address == address:
                index = i
                break
        if index is None or index >= len(srcmap):
            return None
        offset, length, file_index = srcmap[index]
        if file_index < 0 or file_index not in self.sources:
            return None
        text = self.source_texts.get(file_index) or ""
        lineno = text.count("\n", 0, offset) + 1 if text else None
        code = text[offset:offset + length] if text else ""
        return SourceMapping(offset, length, file_index,
                             filename=self.sources.get(file_index, ""),
                             lineno=lineno, code=code)

    @property
    def filename(self) -> str:
        return self.input_file


def _strip_unlinked(bytecode: str) -> str:
    """Library placeholders (__$...$__) are not hex; zero them so the
    disassembler can proceed."""
    return bytecode.replace("_", "0").replace("$", "0")


def get_contracts_from_file(input_file: str, solc_binary: str = "solc",
                            solc_settings_json: Optional[str] = None,
                            name: Optional[str] = None
                            ) -> Iterator[SolidityContract]:
    output = get_solc_json(input_file, solc_binary=solc_binary,
                           solc_settings_json=solc_settings_json)
    yield from SolidityContract.from_standard_json(output, input_file,
                                                   contract_name=name)


def get_contracts_from_foundry(project_root: str
                               ) -> Iterator[SolidityContract]:
    """Load forge build artifacts (reference soliditycontract.py:140)."""
    out_dir = os.path.join(project_root, "out")
    if not os.path.isdir(out_dir):
        raise SolcError(f"no foundry output directory at {out_dir}")
    for sol_dir in sorted(os.listdir(out_dir)):
        full = os.path.join(out_dir, sol_dir)
        if not os.path.isdir(full):
            continue
        for artifact in sorted(os.listdir(full)):
            if not artifact.endswith(".json"):
                continue
            with open(os.path.join(full, artifact)) as handle:
                data = json.load(handle)
            runtime = data.get("deployedBytecode", {})
            creation = data.get("bytecode", {})
            code = _strip_unlinked(
                (runtime.get("object", "") or "").replace("0x", "", 1))
            if not code:
                continue
            yield SolidityContract(
                input_file=os.path.join(sol_dir, artifact),
                name=os.path.splitext(artifact)[0], code=code,
                creation_code=_strip_unlinked(
                    (creation.get("object", "") or "").replace("0x", "", 1)),
                srcmap_runtime=runtime.get("sourceMap", ""),
                srcmap_creation=creation.get("sourceMap", ""),
                sources={}, source_texts={})
