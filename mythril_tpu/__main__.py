"""`python -m mythril_tpu ...` == `myth-tpu ...` (reference parity: the
`myth` console script, mythril setup.py:139 / myth:1-11)."""

import sys

from .interfaces.cli import main

if __name__ == "__main__":
    sys.exit(main())
