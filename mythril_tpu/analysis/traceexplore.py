"""Serializable statespace export for `--statespace-json`
(capability parity: mythril/analysis/traceexplore.py:52 —
get_serializable_statespace)."""

from __future__ import annotations

from typing import Dict, List

_COLORS = [
    "#6c54de", "#de5454", "#54de89", "#de9a54", "#54bade", "#d354de",
    "#dede54", "#54de54",
]


def get_serializable_statespace(statespace) -> Dict:
    """Nodes/edges/states of one exploration as plain JSON-able dicts."""
    nodes: List[Dict] = []
    edges: List[Dict] = []

    color_map: Dict[str, str] = {}
    for uid, node in statespace.nodes.items():
        function_name = getattr(node, "function_name", "unknown")
        if function_name not in color_map:
            color_map[function_name] = _COLORS[len(color_map) % len(_COLORS)]
        code_lines = []
        for state in node.states:
            try:
                instruction = state.get_current_instruction()
            except Exception:
                continue
            code_lines.append(
                f"{instruction['address']} {instruction['opcode']} "
                f"{instruction.get('argument', '') or ''}".strip())
        nodes.append({
            "id": str(uid),
            "func": function_name,
            "color": color_map[function_name],
            "code": code_lines,
            "instructions": code_lines,
            "contract": getattr(node, "contract_name", "Unknown"),
            "startAddr": getattr(node, "start_addr", None),
            "isExpanded": False,
            "truncLabel": f"{function_name}",
            "states": [
                {
                    "pc": state.mstate.pc,
                    "depth": state.mstate.depth,
                    "gas": {"min": state.mstate.min_gas_used,
                            "max": state.mstate.max_gas_used},
                    "stackSize": len(state.mstate.stack),
                } for state in node.states],
        })

    for edge in statespace.edges:
        edges.append({
            "from": str(edge.node_from),
            "to": str(edge.node_to),
            "arrows": "to",
            "label": str(edge.condition) if edge.condition is not None else "",
            "smooth": {"type": "cubicBezier"},
        })

    return {"nodes": nodes, "edges": edges,
            "totalStates": sum(len(n["states"]) for n in nodes)}
