"""Interactive HTML call-graph export for `--graph`
(capability parity: mythril/analysis/callgraph.py:220 — generate_graph; the
reference renders through jinja2 + vis.js from a CDN. This build inlines a
dependency-free HTML template: the graph data is embedded as JSON and drawn on
a <canvas> with a small static force layout, so the artifact opens offline)."""

from __future__ import annotations

import html
import json

from .traceexplore import get_serializable_statespace

_TEMPLATE = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8"/>
<title>call graph — {title}</title>
<style>
 body {{ margin:0; font-family: monospace; background:#111; color:#eee; }}
 #info {{ position:fixed; top:0; right:0; width:34%; height:100%;
         overflow:auto; background:#1b1b1b; padding:8px;
         border-left:1px solid #333; white-space:pre; font-size:12px; }}
 canvas {{ display:block; }}
</style>
</head>
<body>
<canvas id="c"></canvas><div id="info">click a node…</div>
<script>
const GRAPH = {graph_json};
const canvas = document.getElementById('c');
const ctx = canvas.getContext('2d');
const W = () => canvas.width = innerWidth * 0.65;
const H = () => canvas.height = innerHeight;
W(); H();
const nodes = GRAPH.nodes.map((n, i) => Object.assign({{}}, n, {{
  x: 60 + (i % 8) * (canvas.width - 120) / 8 + Math.random() * 30,
  y: 40 + Math.floor(i / 8) * 90 + Math.random() * 20, vx: 0, vy: 0 }}));
const byId = Object.fromEntries(nodes.map(n => [n.id, n]));
const edges = GRAPH.edges.filter(e => byId[e.from] && byId[e.to]);
for (let iter = 0; iter < {physics_iters}; iter++) {{
  for (const e of edges) {{
    const a = byId[e.from], b = byId[e.to];
    const dx = b.x - a.x, dy = b.y - a.y;
    const d = Math.hypot(dx, dy) || 1, f = (d - 90) * 0.01;
    a.vx += f * dx / d; a.vy += f * dy / d;
    b.vx -= f * dx / d; b.vy -= f * dy / d;
  }}
  for (const n of nodes) {{
    n.x = Math.max(20, Math.min(canvas.width - 20, n.x + n.vx));
    n.y = Math.max(20, Math.min(canvas.height - 20, n.y + n.vy));
    n.vx *= 0.85; n.vy *= 0.85;
  }}
}}
function draw() {{
  ctx.clearRect(0, 0, canvas.width, canvas.height);
  ctx.strokeStyle = '#555';
  for (const e of edges) {{
    const a = byId[e.from], b = byId[e.to];
    ctx.beginPath(); ctx.moveTo(a.x, a.y); ctx.lineTo(b.x, b.y); ctx.stroke();
    const ang = Math.atan2(b.y - a.y, b.x - a.x);
    ctx.beginPath();
    ctx.moveTo(b.x - 10 * Math.cos(ang - 0.4), b.y - 10 * Math.sin(ang - 0.4));
    ctx.lineTo(b.x, b.y);
    ctx.lineTo(b.x - 10 * Math.cos(ang + 0.4), b.y - 10 * Math.sin(ang + 0.4));
    ctx.stroke();
  }}
  for (const n of nodes) {{
    ctx.fillStyle = n.color || '#6c54de';
    ctx.beginPath(); ctx.arc(n.x, n.y, 8, 0, 7); ctx.fill();
    ctx.fillStyle = '#ccc';
    ctx.fillText(n.truncLabel || n.id, n.x + 10, n.y + 3);
  }}
}}
draw();
canvas.onclick = (ev) => {{
  const r = canvas.getBoundingClientRect();
  const x = ev.clientX - r.left, y = ev.clientY - r.top;
  for (const n of nodes) if (Math.hypot(n.x - x, n.y - y) < 10) {{
    document.getElementById('info').textContent =
      'node ' + n.id + '  (' + n.func + ')\\n\\n' + n.code.join('\\n');
    return;
  }}
}};
onresize = () => {{ W(); H(); draw(); }};
</script>
</body>
</html>
"""


def generate_graph(statespace, title: str = "mythril-tpu call graph",
                   physics: bool = False) -> str:
    graph = get_serializable_statespace(statespace)
    return _TEMPLATE.format(
        title=html.escape(title),
        graph_json=json.dumps(graph),
        physics_iters=300 if physics else 60,
    )
