"""SymExecWrapper: configure and run one full analysis (API parity:
mythril/analysis/symbolic.py:44 — strategy selection, plugin wiring, detector hook
installation, sym_exec run, post-hoc Call extraction from the statespace)."""

from __future__ import annotations

import copy
import logging
from typing import Dict, List, Optional, Union

from ..core.plugin import LaserPluginLoader
from ..core.plugin.plugins import (BenchmarkPluginBuilder, CallDepthLimitBuilder,
                                   CoverageMetricsPluginBuilder,
                                   CoveragePluginBuilder, DependencyPrunerBuilder,
                                   StateMergePluginBuilder,
                                   InstructionProfilerBuilder,
                                   MutationPrunerBuilder)
from ..core.strategy import (BasicSearchStrategy, BeamSearch,
                             BoundedLoopsStrategy, BreadthFirstSearchStrategy,
                             DelayConstraintStrategy, DepthFirstSearchStrategy,
                             ReturnRandomNaivelyStrategy,
                             ReturnWeightedRandomStrategy)
from ..core.svm import LaserEVM
from ..core.state.world_state import WorldState
from ..core.transaction.transaction_models import tx_id_manager
from ..smt import BitVec, symbol_factory
from ..support.support_args import args
from . import module_screen
from .module import ModuleLoader, get_detection_module_hooks
from .module.base import EntryPoint
from .ops import Call, VarType, get_variable
from .potential_issues import check_potential_issues

log = logging.getLogger(__name__)


class SymExecWrapper:
    def __init__(self, contract, address: Optional[Union[int, str, BitVec]],
                 strategy: str = "dfs", dynloader=None, max_depth: int = 22,
                 execution_timeout: Optional[int] = None,
                 loop_bound: int = 3, create_timeout: Optional[int] = None,
                 transaction_count: int = 2, modules: Optional[List[str]] = None,
                 compulsory_statespace: bool = True,
                 disable_dependency_pruning: bool = False,
                 run_analysis_modules: bool = True, enable_coverage_strategy: bool = False,
                 custom_modules_directory: str = "", engine: str = "host",
                 checkpoint_path: Optional[str] = None,
                 resume_path: Optional[str] = None,
                 fleet=None):
        if isinstance(address, str):
            address = symbol_factory.BitVecVal(int(address, 16), 256)
        elif isinstance(address, int):
            address = symbol_factory.BitVecVal(address, 256)

        strategy_class = {
            "dfs": DepthFirstSearchStrategy,
            "bfs": BreadthFirstSearchStrategy,
            "naive-random": ReturnRandomNaivelyStrategy,
            "weighted-random": ReturnWeightedRandomStrategy,
            "beam-search": BeamSearch,
            "pending": DelayConstraintStrategy,
        }.get(strategy)
        if strategy_class is None:
            raise ValueError(f"invalid search strategy: {strategy}")

        requires_statespace = compulsory_statespace or \
            len(ModuleLoader().get_detection_modules(
                EntryPoint.POST, modules)) > 0
        self.modules = modules
        if fleet is None:
            tx_id_manager.restart_counter()
            # a fresh analysis must not inherit another's keccak axioms: with
            # restarted tx ids, symbol names recur and stale concrete-hash
            # conditions would conflict with this run's (making everything
            # unsat)
            from ..core.function_managers import keccak_function_manager

            keccak_function_manager.reset()
        # fleet members get fresh tx/keccak namespaces from the driver's
        # per-turn swap; restarting here would clobber the swapped-in state

        # non-incremental exploration: the RF prioritizer predicts which
        # function sequence to explore (reference symbolic.py:107-110)
        tx_strategy = None
        if not args.incremental_txs:
            from ..core.tx_prioritiser import RfTxPrioritiser

            tx_strategy = RfTxPrioritiser(contract)

        self.laser = LaserEVM(
            dynamic_loader=dynloader,
            max_depth=max_depth,
            execution_timeout=execution_timeout,
            create_timeout=create_timeout,
            strategy=strategy_class,
            transaction_count=transaction_count,
            requires_statespace=requires_statespace,
            tx_strategy=tx_strategy,
            engine=engine,
            checkpoint_path=checkpoint_path,
            resume_path=resume_path,
        )
        if fleet is not None:
            fleet.install(self.laser)
        if loop_bound is not None:
            self.laser.extend_strategy(BoundedLoopsStrategy,
                                       loop_bound=loop_bound)

        plugin_loader = LaserPluginLoader()
        plugin_loader.reset()
        plugin_loader.load(CoverageMetricsPluginBuilder())
        plugin_loader.load(CoveragePluginBuilder())
        if not args.disable_mutation_pruner:
            plugin_loader.load(MutationPrunerBuilder())
        if not args.disable_iprof:
            plugin_loader.load(InstructionProfilerBuilder())
        plugin_loader.load(CallDepthLimitBuilder())
        plugin_loader.add_args("call-depth-limit",
                               call_depth_limit=args.call_depth_limit)
        if not disable_dependency_pruning:
            plugin_loader.load(DependencyPrunerBuilder())
        if args.enable_state_merging:
            plugin_loader.load(StateMergePluginBuilder())
        # issue emission is deferred to summary validation only while the
        # summary plugin is active (it must not leak into later analyses in
        # the same process)
        args.use_issue_annotations = args.enable_summaries
        if args.enable_summaries:
            from ..core.plugin.plugins.summary import SummaryPluginBuilder

            plugin_loader.load(SummaryPluginBuilder())
        plugin_loader.instrument_virtual_machine(self.laser, None)

        self.plugin_loader = plugin_loader

        # runtime-code analysis builds its world state up front: the taint
        # module screen needs the contract's disassembly before hooks are
        # registered
        creation_mode = isinstance(contract, str) or (
            hasattr(contract, "creation_code") and contract.creation_code
            and getattr(contract, "name", None))
        world_state = account = None
        if not creation_mode:
            world_state = WorldState()
            account = world_state.create_account(
                balance=10 ** 18,
                address=address.value if address is not None else None,
                concrete_storage=False, dynamic_loader=dynloader)
            if hasattr(contract, "disassembly"):
                account.code = contract.disassembly
            else:
                from ..frontends.disassembler import Disassembly

                account.code = Disassembly(
                    contract.code if hasattr(contract, "code") else contract)
            account.contract_name = getattr(contract, "name", "Unknown")

        if run_analysis_modules:
            analysis_modules = ModuleLoader().get_detection_modules(
                EntryPoint.CALLBACK, white_list=modules)
            if account is not None and dynloader is None:
                # creation transactions and dynamically loaded code run
                # hooks over bytecode the summary never saw, so the
                # whole-module screen only applies to pure runtime runs
                analysis_modules, skipped = module_screen.screen_modules(
                    analysis_modules, account.code)
                if skipped:
                    log.info(
                        "module screen: %d module(s) skipped — no "
                        "reachable hook opcode: %s", len(skipped),
                        ", ".join(type(m).__name__ for m in skipped))
            self.laser.register_hooks(
                hook_type="pre",
                hook_dict=get_detection_module_hooks(analysis_modules,
                                                     hook_type="pre"))
            self.laser.register_hooks(
                hook_type="post",
                hook_dict=get_detection_module_hooks(analysis_modules,
                                                     hook_type="post"))

            # two-phase PotentialIssue resolution at every transaction end
            @self.laser.laser_hook("transaction_end")
            def transaction_end_hook(global_state, transaction,
                                     return_global_state, revert):
                if return_global_state is None and not revert:
                    check_potential_issues(global_state)

        self.address = address
        if isinstance(contract, str):
            # raw creation bytecode
            self.laser.sym_exec(creation_code=contract, contract_name="Unknown")
        elif hasattr(contract, "creation_code") and contract.creation_code and \
                getattr(contract, "name", None):
            self.laser.sym_exec(creation_code=contract.creation_code,
                                contract_name=contract.name)
        else:
            # runtime-code analysis on the world state prepared above
            self.laser.sym_exec(world_state=world_state,
                                target_address=account.address.value)

        # statespace bookkeeping for POST modules / graph export
        self.nodes = self.laser.nodes
        self.edges = self.laser.edges
        if requires_statespace:
            self.calls = self._extract_calls()
        else:
            self.calls = []

    def _extract_calls(self) -> List[Call]:
        """Post-hoc Call extraction (reference symbolic.py:250-330)."""
        calls: List[Call] = []
        for node_id, node in self.nodes.items():
            for state in node.states:
                instruction = state.get_current_instruction()
                op = instruction["opcode"]
                if op not in ("CALL", "CALLCODE", "DELEGATECALL", "STATICCALL"):
                    continue
                stack = state.mstate.stack
                if len(stack) < 7:
                    continue
                if op in ("CALL", "CALLCODE"):
                    gas, to, value = (get_variable(stack[-1]),
                                      get_variable(stack[-2]),
                                      get_variable(stack[-3]))
                    calls.append(Call(node, state, None, op, to, gas, value))
                else:
                    gas, to = get_variable(stack[-1]), get_variable(stack[-2])
                    calls.append(Call(node, state, None, op, to, gas))
        return calls
