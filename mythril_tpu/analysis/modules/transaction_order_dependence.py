"""SWC-114 Transaction order dependence (capability parity:
mythril/analysis/module/modules/transaction_order_dependence.py: the value of
an ether transfer is tainted by BALANCE/SLOAD reads whose writer another
(attacker) transaction could be — front-runnable race; two-phase
PotentialIssue flow)."""

from __future__ import annotations

import logging

from ...core.state.global_state import GlobalState
from ...core.transaction.symbolic import ACTORS
from ...smt import Or, symbol_factory
from ..module.base import DetectionModule, EntryPoint
from ..potential_issues import PotentialIssue, get_potential_issues_annotation
from ..swc_data import TX_ORDER_DEPENDENCE

log = logging.getLogger(__name__)


class BalanceAnnotation:
    def __init__(self, caller):
        self.caller = caller


class StorageAnnotation:
    def __init__(self, caller):
        self.caller = caller


class TxOrderDependence(DetectionModule):
    name = "Transaction Order Dependence"
    swc_id = TX_ORDER_DEPENDENCE
    description = "Search for calls whose value depends on balance or storage."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["CALL"]
    post_hooks = ["BALANCE", "SLOAD"]
    taint_sinks = {"CALL": ()}

    @staticmethod
    def _annotate_read(state: GlobalState, opcode: str):
        value = state.mstate.stack[-1]
        annotation_type = (BalanceAnnotation if opcode == "BALANCE"
                           else StorageAnnotation)
        if not list(value.get_annotations(annotation_type)):
            value.annotate(annotation_type(state.environment.sender))
        return []

    def _execute(self, state: GlobalState):
        opcode = state.get_current_instruction()["opcode"]
        if opcode != "CALL":
            opcode = state.environment.code.instruction_list[
                state.mstate.pc - 1].op_code
        if opcode in ("BALANCE", "SLOAD"):
            return self._annotate_read(state, opcode)

        value = state.mstate.stack[-3]
        storage_annotations = list(value.get_annotations(StorageAnnotation))
        balance_annotations = list(value.get_annotations(BalanceAnnotation))
        if not storage_annotations and not balance_annotations:
            return []
        callers = [a.caller for a in storage_annotations[:1]] + \
                  [a.caller for a in balance_annotations[:1]]

        # the competing writer transaction must be attacker-sendable
        call_constraint = symbol_factory.BoolVal(False)
        for caller in callers:
            call_constraint = Or(call_constraint, ACTORS.attacker == caller)

        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=getattr(state.environment, "active_function_name",
                                  "fallback"),
            address=state.get_current_instruction()["address"],
            swc_id=self.swc_id,
            bytecode=state.environment.code.bytecode,
            title="Transaction Order Dependence",
            severity="Medium",
            description_head="The value of the call is dependent on balance "
                             "or storage write",
            description_tail=(
                "This can lead to race conditions. An attacker may be able to "
                "run a transaction after our transaction which can change the "
                "value of the call"),
            detector=self,
            constraints=[call_constraint],
        )
        get_potential_issues_annotation(state).potential_issues.append(
            potential_issue)
        return []
