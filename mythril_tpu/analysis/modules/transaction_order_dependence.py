"""SWC-114 Transaction order dependence (capability parity:
mythril/analysis/module/modules/transaction_order_dependence.py: the value or
target of an ether transfer depends on storage another transaction can change)."""

from __future__ import annotations

import logging

from ...core.state.global_state import GlobalState
from ...exceptions import UnsatError
from ...smt import UGT, symbol_factory, terms
from ..module.base import DetectionModule, EntryPoint
from ..report import Issue
from ..solver import get_transaction_sequence
from ..swc_data import TX_ORDER_DEPENDENCE

log = logging.getLogger(__name__)


class TxOrderDependence(DetectionModule):
    name = "Transaction order dependence"
    swc_id = TX_ORDER_DEPENDENCE
    description = ("Check whether the value or target of an ether transfer "
                   "depends on mutable storage (front-runnable).")
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["CALL"]

    def _execute(self, state: GlobalState):
        value = state.mstate.stack[-3]
        to = state.mstate.stack[-2]
        # the transfer is order-dependent when value or target reads storage
        if not (_depends_on_storage(value) or _depends_on_storage(to)):
            return []
        try:
            transaction_sequence = get_transaction_sequence(
                state,
                state.world_state.constraints.get_all_constraints()
                + [UGT(value, symbol_factory.BitVecVal(0, 256))])
        except UnsatError:
            return []
        return [Issue(
            contract=state.environment.active_account.contract_name,
            function_name=getattr(state.environment, "active_function_name",
                                  "fallback"),
            address=state.get_current_instruction()["address"],
            swc_id=self.swc_id,
            bytecode=state.environment.code.bytecode,
            title="Transaction Order Dependence",
            severity="Medium",
            description_head="The value of the call is dependent on storage "
                             "that other transactions can modify.",
            description_tail=(
                "The value or target of this ether transfer is read from "
                "contract storage. Another pending transaction that writes "
                "this storage can front-run this transfer and change its "
                "outcome (race condition / SWC-114). Consider using "
                "pull-payment patterns or commit-reveal schemes."),
            gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
            transaction_sequence=transaction_sequence,
        )]


def _depends_on_storage(expression) -> bool:
    for node in terms.walk(expression.raw):
        if node.op == "select" or (node.op == "var" and
                                   str(node.params[0]).startswith("Storage[")):
            return True
    return False
