"""SWC-110 user-level assertion reporting (capability parity:
mythril/analysis/module/modules/user_assertions.py: decodes Panic(uint256) and
assert-style revert payloads)."""

from __future__ import annotations

import logging

from ...core.state.global_state import GlobalState
from ...exceptions import UnsatError
from ...smt import BitVec
from ..module.base import DetectionModule, EntryPoint
from ..report import Issue
from ..solver import get_transaction_sequence
from ..swc_data import ASSERT_VIOLATION

log = logging.getLogger(__name__)

PANIC_SELECTOR = 0x4E487B71  # keccak("Panic(uint256)")[:4]
ERROR_SELECTOR = 0x08C379A0  # keccak("Error(string)")[:4]

PANIC_CODES = {
    0x01: "generic assert violation",
    0x11: "arithmetic overflow/underflow (checked arithmetic)",
    0x12: "division by zero",
    0x21: "enum conversion out of range",
    0x31: "pop on empty array",
    0x32: "array index out of bounds",
    0x41: "allocation of too much memory",
    0x51: "call to a zero-initialized internal function",
}


class UserAssertions(DetectionModule):
    name = "A user-defined assertion has been triggered"
    swc_id = ASSERT_VIOLATION
    description = "Search for reachable user-supplied exceptions (Panic/Error reverts)."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["REVERT"]

    def _execute(self, state: GlobalState):
        offset, length = state.mstate.stack[-1], state.mstate.stack[-2]
        if not (offset.raw.is_const and length.raw.is_const):
            return []
        size = length.value
        if size < 4:
            return []
        data = state.mstate.memory[offset.value:offset.value + min(size, 68)]
        if not all(isinstance(b, BitVec) and b.raw.is_const for b in data[:4]):
            return []
        selector = int.from_bytes(bytes(b.value for b in data[:4]), "big")
        if selector == PANIC_SELECTOR and size >= 36:
            code_bytes = data[4:36]
            if all(b.raw.is_const for b in code_bytes):
                panic_code = int.from_bytes(
                    bytes(b.value for b in code_bytes), "big")
                if panic_code not in PANIC_CODES:
                    return []
                detail = PANIC_CODES[panic_code]
            else:
                detail = "panic with symbolic code"
        elif selector == ERROR_SELECTOR:
            detail = "require()/revert() with reason string"
            return []  # plain require failures are not assertion violations
        else:
            return []
        try:
            transaction_sequence = get_transaction_sequence(
                state, state.world_state.constraints.get_all_constraints())
        except UnsatError:
            return []
        return [Issue(
            contract=state.environment.active_account.contract_name,
            function_name=getattr(state.environment, "active_function_name",
                                  "fallback"),
            address=state.get_current_instruction()["address"],
            swc_id=self.swc_id,
            title="Exception State",
            severity="Medium",
            bytecode=state.environment.code.bytecode,
            description_head="A user-provided assertion failed.",
            description_tail=f"A reachable user-level assertion failure was "
                             f"found: {detail}.",
            gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
            transaction_sequence=transaction_sequence,
        )]
