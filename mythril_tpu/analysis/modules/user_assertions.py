"""SWC-110 user-level assertion reporting (capability parity:
mythril/analysis/module/modules/user_assertions.py — `emit
AssertionFailed(string)` events via LOG1 and the 0xcafecafe... MSTORE
property-check pattern; Panic(uint256) reverts are handled by the
`exceptions` module)."""

from __future__ import annotations

import logging

from ...core.state.global_state import GlobalState
from ...exceptions import UnsatError
from ...smt import Extract
from ..issue_annotation import attach_issue_annotation
from ..module.base import DetectionModule, EntryPoint
from ..report import Issue
from ..solver import get_transaction_sequence
from ..swc_data import ASSERT_VIOLATION

log = logging.getLogger(__name__)

#: keccak("AssertionFailed(string)")
ASSERTION_FAILED_HASH = \
    0xB42604CB105A16C8F6DB8A41E6B00C0C1B4826465E8BC504B3EB3E88B3E6A4A0

#: MythX-style property-check marker written via MSTORE
MSTORE_PATTERN = "0xcafecafecafecafecafecafecafecafecafecafecafecafecafecafecafe"


def _decode_abi_string(data: list) -> str:
    """Hand-decoded `abi.encode(string)` tail: 32-byte length + bytes."""
    if len(data) < 32:
        return ""
    if not all(b.raw.is_const for b in data[:32]):
        return ""
    length = int.from_bytes(bytes(b.value for b in data[:32]), "big")
    if length > len(data) - 32:
        return ""
    payload = data[32:32 + length]
    if not all(b.raw.is_const for b in payload):
        return ""
    return bytes(b.value for b in payload).decode("utf-8", errors="replace")


class UserAssertions(DetectionModule):
    name = "A user-defined assertion has been triggered"
    swc_id = ASSERT_VIOLATION
    description = ("Search for reachable user-supplied exceptions: "
                   "emit AssertionFailed(string).")
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["LOG1", "MSTORE"]
    taint_sinks = {"LOG1": (), "MSTORE": ()}

    def _execute(self, state: GlobalState):
        opcode = state.get_current_instruction()["opcode"]
        message = None
        if opcode == "MSTORE":
            value = state.mstate.stack[-2]
            if not value.raw.is_const:
                return []
            if MSTORE_PATTERN not in hex(value.raw.value)[:126]:
                return []
            message = f"Failed property id {Extract(15, 0, value).raw.value}"
        else:  # LOG1
            topic, size, mem_start = state.mstate.stack[-3:]
            if not topic.raw.is_const or topic.raw.value != ASSERTION_FAILED_HASH:
                return []
            if mem_start.raw.is_const and size.raw.is_const:
                data = state.mstate.memory[
                    mem_start.raw.value + 32:
                    mem_start.raw.value + size.raw.value]
                decoded = _decode_abi_string(data)
                if decoded:
                    message = decoded

        constraints = state.world_state.constraints.get_all_constraints()
        try:
            transaction_sequence = get_transaction_sequence(state, constraints)
        except UnsatError:
            return []
        description_tail = (
            f"A user-provided assertion failed with the message '{message}'"
            if message else "A user-provided assertion failed.")
        issue = Issue(
            contract=state.environment.active_account.contract_name,
            function_name=getattr(state.environment, "active_function_name",
                                  "fallback"),
            address=state.get_current_instruction()["address"],
            swc_id=self.swc_id,
            title="Exception State",
            severity="Medium",
            bytecode=state.environment.code.bytecode,
            description_head="A user-provided assertion failed.",
            description_tail=description_tail,
            gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
            transaction_sequence=transaction_sequence,
        )
        attach_issue_annotation(state, issue, self, constraints)
        return [issue]
