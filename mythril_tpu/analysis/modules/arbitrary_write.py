"""SWC-124 Write to arbitrary storage (capability parity:
mythril/analysis/module/modules/arbitrary_write.py: SSTORE key fully
attacker-controllable)."""

from __future__ import annotations

import logging

from ...core.state.global_state import GlobalState
from ...smt import symbol_factory
from ..module.base import DetectionModule, EntryPoint
from ..potential_issues import PotentialIssue, get_potential_issues_annotation
from ..swc_data import WRITE_TO_ARBITRARY_STORAGE

log = logging.getLogger(__name__)


class ArbitraryStorage(DetectionModule):
    name = "Caller can write to arbitrary storage locations"
    swc_id = WRITE_TO_ARBITRARY_STORAGE
    description = "Check for writes to arbitrary storage locations"
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["SSTORE"]
    # presence-only: a deterministic slot equal to the probe constant
    # would still satisfy `write_slot == probe`, so skipping untainted
    # sites could drop a PotentialIssue the unscreened run reports
    taint_sinks = {"SSTORE": ()}

    def _execute(self, state: GlobalState):
        write_slot = state.mstate.stack[-1]
        if write_slot.raw.is_const:
            return []
        # a CONCRETE improbable probe slot (reference arbitrary_write.py:56):
        # a fresh symbolic probe would be trivially satisfiable for any
        # symbolic key and would report every symbolic write
        probe = symbol_factory.BitVecVal(324345425435, 256)
        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=getattr(state.environment, "active_function_name",
                                  "fallback"),
            address=state.get_current_instruction()["address"],
            swc_id=self.swc_id,
            title="Write to an arbitrary storage location",
            severity="High",
            bytecode=state.environment.code.bytecode,
            description_head="The caller can write to arbitrary storage "
                             "locations.",
            description_tail=(
                "It is possible to write to arbitrary storage locations. By "
                "modifying the values of storage variables, attackers may bypass "
                "security controls or manipulate the business logic of the smart "
                "contract."),
            detector=self,
            constraints=[write_slot == probe],
        )
        get_potential_issues_annotation(state).potential_issues.append(
            potential_issue)
        return []
