"""SWC-112 Delegatecall to untrusted callee (capability parity:
mythril/analysis/module/modules/delegatecall.py: DELEGATECALL target solvable to an
attacker-chosen address, with calldata-tainted target)."""

from __future__ import annotations

import logging

from ...core.state.global_state import GlobalState
from ...core.transaction.symbolic import ACTORS
from ...core.transaction.transaction_models import ContractCreationTransaction
from ...smt import UGT, symbol_factory
from ..module.base import DetectionModule, EntryPoint
from ..potential_issues import PotentialIssue, get_potential_issues_annotation
from ..swc_data import DELEGATECALL_TO_UNTRUSTED_CONTRACT

log = logging.getLogger(__name__)


class ArbitraryDelegateCall(DetectionModule):
    name = "Delegatecall to a user-specified address"
    swc_id = DELEGATECALL_TO_UNTRUSTED_CONTRACT
    description = "Check for invocations of delegatecall to a user-supplied address."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["DELEGATECALL"]
    # presence-only: a deterministic `to` equal to the attacker actor
    # address would still satisfy the module's constraints
    taint_sinks = {"DELEGATECALL": ()}

    def _execute(self, state: GlobalState):
        gas = state.mstate.stack[-1]
        to = state.mstate.stack[-2]
        if to.raw.is_const:
            return []  # fixed library target: fine

        constraints = [
            to == ACTORS.attacker,
            # enough gas forwarded for meaningful reentry, and the call must
            # succeed (reference delegatecall.py:49-57)
            UGT(gas, symbol_factory.BitVecVal(2300, 256)),
            state.new_bitvec(
                f"retval_{state.get_current_instruction()['address']}",
                256) == 1,
            *[transaction.caller == ACTORS.attacker
              for transaction in state.world_state.transaction_sequence
              if not isinstance(transaction, ContractCreationTransaction)],
        ]
        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=getattr(state.environment, "active_function_name",
                                  "fallback"),
            address=state.get_current_instruction()["address"],
            swc_id=self.swc_id,
            title="Delegatecall to user-supplied address",
            bytecode=state.environment.code.bytecode,
            severity="High",
            description_head="The contract delegates execution to another "
                             "contract with a user-supplied address.",
            description_tail=(
                "The smart contract delegates execution to a user-supplied "
                "address. This could allow an attacker to execute arbitrary code "
                "in the context of this contract account and manipulate the "
                "state of the contract account or execute actions on its "
                "behalf."),
            detector=self,
            constraints=constraints,
        )
        get_potential_issues_annotation(state).potential_issues.append(
            potential_issue)
        return []
