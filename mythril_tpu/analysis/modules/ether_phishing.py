"""Ether phishing detection (capability parity:
mythril/analysis/module/modules/ether_phishing.py: a victim (SOMEGUY) transaction
can be tricked into transferring ether to the attacker — phishing via crafted
intermediate contract state)."""

from __future__ import annotations

import logging

from ...core.state.global_state import GlobalState
from ...core.transaction.symbolic import ACTORS
from ...core.transaction.transaction_models import ContractCreationTransaction
from ...smt import UGT
from ..module.base import DetectionModule, EntryPoint
from ..potential_issues import PotentialIssue, get_potential_issues_annotation
from ..swc_data import UNPROTECTED_ETHER_WITHDRAWAL

log = logging.getLogger(__name__)


class EtherPhishing(DetectionModule):
    name = "A victim transaction can be redirected to benefit the attacker"
    swc_id = UNPROTECTED_ETHER_WITHDRAWAL
    description = ("Search for cases where a benign sender's transaction "
                   "profits the attacker (phishing-style withdrawal): the "
                   "attacker sets up state, a victim transaction pays out to "
                   "the attacker.")
    entry_point = EntryPoint.CALLBACK
    post_hooks = ["CALL"]
    taint_sinks = {"CALL": ()}

    def _execute(self, state: GlobalState):
        world_state = state.world_state
        transactions = [t for t in world_state.transaction_sequence
                        if not isinstance(t, ContractCreationTransaction)]
        if len(transactions) < 2:
            return []
        constraints = []
        # attacker sends all but the last tx; the victim (someguy) sends the last
        for transaction in transactions[:-1]:
            constraints.append(transaction.caller == ACTORS.attacker)
            constraints.append(transaction.call_value == 0)
        constraints.append(transactions[-1].caller == ACTORS.someguy)
        constraints.append(UGT(
            world_state.balances[ACTORS.attacker],
            world_state.starting_balances[ACTORS.attacker]))

        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=getattr(state.environment, "active_function_name",
                                  "fallback"),
            address=state.get_current_instruction()["address"] - 1,
            swc_id=self.swc_id,
            title="Ether phishing",
            severity="High",
            bytecode=state.environment.code.bytecode,
            description_head="An attacker can profit from a transaction sent "
                             "by a different user.",
            description_tail=(
                "The attacker can prepare contract state such that a "
                "transaction sent by another (benign) user transfers Ether to "
                "the attacker. This is a phishing-style vulnerability: review "
                "authorization of value transfers and avoid letting one user's "
                "state setup redirect another user's funds."),
            detector=self,
            constraints=constraints,
        )
        get_potential_issues_annotation(state).potential_issues.append(
            potential_issue)
        return []
