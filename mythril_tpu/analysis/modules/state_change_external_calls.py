"""SWC-107 State change after external call (capability parity:
mythril/analysis/module/modules/state_change_external_calls.py)."""

from __future__ import annotations

import logging
from typing import List, Optional

from ...core.state.annotation import StateAnnotation
from ...core.state.global_state import GlobalState
from ...exceptions import UnsatError
from ...smt import BitVec, UGT, symbol_factory
from ...support.model import get_model
from ..module.base import DetectionModule, EntryPoint
from ..potential_issues import PotentialIssue, get_potential_issues_annotation
from ..swc_data import REENTRANCY

log = logging.getLogger(__name__)


class StateChangeCallsAnnotation(StateAnnotation):
    def __init__(self, call_state: GlobalState, user_defined_address: bool):
        self.call_state = call_state
        self.state_change_states: List[GlobalState] = []
        self.user_defined_address = user_defined_address

    def __copy__(self):
        result = StateChangeCallsAnnotation(self.call_state,
                                            self.user_defined_address)
        result.state_change_states = list(self.state_change_states)
        return result


class StateChangeAfterCall(DetectionModule):
    name = "State change after an external call"
    swc_id = REENTRANCY
    description = ("Check whether the account state is accessed after an "
                   "external call to a user-defined address.")
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["CALL", "SSTORE", "DELEGATECALL", "CALLCODE"]

    STATE_READ_WRITE_LIST = ["SSTORE", "SLOAD", "CREATE", "CREATE2"]

    def _execute(self, state: GlobalState):
        opcode = state.get_current_instruction()["opcode"]
        annotations = [a for a in state.annotations
                       if isinstance(a, StateChangeCallsAnnotation)]

        if opcode in ("CALL", "DELEGATECALL", "CALLCODE"):
            gas = state.mstate.stack[-1]
            to = state.mstate.stack[-2]
            # a call that forwards enough gas for reentry
            try:
                get_model(tuple(
                    state.world_state.constraints.get_all_constraints()
                    + [UGT(gas, symbol_factory.BitVecVal(2300, 256))]))
            except UnsatError:
                return []
            user_defined = not to.raw.is_const or (
                to.raw.is_const and to.value > 10
                and to.value not in state.world_state.accounts)
            state.annotate(StateChangeCallsAnnotation(state, user_defined))
            return []

        # SSTORE after a prior qualifying call
        issues = []
        for annotation in annotations:
            call_state = annotation.call_state
            severity = "Medium" if annotation.user_defined_address else "Low"
            address_desc = ("user-defined" if annotation.user_defined_address
                            else "fixed")
            potential_issue = PotentialIssue(
                contract=state.environment.active_account.contract_name,
                function_name=getattr(state.environment,
                                      "active_function_name", "fallback"),
                address=call_state.get_current_instruction()["address"],
                swc_id=self.swc_id,
                title="State access after external call",
                severity=severity,
                bytecode=state.environment.code.bytecode,
                description_head=f"Write to persistent state following an "
                                 f"external call to a {address_desc} address.",
                description_tail=(
                    "The contract account state is accessed after an external "
                    "call. To prevent reentrancy issues, consider accessing the "
                    "state only before the call, especially if the callee is "
                    "untrusted. Alternatively, a reentrancy lock can be used to "
                    "prevent untrusted callees from re-entering the contract in "
                    "an intermediate state."),
                detector=self,
                constraints=[],
            )
            get_potential_issues_annotation(state).potential_issues.append(
                potential_issue)
        # consume annotations so each call reports at most once
        state._annotations = [a for a in state.annotations
                              if not isinstance(a, StateChangeCallsAnnotation)]
        return []
