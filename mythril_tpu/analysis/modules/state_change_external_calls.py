"""SWC-107 State change after external call (capability parity:
mythril/analysis/module/modules/state_change_external_calls.py — record
qualifying external calls, then report any later persistent-state access
(SSTORE/SLOAD/CREATE/CREATE2, or a value-transferring call) on the same
path; two-phase PotentialIssue flow)."""

from __future__ import annotations

import logging
from typing import List, Optional

from ...core.state.annotation import StateAnnotation
from ...core.state.global_state import GlobalState
from ...exceptions import UnsatError
from ...smt import BitVec, Or, UGT, symbol_factory
from ...support.model import get_model
from ..module.base import DetectionModule, EntryPoint
from ..potential_issues import PotentialIssue, get_potential_issues_annotation
from ..solver import get_transaction_sequence
from ..swc_data import REENTRANCY

log = logging.getLogger(__name__)

CALL_LIST = ["CALL", "DELEGATECALL", "CALLCODE"]
STATE_READ_WRITE_LIST = ["SSTORE", "SLOAD", "CREATE", "CREATE2"]

#: probe address for "can the attacker choose the callee"
ATTACKER_PROBE = 0xDEADBEEFDEADBEEFDEADBEEFDEADBEEFDEADBEEF


class StateChangeCallsAnnotation(StateAnnotation):
    def __init__(self, call_state: GlobalState, user_defined_address: bool):
        self.call_state = call_state
        self.state_change_states: List[GlobalState] = []
        self.user_defined_address = user_defined_address

    def __copy__(self):
        result = StateChangeCallsAnnotation(self.call_state,
                                            self.user_defined_address)
        result.state_change_states = list(self.state_change_states)
        return result

    def get_issue(self, global_state: GlobalState,
                  detector: "StateChangeAfterCall") -> Optional[PotentialIssue]:
        if not self.state_change_states:
            return None
        gas = self.call_state.mstate.stack[-1]
        to = self.call_state.mstate.stack[-2]
        constraints = [
            UGT(gas, symbol_factory.BitVecVal(2300, 256)),
            Or(to > symbol_factory.BitVecVal(16, 256),
               to == symbol_factory.BitVecVal(0, 256)),
        ]
        if self.user_defined_address:
            constraints.append(to == ATTACKER_PROBE)
        try:
            get_transaction_sequence(
                global_state,
                global_state.world_state.constraints.get_all_constraints()
                + constraints)
        except UnsatError:
            return None

        severity = "Medium" if self.user_defined_address else "Low"
        read_or_write = "Write to"
        if global_state.get_current_instruction()["opcode"] == "SLOAD":
            read_or_write = "Read of"
        address_type = "user defined" if self.user_defined_address else "fixed"
        return PotentialIssue(
            contract=global_state.environment.active_account.contract_name,
            function_name=getattr(global_state.environment,
                                  "active_function_name", "fallback"),
            address=global_state.get_current_instruction()["address"],
            title="State access after external call",
            severity=severity,
            description_head=f"{read_or_write} persistent state following "
                             f"external call",
            description_tail=(
                f"The contract account state is accessed after an external "
                f"call to a {address_type} address. To prevent reentrancy "
                f"issues, consider accessing the state only before the call, "
                f"especially if the callee is untrusted. Alternatively, a "
                f"reentrancy lock can be used to prevent untrusted callees "
                f"from re-entering the contract in an intermediate state."),
            swc_id=REENTRANCY,
            bytecode=global_state.environment.code.bytecode,
            constraints=constraints,
            detector=detector,
        )


class StateChangeAfterCall(DetectionModule):
    name = "State change after an external call"
    swc_id = REENTRANCY
    description = ("Check whether the account state is accessed after an "
                   "external call to a user-defined address.")
    entry_point = EntryPoint.CALLBACK
    pre_hooks = CALL_LIST + STATE_READ_WRITE_LIST
    taint_sinks = {"CALL": (), "DELEGATECALL": (), "CALLCODE": (),
                   "SSTORE": ()}

    def _execute(self, state: GlobalState):
        if getattr(state.environment, "active_function_name",
                   "") == "constructor":
            return []
        annotations = list(state.get_annotations(StateChangeCallsAnnotation))
        opcode = state.get_current_instruction()["opcode"]

        if not annotations and opcode in STATE_READ_WRITE_LIST:
            return []
        if opcode in STATE_READ_WRITE_LIST:
            for annotation in annotations:
                annotation.state_change_states.append(state)
        if opcode in CALL_LIST:
            # a value transfer is itself a state change on the annotated paths
            # (CALL/CALLCODE only: DELEGATECALL has no value argument —
            # stack[-3] there is the input memory offset)
            if opcode != "DELEGATECALL":
                value: BitVec = state.mstate.stack[-3]
                if self._balance_change(value, state):
                    for annotation in annotations:
                        annotation.state_change_states.append(state)
            self._add_external_call(state)

        potential_issues = []
        for annotation in annotations:
            if not annotation.state_change_states:
                continue
            issue = annotation.get_issue(state, self)
            if issue:
                potential_issues.append(issue)
        get_potential_issues_annotation(state).potential_issues.extend(
            potential_issues)
        return []

    @staticmethod
    def _add_external_call(state: GlobalState) -> None:
        gas = state.mstate.stack[-1]
        to = state.mstate.stack[-2]
        base = state.world_state.constraints.get_all_constraints()
        try:
            get_model(tuple(base + [
                UGT(gas, symbol_factory.BitVecVal(2300, 256)),
                Or(to > symbol_factory.BitVecVal(16, 256),
                   to == symbol_factory.BitVecVal(0, 256))]))
        except UnsatError:
            return
        except Exception:
            return  # solver timeout
        try:
            get_model(tuple(base + [to == ATTACKER_PROBE]))
            state.annotate(StateChangeCallsAnnotation(state, True))
        except UnsatError:
            state.annotate(StateChangeCallsAnnotation(state, False))
        except Exception:
            state.annotate(StateChangeCallsAnnotation(state, False))

    @staticmethod
    def _balance_change(value: BitVec, state: GlobalState) -> bool:
        if value.raw.is_const:
            return value.raw.value > 0
        try:
            get_model(tuple(
                state.world_state.constraints.get_all_constraints()
                + [UGT(value, symbol_factory.BitVecVal(0, 256))]))
            return True
        except Exception:
            return False
