"""SWC-107 External call to user-supplied address (capability parity:
mythril/analysis/module/modules/external_calls.py: CALL with attacker-controlled
target and non-trivial forwarded gas => reentrancy surface)."""

from __future__ import annotations

import logging

from ...core.state.global_state import GlobalState
from ...core.transaction.symbolic import ACTORS
from ...exceptions import UnsatError
from ...smt import UGT, symbol_factory
from ...support.model import get_model
from ..module.base import DetectionModule, EntryPoint
from ..report import Issue
from ..solver import get_transaction_sequence
from ..swc_data import REENTRANCY

log = logging.getLogger(__name__)


class ExternalCalls(DetectionModule):
    name = "External call to another contract"
    swc_id = REENTRANCY
    description = ("Check whether there is a state change of the contract after "
                   "the execution of an external call")
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["CALL"]

    def _execute(self, state: GlobalState):
        gas = state.mstate.stack[-1]
        to = state.mstate.stack[-2]
        if to.raw.is_const and to.value <= 10:
            return []  # precompile
        base = state.world_state.constraints.get_all_constraints()
        try:
            # enough gas forwarded for the callee to do damage (2300 stipend is safe)
            constraints = base + [UGT(gas, symbol_factory.BitVecVal(2300, 256))]
            if not to.raw.is_const:
                constraints.append(to == ACTORS.attacker)
            transaction_sequence = get_transaction_sequence(state, constraints)
        except UnsatError:
            return []
        if not to.raw.is_const:
            description_head = ("A call to a user-supplied address is executed.")
            description_tail = (
                "An external message call to an address specified by the caller "
                "is executed. Note that the callee account might contain "
                "arbitrary code and could re-enter any function within this "
                "contract. Reentering the contract in an intermediate state may "
                "lead to unexpected behaviour. Make sure that no state "
                "modifications are executed after this call and/or reentrancy "
                "guards are in place.")
            severity = "Low"
        else:
            description_head = ("An external function call to a fixed contract "
                                "address is executed.")
            description_tail = (
                "Calling external contracts opens the opportunity for the callee "
                "to re-enter. Make sure that no state modifications are executed "
                "after this call and/or reentrancy guards are in place.")
            severity = "Low"
        return [Issue(
            contract=state.environment.active_account.contract_name,
            function_name=getattr(state.environment, "active_function_name",
                                  "fallback"),
            address=state.get_current_instruction()["address"],
            swc_id=self.swc_id,
            title="External Call To User-Supplied Address"
            if not to.raw.is_const else "External Call To Fixed Address",
            severity=severity,
            bytecode=state.environment.code.bytecode,
            description_head=description_head,
            description_tail=description_tail,
            gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
            transaction_sequence=transaction_sequence,
        )]
