"""SWC-107 External call to user-supplied address (capability parity:
mythril/analysis/module/modules/external_calls.py: CALL with
attacker-controlled target and more than stipend gas forwarded => reentrancy
surface; two-phase PotentialIssue flow)."""

from __future__ import annotations

import logging

from ...core.state.global_state import GlobalState
from ...core.transaction.symbolic import ACTORS
from ...exceptions import UnsatError
from ...smt import UGT, symbol_factory
from ..module.base import DetectionModule, EntryPoint
from ..potential_issues import PotentialIssue, get_potential_issues_annotation
from ..solver import get_transaction_sequence
from ..swc_data import REENTRANCY

log = logging.getLogger(__name__)


class ExternalCalls(DetectionModule):
    name = "External call to another contract"
    swc_id = REENTRANCY
    description = ("Check for external calls with enough forwarded gas for the "
                   "callee to re-enter (reference external_calls.py).")
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["CALL"]
    taint_sinks = {"CALL": ()}

    def _execute(self, state: GlobalState):
        if getattr(state.environment, "active_function_name",
                   "") == "constructor":
            return []

        gas = state.mstate.stack[-1]
        to = state.mstate.stack[-2]

        # enough gas forwarded for the callee to do damage (the 2300 stipend
        # is reentrancy-safe), target steerable to the attacker
        constraints = [UGT(gas, symbol_factory.BitVecVal(2300, 256)),
                       to == ACTORS.attacker]
        try:
            get_transaction_sequence(
                state,
                state.world_state.constraints.get_all_constraints()
                + constraints)
        except UnsatError:
            return []

        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=getattr(state.environment, "active_function_name",
                                  "fallback"),
            address=state.get_current_instruction()["address"],
            swc_id=self.swc_id,
            title="External Call To User-Supplied Address",
            bytecode=state.environment.code.bytecode,
            severity="Low",
            description_head="A call to a user-supplied address is executed.",
            description_tail=(
                "An external message call to an address specified by the caller "
                "is executed. Note that the callee account might contain "
                "arbitrary code and could re-enter any function within this "
                "contract. Reentering the contract in an intermediate state may "
                "lead to unexpected behaviour. Make sure that no state "
                "modifications are executed after this call and/or reentrancy "
                "guards are in place."),
            constraints=constraints,
            detector=self,
        )
        get_potential_issues_annotation(state).potential_issues.append(
            potential_issue)
        return []
