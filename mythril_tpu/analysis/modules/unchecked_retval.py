"""SWC-104 Unchecked call return value (capability parity:
mythril/analysis/module/modules/unchecked_retval.py: retval of CALL never
constrained by a branch before the transaction ends)."""

from __future__ import annotations

import logging
from typing import List

from ...core.state.annotation import StateAnnotation
from ...core.state.global_state import GlobalState
from ...exceptions import UnsatError
from ...smt import And
from ..issue_annotation import IssueAnnotation
from ..module.base import DetectionModule, EntryPoint
from ..report import Issue
from ..solver import get_transaction_sequence
from ..swc_data import UNCHECKED_RET_VAL

log = logging.getLogger(__name__)


class UncheckedRetvalAnnotation(StateAnnotation):
    def __init__(self):
        self.retvals: List[dict] = []

    def __copy__(self):
        result = UncheckedRetvalAnnotation()
        result.retvals = [dict(entry) for entry in self.retvals]
        return result


class UncheckedRetval(DetectionModule):
    name = "Return value of an external call is not checked"
    swc_id = UNCHECKED_RET_VAL
    description = ("Check whether CALL return value is checked before the "
                   "transaction ends.")
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["STOP", "RETURN"]
    post_hooks = ["CALL", "DELEGATECALL", "STATICCALL", "CALLCODE"]
    taint_sinks = {"CALL": (), "DELEGATECALL": (), "STATICCALL": (),
                   "CALLCODE": ()}

    def _execute(self, state: GlobalState):
        instruction = state.get_current_instruction()
        annotations = list(state.get_annotations(UncheckedRetvalAnnotation))
        if not annotations:
            annotation = UncheckedRetvalAnnotation()
            state.annotate(annotation)
        else:
            annotation = annotations[0]

        if instruction["opcode"] not in ("STOP", "RETURN"):
            # CALL-family post-hook (successor state): record the fresh retval
            retval = state.mstate.stack[-1]
            if retval.raw.is_const:
                return []
            call_address = state.environment.code.instruction_list[
                state.mstate.pc - 1].address
            annotation.retvals.append(
                {"address": call_address, "retval": retval})
            return []

        # STOP/RETURN: a retval is unchecked if BOTH values are still possible
        issues = []
        for entry in annotation.retvals:
            retval = entry["retval"]
            base = state.world_state.constraints.get_all_constraints()
            try:
                get_transaction_sequence(state, base + [retval == 1])
                transaction_sequence = get_transaction_sequence(
                    state, base + [retval == 0])
            except UnsatError:
                continue
            issue = Issue(
                contract=state.environment.active_account.contract_name,
                function_name=getattr(state.environment,
                                      "active_function_name", "fallback"),
                address=entry["address"],
                swc_id=self.swc_id,
                bytecode=state.environment.code.bytecode,
                title="Unchecked return value from external call.",
                severity="Medium",
                description_head="The return value of a message call is not "
                                 "checked.",
                description_tail=(
                    "External calls return a boolean value. If the callee halts "
                    "with an exception, 'false' is returned and execution "
                    "continues in the caller. The caller should check whether "
                    "an exception happened and react accordingly to avoid "
                    "unexpected behavior. For example it is often desirable to "
                    "wrap external calls in require() so the transaction is "
                    "reverted if the call fails."),
                gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
                transaction_sequence=transaction_sequence,
            )
            state.annotate(IssueAnnotation(
                conditions=[And(*(base + [retval == 1])),
                            And(*(base + [retval == 0]))],
                issue=issue, detector=self))
            issues.append(issue)
        return issues
