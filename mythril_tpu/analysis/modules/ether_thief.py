"""SWC-105 Unprotected Ether Withdrawal (capability parity:
mythril/analysis/module/modules/ether_thief.py — two-phase PotentialIssue flow:
attacker ends with more ether than they put in)."""

from __future__ import annotations

import logging

from ...core.state.global_state import GlobalState
from ...core.transaction.symbolic import ACTORS
from ...exceptions import SolverTimeOutException, UnsatError
from ...smt import UGT
from ...support.model import get_model
from ..module.base import DetectionModule, EntryPoint
from ..potential_issues import PotentialIssue, get_potential_issues_annotation
from ..swc_data import UNPROTECTED_ETHER_WITHDRAWAL

log = logging.getLogger(__name__)


class EtherThief(DetectionModule):
    name = "Any sender can withdraw ETH from the contract account"
    swc_id = UNPROTECTED_ETHER_WITHDRAWAL
    description = ("Search for cases where Ether can be withdrawn to a "
                   "user-specified address.")
    entry_point = EntryPoint.CALLBACK
    post_hooks = ["CALL", "STATICCALL"]
    taint_sinks = {"CALL": (), "STATICCALL": ()}

    def _execute(self, state: GlobalState):
        # runs right after the CALL's post handler: inspect the completed
        # transfer. Constraint set mirrors reference ether_thief.py:100-112:
        # attacker profits, final tx sent directly by the attacker.
        world_state = state.world_state
        constraints = [
            UGT(world_state.balances[ACTORS.attacker],
                world_state.starting_balances[ACTORS.attacker]),
            state.environment.sender == ACTORS.attacker,
            state.current_transaction.caller == state.current_transaction.origin,
        ]

        # pre-solve so a potential issue is only recorded on feasible profit
        try:
            get_model(tuple(world_state.constraints.get_all_constraints()
                            + constraints))
        except (UnsatError, SolverTimeOutException):
            return []

        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=getattr(state.environment, "active_function_name",
                                  "fallback"),
            address=state.get_current_instruction()["address"] - 1,
            swc_id=self.swc_id,
            title="Unprotected Ether Withdrawal",
            severity="High",
            bytecode=state.environment.code.bytecode,
            description_head="Any sender can withdraw Ether from the contract "
                             "account.",
            description_tail=(
                "Arbitrary senders other than the contract creator can profitably "
                "extract Ether from the contract account. Verify the business "
                "logic carefully and make sure that appropriate security controls "
                "are in place to prevent unexpected loss of funds."),
            detector=self,
            constraints=constraints,
        )
        get_potential_issues_annotation(state).potential_issues.append(
            potential_issue)
        return []
