"""SWC-113 Multiple external sends in one transaction (capability parity:
mythril/analysis/module/modules/multiple_sends.py: DoS with failed call — a second
external call in the same transaction)."""

from __future__ import annotations

import logging

from ...core.state.annotation import StateAnnotation
from ...core.state.global_state import GlobalState
from ...exceptions import UnsatError
from ..issue_annotation import attach_issue_annotation
from ..module.base import DetectionModule, EntryPoint
from ..report import Issue
from ..solver import get_transaction_sequence
from ..swc_data import MULTIPLE_SENDS

log = logging.getLogger(__name__)


class MultipleSendsAnnotation(StateAnnotation):
    def __init__(self):
        self.call_offsets = []

    def __copy__(self):
        result = MultipleSendsAnnotation()
        result.call_offsets = list(self.call_offsets)
        return result


class MultipleSends(DetectionModule):
    name = "Multiple external calls in the same transaction"
    swc_id = MULTIPLE_SENDS
    description = "Check for multiple sends in a single transaction"
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["CALL", "DELEGATECALL", "STATICCALL", "CALLCODE", "RETURN", "STOP"]
    taint_sinks = {"CALL": (), "DELEGATECALL": (), "STATICCALL": (),
                   "CALLCODE": ()}

    def _execute(self, state: GlobalState):
        annotations = list(state.get_annotations(MultipleSendsAnnotation))
        if not annotations:
            annotation = MultipleSendsAnnotation()
            state.annotate(annotation)
        else:
            annotation = annotations[0]

        instruction = state.get_current_instruction()
        if instruction["opcode"] in ("CALL", "DELEGATECALL", "STATICCALL",
                                     "CALLCODE"):
            annotation.call_offsets.append(instruction["address"])
            return []

        # RETURN/STOP: report if more than one external call happened
        if len(annotation.call_offsets) < 2:
            return []
        constraints = state.world_state.constraints.get_all_constraints()
        try:
            transaction_sequence = get_transaction_sequence(state, constraints)
        except UnsatError:
            return []
        issue = Issue(
            contract=state.environment.active_account.contract_name,
            function_name=getattr(state.environment, "active_function_name",
                                  "fallback"),
            address=annotation.call_offsets[1],
            swc_id=self.swc_id,
            bytecode=state.environment.code.bytecode,
            title="Multiple Calls in a Single Transaction",
            severity="Low",
            description_head="Multiple calls are executed in the same "
                             "transaction.",
            description_tail=(
                "This call is executed following another call within the same "
                "transaction. It is possible that the call never gets executed "
                "if a prior call fails permanently. This might be caused "
                "intentionally by a malicious callee. If possible, refactor the "
                "code such that each transaction only executes one external "
                "call, or make sure that all callees can be trusted (i.e. "
                "they're part of your own codebase)."),
            gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
            transaction_sequence=transaction_sequence,
        )
        attach_issue_annotation(state, issue, self, constraints)
        return [issue]
