"""SWC-110 Assert violation (capability parity:
mythril/analysis/module/modules/exceptions.py — reachable INVALID, plus
Solidity >=0.8 assertion failures, which REVERT with Panic(uint256) code 1;
the last JUMP address is tracked as the issue's source location)."""

from __future__ import annotations

import logging
from typing import Optional

from ...core.state.annotation import StateAnnotation
from ...core.state.global_state import GlobalState
from ...core.util import get_concrete_int
from ...exceptions import UnsatError
from ...utils.helpers import get_code_hash
from ..issue_annotation import attach_issue_annotation
from ..module.base import DetectionModule, EntryPoint
from ..report import Issue
from ..solver import get_transaction_sequence
from ..swc_data import ASSERT_VIOLATION

log = logging.getLogger(__name__)

#: function selector of Panic(uint256)
PANIC_SIGNATURE = [78, 72, 123, 113]


class LastJumpAnnotation(StateAnnotation):
    """Tracks the last JUMP address: the assert's jump-over branch, used as
    the issue's source location (reference exceptions.py:25)."""

    def __init__(self, last_jump: Optional[int] = None) -> None:
        self.last_jump = last_jump

    def __copy__(self):
        return LastJumpAnnotation(self.last_jump)


def is_assertion_failure(state: GlobalState) -> bool:
    """A REVERT is an assertion failure iff its return data is
    Panic(uint256) with code 1 (reference exceptions.py:140-150)."""
    mstate = state.mstate
    offset, length = mstate.stack[-1], mstate.stack[-2]
    try:
        start = get_concrete_int(offset)
        end = get_concrete_int(offset + length)
    except Exception:
        return False
    return_data = []
    for raw_byte in mstate.memory[start:end]:
        if not raw_byte.raw.is_const:
            return False
        return_data.append(raw_byte.raw.value)
    if len(return_data) < 5:
        return False
    return return_data[:4] == PANIC_SIGNATURE and return_data[-1] == 1


class Exceptions(DetectionModule):
    name = "Assertion violation"
    swc_id = ASSERT_VIOLATION
    description = "Check whether an exception is triggered (reachable INVALID " \
                  "or Panic(1) revert)."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["INVALID", "JUMP", "REVERT"]
    # presence-only: a constant invalid JUMP dest is a real assert-style
    # finding, so untainted sites must still run the hook
    taint_sinks = {"INVALID": (), "JUMP": ()}

    def __init__(self):
        super().__init__()
        self.auto_cache = False  # cache is keyed by source location instead

    def _execute(self, state: GlobalState):
        instruction = state.get_current_instruction()
        opcode = instruction["opcode"]

        annotations = list(state.get_annotations(LastJumpAnnotation))
        if not annotations:
            state.annotate(LastJumpAnnotation())
            annotations = list(state.get_annotations(LastJumpAnnotation))

        if opcode == "JUMP":
            annotations[0].last_jump = instruction["address"]
            return []
        if opcode == "REVERT" and not is_assertion_failure(state):
            return []

        source_location = annotations[0].last_jump
        code_hash = get_code_hash(state.environment.code.bytecode)
        if (source_location, code_hash) in self.cache:
            return []

        constraints = state.world_state.constraints.get_all_constraints()
        try:
            transaction_sequence = get_transaction_sequence(state, constraints)
        except UnsatError:
            return []
        issue = Issue(
            contract=state.environment.active_account.contract_name,
            function_name=getattr(state.environment, "active_function_name",
                                  "fallback"),
            address=instruction["address"],
            swc_id=self.swc_id,
            title="Exception State",
            severity="Medium",
            bytecode=state.environment.code.bytecode,
            description_head="An assertion violation was triggered.",
            description_tail=(
                "It is possible to trigger an assertion violation. Note that "
                "Solidity assert() statements should only be used to check "
                "invariants. Review the transaction trace generated for this "
                "issue and either make sure your program logic is correct, or "
                "use require() instead of assert() if your goal is to constrain "
                "user inputs or enforce preconditions."),
            gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
            transaction_sequence=transaction_sequence,
        )
        issue.source_location = source_location
        self.cache.add((source_location, code_hash))
        attach_issue_annotation(state, issue, self, constraints)
        return [issue]
