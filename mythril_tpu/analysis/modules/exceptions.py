"""SWC-110 Assert violation via reachable INVALID/assert-fail (capability parity:
mythril/analysis/module/modules/exceptions.py)."""

from __future__ import annotations

import logging

from ...core.state.global_state import GlobalState
from ...exceptions import UnsatError
from ..module.base import DetectionModule, EntryPoint
from ..report import Issue
from ..solver import get_transaction_sequence
from ..swc_data import ASSERT_VIOLATION

log = logging.getLogger(__name__)


class Exceptions(DetectionModule):
    name = "Assertion violation"
    swc_id = ASSERT_VIOLATION
    description = "Check whether an exception is triggered (reachable INVALID)."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["INVALID"]

    def _execute(self, state: GlobalState):
        instruction = state.get_current_instruction()
        try:
            transaction_sequence = get_transaction_sequence(
                state, state.world_state.constraints.get_all_constraints())
        except UnsatError:
            return []
        return [Issue(
            contract=state.environment.active_account.contract_name,
            function_name=getattr(state.environment, "active_function_name",
                                  "fallback"),
            address=instruction["address"],
            swc_id=self.swc_id,
            title="Exception State",
            severity="Medium",
            bytecode=state.environment.code.bytecode,
            description_head="An assertion violation was triggered.",
            description_tail=(
                "It is possible to trigger an assertion violation. Note that "
                "Solidity assert() statements should only be used to check "
                "invariants. Review the transaction trace generated for this "
                "issue and either make sure your program logic is correct, or "
                "use require() instead of assert() if your goal is to constrain "
                "user inputs or enforce preconditions."),
            gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
            transaction_sequence=transaction_sequence,
        )]
