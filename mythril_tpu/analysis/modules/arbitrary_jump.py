"""SWC-127 Arbitrary jump (capability parity:
mythril/analysis/module/modules/arbitrary_jump.py: JUMP destination is symbolic and
attacker-influenceable)."""

from __future__ import annotations

import logging

from ...core.state.global_state import GlobalState
from ...exceptions import UnsatError
from ...smt.solver import cfa_screen
from ...support.model import get_model
from ..issue_annotation import attach_issue_annotation
from ..module.base import DetectionModule, EntryPoint
from ..report import Issue
from ..solver import get_transaction_sequence
from ..swc_data import ARBITRARY_JUMP

log = logging.getLogger(__name__)


class ArbitraryJump(DetectionModule):
    name = "Caller can redirect execution to arbitrary bytecode locations"
    swc_id = ARBITRARY_JUMP
    description = "Check for jumps to arbitrary locations in the bytecode"
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["JUMP", "JUMPI"]
    # an untainted dest is a deterministic function of the bytecode: on a
    # per-path engine it reaches the hook as a concrete value, and the
    # is_const early-return above fires — skipping the hook is
    # detection-identical, so operand-level screening is sound here
    taint_sinks = {"JUMP": (0,), "JUMPI": (0,)}

    def _execute(self, state: GlobalState):
        jump_dest = state.mstate.stack[-1]
        if jump_dest.raw.is_const:
            return []
        # CFA-resolved site: the dataflow pinned every feasible target
        # statically, so a <=1-target site is structurally not
        # attacker-steerable — skip the two _is_unique_jumpdest solver
        # queries it would otherwise take to prove that
        targets = cfa_screen.resolved_jump_targets(
            state.environment.code,
            state.get_current_instruction()["address"])
        if targets is not None and len(targets) <= 1:
            return []
        if self._is_unique_jumpdest(jump_dest, state):
            # symbolic but pinned to one feasible value: not attacker-steerable
            # (reference arbitrary_jump.py:22-44)
            return []
        constraints = state.world_state.constraints.get_all_constraints()
        try:
            transaction_sequence = get_transaction_sequence(state, constraints)
        except UnsatError:
            return []
        issue = Issue(
            contract=state.environment.active_account.contract_name,
            function_name=getattr(state.environment, "active_function_name",
                                  "fallback"),
            address=state.get_current_instruction()["address"],
            swc_id=self.swc_id,
            title="Jump to an arbitrary instruction",
            severity="High",
            bytecode=state.environment.code.bytecode,
            description_head="The caller can redirect execution to arbitrary "
                             "bytecode locations.",
            description_tail=(
                "It is possible to redirect the control flow to arbitrary "
                "locations in the code. This may allow an attacker to bypass "
                "security controls or manipulate the business logic of the "
                "smart contract. Avoid using low-level-operations and "
                "assembly to prevent this issue."),
            gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
            transaction_sequence=transaction_sequence,
        )
        attach_issue_annotation(state, issue, self, constraints)
        return [issue]

    @staticmethod
    def _is_unique_jumpdest(jump_dest, state: GlobalState) -> bool:
        """True when the symbolic destination admits exactly one model."""
        try:
            model = get_model(tuple(
                state.world_state.constraints.get_all_constraints()))
            concrete_dest = model.eval(jump_dest.raw)
            get_model(tuple(
                state.world_state.constraints.get_all_constraints()
                + [jump_dest != concrete_dest]))
        except UnsatError:
            return True  # no second value exists
        except Exception:
            return True  # solver timeout: do not report on uncertainty
        return False
