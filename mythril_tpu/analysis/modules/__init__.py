from .suicide import AccidentallyKillable
from .ether_thief import EtherThief
from .external_calls import ExternalCalls
from .dependence_on_origin import TxOrigin
from .dependence_on_predictable_vars import PredictableVariables
from .delegatecall import ArbitraryDelegateCall
from .arbitrary_jump import ArbitraryJump
from .arbitrary_write import ArbitraryStorage
from .exceptions import Exceptions
from .integer import IntegerArithmetics
from .multiple_sends import MultipleSends
from .requirements_violation import RequirementsViolation
from .state_change_external_calls import StateChangeAfterCall
from .transaction_order_dependence import TxOrderDependence
from .unchecked_retval import UncheckedRetval
from .unexpected_ether import UnexpectedEther
from .user_assertions import UserAssertions
from .ether_phishing import EtherPhishing

__all__ = [
    "AccidentallyKillable", "EtherThief", "ExternalCalls", "TxOrigin",
    "PredictableVariables", "ArbitraryDelegateCall", "ArbitraryJump",
    "ArbitraryStorage", "Exceptions", "IntegerArithmetics", "MultipleSends",
    "RequirementsViolation", "StateChangeAfterCall", "TxOrderDependence",
    "UncheckedRetval", "UnexpectedEther", "UserAssertions", "EtherPhishing",
]
