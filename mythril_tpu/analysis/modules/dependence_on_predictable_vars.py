"""SWC-116/120 block-value dependence (capability parity:
mythril/analysis/module/modules/dependence_on_predictable_vars.py: TIMESTAMP /
NUMBER / PREVRANDAO / COINBASE / GASLIMIT values influencing control flow ahead of
an ether transfer, and BLOCKHASH of a predictable block)."""

from __future__ import annotations

import logging

from ...core.state.global_state import GlobalState
from ...exceptions import UnsatError
from ..module.base import DetectionModule, EntryPoint
from ..report import Issue
from ..solver import get_transaction_sequence
from ..swc_data import TIMESTAMP_DEPENDENCE, WEAK_RANDOMNESS

log = logging.getLogger(__name__)

PREDICTABLE_OPS = ["TIMESTAMP", "NUMBER", "COINBASE", "GASLIMIT", "PREVRANDAO",
                   "DIFFICULTY"]


class PredictableValueAnnotation:
    def __init__(self, operation: str):
        self.operation = operation


class PredictablePathAnnotation:
    """State annotation: control flow already branched on a predictable value."""

    def __init__(self, operation: str, location: int):
        self.operation = operation
        self.location = location

    def __copy__(self):
        return PredictablePathAnnotation(self.operation, self.location)


class PredictableVariables(DetectionModule):
    name = "Control flow depends on a predictable environment variable"
    swc_id = f"{TIMESTAMP_DEPENDENCE}, {WEAK_RANDOMNESS}"
    description = ("Check whether control flow decisions are influenced by block "
                   "attributes (block.number, block.timestamp, block.prevrandao, "
                   "coinbase, gaslimit) or blockhash.")
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["JUMPI", "BLOCKHASH", "CALL"]
    post_hooks = PREDICTABLE_OPS

    def _execute(self, state: GlobalState):
        instruction = state.get_current_instruction()
        opcode = instruction["opcode"]
        if opcode not in ("JUMPI", "CALL", "BLOCKHASH"):
            # post-hook on a block-value op (fires on the successor state):
            # the producing instruction is the previous one
            producer = state.environment.code.instruction_list[
                state.mstate.pc - 1].op_code
            operation = "block.timestamp" if producer == "TIMESTAMP" else \
                f"block.{producer.lower()}"
            state.mstate.stack[-1].annotate(PredictableValueAnnotation(operation))
            return []

        if opcode == "BLOCKHASH":
            # pre-hook: blockhash of a predictable block is weak randomness
            state.mstate.stack[-1].annotate(
                PredictableValueAnnotation("blockhash"))
            return []

        if opcode == "JUMPI":
            condition = state.mstate.stack[-2]
            markers = [annotation for annotation in condition.annotations
                       if isinstance(annotation, PredictableValueAnnotation)]
            if markers:
                state.annotate(PredictablePathAnnotation(
                    markers[0].operation, instruction["address"]))
            return []

        # CALL with value, on a path that branched on a predictable value
        annotations = [a for a in state.annotations
                       if isinstance(a, PredictablePathAnnotation)]
        if not annotations:
            return []
        try:
            transaction_sequence = get_transaction_sequence(
                state, state.world_state.constraints.get_all_constraints())
        except UnsatError:
            return []
        operation = annotations[0].operation
        swc_id = TIMESTAMP_DEPENDENCE if "timestamp" in operation else WEAK_RANDOMNESS
        return [Issue(
            contract=state.environment.active_account.contract_name,
            function_name=getattr(state.environment, "active_function_name",
                                  "fallback"),
            address=annotations[0].location,
            swc_id=swc_id,
            bytecode=state.environment.code.bytecode,
            title="Dependence on predictable environment variable",
            severity="Low",
            description_head=f"A control flow decision is made based on "
                             f"{operation}.",
            description_tail=(
                f"The {operation} environment variable is used to determine a "
                "control flow decision ahead of an ether transfer. Note that the "
                "values of variables like coinbase, gaslimit, block number and "
                "timestamp are predictable and can be manipulated by a malicious "
                "miner. Don't use them for random number generation or to make "
                "critical control flow decisions."),
            gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
            transaction_sequence=transaction_sequence,
        )]
