"""SWC-116/120 block-value dependence (capability parity:
mythril/analysis/module/modules/dependence_on_predictable_vars.py: TIMESTAMP /
NUMBER / PREVRANDAO / COINBASE / GASLIMIT values influencing a control flow
decision, and BLOCKHASH of a predictable (older) block number)."""

from __future__ import annotations

import logging

from ...core.state.annotation import StateAnnotation
from ...core.state.global_state import GlobalState
from ...exceptions import UnsatError
from ...smt import ULT, symbol_factory
from ...support.model import get_model
from ..issue_annotation import attach_issue_annotation
from ..module.base import DetectionModule, EntryPoint
from ..report import Issue
from ..solver import get_transaction_sequence
from ..swc_data import TIMESTAMP_DEPENDENCE, WEAK_RANDOMNESS

log = logging.getLogger(__name__)

PREDICTABLE_OPS = ["TIMESTAMP", "NUMBER", "COINBASE", "GASLIMIT", "PREVRANDAO",
                   "DIFFICULTY"]


class PredictableValueAnnotation:
    """Expression marker: value derives from a predictable block attribute."""

    def __init__(self, operation: str):
        self.operation = operation


class OldBlockNumberUsedAnnotation(StateAnnotation):
    """State marker: BLOCKHASH was invoked with a provably older block number
    (reference dependence_on_predictable_vars.py:40)."""

    def __copy__(self):
        return OldBlockNumberUsedAnnotation()


class PredictableVariables(DetectionModule):
    name = "Control flow depends on a predictable environment variable"
    swc_id = f"{TIMESTAMP_DEPENDENCE}, {WEAK_RANDOMNESS}"
    description = ("Check whether control flow decisions are influenced by block "
                   "attributes (block.number, block.timestamp, block.prevrandao, "
                   "coinbase, gaslimit) or blockhash.")
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["JUMPI", "BLOCKHASH"]
    post_hooks = PREDICTABLE_OPS + ["BLOCKHASH"]
    taint_sinks = {"BLOCKHASH": (), "JUMPI": ()}

    def _execute(self, state: GlobalState):
        instruction = state.get_current_instruction()
        opcode = instruction["opcode"]

        if opcode == "JUMPI":
            # pre-hook: report every predictable value feeding the condition
            issues = []
            for marker in [a for a in state.mstate.stack[-2].annotations
                           if isinstance(a, PredictableValueAnnotation)]:
                constraints = state.world_state.constraints.get_all_constraints()
                try:
                    transaction_sequence = get_transaction_sequence(
                        state, constraints)
                except UnsatError:
                    continue
                operation = marker.operation
                swc_id = (TIMESTAMP_DEPENDENCE if "timestamp" in operation
                          else WEAK_RANDOMNESS)
                issue = Issue(
                    contract=state.environment.active_account.contract_name,
                    function_name=getattr(state.environment,
                                          "active_function_name", "fallback"),
                    address=instruction["address"],
                    swc_id=swc_id,
                    bytecode=state.environment.code.bytecode,
                    title="Dependence on predictable environment variable",
                    severity="Low",
                    description_head=f"A control flow decision is made based "
                                     f"on {operation}.",
                    description_tail=(
                        f"{operation} is used to determine a control flow "
                        "decision. Note that the values of variables like "
                        "coinbase, gaslimit, block number and timestamp are "
                        "predictable and can be manipulated by a malicious "
                        "miner. Also keep in mind that attackers know hashes "
                        "of earlier blocks. Don't use any of those environment "
                        "variables as sources of randomness and be aware that "
                        "use of these variables introduces a certain level of "
                        "trust into miners."),
                    gas_used=(state.mstate.min_gas_used,
                              state.mstate.max_gas_used),
                    transaction_sequence=transaction_sequence,
                )
                attach_issue_annotation(state, issue, self, constraints)
                issues.append(issue)
            return issues

        if opcode == "BLOCKHASH":
            # pre-hook: can the argument be an OLDER block number?
            param = state.mstate.stack[-1]
            block_number = state.environment.block_number
            try:
                get_model(tuple(
                    state.world_state.constraints.get_all_constraints() + [
                        ULT(param, block_number),
                        # bound so the comparison cannot be satisfied by wrap
                        ULT(block_number,
                            symbol_factory.BitVecVal(2 ** 255, 256)),
                    ]))
                state.annotate(OldBlockNumberUsedAnnotation())
            except Exception:
                pass
            return []

        # post-hooks (successor state): the producing instruction is previous
        producer = state.environment.code.instruction_list[
            state.mstate.pc - 1].op_code
        if producer == "BLOCKHASH":
            if list(state.get_annotations(OldBlockNumberUsedAnnotation)):
                state.mstate.stack[-1].annotate(PredictableValueAnnotation(
                    "The block hash of a previous block"))
            return []
        operation = ("block.timestamp" if producer == "TIMESTAMP"
                     else f"block.{producer.lower()}")
        state.mstate.stack[-1].annotate(PredictableValueAnnotation(operation))
        return []
