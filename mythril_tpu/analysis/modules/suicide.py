"""SWC-106 Unprotected SELFDESTRUCT (capability parity:
mythril/analysis/module/modules/suicide.py — constrain the kill to be triggerable
by an arbitrary attacker, with optional beneficiary==attacker strengthening)."""

from __future__ import annotations

import logging

from ...core.state.global_state import GlobalState
from ...core.transaction.symbolic import ACTORS
from ...core.transaction.transaction_models import ContractCreationTransaction
from ...exceptions import UnsatError
from ...smt import And
from ..issue_annotation import attach_issue_annotation
from ..module.base import DetectionModule, EntryPoint
from ..report import Issue
from ..solver import get_transaction_sequence
from ..swc_data import UNPROTECTED_SELFDESTRUCT

log = logging.getLogger(__name__)


class AccidentallyKillable(DetectionModule):
    name = "Contract can be accidentally killed by anyone"
    swc_id = UNPROTECTED_SELFDESTRUCT
    description = ("Check if the contact can be 'accidentally' killed by anyone. "
                   "For kill-able contracts, also check whether it is possible to "
                   "direct the contract balance to the attacker.")
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["SELFDESTRUCT"]
    taint_sinks = {"SELFDESTRUCT": ()}

    def _execute(self, state: GlobalState):
        instruction = state.get_current_instruction()
        to = state.mstate.stack[-1]

        log.debug("SELFDESTRUCT found at pc %d", instruction["address"])

        # Only attacker-triggerable kills count: every tx in the sequence must be
        # sendable by the attacker directly — caller == origin suppresses
        # contract-mediated false positives (reference suicide.py:66-69).
        attacker_constraints = []
        for transaction in state.world_state.transaction_sequence:
            if not isinstance(transaction, ContractCreationTransaction):
                attacker_constraints.append(And(
                    transaction.caller == ACTORS.attacker,
                    transaction.caller == transaction.origin))
        base = state.world_state.constraints.get_all_constraints()

        description_head = "Any sender can cause the contract to self-destruct."
        try:
            try:
                constraints = base + attacker_constraints + [to == ACTORS.attacker]
                transaction_sequence = get_transaction_sequence(
                    state, constraints)
                description_tail = (
                    "Any sender can trigger execution of the SELFDESTRUCT "
                    "instruction to destroy this contract account and withdraw "
                    "its balance to an arbitrary address. Review the transaction "
                    "trace generated for this issue and make sure that "
                    "appropriate security controls are in place to prevent "
                    "unrestricted access.")
            except UnsatError:
                constraints = base + attacker_constraints
                transaction_sequence = get_transaction_sequence(
                    state, constraints)
                description_tail = (
                    "Any sender can trigger execution of the SELFDESTRUCT "
                    "instruction to destroy this contract account. Review the "
                    "transaction trace generated for this issue and make sure "
                    "that appropriate security controls are in place to prevent "
                    "unrestricted access.")
            issue = Issue(
                contract=state.environment.active_account.contract_name,
                function_name=getattr(state.environment, "active_function_name",
                                      "fallback"),
                address=instruction["address"],
                swc_id=self.swc_id,
                bytecode=state.environment.code.bytecode,
                title="Unprotected Selfdestruct",
                severity="High",
                description_head=description_head,
                description_tail=description_tail,
                transaction_sequence=transaction_sequence,
                gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
            )
            attach_issue_annotation(state, issue, self, constraints)
            return [issue]
        except UnsatError:
            log.debug("no model found for killable path")
        return []
