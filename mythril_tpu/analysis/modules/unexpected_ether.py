"""SWC-132 Unexpected ether balance (capability parity:
mythril/analysis/module/modules/unexpected_ether.py: strict balance equality used
in a control-flow decision — breakable by force-feeding ether via selfdestruct)."""

from __future__ import annotations

import logging

from ...core.state.annotation import StateAnnotation
from ...core.state.global_state import GlobalState
from ...exceptions import UnsatError
from ..issue_annotation import attach_issue_annotation
from ..module.base import DetectionModule, EntryPoint
from ..report import Issue
from ..solver import get_transaction_sequence
from ..swc_data import UNEXPECTED_ETHER_BALANCE

log = logging.getLogger(__name__)


class BalanceAnnotation:
    """Marker on values derived from SELFBALANCE/BALANCE(this)."""


class UnexpectedEther(DetectionModule):
    name = "Contract behavior depends on its exact balance"
    swc_id = UNEXPECTED_ETHER_BALANCE
    description = ("Check for strict comparisons on the contract's own balance "
                   "(breakable by force-feeding ether).")
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["JUMPI"]
    post_hooks = ["BALANCE", "SELFBALANCE"]
    taint_sinks = {"BALANCE": (), "SELFBALANCE": ()}

    def _execute(self, state: GlobalState):
        instruction = state.get_current_instruction()
        if instruction["opcode"] != "JUMPI":
            # BALANCE/SELFBALANCE post-hook (successor state): taint pushed value
            state.mstate.stack[-1].annotate(BalanceAnnotation())
            return []

        condition = state.mstate.stack[-2]
        if not any(isinstance(a, BalanceAnnotation)
                   for a in condition.annotations):
            return []
        # strict equality on balance: an eq term over a balance-tainted value
        if not _contains_strict_equality(condition):
            return []
        constraints = state.world_state.constraints.get_all_constraints()
        try:
            transaction_sequence = get_transaction_sequence(state, constraints)
        except UnsatError:
            return []
        issue = Issue(
            contract=state.environment.active_account.contract_name,
            function_name=getattr(state.environment, "active_function_name",
                                  "fallback"),
            address=instruction["address"],
            swc_id=self.swc_id,
            bytecode=state.environment.code.bytecode,
            title="Dependence on the exact contract balance",
            severity="Medium",
            description_head="The contract's behavior depends on its exact "
                             "Ether balance.",
            description_tail=(
                "A control flow decision depends on a strict comparison with "
                "the contract's own balance. Since Ether can be forcibly sent "
                "to any contract (e.g. via selfdestruct or as a coinbase "
                "reward), strict equality checks on the balance can be broken "
                "by an attacker, potentially locking the contract's logic."),
            gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
            transaction_sequence=transaction_sequence,
        )
        attach_issue_annotation(state, issue, self, constraints)
        return [issue]


def _contains_strict_equality(condition) -> bool:
    from ...smt import terms

    for node in terms.walk(condition.raw):
        if node.op == "eq" and isinstance(node.args[0].sort, int):
            return True
    return False
