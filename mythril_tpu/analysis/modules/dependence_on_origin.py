"""SWC-115 tx.origin authorization (capability parity:
mythril/analysis/module/modules/dependence_on_origin.py: ORIGIN value flowing into
a JUMPI condition — traced through expression taint annotations)."""

from __future__ import annotations

import logging

from ...core.state.global_state import GlobalState
from ...exceptions import UnsatError
from ..issue_annotation import attach_issue_annotation
from ..module.base import DetectionModule, EntryPoint
from ..report import Issue
from ..solver import get_transaction_sequence
from ..swc_data import TX_ORIGIN_USAGE

log = logging.getLogger(__name__)


class OriginAnnotation:
    """Taint marker placed on the ORIGIN value."""


class TxOrigin(DetectionModule):
    name = "Control flow depends on tx.origin"
    swc_id = TX_ORIGIN_USAGE
    description = ("Check whether control flow decisions are influenced by "
                   "tx.origin.")
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["JUMPI"]
    post_hooks = ["ORIGIN"]
    taint_sinks = {"ORIGIN": ()}

    def _execute(self, state: GlobalState):
        instruction = state.get_current_instruction()
        if instruction["opcode"] != "JUMPI":
            # ORIGIN post-hook (fires on the successor state): taint the pushed value
            state.mstate.stack[-1].annotate(OriginAnnotation())
            return []

        # JUMPI pre-hook: condition is the second stack item
        condition = state.mstate.stack[-2]
        if not any(isinstance(annotation, OriginAnnotation)
                   for annotation in condition.annotations):
            return []
        constraints = state.world_state.constraints.get_all_constraints()
        try:
            transaction_sequence = get_transaction_sequence(state, constraints)
        except UnsatError:
            return []
        issue = Issue(
            contract=state.environment.active_account.contract_name,
            function_name=getattr(state.environment, "active_function_name",
                                  "fallback"),
            address=instruction["address"],
            swc_id=self.swc_id,
            bytecode=state.environment.code.bytecode,
            title="Dependence on tx.origin",
            severity="Low",
            description_head="Use of tx.origin as a part of authorization control.",
            description_tail=(
                "The tx.origin environment variable has been found to influence "
                "a control flow decision. Note that using tx.origin as a security "
                "control might cause a vulnerability where a malicious contract "
                "can trick users into performing sensitive actions. Consider "
                "using msg.sender instead."),
            gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
            transaction_sequence=transaction_sequence,
        )
        attach_issue_annotation(state, issue, self, constraints)
        return [issue]
