"""SWC-123 Requirement violation (capability parity:
mythril/analysis/module/modules/requirements_violation.py: a nested call reverts
on a require() whose condition is fed by the calling contract)."""

from __future__ import annotations

import logging

from ...core.state.global_state import GlobalState
from ...exceptions import UnsatError
from ..issue_annotation import attach_issue_annotation
from ..module.base import DetectionModule, EntryPoint
from ..report import Issue
from ..solver import get_transaction_sequence
from ..swc_data import REQUIREMENT_VIOLATION

log = logging.getLogger(__name__)


class RequirementsViolation(DetectionModule):
    name = "Requirement violation in a nested call"
    swc_id = REQUIREMENT_VIOLATION
    description = ("Check whether a nested message call reverts due to a "
                   "require() over caller-provided inputs.")
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["REVERT"]
    taint_sinks = {"REVERT": ()}

    def _execute(self, state: GlobalState):
        # only reverts inside a NESTED frame qualify (the calling contract
        # passed inputs that violate the callee's requirement)
        if len(state.transaction_stack) < 2:
            return []
        constraints = state.world_state.constraints.get_all_constraints()
        try:
            transaction_sequence = get_transaction_sequence(state, constraints)
        except UnsatError:
            return []
        issue = Issue(
            contract=state.environment.active_account.contract_name,
            function_name=getattr(state.environment, "active_function_name",
                                  "fallback"),
            address=state.get_current_instruction()["address"],
            swc_id=self.swc_id,
            bytecode=state.environment.code.bytecode,
            title="Requirement Violation",
            severity="Medium",
            description_head="A requirement was violated in a nested call and "
                             "the call was reverted as a result.",
            description_tail=(
                "Make sure valid inputs are provided to the nested call (for "
                "instance, via passed arguments). A reachable requirement "
                "failure in a callee signals that the caller can provide "
                "arguments that violate the callee's preconditions."),
            gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
            transaction_sequence=transaction_sequence,
        )
        attach_issue_annotation(state, issue, self, constraints)
        return [issue]
