"""SWC-101 Integer overflow/underflow (capability parity:
mythril/analysis/module/modules/integer.py).

Mechanism (value-flow precise, as in the reference): source handlers annotate an
operand wrapper with the overflow condition — annotation union through every
subsequent operation carries the marker to all derived values. Sink handlers
(SSTORE value, JUMPI condition, CALL value, RETURNed memory) harvest markers from
the value that actually reaches them into a state-level annotation; at transaction
end each harvested overflow condition is solved together with the final path
constraints and surviving ones become Issues anchored at the arithmetic site."""

from __future__ import annotations

import logging
from typing import List, Set

from ...core.state.annotation import StateAnnotation
from ...core.state.global_state import GlobalState
from ...exceptions import UnsatError
from ...smt import (BVAddNoOverflow, BVMulNoOverflow, BVSubNoUnderflow,
                    Expression, Not, UGT, symbol_factory)
from ...support.model import get_model
from ..module.base import DetectionModule, EntryPoint
from ..report import Issue
from ..issue_annotation import attach_issue_annotation
from ..solver import get_transaction_sequence
from ..swc_data import INTEGER_OVERFLOW_AND_UNDERFLOW

log = logging.getLogger(__name__)


class OverUnderflowAnnotation:
    """Rides on expression wrappers from the arithmetic site to the sinks."""

    __slots__ = ("overflowing_state", "operator", "constraint")

    def __init__(self, overflowing_state: GlobalState, operator: str, constraint):
        self.overflowing_state = overflowing_state
        self.operator = operator
        self.constraint = constraint

    def __deepcopy__(self, memo):
        return self


class OverUnderflowStateAnnotation(StateAnnotation):
    """State-level set of markers whose values reached a sink on this path."""

    def __init__(self):
        self.overflowing_state_annotations: Set[OverUnderflowAnnotation] = set()

    def __copy__(self):
        result = OverUnderflowStateAnnotation()
        result.overflowing_state_annotations = set(
            self.overflowing_state_annotations)
        return result


def _get_state_annotation(state: GlobalState) -> OverUnderflowStateAnnotation:
    for annotation in state.annotations:
        if isinstance(annotation, OverUnderflowStateAnnotation):
            return annotation
    annotation = OverUnderflowStateAnnotation()
    state.annotate(annotation)
    return annotation


class IntegerArithmetics(DetectionModule):
    name = "Integer overflow or underflow"
    swc_id = INTEGER_OVERFLOW_AND_UNDERFLOW
    description = ("For every potential overflow/underflow in ADD/SUB/MUL/EXP, "
                   "check whether the corrupted value reaches a sink "
                   "(storage write, branch, call value, return data).")
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["ADD", "SUB", "MUL", "EXP", "SSTORE", "JUMPI", "CALL",
                 "RETURN", "STOP"]
    taint_sinks = {"ADD": (), "SUB": (), "MUL": (), "EXP": ()}

    def __init__(self):
        super().__init__()
        self._ostates_satisfiable: Set[int] = set()
        self._ostates_unsatisfiable: Set[int] = set()

    def reset_module(self):
        super().reset_module()
        self._ostates_satisfiable = set()
        self._ostates_unsatisfiable = set()

    def _execute(self, state: GlobalState) -> List[Issue]:
        opcode = state.get_current_instruction()["opcode"]
        handlers = {
            "ADD": [self._handle_add],
            "SUB": [self._handle_sub],
            "MUL": [self._handle_mul],
            "EXP": [self._handle_exp],
            "SSTORE": [self._handle_sstore],
            "JUMPI": [self._handle_jumpi],
            "CALL": [self._handle_call],
            "RETURN": [self._handle_return, self._handle_transaction_end],
            "STOP": [self._handle_transaction_end],
        }
        issues: List[Issue] = []
        for handler in handlers[opcode]:
            result = handler(state)
            if result:
                issues.extend(result)
        return issues

    # -- sources: annotate an operand so the marker propagates to the result --------
    @staticmethod
    def _operands(state: GlobalState):
        return state.mstate.stack[-1], state.mstate.stack[-2]

    def _annotate_operand(self, state, operand, operator, condition) -> None:
        operand.annotate(OverUnderflowAnnotation(state, operator, condition))

    def _handle_add(self, state: GlobalState):
        a, b = self._operands(state)
        if a.raw.is_const and b.raw.is_const:
            return
        self._annotate_operand(state, a, "addition",
                               Not(BVAddNoOverflow(a, b, False)))

    def _handle_sub(self, state: GlobalState):
        a, b = self._operands(state)
        if a.raw.is_const and b.raw.is_const:
            return
        self._annotate_operand(state, a, "subtraction",
                               Not(BVSubNoUnderflow(a, b, False)))

    def _handle_mul(self, state: GlobalState):
        a, b = self._operands(state)
        if a.raw.is_const and b.raw.is_const:
            return
        if (a.raw.is_const and a.value < 2) or (b.raw.is_const and b.value < 2):
            return
        self._annotate_operand(state, a, "multiplication",
                               Not(BVMulNoOverflow(a, b, False)))

    def _handle_exp(self, state: GlobalState):
        base, exponent = self._operands(state)
        if base.raw.is_const and exponent.raw.is_const:
            return
        if base.raw.is_const and base.value < 2:
            return
        self._annotate_operand(state, base, "exponentiation",
                               UGT(exponent, symbol_factory.BitVecVal(255, 256)))

    # -- sinks: harvest markers from the value that reaches them --------------------
    @staticmethod
    def _harvest(state: GlobalState, value) -> None:
        if not isinstance(value, Expression):
            return
        container = _get_state_annotation(state)
        for annotation in value.annotations:
            if isinstance(annotation, OverUnderflowAnnotation):
                container.overflowing_state_annotations.add(annotation)

    def _handle_sstore(self, state: GlobalState):
        self._harvest(state, state.mstate.stack[-2])

    def _handle_jumpi(self, state: GlobalState):
        self._harvest(state, state.mstate.stack[-2])

    def _handle_call(self, state: GlobalState):
        self._harvest(state, state.mstate.stack[-3])

    def _handle_return(self, state: GlobalState):
        offset, length = state.mstate.stack[-1], state.mstate.stack[-2]
        if not (offset.raw.is_const and length.raw.is_const):
            return
        for element in state.mstate.memory[
                offset.value:offset.value + min(length.value, 320)]:
            self._harvest(state, element)

    # -- resolution at transaction end ----------------------------------------------

    def _handle_transaction_end(self, state: GlobalState) -> List[Issue]:
        issues: List[Issue] = []
        container = _get_state_annotation(state)
        for annotation in container.overflowing_state_annotations:
            ostate = annotation.overflowing_state
            ostate_key = id(ostate)
            if ostate_key in self._ostates_unsatisfiable:
                continue
            if ostate_key not in self._ostates_satisfiable:
                try:
                    get_model(tuple(
                        ostate.world_state.constraints.get_all_constraints()
                        + [annotation.constraint]))
                    self._ostates_satisfiable.add(ostate_key)
                except Exception:
                    self._ostates_unsatisfiable.add(ostate_key)
                    continue
            constraints = (state.world_state.constraints.get_all_constraints()
                           + [annotation.constraint])
            try:
                transaction_sequence = get_transaction_sequence(
                    state, constraints)
            except UnsatError:
                continue
            issue = Issue(
                contract=ostate.environment.active_account.contract_name,
                function_name=getattr(ostate.environment,
                                      "active_function_name", "fallback"),
                address=ostate.get_current_instruction()["address"],
                swc_id=self.swc_id,
                bytecode=ostate.environment.code.bytecode,
                title="Integer Arithmetic Bugs",
                severity="High",
                description_head="The arithmetic operator can {}.".format(
                    "underflow" if annotation.operator == "subtraction"
                    else "overflow"),
                description_tail=(
                    "It is possible to cause an integer overflow or underflow "
                    "in the arithmetic operation. Prevent this by constraining "
                    "inputs using the require() statement or use checked "
                    "arithmetic (Solidity >= 0.8 / SafeMath). Refer to the "
                    "transaction trace generated for this issue to reproduce "
                    "it."),
                gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
                transaction_sequence=transaction_sequence,
            )
            attach_issue_annotation(state, issue, self, constraints)
            issues.append(issue)
        return issues


def harvest_values(state, values) -> None:
    """Harvest OverUnderflowAnnotations from `values` into `state`'s
    container — the device frontier's stand-in for the SSTORE/JUMPI sink
    pre-hooks on instructions it executed in the fused loop
    (parallel/frontier.py materialization). Delegates to the module's own
    sink rule so the two paths cannot diverge."""
    for value in values:
        IntegerArithmetics._harvest(state, value)
