"""Witness extraction: concrete exploit transaction sequences
(capability parity: mythril/analysis/solver.py — get_transaction_sequence:54,
_set_minimisation_constraints:219, _get_concrete_transaction:187,
_replace_with_actual_sha:131).

Produces the `initialState` + `steps` dict printed in reports, with calldatasize /
call-value minimization via the Optimize backend and keccak back-substitution so
witness calldata contains real hashes."""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from ..core.function_managers import keccak_function_manager
from ..utils.keccak import keccak256
from ..core.state.world_state import WorldState
from ..core.transaction.transaction_models import (BaseTransaction,
                                                   ContractCreationTransaction)
from ..core.transaction.symbolic import ACTORS
from ..exceptions import UnsatError
from ..smt import Bool, UGE, ULE, symbol_factory, terms
from ..support.model import get_model, prefetch_models

log = logging.getLogger(__name__)


def pretty_print_model(model) -> str:
    out = ""
    for item in model.decls():
        out += f"%s: %s\n" % (item.name, model.assignment[item])
    return out


def get_transaction_sequence(global_state, constraints) -> Dict:
    """Generate concrete transaction sequence satisfying `constraints`.

    Raises UnsatError if no valid transaction sequence exists."""
    transaction_sequence = global_state.world_state.transaction_sequence
    concrete_transactions: List[Dict] = []

    tx_constraints, minimize = _set_minimisation_constraints(
        transaction_sequence, list(constraints), [], 5000, global_state.world_state)

    # issue-confirmation prefetch (`--solver jax` + batching, no-op
    # otherwise): queue the base feasibility query together with the
    # Optimize extreme-probe ladder (every minimized objective pinned to 0
    # — the overwhelmingly common witness) so the whole confirmation
    # sequence solves as one device batch instead of a launch per probe
    speculative = [tuple(tx_constraints)]
    pinned = []
    for objective in minimize:
        raw = objective.raw if hasattr(objective, "raw") else objective
        pinned.append(Bool(terms.bv_cmp(
            "eq", raw, terms.bv_const(0, raw.width))))
        speculative.append(tuple(tx_constraints) + tuple(pinned))
    prefetch_models(speculative)

    try:
        model = get_model(tuple(tx_constraints), minimize=tuple(minimize))
    except UnsatError:
        raise

    # initial balances of involved accounts under the model
    initial_accounts = {}
    for address, account in global_state.world_state.accounts.items():
        try:
            balance_value = model.eval(
                global_state.world_state.starting_balances[account.address])
        except Exception:
            balance_value = 0
        initial_accounts["0x{:040x}".format(address)] = {
            "nonce": account.nonce,
            "code": "0x" + account.serialised_code(),
            "storage": {},
            "balance": hex(balance_value),
        }

    for transaction in transaction_sequence:
        concrete_transactions.append(
            _get_concrete_transaction(model, transaction))
    _replace_with_actual_sha(concrete_transactions, model)

    steps = {"initialState": {"accounts": initial_accounts},
             "steps": concrete_transactions}
    return steps


def _replace_with_actual_sha(concrete_transactions: List[Dict], model) -> None:
    """Patch solver-chosen hash values in witness calldata with real keccaks
    (reference analysis/solver.py:131).

    The owned solver picks a value for each symbolic keccak application that
    satisfies the interval axioms but is not the real digest; wherever that
    placeholder word appears in the witness calldata, substitute
    keccak256(model(input)) so replaying the witness on a real EVM matches."""
    substitutions: Dict[str, str] = {}
    for hash_expr, input_expr in keccak_function_manager.quick_inverse.items():
        # completion OFF: when the word-level simplifier eliminates a keccak
        # application from the final query, neither the hash nor its input is
        # constrained in the model — completion would evaluate both to 0 and
        # the all-zeros "placeholder" would string-replace every run of
        # zero-padding in the calldata
        try:
            placeholder_value = model.eval(hash_expr, model_completion=False)
            input_value = model.eval(input_expr, model_completion=False)
        except Exception:
            continue
        if placeholder_value is None or input_value is None:
            continue
        width = input_expr.size()
        real = int.from_bytes(
            keccak256(input_value.to_bytes(width // 8, "big")), "big")
        if real == placeholder_value:
            continue
        substitutions["{:064x}".format(placeholder_value)] = \
            "{:064x}".format(real)
    if not substitutions:
        return
    for transaction in concrete_transactions:
        input_hex = transaction["input"][2:]
        for placeholder, real_hex in substitutions.items():
            input_hex = input_hex.replace(placeholder, real_hex)
        transaction["input"] = "0x" + input_hex
        transaction["calldata"] = transaction["input"]


def _get_concrete_transaction(model, transaction: BaseTransaction) -> Dict:
    """Concretize one transaction under the model (reference solver.py:187)."""
    if isinstance(transaction, ContractCreationTransaction):
        code = transaction.code.bytecode if transaction.code else ""
        # constructor ARGUMENTS follow the code (reference solver.py:195-204
        # appends call_data.concrete(model)); the symbolic creation calldata
        # models args at offset 0
        try:
            arg_bytes = transaction.call_data.concrete(model)
        except (AttributeError, TypeError) as error:
            log.warning(
                "constructor-argument concretization failed (%s: %s); "
                "emitting creation witness without args — it may not "
                "reproduce", type(error).__name__, error)
            arg_bytes = []
        args_hex = "".join("{:02x}".format(b if isinstance(b, int) else 0)
                           for b in (arg_bytes or [])[:0x200])
        code = code + args_hex
        return {
            "address": "",
            "input": "0x" + code,
            "origin": _concrete_address(model, transaction.caller),
            "name": "unknown",
            "value": hex(_eval(model, transaction.call_value)),
            "gasLimit": hex(transaction.gas_limit or 8000000),
            "gasPrice": hex(_eval(model, transaction.gas_price)),
            "calldata": "0x" + code,
        }
    calldata = bytes(transaction.call_data.concrete(model))
    address = transaction.callee_account.address
    return {
        "address": "0x{:040x}".format(address.raw.value)
        if address.raw.is_const else str(address),
        "input": "0x" + calldata.hex(),
        "origin": _concrete_address(model, transaction.caller),
        "name": "unknown",
        "value": hex(_eval(model, transaction.call_value)),
        "gasLimit": hex(transaction.gas_limit or 8000000),
        "gasPrice": hex(_eval(model, transaction.gas_price)),
        "calldata": "0x" + calldata.hex(),
    }


def _eval(model, expression) -> int:
    try:
        return model.eval(expression)
    except Exception:
        return 0


def _concrete_address(model, address_expression) -> str:
    value = _eval(model, address_expression)
    return "0x{:040x}".format(value)


def _set_minimisation_constraints(transaction_sequence, constraints, minimize,
                                  max_size: int, world_state: WorldState):
    """Bound balances, prefer short calldata and small call values
    (reference solver.py:219)."""
    for transaction in transaction_sequence:
        if isinstance(transaction, ContractCreationTransaction):
            # creation calldatasize is PINNED to code + 0x200 arg space by
            # codesize_ (instructions.py) — bounding it to max_size would
            # make every witness query unsat for creation code > ~4.5 KB
            minimize.append(transaction.call_value)
            continue
        # bound calldata size so witnesses stay printable
        constraints.append(
            ULE(transaction.call_data.calldatasize,
                symbol_factory.BitVecVal(max_size, 256)))
        minimize.append(transaction.call_data.calldatasize)
        minimize.append(transaction.call_value)
    # attacker's starting balance is bounded (no magic riches)
    constraints.append(
        ULE(world_state.starting_balances[ACTORS.attacker],
            symbol_factory.BitVecVal(10 ** 20, 256)))
    return constraints, minimize
