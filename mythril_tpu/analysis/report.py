"""Issues and reports in four output formats (capability parity:
mythril/analysis/report.py — Issue:29 with source mapping + function-name
resolution, Report:262 with as_text/as_json/as_swc_standard_format/as_markdown).

Templates are generated in code rather than jinja2 (no template dependency)."""

from __future__ import annotations

import hashlib
import json
import logging
from typing import Dict, List, Optional

from ..support.signatures import SignatureDB
from ..utils.helpers import get_code_hash
from .swc_data import SWC_TO_TITLE

log = logging.getLogger(__name__)


class TransactionSequence(dict):
    """The initialState + steps witness dict (concolic ConcreteData schema)."""


class Issue:
    def __init__(self, contract: str, function_name: str, address: int,
                 swc_id: str, title: str, bytecode: str,
                 gas_used=(None, None), severity: str = "Medium",
                 description_head: str = "", description_tail: str = "",
                 transaction_sequence: Optional[Dict] = None):
        self.title = title
        self.contract = contract
        self.function = function_name
        self.address = address
        self.description_head = description_head
        self.description_tail = description_tail
        self.description = f"{description_head}\n{description_tail}".strip()
        self.severity = severity
        self.swc_id = swc_id
        self.min_gas_used, self.max_gas_used = gas_used
        self.bytecode = bytecode
        self.filename = None
        self.code = None
        self.lineno = None
        self.source_mapping = None
        self.discovery_time = 0.0
        self.bytecode_hash = get_code_hash(bytecode) if bytecode else "0x"
        self.transaction_sequence = transaction_sequence
        self.source_location = None

    @property
    def transaction_sequence_users(self):
        """Witness with symbolic senders resolved to actor names."""
        return self.transaction_sequence

    @property
    def as_dict(self) -> Dict:
        issue = {
            "title": self.title,
            "swc-id": self.swc_id,
            "contract": self.contract,
            "description": self.description,
            "function": self.function,
            "severity": self.severity,
            "address": self.address,
            "tx_sequence": self.transaction_sequence,
            "min_gas_used": self.min_gas_used,
            "max_gas_used": self.max_gas_used,
            "sourceMap": self.source_mapping,
        }
        if self.filename and self.lineno:
            issue["filename"] = self.filename
            issue["lineno"] = self.lineno
        if self.code:
            issue["code"] = self.code
        return issue

    def resolve_function_name(self) -> None:
        """4-byte-based function-name resolution from the witness calldata
        (reference report.py:190-248)."""
        if self.transaction_sequence is None:
            return
        steps = self.transaction_sequence.get("steps", [])
        if not steps:
            return
        last_input = steps[-1].get("input", "0x")
        if len(last_input) < 10:
            return
        selector = last_input[:10]
        if self.function and not self.function.startswith("_function_"):
            return
        matches = SignatureDB().get(selector)
        if matches:
            self.function = matches[0]

    def add_code_info(self, contract) -> None:
        """Source mapping via the contract's solc srcmap (reference report.py:148)."""
        if self.address is None or not hasattr(contract, "get_source_info"):
            return
        is_constructor = self.function == "constructor"
        try:
            source_info = contract.get_source_info(self.address,
                                                   constructor=is_constructor)
        except Exception:
            return
        if source_info is None:
            return
        self.filename = source_info.filename
        self.code = source_info.code
        self.lineno = source_info.lineno
        self.source_mapping = f"{self.address}"


class Report:
    environment: Dict = {}

    def __init__(self, contracts=None, exceptions=None,
                 execution_info: Optional[List] = None):
        self.issues: Dict[bytes, Issue] = {}
        self.solc_version = ""
        self.meta: Dict = {}
        self.source = contracts
        self.exceptions = exceptions or []
        self.execution_info = execution_info or []
        #: the global analysis deadline fired and the frontier was drained
        #: gracefully: issues found so far are valid, but exploration is
        #: partial (core/svm.py graceful drain)
        self.incomplete = False
        #: coverage stats accompanying an incomplete report (executed nodes,
        #: explored/dropped state counts, transactions reached)
        self.coverage: Dict = {}

    def sorted_issues(self) -> List[Dict]:
        return [issue.as_dict for key, issue in
                sorted(self.issues.items(), key=lambda kv: kv[1].address)]

    def append_issue(self, issue: Issue) -> None:
        disambiguator = f"{issue.swc_id}-{issue.title}-{issue.address}-{issue.function}"
        key = hashlib.md5(disambiguator.encode()).digest()
        self.issues[key] = issue

    # -- formats --------------------------------------------------------------------
    def _incomplete_banner(self) -> str:
        stats = ", ".join(f"{key}: {value}" for key, value
                          in self.coverage.items())
        return ("==== INCOMPLETE ANALYSIS ====\n"
                "The analysis deadline expired before exploration finished; "
                "the results below are valid but partial.\n"
                + (f"Coverage: {stats}\n" if stats else ""))

    def as_text(self) -> str:
        if not self.issues:
            if self.incomplete:
                return self._incomplete_banner() + \
                    "No issues were detected in the explored portion.\n"
            return "The analysis was completed successfully. " \
                   "No issues were detected.\n"
        blocks = []
        if self.incomplete:
            blocks.append(self._incomplete_banner())
        for issue in (issue for _, issue in
                      sorted(self.issues.items(), key=lambda kv: kv[1].address)):
            lines = [
                f"==== {issue.title} ====",
                f"SWC ID: {issue.swc_id}",
                f"Severity: {issue.severity}",
                f"Contract: {issue.contract}",
                f"Function name: {issue.function}",
                f"PC address: {issue.address}",
                f"Estimated Gas Usage: {issue.min_gas_used} - {issue.max_gas_used}",
                issue.description,
            ]
            if issue.filename and issue.lineno:
                lines.append(f"--------------------\nIn file: "
                             f"{issue.filename}:{issue.lineno}")
            if issue.code:
                lines.append(f"\n{issue.code}\n--------------------")
            if issue.transaction_sequence:
                lines.append("\nTransaction Sequence:\n")
                lines.append(self._format_tx_sequence(issue.transaction_sequence))
            blocks.append("\n".join(lines))
        return "\n\n".join(blocks) + "\n"

    @staticmethod
    def _format_tx_sequence(sequence: Dict) -> str:
        out = []
        for index, step in enumerate(sequence.get("steps", [])):
            kind = "CREATE" if step.get("address", "") == "" else "CALL"
            line = (f"Caller: [{step.get('origin', '?')}], "
                    f"function: {step.get('name', 'unknown')}, "
                    f"txdata: {step.get('input', '0x')}, "
                    f"value: {step.get('value', '0x0')}")
            out.append(f"{index}: {kind} {line}")
        return "\n".join(out)

    def as_json(self) -> str:
        result = {"success": True, "error": None, "issues": self.sorted_issues()}
        if self.incomplete:
            result["incomplete"] = True
            result["coverage"] = self.coverage
        if self.execution_info:
            result["extra"] = {
                "execution_info": [info.as_dict() for info in self.execution_info]}
        return json.dumps(result, default=str)

    def as_swc_standard_format(self) -> str:
        """jsonv2: SWC standard format with testCases (reference report.py:352)."""
        issues_grouped = []
        for _, issue in sorted(self.issues.items(), key=lambda kv: kv[1].address):
            entry = {
                "swcID": f"SWC-{issue.swc_id}",
                "swcTitle": SWC_TO_TITLE.get(issue.swc_id, ""),
                "description": {
                    "head": issue.description_head,
                    "tail": issue.description_tail,
                },
                "severity": issue.severity,
                "locations": [{"bytecodeOffset": issue.address}],
                "extra": {},
            }
            if issue.transaction_sequence:
                entry["extra"]["testCases"] = [issue.transaction_sequence]
            issues_grouped.append(entry)
        result = [{
            "issues": issues_grouped,
            "sourceType": "raw-bytecode",
            "sourceFormat": "evm-byzantium-bytecode",
            "sourceList": [issue.bytecode_hash
                           for _, issue in self.issues.items()][:1],
            "meta": self.meta,
        }]
        return json.dumps(result, default=str)

    def as_markdown(self) -> str:
        if not self.issues:
            return "# Analysis results\n\nThe analysis was completed " \
                   "successfully. No issues were detected.\n"
        blocks = ["# Analysis results"]
        for _, issue in sorted(self.issues.items(), key=lambda kv: kv[1].address):
            block = [
                f"## {issue.title}",
                f"- SWC ID: {issue.swc_id}",
                f"- Severity: {issue.severity}",
                f"- Contract: {issue.contract}",
                f"- Function name: `{issue.function}`",
                f"- PC address: {issue.address}",
                f"- Estimated Gas Usage: {issue.min_gas_used} - {issue.max_gas_used}",
                "",
                "### Description",
                issue.description,
            ]
            if issue.filename and issue.lineno:
                block.append(f"\nIn file: {issue.filename}:{issue.lineno}")
            blocks.append("\n".join(block))
        return "\n\n".join(blocks) + "\n"
