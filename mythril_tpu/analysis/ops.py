"""Helper value model for POST modules and callgraph (API parity:
mythril/analysis/ops.py — VarType, Variable, Call, get_variable)."""

from __future__ import annotations

from enum import Enum

from ..smt import BitVec


class VarType(Enum):
    SYMBOLIC = 1
    CONCRETE = 2


class Variable:
    def __init__(self, val, var_type: VarType):
        self.val = val
        self.type = var_type

    def __str__(self):
        return str(self.val)


def get_variable(expression) -> Variable:
    if isinstance(expression, int):
        return Variable(expression, VarType.CONCRETE)
    if isinstance(expression, BitVec) and expression.raw.is_const:
        return Variable(expression.value, VarType.CONCRETE)
    return Variable(expression, VarType.SYMBOLIC)


class Call:
    def __init__(self, node, state, state_index, call_type, to,
                 gas, value=Variable(0, VarType.CONCRETE), data=None):
        self.to = to
        self.gas = gas
        self.type = call_type
        self.node = node
        self.state = state
        self.state_index = state_index
        self.value = value
        self.data = data
