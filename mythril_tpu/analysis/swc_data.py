"""SWC registry id <-> title table (capability parity: mythril/analysis/swc_data.py)."""

DELEGATECALL_TO_UNTRUSTED_CONTRACT = "112"
PRECOMPILED_CONTRACT_WRONG_INPUT = "127"
TX_ORIGIN_USAGE = "115"
UNCHECKED_RET_VAL = "104"
UNPROTECTED_ETHER_WITHDRAWAL = "105"
UNPROTECTED_SELFDESTRUCT = "106"
REENTRANCY = "107"
MULTIPLE_SENDS = "113"
TX_ORDER_DEPENDENCE = "114"
ASSERT_VIOLATION = "110"
DEPRECATED_FUNCTIONS_USAGE = "111"
INTEGER_OVERFLOW_AND_UNDERFLOW = "101"
TIMESTAMP_DEPENDENCE = "116"
WEAK_RANDOMNESS = "120"
REQUIREMENT_VIOLATION = "123"
WRITE_TO_ARBITRARY_STORAGE = "124"
ARBITRARY_JUMP = "127"
UNEXPECTED_ETHER_BALANCE = "132"

SWC_TO_TITLE = {
    "100": "Function Default Visibility",
    "101": "Integer Overflow and Underflow",
    "102": "Outdated Compiler Version",
    "103": "Floating Pragma",
    "104": "Unchecked Call Return Value",
    "105": "Unprotected Ether Withdrawal",
    "106": "Unprotected SELFDESTRUCT Instruction",
    "107": "Reentrancy",
    "108": "State Variable Default Visibility",
    "109": "Uninitialized Storage Pointer",
    "110": "Assert Violation",
    "111": "Use of Deprecated Solidity Functions",
    "112": "Delegatecall to Untrusted Callee",
    "113": "DoS with Failed Call",
    "114": "Transaction Order Dependence",
    "115": "Authorization through tx.origin",
    "116": "Block values as a proxy for time",
    "120": "Weak Sources of Randomness from Chain Attributes",
    "123": "Requirement Violation",
    "124": "Write to Arbitrary Storage Location",
    "127": "Arbitrary Jump with Function Type Variable",
    "132": "Unexpected Ether balance",
}
