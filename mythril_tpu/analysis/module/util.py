"""Hook wiring for CALLBACK detection modules (API parity:
mythril/analysis/module/util.py — get_detection_module_hooks)."""

from __future__ import annotations

import logging
from collections import defaultdict
from typing import Callable, Dict, List

from .. import module_screen
from .base import DetectionModule, EntryPoint
from .loader import ModuleLoader

log = logging.getLogger(__name__)


def get_detection_module_hooks(modules: List[DetectionModule],
                               hook_type: str = "pre") -> Dict[str, List[Callable]]:
    hook_dict: Dict[str, List[Callable]] = defaultdict(list)
    for module in modules:
        hooks = module.pre_hooks if hook_type == "pre" else module.post_hooks
        for op_code in hooks:
            def hook_wrapper(module_reference=module, op=op_code):
                if hook_type == "pre":
                    # the taint module screen can prove some sites
                    # issue-free (untainted sink operands) before any
                    # solver query; post hooks fire after the op, where
                    # the summary's site pc no longer lines up
                    def hook(global_state):
                        if module_screen.should_skip_site(
                                module_reference, op, global_state):
                            return
                        module_reference.execute(global_state)
                else:
                    def hook(global_state):
                        module_reference.execute(global_state)

                return hook

            hook_dict[op_code].append(hook_wrapper())
    return dict(hook_dict)


def reset_callback_modules(module_names=(), allow_blank_modules: bool = False) -> None:
    modules = ModuleLoader().get_detection_modules(
        entry_point=EntryPoint.CALLBACK, white_list=module_names or None)
    for module in modules:
        module.reset_module()
