from .base import DetectionModule, EntryPoint
from .loader import ModuleLoader
from .util import get_detection_module_hooks, reset_callback_modules

__all__ = ["DetectionModule", "EntryPoint", "ModuleLoader",
           "get_detection_module_hooks", "reset_callback_modules"]
