"""Detection-module registry (API parity: mythril/analysis/module/loader.py:37 —
singleton with the 18 built-ins, entry-point and white-list filtering)."""

from __future__ import annotations

import logging
from typing import List, Optional

from ...exceptions import DetectorNotFoundError
from .base import DetectionModule, EntryPoint

log = logging.getLogger(__name__)


class ModuleLoader:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance._modules = []
            cls._instance._register_mythril_modules()
        return cls._instance

    def register_module(self, detection_module: DetectionModule):
        if not isinstance(detection_module, DetectionModule):
            raise ValueError("not a DetectionModule")
        self._modules.append(detection_module)

    def get_detection_modules(self, entry_point: Optional[EntryPoint] = None,
                              white_list: Optional[List[str]] = None
                              ) -> List[DetectionModule]:
        result = self._modules[:]
        if white_list:
            available = {type(module).__name__ for module in result}
            for name in white_list:
                if name not in available:
                    raise DetectorNotFoundError(
                        f"invalid detection module: {name}")
            result = [m for m in result if type(m).__name__ in white_list]
        if entry_point:
            result = [m for m in result if m.entry_point == entry_point]
        return result

    def _register_mythril_modules(self):
        from ..modules import (
            AccidentallyKillable, ArbitraryDelegateCall, ArbitraryJump,
            ArbitraryStorage, EtherThief, EtherPhishing, Exceptions,
            ExternalCalls, IntegerArithmetics, MultipleSends,
            PredictableVariables, RequirementsViolation, StateChangeAfterCall,
            TxOrderDependence, TxOrigin, UncheckedRetval, UnexpectedEther,
            UserAssertions,
        )

        self._modules.extend([
            AccidentallyKillable(), ArbitraryDelegateCall(), ArbitraryJump(),
            ArbitraryStorage(), EtherThief(), EtherPhishing(), Exceptions(),
            ExternalCalls(), IntegerArithmetics(), MultipleSends(),
            PredictableVariables(), RequirementsViolation(),
            StateChangeAfterCall(), TxOrderDependence(), TxOrigin(),
            UncheckedRetval(), UnexpectedEther(), UserAssertions(),
        ])
