"""Detection-module framework (API parity: mythril/analysis/module/base.py —
EntryPoint:20, DetectionModule:31 with pre/post hook declarations and the
(address, code_hash)-keyed issue cache)."""

from __future__ import annotations

import logging
from enum import Enum
from typing import Dict, List, Optional, Set, Tuple

from ...core.state.global_state import GlobalState
from ...support.support_args import args
from ...utils.helpers import get_code_hash
from ..report import Issue

log = logging.getLogger(__name__)


class EntryPoint(Enum):
    """POST modules scan the recorded statespace after exploration; CALLBACK
    modules run as SVM opcode hooks during it."""

    POST = 1
    CALLBACK = 2


class DetectionModule:
    name = "detection module"
    swc_id = ""
    description = ""
    entry_point = EntryPoint.CALLBACK
    pre_hooks: List[str] = []
    post_hooks: List[str] = []
    #: sink declaration for the taint module screen
    #: (analysis/module_screen.py): hooked opcode -> operand indices
    #: (0 = top of stack at the hook site) whose untaintedness makes an
    #: issue impossible there. An EMPTY tuple is a presence-only sink:
    #: it documents what the module sinks on but opts out of site-level
    #: screening (the module can flag sites with deterministic operands
    #: too, so skipping on "untainted" would change detections). Only
    #: declare operand indices when `every operand untainted (i.e. a
    #: deterministic function of the bytecode) => _execute returns no
    #: issue` provably holds.
    taint_sinks: Dict[str, Tuple[int, ...]] = {}

    def __init__(self):
        self.issues: List[Issue] = []
        self.cache: Set[Tuple[int, str]] = set()
        self.auto_cache = True

    def reset_module(self) -> None:
        self.issues = []
        # the (address, code_hash) cache must not outlive one analysis: a
        # fresh analysis of the same bytecode would silently report nothing
        self.cache = set()

    def update_cache(self, issues: Optional[List[Issue]] = None) -> None:
        issues = issues if issues is not None else self.issues
        for issue in issues:
            self.cache.add((issue.address, issue.bytecode_hash))

    def execute(self, target: GlobalState) -> Optional[List[Issue]]:
        log.debug("entering module %s", type(self).__name__)
        if self.auto_cache and isinstance(target, GlobalState):
            if self._cache_hit(target):
                return []
        result = self._execute(target)
        if result:
            # in issue-annotation mode (--enable-summaries) issues are deferred:
            # the summary plugin re-validates the attached IssueAnnotations
            # against substituted conditions (reference module/base.py:93)
            if not args.use_issue_annotations:
                self.issues.extend(result)
                self.update_cache(result)
        return result

    def _cache_hit(self, state: GlobalState) -> bool:
        address = state.get_current_instruction()["address"]
        code_hash = get_code_hash(state.environment.code.bytecode)
        return (address, code_hash) in self.cache

    def _execute(self, target) -> Optional[List[Issue]]:
        raise NotImplementedError

    def __repr__(self) -> str:
        return (f"<DetectionModule name={self.name} swc_id={self.swc_id} "
                f"pre_hooks={self.pre_hooks} post_hooks={self.post_hooks}>")
