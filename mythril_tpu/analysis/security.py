"""Analysis orchestration: run detection modules (API parity:
mythril/analysis/security.py — fire_lasers:28, retrieve_callback_issues:14)."""

from __future__ import annotations

import logging
from typing import List, Optional

from .module import ModuleLoader, get_detection_module_hooks
from .module.base import EntryPoint
from .report import Issue

log = logging.getLogger(__name__)


def retrieve_callback_issues(white_list: Optional[List[str]] = None) -> List[Issue]:
    """Harvest issues accumulated by CALLBACK modules during exploration."""
    issues: List[Issue] = []
    for module in ModuleLoader().get_detection_modules(
            entry_point=EntryPoint.CALLBACK, white_list=white_list):
        issues.extend(module.issues)
    reset_callback_modules(white_list)
    return issues


def reset_callback_modules(white_list: Optional[List[str]] = None) -> None:
    for module in ModuleLoader().get_detection_modules(
            entry_point=EntryPoint.CALLBACK, white_list=white_list):
        module.reset_module()


def fire_lasers(statespace, white_list: Optional[List[str]] = None) -> List[Issue]:
    """Run POST modules over the statespace and merge CALLBACK results."""
    issues: List[Issue] = []
    for module in ModuleLoader().get_detection_modules(
            entry_point=EntryPoint.POST, white_list=white_list):
        log.info("executing %s", module.name)
        result = module.execute(statespace)
        if result:
            issues.extend(result)
    issues.extend(retrieve_callback_issues(white_list))
    for issue in issues:
        issue.resolve_function_name()
    return issues
