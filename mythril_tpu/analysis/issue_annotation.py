"""IssueAnnotation (API parity: mythril/analysis/issue_annotation.py:9): ties an
Issue to the conditions under which it fired. Every detector attaches one per
issue (reference modules do the same); with `--enable-summaries`
(args.use_issue_annotations) the annotations replace direct issue emission and
are re-validated when a summary is recorded or replayed."""

from __future__ import annotations

from typing import List

from ..core.state.annotation import StateAnnotation
from ..smt import And, Bool


class IssueAnnotation(StateAnnotation):
    def __init__(self, conditions: List[Bool], issue, detector):
        self.conditions = conditions
        self.issue = issue
        self.detector = detector

    @property
    def persist_to_world_state(self) -> bool:
        return True

    def __copy__(self):
        return IssueAnnotation(list(self.conditions), self.issue, self.detector)


def attach_issue_annotation(state, issue, detector, constraints) -> None:
    """Annotate the state with the proven condition set for `issue`
    (reference modules attach IssueAnnotation(conditions=[And(*constraints)])
    at every emission site, e.g. suicide.py:114)."""
    state.annotate(IssueAnnotation(
        conditions=[And(*constraints)], issue=issue, detector=detector))
