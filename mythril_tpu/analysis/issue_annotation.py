"""IssueAnnotation (API parity: mythril/analysis/issue_annotation.py:9): ties an
Issue to the conditions under which it fired (used by symbolic summaries)."""

from __future__ import annotations

from typing import List

from ..core.state.annotation import StateAnnotation
from ..smt import Bool


class IssueAnnotation(StateAnnotation):
    def __init__(self, conditions: List[Bool], issue, detector):
        self.conditions = conditions
        self.issue = issue
        self.detector = detector

    @property
    def persist_to_world_state(self) -> bool:
        return True

    def __copy__(self):
        return IssueAnnotation(list(self.conditions), self.issue, self.detector)
