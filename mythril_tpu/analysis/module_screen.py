"""Taint-summary screening of detection modules (the module screen).

The counted adapter between the per-contract taint summaries
(``staticanalysis/summary.py``) and the opcode-hook-driven detection
modules — the same consumer-funnel shape ``smt/solver/cfa_screen.py``
gives the cfa tables. Two screening levels:

* **module-level** (:func:`screen_modules`, consulted once at hook
  registration): a module whose pre+post hook opcodes none appear in the
  contract's reachable code can never fire — skipping it wholesale is
  trivially detection-identical. Only applied when no dynamic loader is
  configured and the contract cannot spawn code at runtime
  (CREATE/CREATE2 reachable ⇒ hooks may fire on constructor bytecode the
  summary never saw).
* **site-level** (:func:`should_skip_site`, consulted per pre-hook
  firing): a module may declare, via its ``taint_sinks`` attribute, that
  specific operands being untainted at a hook site makes an issue
  impossible there; the screen then skips the hook — and its solver
  queries — at sites the summary proves untainted. Untainted means
  "deterministic function of the bytecode alone" (see
  ``staticanalysis/taint.py``), so the declaration must hold for
  deterministic operand values too; modules that cannot promise that
  declare presence-only sinks (empty operand tuple) and are never
  site-screened.

Everything funnels through :func:`enabled` — ``--no-taint`` /
``MYTHRIL_TPU_TAINT=0`` disable both levels for A/B parity runs, and a
missing summary (cfa bailed, fixpoint blew its cap) means "no verdict":
every module runs, every hook fires. Skips are counted in the
``taint.screen.*`` metrics.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..observe import metrics
from ..staticanalysis import ContractSummary, get_summary
from ..support.support_args import args


def enabled() -> bool:
    """Screening on? Both the CLI flag (--no-taint) and the env knob
    (MYTHRIL_TPU_TAINT=0) can turn the consumers off."""
    from ..support import tpu_config
    return bool(getattr(args, "taint", True)) \
        and tpu_config.get_flag("MYTHRIL_TPU_TAINT")


def summary_for(disassembly) -> Optional[ContractSummary]:
    """The contract's taint summary, or None when screening is disabled
    or the analysis had no verdict."""
    if not enabled() or disassembly is None:
        return None
    return get_summary(disassembly)


def warm(disassembly) -> None:
    """Force-build the summary ahead of the hot path (lane seeding,
    serve warmup)."""
    summary_for(disassembly)


def module_hook_ops(module) -> frozenset:
    """Every opcode a module hooks, pre and post."""
    return frozenset(getattr(module, "pre_hooks", None) or ()) \
        | frozenset(getattr(module, "post_hooks", None) or ())


def screen_modules(modules: Sequence, disassembly) -> Tuple[List, List]:
    """Partition `modules` into (kept, skipped): skipped modules hook
    only opcodes absent from the contract's reachable code, so their
    hooks can never fire. Returns everything kept when screening is off,
    the summary is missing, or the contract can spawn code at runtime."""
    modules = list(modules)
    summary = summary_for(disassembly)
    if summary is None:
        return modules, []
    if summary.reachable_ops & {"CREATE", "CREATE2"}:
        # runtime-spawned constructor code executes under this contract's
        # hook set but was never summarized — no sound whole-module skip
        return modules, []
    kept, skipped = [], []
    for module in modules:
        hooks = module_hook_ops(module)
        if hooks and not (hooks & summary.reachable_ops):
            skipped.append(module)
        else:
            kept.append(module)
    if skipped:
        metrics.inc("taint.screen.modules_skipped", len(skipped))
    return kept, skipped


def should_skip_site(module, op_code: str, global_state) -> bool:
    """True when the summary proves the module's declared sink operands
    untainted (deterministic) at this pre-hook site, so executing the
    module cannot produce an issue here. Conservative on every miss:
    undeclared ops, presence-only sinks, unknown pcs, and missing
    summaries all run the hook."""
    sinks = getattr(module, "taint_sinks", None)
    if not sinks:
        return False
    operand_indices = sinks.get(op_code)
    if not operand_indices:
        return False  # undeclared or presence-only: not site-screenable
    try:
        disassembly = global_state.environment.code
        pc = global_state.get_current_instruction()["address"]
    except (AttributeError, IndexError, KeyError, TypeError):
        return False
    summary = summary_for(disassembly)
    if summary is None:
        return False
    site = summary.sink_at(pc)
    if site is None or site.op != op_code:
        return False  # site the summary never saw: run the hook
    try:
        untainted = all(not site.operand_taint[index]
                        for index in operand_indices)
    except IndexError:
        return False
    if untainted:
        metrics.inc("taint.screen.sites_skipped")
        return True
    return False


def loop_header_at(disassembly, pc: int) -> Optional[int]:
    """Header pc of the innermost natural loop containing `pc`, or None
    (no loop, screening off, no verdict). The frontier tags lanes with
    this for bounded-unroll budgeting."""
    summary = summary_for(disassembly)
    if summary is None or not summary.loop_header_of:
        return None
    from ..staticanalysis import get_cfa
    cfa = get_cfa(disassembly)
    if cfa is None:
        return None
    block = cfa.block_at(pc)
    if block is None:
        return None
    return summary.loop_header_of.get(block)


def function_order(disassembly) -> Tuple[int, ...]:
    """Function entry pcs in dispatcher order; () without a verdict.
    Fleet seeding uses this to group per-function work."""
    summary = summary_for(disassembly)
    if summary is None:
        return ()
    return summary.function_order()
