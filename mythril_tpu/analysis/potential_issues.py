"""Two-phase issue flow (capability parity: mythril/analysis/potential_issues.py —
PotentialIssue:11, check_potential_issues:82).

CALLBACK detectors record PotentialIssues with unsolved constraints on the state's
annotations; when a transaction ends, check_potential_issues re-solves them against
the final world-state constraints and promotes survivors to real Issues with
concrete witnesses."""

from __future__ import annotations

from ..core.state.annotation import StateAnnotation
from ..core.state.global_state import GlobalState
from ..exceptions import UnsatError
from ..support.support_args import args
from ..utils.helpers import get_code_hash
from .issue_annotation import attach_issue_annotation
from .report import Issue
from .solver import get_transaction_sequence


class PotentialIssue:
    def __init__(self, contract, function_name, address, swc_id, title, bytecode,
                 detector, severity: str = "Medium", description_head: str = "",
                 description_tail: str = "", constraints=None):
        self.title = title
        self.contract = contract
        self.function_name = function_name
        self.address = address
        self.description_head = description_head
        self.description_tail = description_tail
        self.severity = severity
        self.swc_id = swc_id
        self.bytecode = bytecode
        self.constraints = constraints or []
        self.detector = detector


class PotentialIssuesAnnotation(StateAnnotation):
    def __init__(self):
        self.potential_issues = []

    @property
    def search_importance(self):
        return 10 * len(self.potential_issues)

    def __copy__(self):
        # shared across forks intentionally? No: each path tracks its own
        result = PotentialIssuesAnnotation()
        result.potential_issues = list(self.potential_issues)
        return result


def get_potential_issues_annotation(state: GlobalState) -> PotentialIssuesAnnotation:
    for annotation in state.annotations:
        if isinstance(annotation, PotentialIssuesAnnotation):
            return annotation
    annotation = PotentialIssuesAnnotation()
    state.annotate(annotation)
    return annotation


def check_potential_issues(state: GlobalState) -> None:
    """Re-check recorded potential issues at transaction end
    (called from svm transaction_end hook wiring in analysis/symbolic.py)."""
    annotation = get_potential_issues_annotation(state)
    unsat_issues = []
    for potential_issue in annotation.potential_issues:
        try:
            transaction_sequence = get_transaction_sequence(
                state,
                state.world_state.constraints + potential_issue.constraints)
        except UnsatError:
            unsat_issues.append(potential_issue)
            continue
        issue = Issue(
            contract=potential_issue.contract,
            function_name=potential_issue.function_name,
            address=potential_issue.address,
            title=potential_issue.title,
            bytecode=potential_issue.bytecode,
            swc_id=potential_issue.swc_id,
            gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
            description_head=potential_issue.description_head,
            description_tail=potential_issue.description_tail,
            severity=potential_issue.severity,
            transaction_sequence=transaction_sequence,
        )
        attach_issue_annotation(
            state, issue, potential_issue.detector,
            list(state.world_state.constraints) + list(potential_issue.constraints))
        # deferred mode (--enable-summaries): the summary plugin promotes
        # validated annotations instead (reference potential_issues.py:123-125)
        if not args.use_issue_annotations:
            potential_issue.detector.issues.append(issue)
            potential_issue.detector.cache.add(
                (potential_issue.address,
                 get_code_hash(potential_issue.bytecode)))
    annotation.potential_issues = unsat_issues
