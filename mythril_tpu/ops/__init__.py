from .opcodes import OPCODES, ADDRESS, GAS, STACK, opcode_by_number, opcode_name

__all__ = ["OPCODES", "ADDRESS", "GAS", "STACK", "opcode_by_number", "opcode_name"]
