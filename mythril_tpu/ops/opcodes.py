"""EVM opcode metadata through the Cancun fork.

Capability parity with the reference's opcode table (mythril/support/opcodes.py:16):
each mnemonic maps to its byte value, stack effect (pops, pushes) and a (min, max) gas
estimate used for the gas-range accounting in reports. Values follow the Yellow Paper /
EIP gas schedules (Berlin cold/warm access costs give the min/max spread for state-
touching ops; memory-expansion and per-byte components are accounted dynamically by the
interpreter, not in this static table).

This table is also the single source of truth for the TPU lockstep interpreter's
dispatch: `opcode_by_number` is densified into arrays consumed by
mythril_tpu.parallel.lockstep.
"""

from __future__ import annotations

from typing import Dict, Tuple

ADDRESS = "address"
STACK = "stack"
GAS = "gas"

_G_ZERO = (0, 0)
_G_BASE = (2, 2)
_G_VERYLOW = (3, 3)
_G_LOW = (5, 5)
_G_MID = (8, 8)
_G_HIGH = (10, 10)
_G_JUMPDEST = (1, 1)

# name: (byte, pops, pushes, gas_min, gas_max)
_RAW: Dict[str, Tuple[int, int, int, int, int]] = {
    "STOP": (0x00, 0, 0, 0, 0),
    "ADD": (0x01, 2, 1, 3, 3),
    "MUL": (0x02, 2, 1, 5, 5),
    "SUB": (0x03, 2, 1, 3, 3),
    "DIV": (0x04, 2, 1, 5, 5),
    "SDIV": (0x05, 2, 1, 5, 5),
    "MOD": (0x06, 2, 1, 5, 5),
    "SMOD": (0x07, 2, 1, 5, 5),
    "ADDMOD": (0x08, 3, 1, 8, 8),
    "MULMOD": (0x09, 3, 1, 8, 8),
    "EXP": (0x0A, 2, 1, 10, 10 + 50 * 32),  # 10 + 50/exponent byte
    "SIGNEXTEND": (0x0B, 2, 1, 5, 5),
    "LT": (0x10, 2, 1, 3, 3),
    "GT": (0x11, 2, 1, 3, 3),
    "SLT": (0x12, 2, 1, 3, 3),
    "SGT": (0x13, 2, 1, 3, 3),
    "EQ": (0x14, 2, 1, 3, 3),
    "ISZERO": (0x15, 1, 1, 3, 3),
    "AND": (0x16, 2, 1, 3, 3),
    "OR": (0x17, 2, 1, 3, 3),
    "XOR": (0x18, 2, 1, 3, 3),
    "NOT": (0x19, 1, 1, 3, 3),
    "BYTE": (0x1A, 2, 1, 3, 3),
    "SHL": (0x1B, 2, 1, 3, 3),
    "SHR": (0x1C, 2, 1, 3, 3),
    "SAR": (0x1D, 2, 1, 3, 3),
    "SHA3": (0x20, 2, 1, 30, 30 + 6 * 8),  # 30 + 6/word; max assumes modest input
    "ADDRESS": (0x30, 0, 1, 2, 2),
    "BALANCE": (0x31, 1, 1, 100, 2600),  # warm / cold (EIP-2929)
    "ORIGIN": (0x32, 0, 1, 2, 2),
    "CALLER": (0x33, 0, 1, 2, 2),
    "CALLVALUE": (0x34, 0, 1, 2, 2),
    "CALLDATALOAD": (0x35, 1, 1, 3, 3),
    "CALLDATASIZE": (0x36, 0, 1, 2, 2),
    "CALLDATACOPY": (0x37, 3, 0, 3, 3 + 3 * 768),
    "CODESIZE": (0x38, 0, 1, 2, 2),
    "CODECOPY": (0x39, 3, 0, 3, 3 + 3 * 768),
    "GASPRICE": (0x3A, 0, 1, 2, 2),
    "EXTCODESIZE": (0x3B, 1, 1, 100, 2600),
    "EXTCODECOPY": (0x3C, 4, 0, 100, 2600 + 3 * 768),
    "RETURNDATASIZE": (0x3D, 0, 1, 2, 2),
    "RETURNDATACOPY": (0x3E, 3, 0, 3, 3 + 3 * 768),
    "EXTCODEHASH": (0x3F, 1, 1, 100, 2600),
    "BLOCKHASH": (0x40, 1, 1, 20, 20),
    "COINBASE": (0x41, 0, 1, 2, 2),
    "TIMESTAMP": (0x42, 0, 1, 2, 2),
    "NUMBER": (0x43, 0, 1, 2, 2),
    "PREVRANDAO": (0x44, 0, 1, 2, 2),  # ex-DIFFICULTY (EIP-4399)
    "GASLIMIT": (0x45, 0, 1, 2, 2),
    "CHAINID": (0x46, 0, 1, 2, 2),
    "SELFBALANCE": (0x47, 0, 1, 5, 5),
    "BASEFEE": (0x48, 0, 1, 2, 2),
    "BLOBHASH": (0x49, 1, 1, 3, 3),
    "BLOBBASEFEE": (0x4A, 0, 1, 2, 2),
    "POP": (0x50, 1, 0, 2, 2),
    "MLOAD": (0x51, 1, 1, 3, 96),
    "MSTORE": (0x52, 2, 0, 3, 98),
    "MSTORE8": (0x53, 2, 0, 3, 98),
    "SLOAD": (0x54, 1, 1, 100, 2100),  # warm / cold
    "SSTORE": (0x55, 2, 0, 100, 22100),  # warm-dirty / cold-fresh-set
    "JUMP": (0x56, 1, 0, 8, 8),
    "JUMPI": (0x57, 2, 0, 10, 10),
    "PC": (0x58, 0, 1, 2, 2),
    "MSIZE": (0x59, 0, 1, 2, 2),
    "GAS": (0x5A, 0, 1, 2, 2),
    "JUMPDEST": (0x5B, 0, 0, 1, 1),
    "TLOAD": (0x5C, 1, 1, 100, 100),  # EIP-1153
    "TSTORE": (0x5D, 2, 0, 100, 100),
    "MCOPY": (0x5E, 3, 0, 3, 3 + 3 * 768),  # EIP-5656
    "PUSH0": (0x5F, 0, 1, 2, 2),  # EIP-3855
    "LOG0": (0xA0, 2, 0, 375, 375 + 8 * 32),
    "LOG1": (0xA1, 3, 0, 750, 750 + 8 * 32),
    "LOG2": (0xA2, 4, 0, 1125, 1125 + 8 * 32),
    "LOG3": (0xA3, 5, 0, 1500, 1500 + 8 * 32),
    "LOG4": (0xA4, 6, 0, 1875, 1875 + 8 * 32),
    "CREATE": (0xF0, 3, 1, 32000, 32000),
    "CALL": (0xF1, 7, 1, 100, 2600 + 9000 + 25000),
    "CALLCODE": (0xF2, 7, 1, 100, 2600 + 9000),
    "RETURN": (0xF3, 2, 0, 0, 0),
    "DELEGATECALL": (0xF4, 6, 1, 100, 2600),
    "CREATE2": (0xF5, 4, 1, 32000, 32000 + 6 * 768),
    "STATICCALL": (0xFA, 6, 1, 100, 2600),
    "REVERT": (0xFD, 2, 0, 0, 0),
    "INVALID": (0xFE, 0, 0, 0, 0),
    "SELFDESTRUCT": (0xFF, 1, 0, 5000, 30000),
}

for _i in range(1, 33):  # PUSH1..PUSH32
    _RAW[f"PUSH{_i}"] = (0x5F + _i, 0, 1, 3, 3)
for _i in range(1, 17):  # DUP1..DUP16
    _RAW[f"DUP{_i}"] = (0x7F + _i, _i, _i + 1, 3, 3)
for _i in range(1, 17):  # SWAP1..SWAP16
    _RAW[f"SWAP{_i}"] = (0x8F + _i, _i + 1, _i + 1, 3, 3)

#: mnemonic -> {"address": byte, "stack": (pops, pushes), "gas": (min, max)}
OPCODES: Dict[str, dict] = {
    name: {ADDRESS: vals[0], STACK: (vals[1], vals[2]), GAS: (vals[3], vals[4])}
    for name, vals in _RAW.items()
}

_BY_NUMBER: Dict[int, str] = {meta[ADDRESS]: name for name, meta in OPCODES.items()}
# Historical alias: pre-Merge tooling calls 0x44 DIFFICULTY.
OPCODES["DIFFICULTY"] = OPCODES["PREVRANDAO"]


def opcode_by_number(byte_value: int) -> str | None:
    """Mnemonic for an opcode byte, or None for unassigned bytes."""
    return _BY_NUMBER.get(byte_value)


def opcode_name(byte_value: int) -> str:
    """Mnemonic, or 'UNKNOWN_0xXX' for unassigned bytes (disassembly display)."""
    return _BY_NUMBER.get(byte_value, f"UNKNOWN_0x{byte_value:02x}")


def push_width(name: str) -> int:
    """Immediate width in bytes for PUSHn (0 for PUSH0 and non-push opcodes)."""
    if name.startswith("PUSH") and name != "PUSH0":
        return int(name[4:])
    return 0
