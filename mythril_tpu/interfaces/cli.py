"""`myth-tpu` command-line interface.

Capability parity target: mythril/interfaces/cli.py (subcommands analyze|a,
disassemble|d, concolic, safe-functions, read-storage, function-to-hash,
hash-to-address, list-detectors, version — reference cli.py:243-356). Milestone-1
stub: disassemble and version are live; analyze lands with the engine.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    from .. import __version__

    parser = argparse.ArgumentParser(prog="myth-tpu",
                                     description="TPU-native EVM security analysis")
    subparsers = parser.add_subparsers(dest="command")

    disasm = subparsers.add_parser("disassemble", aliases=["d"],
                                   help="disassemble EVM bytecode")
    disasm.add_argument("-c", "--code", help="hex bytecode", default=None)
    disasm.add_argument("-f", "--codefile", help="file containing hex bytecode",
                        default=None)

    subparsers.add_parser("version", help="print version")

    args = parser.parse_args(argv)
    if args.command in ("disassemble", "d"):
        from ..frontends import Disassembly

        code = args.code
        if code is None and args.codefile:
            with open(args.codefile) as handle:
                code = handle.read().strip()
        if not code:
            parser.error("provide -c or -f")
        sys.stdout.write(Disassembly(code).get_easm())
        return 0
    if args.command == "version":
        print(f"myth-tpu {__version__}")
        return 0
    parser.print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())
