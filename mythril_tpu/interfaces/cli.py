"""`myth-tpu` command-line interface.

Capability parity: mythril/interfaces/cli.py:243-356 — subcommands
analyze|a, disassemble|d, foundry|f, concolic, safe-functions, read-storage,
function-to-hash, hash-to-address, list-detectors, version; the full analysis
flag surface (strategy, tx count, timeouts, pruning, modules, reports) at
cli.py:438-600. Exit code 1 iff issues were found (cli.py:880-883).

TPU-specific additions: `--solver jax` selects the batched device solver
(parallel/jax_solver.py); `--engine lockstep` routes concrete replay through
the batched interpreter."""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys


def _add_analysis_args(parser: argparse.ArgumentParser) -> None:
    inputs = parser.add_argument_group("input")
    inputs.add_argument("solidity_files", nargs="*",
                        help=".sol files (optionally file:ContractName)")
    inputs.add_argument("-c", "--code", help="hex creation bytecode")
    inputs.add_argument("-f", "--codefile", action="append",
                        help="file containing hex bytecode (repeatable: "
                             "with --fleet every -f is one corpus member)")
    inputs.add_argument("-a", "--address", help="on-chain contract address")
    inputs.add_argument("--bin-runtime", action="store_true",
                        help="treat -c/-f input as runtime (deployed) code")

    options = parser.add_argument_group("options")
    options.add_argument("-m", "--modules",
                         help="comma-separated detection module list")
    options.add_argument("--strategy", default="bfs",
                         choices=["dfs", "bfs", "naive-random",
                                  "weighted-random", "beam-search", "pending"])
    options.add_argument("-t", "--transaction-count", type=int, default=2)
    options.add_argument("--execution-timeout", type=int, default=86400)
    options.add_argument("--create-timeout", type=int, default=10)
    options.add_argument("--solver-timeout", type=int, default=10000)
    options.add_argument("--max-depth", type=int, default=128)
    options.add_argument("-b", "--loop-bound", type=int, default=3)
    options.add_argument("--call-depth-limit", type=int, default=3)
    options.add_argument("--pruning-factor", type=float, default=None)
    options.add_argument("--incremental-txs", default=True,
                         type=lambda x: str(x).lower() not in ("false", "0"),
                         help="False = explore RF-prioritized function "
                              "sequences instead of all orderings")
    options.add_argument("--enable-state-merging", action="store_true",
                         help="merge similar world states after each tx")
    options.add_argument("--enable-summaries", action="store_true",
                         help="record and replay symbolic transaction "
                              "summaries instead of re-executing")
    options.add_argument("--unconstrained-storage", action="store_true")
    options.add_argument("--disable-dependency-pruning", action="store_true")
    options.add_argument("--disable-mutation-pruner", action="store_true")
    options.add_argument("--enable-iprof", action="store_true")
    options.add_argument("--solver-log", help="directory for .smt2 query dumps")
    options.add_argument("--solver", default="cdcl", choices=["cdcl", "jax"],
                         help="SAT backend: native CDCL or batched TPU solver")
    options.add_argument("--no-simplify", action="store_true",
                         help="disable the word-level simplification pass "
                              "ahead of the bit-blaster (A/B measurement)")
    options.add_argument("--no-batch-solve", action="store_true",
                         help="disable the batched device SAT dispatch "
                              "(smt/solver/dispatch.py): every --solver jax "
                              "query pays its own device launch, no verdict "
                              "cache (A/B measurement); flush thresholds "
                              "tune via MYTHRIL_TPU_BATCH_FLUSH / "
                              "MYTHRIL_TPU_BATCH_AGE_MS / "
                              "MYTHRIL_TPU_VERDICT_CACHE")
    options.add_argument("--no-cfa", action="store_true",
                         help="disable the static control-flow-analysis "
                              "screen (staticanalysis/): jump validity, "
                              "merge-point tagging, and dead-code pruning "
                              "fall back to dynamic checks (A/B measurement)")
    options.add_argument("--no-taint", action="store_true",
                         help="disable the taint module screen "
                              "(staticanalysis/taint.py): detection "
                              "modules register and fire on every hook "
                              "site again (A/B measurement)")
    options.add_argument("--no-absint", action="store_true",
                         help="disable the value-range/memory-region "
                              "abstract interpretation "
                              "(staticanalysis/absint.py): memory-plane "
                              "merge widening, proven loop bounds, and "
                              "constant-JUMPI pruning fall back to the "
                              "identical-memory gate and flat defaults "
                              "(A/B measurement; same as "
                              "MYTHRIL_TPU_ABSINT=0)")
    options.add_argument("--no-frontier-telemetry", action="store_true",
                         help="compile the device-resident frontier "
                              "counter plane out of the fused step "
                              "(parallel/symstep.py): no opcode-class "
                              "histogram, lifecycle counters, or counter "
                              "tracks in the trace (A/B measurement; same "
                              "as MYTHRIL_TPU_FRONTIER_TELEMETRY=0)")
    options.add_argument("--no-state-merge", action="store_true",
                         help="disable on-device state merging "
                              "(veritesting) at post-dominator join "
                              "points: reconverged sibling lanes keep "
                              "exploring separately instead of collapsing "
                              "into one ITE-blended lane (A/B "
                              "measurement; same as "
                              "MYTHRIL_TPU_STATE_MERGE=0)")
    options.add_argument("--engine", default="host", choices=["host", "tpu"],
                         help="exploration engine: host worklist or the "
                              "batched TPU symbolic frontier")
    options.add_argument("--fleet", action="store_true",
                         help="pack ALL loaded contracts (multiple .sol "
                              "inputs or repeated -f) into ONE device "
                              "frontier with shared solver dispatch "
                              "(parallel/frontier.py FleetDriver); needs "
                              "--engine tpu; per-contract detections stay "
                              "byte-identical to sequential runs")
    options.add_argument("--beam-width", type=int, default=None)
    options.add_argument("--transaction-sequences", default=None,
                         help="explicit function-sequence list (json)")
    options.add_argument("--checkpoint", default=None, metavar="PATH",
                         help="periodically snapshot the analysis (host "
                              "worklist pickle; device frontier .npz rides "
                              "beside) so a killed run can --resume")
    options.add_argument("--resume", default=None, metavar="PATH",
                         help="resume a killed analysis from --checkpoint "
                              "state; corrupt/absent checkpoints degrade to "
                              "a fresh run")
    options.add_argument("--inject-fault", default=None,
                         metavar="CLASS[:NTH]",
                         help="deterministic fault injection for resilience "
                              "testing: fire failure CLASS (device_oom, "
                              "compile_error, wall_overrun, worker_crash, "
                              "native_crash, divergence, host_crash) at the "
                              "NTH visit of its boundary (N, N+, or *; "
                              "default 1); comma-separate multiple entries")
    options.add_argument("--trace-out", default=None, metavar="PATH",
                         help="write a Chrome/Perfetto trace_event JSON of "
                              "the run (phases, device flushes, XLA "
                              "compiles) to PATH; same as MYTHRIL_TPU_TRACE; "
                              "inspect with `python -m tools.traceview PATH` "
                              "or load at https://ui.perfetto.dev")
    options.add_argument("--metrics-out", default=None, metavar="PATH",
                         help="write an fsync-atomic JSON snapshot of the "
                              "observe/metrics registry (counters, gauges, "
                              "frontier telemetry) to PATH when the "
                              "analysis finishes; same as "
                              "MYTHRIL_TPU_METRICS; inspect with "
                              "`python -m tools.frontierview --metrics PATH`")
    options.add_argument("--device-crosscheck", type=int, default=0,
                         metavar="N",
                         help="re-decide every Nth device sat/unsat verdict "
                              "on the host CDCL oracle; any divergence "
                              "quarantines the device backend for the run "
                              "(0 = off)")

    output = parser.add_argument_group("output")
    output.add_argument("-o", "--outform", default="text",
                        choices=["text", "json", "jsonv2", "markdown"])
    output.add_argument("-g", "--graph", help="write call graph HTML here")
    output.add_argument("-j", "--statespace-json",
                        help="write statespace JSON here")

    rpc = parser.add_argument_group("rpc")
    rpc.add_argument("--rpc", help="custom RPC (host:port, ganache, "
                                   "infura-<net>)")
    rpc.add_argument("--rpctls", action="store_true")
    rpc.add_argument("--no-onchain-data", action="store_true",
                     help="do not fault in on-chain storage/balances/code "
                          "via RPC (on by default when -a/--rpc is given)")


def _load_contracts(parser, cli_args, disassembler):
    """Resolve the input sources into loaded contracts + target address."""
    address = cli_args.address
    if cli_args.code:
        address, _ = disassembler.load_from_bytecode(
            cli_args.code, cli_args.bin_runtime, address)
    elif cli_args.codefile:
        for path in cli_args.codefile:
            with open(path) as handle:
                code = handle.read().strip()
            address, contract = disassembler.load_from_bytecode(
                code, cli_args.bin_runtime, address)
            if len(cli_args.codefile) > 1:
                # corpus sweep: name each member after its file so fleet
                # namespaces/reports stay distinguishable
                contract.name = os.path.splitext(os.path.basename(path))[0]
                contract.input_file = path
    elif cli_args.address:
        address, _ = disassembler.load_from_address(cli_args.address)
    elif cli_args.solidity_files:
        address, _ = disassembler.load_from_solidity(cli_args.solidity_files)
    else:
        parser.error("no input: provide solidity files, -c, -f or -a")
    return address


def _build_disassembler(cli_args):
    from ..mythril import MythrilConfig, MythrilDisassembler

    eth = None
    if getattr(cli_args, "rpc", None) or getattr(cli_args, "address", None):
        config = MythrilConfig()
        config.set_api_rpc(getattr(cli_args, "rpc", None),
                           getattr(cli_args, "rpctls", False))
        eth = config.eth
    return MythrilDisassembler(
        eth=eth,
        solc_version=getattr(cli_args, "solv", None),
        solc_settings_json=getattr(cli_args, "solc_json", None))


def _format_report(report, outform: str) -> str:
    return {"text": report.as_text, "json": report.as_json,
            "jsonv2": report.as_swc_standard_format,
            "markdown": report.as_markdown}[outform]()


def _cmd_analyze(parser, cli_args, safe_functions: bool = False) -> int:
    from ..mythril import MythrilAnalyzer

    disassembler = _build_disassembler(cli_args)
    address = _load_contracts(parser, cli_args, disassembler)
    cli_args.disable_iprof = not cli_args.enable_iprof
    analyzer = MythrilAnalyzer(disassembler, cmd_args=cli_args,
                               strategy=cli_args.strategy, address=address)

    if cli_args.graph:
        with open(cli_args.graph, "w") as handle:
            handle.write(analyzer.graph_html(
                transaction_count=cli_args.transaction_count))
        return 0
    if cli_args.statespace_json:
        with open(cli_args.statespace_json, "w") as handle:
            handle.write(analyzer.dump_statespace(
                transaction_count=cli_args.transaction_count))
        return 0

    modules = cli_args.modules.split(",") if cli_args.modules else None
    report = analyzer.fire_lasers(modules=modules,
                                  transaction_count=cli_args.transaction_count)
    if safe_functions:
        issues = list(report.issues.values())
        unsafe = {issue.function for issue in issues}
        all_functions = set()
        for contract in disassembler.contracts:
            all_functions.update(contract.disassembly
                                 .function_name_to_address.keys())
        safe = sorted(all_functions - unsafe)
        print(json.dumps({"safe_functions": safe,
                          "unsafe_functions": sorted(unsafe)}, indent=2))
        return 0
    print(_format_report(report, cli_args.outform))
    return 1 if report.issues else 0


def _add_optimize_args(parser: argparse.ArgumentParser) -> None:
    inputs = parser.add_argument_group("input")
    inputs.add_argument("-c", "--code", help="hex runtime bytecode")
    inputs.add_argument("-f", "--codefile",
                        help="file containing hex runtime bytecode")

    options = parser.add_argument_group("options")
    options.add_argument("--solver", default="cdcl", choices=["cdcl", "jax"],
                         help="equivalence-proof backend: host CDCL oracle "
                              "or the batched device dispatch queue (one "
                              "flush, shared verdict cache, UNKNOWNs fall "
                              "down the ladder to the host)")
    options.add_argument("--max-block-len", type=int, default=None,
                         metavar="N",
                         help="longest pure-stack block eligible for the "
                              "exhaustive stack-scheduling search (default: "
                              "MYTHRIL_TPU_SUPEROPT_MAX_BLOCK_LEN)")
    options.add_argument("--candidates", type=int, default=None, metavar="N",
                         help="search-sequence budget per block (default: "
                              "MYTHRIL_TPU_SUPEROPT_CANDIDATES)")
    options.add_argument("--crosscheck", type=int, default=None, metavar="N",
                         help="re-decide every Nth accepted proof on the "
                              "host CDCL oracle (default: "
                              "MYTHRIL_TPU_SUPEROPT_CROSSCHECK; 0 = off)")

    output = parser.add_argument_group("output")
    output.add_argument("-o", "--outform", default="text",
                        choices=["text", "json"])
    output.add_argument("--code-out", default=None, metavar="PATH",
                        help="also write the rewritten runtime bytecode "
                             "(hex) to PATH")


def _cmd_optimize(parser, cli_args) -> int:
    from ..superopt import optimize_bytecode

    code = cli_args.code
    if code is None and cli_args.codefile:
        with open(cli_args.codefile) as handle:
            code = handle.read().strip()
    if not code:
        parser.error("optimize needs -c or -f")
    report = optimize_bytecode(
        code, solver=cli_args.solver,
        max_block_len=cli_args.max_block_len,
        candidates_budget=cli_args.candidates,
        crosscheck=cli_args.crosscheck)
    if cli_args.code_out:
        with open(cli_args.code_out, "w") as handle:
            handle.write(report.code_out + "\n")
    if cli_args.outform == "json":
        print(json.dumps(report.to_json(), indent=2, sort_keys=True))
    else:
        stats = report.proof_stats
        print(f"blocks scanned:     {report.blocks_scanned}")
        print(f"candidates proven:  {report.candidates} "
              f"({stats.get('queries', 0)} SAT queries, "
              f"{stats.get('syntactic', 0)} syntactic)")
        print(f"rewrites accepted:  {len(report.rewrites)}")
        print(f"gas saved:          {report.gas_saved} static, "
              f"{report.weighted_gas_saved} loop-weighted")
        for rewrite in report.rewrites:
            print(f"  pc {rewrite.start_pc:#06x} [{rewrite.rule}] "
                  f"-{rewrite.gas_saved} gas (x{rewrite.weight}, "
                  f"{rewrite.proof}): "
                  f"{'; '.join(rewrite.before)} => "
                  f"{'; '.join(rewrite.after) or '<elided>'}")
        if report.note:
            print(f"note: {report.note}")
        print(report.code_out)
    # a crosscheck divergence means an unsound device verdict slipped
    # through: loud, non-zero, and the rewrite was already rejected
    return 1 if report.proof_stats.get("divergences") else 0


def _add_serve_args(parser: argparse.ArgumentParser) -> None:
    transport = parser.add_argument_group("transport")
    transport.add_argument("--socket", default=None, metavar="PATH",
                           help="unix-socket path (default: "
                                "MYTHRIL_TPU_SERVE_SOCKET or "
                                "~/.mythril_tpu/serve.sock)")
    transport.add_argument("--stdio", action="store_true",
                           help="serve one JSON-lines session on "
                                "stdin/stdout instead of a socket "
                                "(logs stay on stderr)")
    transport.add_argument("--http", type=int, default=None, metavar="PORT",
                           help="serve the thin HTTP shim on PORT instead "
                                "of a socket (POST / = one protocol "
                                "request; GET /healthz = ping)")
    transport.add_argument("--http-host", default="127.0.0.1",
                           help="bind address for --http")

    daemon = parser.add_argument_group("daemon")
    daemon.add_argument("--solver", default="cdcl", choices=["cdcl", "jax"],
                        help="default SAT backend for requests that do not "
                             "pick one")
    daemon.add_argument("--engine", default="host", choices=["host", "tpu"],
                        help="default exploration engine")
    daemon.add_argument("--strategy", default="bfs",
                        choices=["dfs", "bfs", "naive-random",
                                 "weighted-random", "beam-search", "pending"])
    daemon.add_argument("--manifest", default=None, metavar="PATH",
                        help="warm-set manifest (default: "
                             "MYTHRIL_TPU_SERVE_MANIFEST or "
                             "~/.mythril_tpu/warmset.json)")
    daemon.add_argument("--no-warmup", action="store_true",
                        help="skip the startup AOT warmup phase")
    daemon.add_argument("--max-inflight", type=int, default=None,
                        help="admitted-but-unfinished request bound "
                             "(default: MYTHRIL_TPU_SERVE_MAX_INFLIGHT)")
    daemon.add_argument("--fleet", action="store_true",
                        help="micro-batch concurrent compatible analyze "
                             "requests into one shared fleet step instead "
                             "of serializing them on the engine lock (same "
                             "as MYTHRIL_TPU_FLEET_SERVE=1; join window / "
                             "batch size via MYTHRIL_TPU_FLEET_WINDOW_MS / "
                             "MYTHRIL_TPU_FLEET_MAX_BATCH)")
    daemon.add_argument("--workers", type=int, default=None, metavar="N",
                        help="run the engine in N supervised worker "
                             "processes instead of in-process: a "
                             "segfault/OOM/hang kills one sandbox, the "
                             "request is retried once, repeat-offender "
                             "contracts are quarantined (same as "
                             "MYTHRIL_TPU_SERVE_WORKERS=N; 0 disables)")
    daemon.add_argument("--workers-min", type=int, default=None,
                        metavar="N",
                        help="autoscale floor for the worker pool (same "
                             "as MYTHRIL_TPU_SERVE_WORKERS_MIN; 0 uses "
                             "the --workers size)")
    daemon.add_argument("--workers-max", type=int, default=None,
                        metavar="N",
                        help="autoscale ceiling for the worker pool: the "
                             "supervisor grows the pool on sustained "
                             "backlog and shrinks it on sustained idle "
                             "(same as MYTHRIL_TPU_SERVE_WORKERS_MAX; "
                             "0, the default, keeps the pool fixed)")
    daemon.add_argument("--queue-max", type=int, default=None, metavar="N",
                        help="bounded admission-queue capacity; past it "
                             "the lowest-priority oldest waiter is shed "
                             "with a typed `overloaded` error (same as "
                             "MYTHRIL_TPU_SERVE_QUEUE_MAX)")
    daemon.add_argument("--inject-fault", default=None, metavar="SPEC",
                        help="deterministic fault injection for the worker "
                             "pool, e.g. worker_segv:2 (kill the worker on "
                             "the 2nd dispatched job); same grammar as the "
                             "analyze-side flag, worker_* classes fire at "
                             "the supervisor's dispatch site")


def _cmd_serve(cli_args) -> int:
    from ..serve.daemon import install_sigterm_drain
    from ..serve.service import AnalysisService
    from ..serve.warmset import default_manifest_path

    # flags are sugar over the knobs the admission queue and autoscaler
    # read at construction time
    for flag, knob in ((cli_args.workers_min,
                        "MYTHRIL_TPU_SERVE_WORKERS_MIN"),
                       (cli_args.workers_max,
                        "MYTHRIL_TPU_SERVE_WORKERS_MAX"),
                       (cli_args.queue_max,
                        "MYTHRIL_TPU_SERVE_QUEUE_MAX")):
        if flag is not None:
            os.environ[knob] = str(flag)
    service = AnalysisService(
        solver=cli_args.solver, engine=cli_args.engine,
        strategy=cli_args.strategy,
        manifest_path=cli_args.manifest or default_manifest_path(),
        warmup=False if cli_args.no_warmup else None,
        max_inflight=cli_args.max_inflight,
        fleet=True if cli_args.fleet else None,
        workers=cli_args.workers,
        inject_fault=cli_args.inject_fault)
    install_sigterm_drain(service)
    if cli_args.stdio:
        from ..serve.daemon import serve_stdio

        serve_stdio(service)
        return 0
    if cli_args.http is not None:
        from ..serve.http_shim import serve_http

        serve_http(service, host=cli_args.http_host, port=cli_args.http)
        return 0
    from ..serve.daemon import serve_socket

    serve_socket(service, socket_path=cli_args.socket)
    return 0


def _cmd_client(parser, cli_args) -> int:
    from ..serve import client as serve_client

    payload = {"op": cli_args.op}
    if cli_args.id is not None:
        payload["id"] = cli_args.id
    if cli_args.op in ("analyze", "optimize"):
        code = cli_args.code
        if code is None and cli_args.codefile:
            with open(cli_args.codefile) as handle:
                code = handle.read().strip()
        if not code:
            parser.error(f"client {cli_args.op} needs -c or -f")
        payload.update(
            code=code, bin_runtime=cli_args.bin_runtime,
            transaction_count=cli_args.transaction_count,
            strategy=cli_args.strategy, max_depth=cli_args.max_depth)
        if cli_args.modules:
            payload["modules"] = cli_args.modules.split(",")
        if cli_args.solver:
            payload["solver"] = cli_args.solver
        if cli_args.engine:
            payload["engine"] = cli_args.engine
        if cli_args.deadline_ms:
            payload["deadline_ms"] = cli_args.deadline_ms
        if cli_args.priority:
            payload["priority"] = cli_args.priority
    try:
        reply = serve_client.request_with_retry(
            payload, socket_path=cli_args.socket,
            timeout=cli_args.timeout,
            attempts=max(1, cli_args.retries))
    except serve_client.ServeClientError as error:
        print(f"myth-tpu client: {error}", file=sys.stderr)
        return 2
    print(json.dumps(reply, indent=2, sort_keys=True))
    return 0 if reply.get("ok") else 2


def main(argv=None) -> int:
    from .. import __version__

    parser = argparse.ArgumentParser(
        prog="myth-tpu", description="TPU-native EVM security analysis")
    parser.add_argument("-v", type=int, default=2, metavar="LOG_LEVEL",
                        help="log level 0-5")
    subparsers = parser.add_subparsers(dest="command")

    analyze = subparsers.add_parser("analyze", aliases=["a"],
                                    help="symbolically analyze a contract")
    _add_analysis_args(analyze)

    safe = subparsers.add_parser("safe-functions",
                                 help="list functions with no detected issues")
    _add_analysis_args(safe)

    optimize = subparsers.add_parser(
        "optimize", aliases=["opt"],
        help="gas-superoptimize runtime bytecode: every rewrite backed "
             "by an equivalence proof (batched device SAT or host CDCL)")
    _add_optimize_args(optimize)

    disasm = subparsers.add_parser("disassemble", aliases=["d"],
                                   help="disassemble EVM bytecode")
    disasm.add_argument("-c", "--code", default=None)
    disasm.add_argument("-f", "--codefile", default=None)
    disasm.add_argument("-a", "--address", default=None)
    disasm.add_argument("--rpc", default=None)
    disasm.add_argument("--rpctls", action="store_true")

    foundry = subparsers.add_parser("foundry", aliases=["f"],
                                    help="analyze a foundry project")
    _add_analysis_args(foundry)
    foundry.add_argument("--project-root", default=".")

    concolic = subparsers.add_parser(
        "concolic", help="flip branches of a concrete transaction trace")
    concolic.add_argument("input", help="ConcreteData json file")
    concolic.add_argument("--branches", required=True,
                          help="comma-separated JUMPI addresses to flip")
    concolic.add_argument("--engine", default="oracle",
                          choices=["oracle", "lockstep"],
                          help="concrete replay engine (lockstep = batched "
                               "TPU interpreter)")

    read_storage = subparsers.add_parser(
        "read-storage", help="read storage slots from a deployed contract")
    read_storage.add_argument("address")
    read_storage.add_argument("params", nargs="+",
                              help="position | position length | "
                                   "mapping position key...")
    read_storage.add_argument("--rpc", default="localhost:8545")
    read_storage.add_argument("--rpctls", action="store_true")

    f2h = subparsers.add_parser("function-to-hash",
                                help="keccak selector of a signature")
    f2h.add_argument("signature")

    h2a = subparsers.add_parser("hash-to-address",
                                help="signature lookup for a 4-byte selector")
    h2a.add_argument("hash")

    serve = subparsers.add_parser(
        "serve", help="run the persistent analysis daemon "
                      "(JSON-lines over stdio/unix-socket/HTTP, "
                      "AOT-warmed solver buckets)")
    _add_serve_args(serve)

    client = subparsers.add_parser(
        "client", help="send one request to a running serve daemon")
    client.add_argument("op", nargs="?", default="analyze",
                        choices=["analyze", "optimize", "ping", "status",
                                 "shutdown"])
    client.add_argument("-c", "--code", help="hex creation bytecode")
    client.add_argument("-f", "--codefile",
                        help="file containing hex bytecode")
    client.add_argument("--bin-runtime", action="store_true",
                        help="treat -c/-f input as runtime (deployed) code")
    client.add_argument("-m", "--modules",
                        help="comma-separated detection module list")
    client.add_argument("-t", "--transaction-count", type=int, default=2)
    client.add_argument("--strategy", default="bfs",
                        choices=["dfs", "bfs", "naive-random",
                                 "weighted-random", "beam-search", "pending"])
    client.add_argument("--max-depth", type=int, default=128)
    client.add_argument("--solver", default=None, choices=["cdcl", "jax"])
    client.add_argument("--engine", default=None, choices=["host", "tpu"])
    client.add_argument("--deadline-ms", type=int, default=None,
                        help="per-request analysis deadline (the daemon "
                             "returns a partial report when it expires)")
    client.add_argument("--priority", default=None,
                        choices=["interactive", "bulk"],
                        help="admission class (default interactive): "
                             "bulk work absorbs shedding under overload "
                             "and yields the engine to interactive "
                             "arrivals")
    client.add_argument("--retries", type=int, default=1, metavar="N",
                        help="total attempts for retryable failures "
                             "(connection reset/refused, busy, "
                             "overloaded) with jittered exponential "
                             "backoff honoring the daemon's "
                             "retry_after_ms hint (default 1: no retry)")
    client.add_argument("--id", default=None, help="request id to echo")
    client.add_argument("--socket", default=None, metavar="PATH",
                        help="daemon socket path (default: "
                             "MYTHRIL_TPU_SERVE_SOCKET or "
                             "~/.mythril_tpu/serve.sock)")
    client.add_argument("--timeout", type=float, default=600.0,
                        help="seconds to wait for the reply")

    subparsers.add_parser("list-detectors", help="list detection modules")
    subparsers.add_parser("version", help="print version")

    cli_args = parser.parse_args(argv)
    if getattr(cli_args, "transaction_sequences", None):
        # "[[0xdeadbeef], [-1]]" -> nested int lists (reference cli.py:651-668;
        # a sequence longer than -t silently extends the tx count there too)
        from ast import literal_eval

        try:
            cli_args.transaction_sequences = literal_eval(
                str(cli_args.transaction_sequences))
        except (ValueError, SyntaxError):
            parser.error("--transaction-sequences is not a valid nested list")
        # validate VALUES, not just shape: hex(h) mangles negative ints other
        # than -1/-2 and selectors wider than 4 bytes would overflow the
        # selector encoding downstream (ADVICE r4)
        if not isinstance(cli_args.transaction_sequences, list):
            parser.error("--transaction-sequences must be a nested list")
        for tx_hashes in cli_args.transaction_sequences:
            if tx_hashes is None:
                continue
            if not isinstance(tx_hashes, list):
                parser.error("--transaction-sequences entries must be lists")
            for h in tx_hashes:
                if isinstance(h, bool):
                    # bool is an int subclass: [true] would silently become
                    # selector 0x00000001
                    parser.error(
                        f"--transaction-sequences value {h!r} is not a "
                        "4-byte function selector or -1/-2")
                if h in (-1, -2):
                    continue
                if not isinstance(h, int) or not 0 <= h < 2 ** 32:
                    parser.error(
                        f"--transaction-sequences value {h!r} is not a "
                        "4-byte function selector or -1/-2")
        if len(cli_args.transaction_sequences) != cli_args.transaction_count:
            cli_args.transaction_count = len(cli_args.transaction_sequences)
    logging.basicConfig(
        level=[logging.NOTSET, logging.CRITICAL, logging.ERROR,
               logging.WARNING, logging.INFO,
               logging.DEBUG][min(cli_args.v, 5)],
        format="%(levelname)s:%(name)s: %(message)s")

    # activate third-party plugins published via the mythril_tpu.plugins
    # entry-point group (reference cli.py boots MythrilPluginLoader the same
    # way; plugin/discovery.py)
    from ..plugin import MythrilPluginLoader

    MythrilPluginLoader().load_default_enabled()

    if cli_args.command == "serve":
        return _cmd_serve(cli_args)
    if cli_args.command == "client":
        return _cmd_client(parser, cli_args)
    if cli_args.command in ("analyze", "a"):
        return _cmd_analyze(parser, cli_args)
    if cli_args.command in ("optimize", "opt"):
        return _cmd_optimize(parser, cli_args)
    if cli_args.command == "safe-functions":
        return _cmd_analyze(parser, cli_args, safe_functions=True)
    if cli_args.command in ("foundry", "f"):
        from ..mythril import MythrilAnalyzer, MythrilDisassembler

        disassembler = MythrilDisassembler()
        disassembler.load_from_foundry(cli_args.project_root)
        cli_args.disable_iprof = not cli_args.enable_iprof
        analyzer = MythrilAnalyzer(disassembler, cmd_args=cli_args,
                                   strategy=cli_args.strategy)
        report = analyzer.fire_lasers(
            modules=cli_args.modules.split(",") if cli_args.modules else None,
            transaction_count=cli_args.transaction_count)
        print(_format_report(report, cli_args.outform))
        return 1 if report.issues else 0
    if cli_args.command in ("disassemble", "d"):
        from ..frontends import Disassembly

        code = cli_args.code
        if code is None and cli_args.codefile:
            with open(cli_args.codefile) as handle:
                code = handle.read().strip()
        if code is None and cli_args.address:
            disassembler = _build_disassembler(cli_args)
            _, contract = disassembler.load_from_address(cli_args.address)
            code = contract.code
        if not code:
            parser.error("provide -c, -f or -a")
        sys.stdout.write(Disassembly(code).get_easm())
        return 0
    if cli_args.command == "concolic":
        from ..concolic.concolic_execution import concolic_execution

        with open(cli_args.input) as handle:
            concrete_data = json.load(handle)
        branches = [int(b, 0) for b in cli_args.branches.split(",")]
        flipped = concolic_execution(concrete_data, branches,
                                     engine=cli_args.engine)
        print(json.dumps(flipped, indent=2))
        return 0
    if cli_args.command == "read-storage":
        disassembler = _build_disassembler(cli_args)
        print(disassembler.get_state_variable_from_storage(
            cli_args.address, cli_args.params))
        return 0
    if cli_args.command == "function-to-hash":
        from ..mythril import MythrilDisassembler

        print(MythrilDisassembler.hash_for_function_signature(
            cli_args.signature))
        return 0
    if cli_args.command == "hash-to-address":
        from ..support.signatures import SignatureDB

        for name in SignatureDB().get(cli_args.hash) or ["unknown"]:
            print(name)
        return 0
    if cli_args.command == "list-detectors":
        from ..analysis.module import ModuleLoader

        for module in ModuleLoader().get_detection_modules():
            print(f"{module.__class__.__name__}: {module.name} "
                  f"(SWC-{module.swc_id})")
        return 0
    if cli_args.command == "version":
        print(f"myth-tpu {__version__}")
        return 0
    parser.print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())
