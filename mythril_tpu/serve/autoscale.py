"""Elastic autoscaling for the serve worker pool.

A fixed pool wastes accelerator RAM when traffic is quiet and queues
requests when it spikes; the autoscaler closes the loop between the
load signals the daemon already exports and the supervisor's pool size:

* **Signals** — admission-queue depth (serve/admission.py ``depths``)
  and pool occupancy (supervisor ``occupancy``: busy vs live workers).
* **Policy** — hysteresis on consecutive ticks, not instantaneous
  state, so one bursty arrival cannot thrash the pool: scale **up** one
  worker after ``MYTHRIL_TPU_SERVE_AUTOSCALE_UP_AFTER`` consecutive
  *backlogged* ticks (requests queued while every live worker is busy),
  scale **down** one worker after the much longer
  ``MYTHRIL_TPU_SERVE_AUTOSCALE_DOWN_AFTER`` consecutive *idle* ticks
  (empty queue, zero busy workers). Up is eager and down is reluctant —
  shedding a request costs more than an idle worker.
* **Bounds** — the target stays in
  [``MYTHRIL_TPU_SERVE_WORKERS_MIN`` (0 → the configured pool size),
  ``MYTHRIL_TPU_SERVE_WORKERS_MAX``]; WORKERS_MAX=0 (the default)
  disables autoscaling entirely and the pool stays fixed.
* **Lever** — ``Supervisor.scale_to``: growth spawns slots that come up
  warm through the durable exec/verdict caches (<2 s on a warmed
  sidecar instead of a cold XLA compile); shrink only retires idle
  workers, so the target is re-asserted every tick until the pool
  converges.

Every decision lands in ``serve.autoscale.target`` (gauge) and
``serve.autoscale.scale_ups`` / ``scale_downs`` (counters), a slog
event, and the rollup ``status()`` block surfaced by /healthz and the
``status`` op.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from ..support import tpu_config

log = logging.getLogger(__name__)


class Autoscaler:
    """Hysteresis controller between admission depth and pool size."""

    def __init__(self, supervisor, admission,
                 minimum: Optional[int] = None,
                 maximum: Optional[int] = None,
                 interval_ms: Optional[int] = None,
                 up_after: Optional[int] = None,
                 down_after: Optional[int] = None):
        self.supervisor = supervisor
        self.admission = admission
        if minimum is None:
            minimum = tpu_config.get_int("MYTHRIL_TPU_SERVE_WORKERS_MIN")
        if maximum is None:
            maximum = tpu_config.get_int("MYTHRIL_TPU_SERVE_WORKERS_MAX")
        base = supervisor.workers if supervisor is not None else 1
        self.minimum = max(1, int(minimum) if minimum else base)
        self.maximum = int(maximum)
        if interval_ms is None:
            interval_ms = tpu_config.get_int(
                "MYTHRIL_TPU_SERVE_AUTOSCALE_INTERVAL_MS")
        self.interval_s = max(int(interval_ms), 50) / 1000.0
        if up_after is None:
            up_after = tpu_config.get_int(
                "MYTHRIL_TPU_SERVE_AUTOSCALE_UP_AFTER")
        if down_after is None:
            down_after = tpu_config.get_int(
                "MYTHRIL_TPU_SERVE_AUTOSCALE_DOWN_AFTER")
        self.up_after = max(1, int(up_after))
        self.down_after = max(1, int(down_after))
        self.enabled = (supervisor is not None and admission is not None
                        and self.maximum > 0
                        and self.maximum > self.minimum)
        self.target = min(max(base, self.minimum),
                          self.maximum) if self.enabled else base
        self.scale_ups = 0
        self.scale_downs = 0
        self.last_event: Optional[dict] = None
        self._backlog_ticks = 0
        self._idle_ticks = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        from ..observe import metrics, slog

        metrics.set_gauge("serve.autoscale.target", float(self.target))
        slog.event("serve.autoscale.start", minimum=self.minimum,
                   maximum=self.maximum, interval_s=self.interval_s,
                   up_after=self.up_after, down_after=self.down_after)
        log.info("autoscaler on: pool [%d, %d], tick %.2fs, up after "
                 "%d backlogged tick(s), down after %d idle tick(s)",
                 self.minimum, self.maximum, self.interval_s,
                 self.up_after, self.down_after)
        self._thread = threading.Thread(target=self._run,
                                        name="serve-autoscaler",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                log.exception("autoscaler tick failed")

    # -- control loop ---------------------------------------------------

    def tick(self) -> None:
        """One control decision (public so tests drive it without the
        timer thread)."""
        from ..observe import metrics, slog

        depths = self.admission.depths()
        depth = sum(depths.values())
        occ = self.supervisor.occupancy()
        backlogged = depth > 0 and occ["busy"] >= occ["live"]
        idle = depth == 0 and occ["busy"] == 0
        if backlogged:
            self._backlog_ticks += 1
            self._idle_ticks = 0
        elif idle:
            self._idle_ticks += 1
            self._backlog_ticks = 0
        else:
            self._backlog_ticks = 0
            self._idle_ticks = 0
        if (self._backlog_ticks >= self.up_after
                and self.target < self.maximum):
            self.target += 1
            self.scale_ups += 1
            self._backlog_ticks = 0
            metrics.inc("serve.autoscale.scale_ups")
            self.last_event = {"dir": "up", "to": self.target,
                               "at": time.time(), "depth": depth,
                               "busy": occ["busy"]}
            slog.event("serve.autoscale.up", target=self.target,
                       depth=depth, busy=occ["busy"], live=occ["live"])
            log.info("autoscale up -> %d worker(s) (depth %d, %d/%d "
                     "busy)", self.target, depth, occ["busy"],
                     occ["live"])
        elif (self._idle_ticks >= self.down_after
                and self.target > self.minimum):
            self.target -= 1
            self.scale_downs += 1
            self._idle_ticks = 0
            metrics.inc("serve.autoscale.scale_downs")
            self.last_event = {"dir": "down", "to": self.target,
                               "at": time.time(), "depth": depth,
                               "busy": occ["busy"]}
            slog.event("serve.autoscale.down", target=self.target,
                       depth=depth, busy=occ["busy"], live=occ["live"])
            log.info("autoscale down -> %d worker(s)", self.target)
        metrics.set_gauge("serve.autoscale.target", float(self.target))
        # re-assert every tick: shrink can only retire idle workers, so
        # the pool may converge to the target over several ticks
        self.supervisor.scale_to(self.target)

    # -- introspection --------------------------------------------------

    def status(self) -> dict:
        occ = (self.supervisor.occupancy()
               if self.supervisor is not None else {"busy": 0, "live": 0})
        return {
            "enabled": self.enabled,
            "min": self.minimum,
            "max": self.maximum,
            "target": self.target,
            "current": occ["live"],
            "busy": occ["busy"],
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "last_event": self.last_event,
        }
