"""JSON-lines request protocol for the `myth-tpu serve` daemon.

One request per line, one reply per line, UTF-8, newline-terminated —
the same framing over stdin/stdout, a unix socket, or (body-per-request)
the HTTP shim. Kept dependency-free (stdlib only, no jax) so clients and
the protocol unit tests never pay an accelerator import.

Request shape::

    {"id": "r1", "op": "analyze", "code": "6080...", "bin_runtime": false,
     "modules": ["AccidentallyKillable"], "transaction_count": 2,
     "deadline_ms": 60000, "solver": "cdcl", "engine": "host",
     "strategy": "bfs"}

Ops: ``analyze`` (the workload), ``optimize`` (gas superoptimization —
shares analyze's code/solver/deadline/priority validation; replies carry
the OptimizationReport), ``ping`` (liveness), ``status`` (warm-set
and metrics introspection), ``healthz`` (liveness + counters rollup),
``metrics`` (Prometheus exposition + the snapshot-ring tail; never
touches the engine lock), ``shutdown`` (drain and exit). Replies echo
the request ``id`` (auto-assigned ``req-N`` when absent) and carry either
``"ok": true`` plus the payload, or ``"ok": false`` plus a typed error::

    {"id": "r1", "ok": false,
     "error": {"code": "bad_request", "message": "..."}}

Error codes: ``line_too_long``, ``bad_json``, ``bad_request``,
``unknown_op``, ``busy`` (in-flight bound reached — retry later),
``overloaded`` (shed by admission control — the queue is past its
high-water mark or the deadline cannot be met at current depth; the
error object carries ``retry_after_ms``, a backoff hint scaled by
observed p95 service time — see serve/admission.py), ``shutting_down``,
``analysis_failed``, ``quarantined`` (this bytecode has repeatedly
killed worker processes and is refused at admission — see
serve/quarantine.py). Validation failures never kill the connection:
the daemon replies with the error and keeps reading.

``priority`` classes every analyze request for admission and fleet
batch composition: ``interactive`` (the default — latency-sensitive,
dequeues first, never shed while bulk work is queued) or ``bulk``
(throughput traffic that absorbs shedding under overload).

``deadline_ms`` rides the engine's existing deadline-drain substrate: it
becomes the analysis execution timeout, so an over-deadline request
returns a valid-but-partial report (``incomplete: true``) instead of
hanging the queue.
"""

from __future__ import annotations

import itertools
import json
from typing import Dict, Iterator, List, Optional

#: hard per-line bound: a runtime bytecode tops out around 24 KiB (48 KiB
#: of hex); 8 MiB leaves room for huge inits while bounding a hostile peer
MAX_LINE_BYTES = 8 << 20

OPS = ("analyze", "optimize", "ping", "status", "shutdown", "healthz",
       "metrics")

STRATEGIES = ("dfs", "bfs", "naive-random", "weighted-random",
              "beam-search", "pending")

#: admission classes, best-first (see serve/admission.py)
PRIORITIES = ("interactive", "bulk")

#: one day, matching the CLI's --execution-timeout default ceiling
MAX_DEADLINE_MS = 86_400_000

_AUTO_ID = itertools.count(1)


class ProtocolError(Exception):
    """A request the daemon must answer with a typed error reply."""

    def __init__(self, code: str, message: str,
                 request_id: Optional[object] = None):
        super().__init__(message)
        self.code = code
        self.message = message
        self.request_id = request_id


class Request:
    """One validated request: ``op``, ``id``, and the analyze params
    (normalized, defaults applied) under ``params``."""

    __slots__ = ("op", "id", "params")

    def __init__(self, op: str, request_id: object, params: Dict):
        self.op = op
        self.id = request_id
        self.params = params


def _require(condition: bool, message: str, request_id: object) -> None:
    if not condition:
        raise ProtocolError("bad_request", message, request_id)


def _hex_body(code: str) -> str:
    body = code[2:] if code.lower().startswith("0x") else code
    return body


def parse_request(line) -> Request:
    """Validate one request line (str or bytes). Raises ProtocolError
    (never anything else) on any malformed input."""
    if isinstance(line, bytes):
        if len(line) > MAX_LINE_BYTES:
            raise ProtocolError("line_too_long",
                                f"request exceeds {MAX_LINE_BYTES} bytes")
        try:
            line = line.decode("utf-8")
        except UnicodeDecodeError as error:
            raise ProtocolError("bad_json", f"not valid UTF-8: {error}")
    elif len(line) > MAX_LINE_BYTES:
        raise ProtocolError("line_too_long",
                            f"request exceeds {MAX_LINE_BYTES} bytes")
    try:
        doc = json.loads(line)
    except (json.JSONDecodeError, ValueError) as error:
        raise ProtocolError("bad_json", f"not valid JSON: {error}")
    if not isinstance(doc, dict):
        raise ProtocolError("bad_request", "request must be a JSON object")

    request_id = doc.get("id")
    if request_id is None:
        request_id = f"req-{next(_AUTO_ID)}"
    _require(isinstance(request_id, (str, int)),
             "id must be a string or integer", None)

    op = doc.get("op")
    _require(isinstance(op, str), "op is required", request_id)
    if op not in OPS:
        raise ProtocolError("unknown_op",
                            f"unknown op {op!r}; expected one of {OPS}",
                            request_id)
    if op not in ("analyze", "optimize"):
        return Request(op, request_id, {})

    code = doc.get("code")
    _require(isinstance(code, str) and code.strip() != "",
             "analyze requires a non-empty hex 'code' field", request_id)
    body = _hex_body(code.strip())
    _require(len(body) % 2 == 0, "code has an odd hex digit count",
             request_id)
    try:
        bytes.fromhex(body)
    except ValueError:
        raise ProtocolError("bad_request", "code is not valid hex",
                            request_id)

    params: Dict = {"code": code.strip()}
    params["bin_runtime"] = bool(doc.get("bin_runtime", False))

    modules = doc.get("modules")
    if modules is not None:
        _require(isinstance(modules, list)
                 and all(isinstance(m, str) for m in modules),
                 "modules must be a list of module names", request_id)
    params["modules"] = modules

    tx_count = doc.get("transaction_count", 2)
    _require(isinstance(tx_count, int) and not isinstance(tx_count, bool)
             and 1 <= tx_count <= 16,
             "transaction_count must be an integer in [1, 16]", request_id)
    params["transaction_count"] = tx_count

    strategy = doc.get("strategy", "bfs")
    _require(strategy in STRATEGIES,
             f"strategy must be one of {STRATEGIES}", request_id)
    params["strategy"] = strategy

    solver = doc.get("solver")
    _require(solver in (None, "cdcl", "jax"),
             "solver must be 'cdcl' or 'jax'", request_id)
    params["solver"] = solver

    engine = doc.get("engine")
    _require(engine in (None, "host", "tpu"),
             "engine must be 'host' or 'tpu'", request_id)
    params["engine"] = engine

    deadline_ms = doc.get("deadline_ms")
    if deadline_ms is not None:
        _require(isinstance(deadline_ms, (int, float))
                 and not isinstance(deadline_ms, bool)
                 and 0 < deadline_ms <= MAX_DEADLINE_MS,
                 f"deadline_ms must be in (0, {MAX_DEADLINE_MS}]",
                 request_id)
    params["deadline_ms"] = deadline_ms

    max_depth = doc.get("max_depth", 128)
    _require(isinstance(max_depth, int) and not isinstance(max_depth, bool)
             and 1 <= max_depth <= 4096,
             "max_depth must be an integer in [1, 4096]", request_id)
    params["max_depth"] = max_depth

    priority = doc.get("priority", "interactive")
    _require(priority in PRIORITIES,
             f"priority must be one of {PRIORITIES}", request_id)
    params["priority"] = priority

    return Request(op, request_id, params)


def encode(reply: Dict) -> str:
    """One newline-terminated reply line (newline-free by construction:
    json.dumps never emits raw newlines)."""
    return json.dumps(reply, sort_keys=True) + "\n"


def ok_reply(request_id: object, **payload) -> Dict:
    reply = {"id": request_id, "ok": True}
    reply.update(payload)
    return reply


def error_reply(request_id: object, code: str, message: str) -> Dict:
    return {"id": request_id, "ok": False,
            "error": {"code": code, "message": message}}


def read_lines(stream) -> Iterator[bytes]:
    """Yield newline-delimited frames from a binary stream, enforcing
    MAX_LINE_BYTES mid-read (an unbounded line is truncated — its parse
    then fails loudly as line_too_long — instead of buffering forever)."""
    # read1 (BufferedReader, socket makefiles) returns as soon as ANY
    # bytes arrive; plain .read(n) would block until n bytes or EOF and
    # deadlock an interactive client that awaits each reply before
    # sending its next request
    read = getattr(stream, "read1", stream.read)
    buffer = bytearray()
    overflow = False
    while True:
        chunk = read(65536)
        if not chunk:
            break
        start = 0
        while True:
            newline = chunk.find(b"\n", start)
            if newline < 0:
                if not overflow:
                    buffer.extend(chunk[start:])
                    if len(buffer) > MAX_LINE_BYTES:
                        overflow = True
                break
            if overflow:
                yield bytes(buffer[:MAX_LINE_BYTES + 1])
                overflow = False
            else:
                buffer.extend(chunk[start:newline])
                yield bytes(buffer)
            buffer.clear()
            start = newline + 1
    if buffer and not overflow:
        yield bytes(buffer)
    elif overflow:
        yield bytes(buffer[:MAX_LINE_BYTES + 1])


def iter_requests(stream) -> Iterator[object]:
    """Parse frames from a binary stream: yields Request objects and, for
    malformed frames, the ProtocolError to reply with (the stream stays
    usable — one bad line is one error reply, not a dropped connection)."""
    for frame in read_lines(stream):
        if not frame.strip():
            continue
        try:
            yield parse_request(frame)
        except ProtocolError as error:
            yield error
