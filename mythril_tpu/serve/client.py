"""Client side of the serve protocol: connect, send, read replies.

Stdlib-only and jax-free — importing this never touches the engine, so
`myth-tpu client` stays instant even when the daemon is mid-warmup.
"""

from __future__ import annotations

import json
import socket
from typing import Dict, List, Optional

from . import protocol
from .daemon import default_socket_path


class ServeClientError(RuntimeError):
    """Connection-level failure talking to the daemon (the daemon's own
    typed errors come back as normal replies, not exceptions)."""


def roundtrip(requests: List[Dict], socket_path: Optional[str] = None,
              timeout: float = 600.0) -> List[Dict]:
    """Send request dicts over one connection; return one reply dict per
    request, in order."""
    path = socket_path or default_socket_path()
    connection = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    connection.settimeout(timeout)
    try:
        connection.connect(path)
    except OSError as error:
        connection.close()
        raise ServeClientError(
            f"no daemon at {path} ({error}); start one with "
            f"`myth-tpu serve`") from error
    replies: List[Dict] = []
    try:
        with connection:
            wfile = connection.makefile("wb")
            rfile = connection.makefile("rb")
            for request in requests:
                wfile.write(protocol.encode(request).encode("utf-8"))
            wfile.flush()
            connection.shutdown(socket.SHUT_WR)
            for line in rfile:
                line = line.strip()
                if not line:
                    continue
                try:
                    replies.append(json.loads(line))
                except ValueError as error:
                    raise ServeClientError(
                        f"malformed reply from daemon: {error}")
    except socket.timeout as error:
        raise ServeClientError(
            f"daemon did not reply within {timeout:.0f}s") from error
    if len(replies) < len(requests):
        raise ServeClientError(
            f"daemon closed the connection after {len(replies)} of "
            f"{len(requests)} replies")
    return replies


def request(payload: Dict, socket_path: Optional[str] = None,
            timeout: float = 600.0) -> Dict:
    """One request, one reply."""
    return roundtrip([payload], socket_path=socket_path,
                     timeout=timeout)[0]
