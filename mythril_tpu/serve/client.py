"""Client side of the serve protocol: connect, send, read replies.

Stdlib-only and jax-free — importing this never touches the engine, so
`myth-tpu client` stays instant even when the daemon is mid-warmup.

Resilience (:func:`request_with_retry`): transport-level failures a
restarting daemon legitimately produces — connection refused, broken
pipe, connection reset, a connection closed before the reply — are
*retryable*; an ``overloaded`` reply is retryable *after honoring its
``retry_after_ms``* hint. Retries use jittered exponential backoff with
a bounded attempt count, so a client neither hammers an overloaded
daemon nor spins forever against a dead one. Protocol-level errors
(``bad_request``, ``quarantined``, ``analysis_failed``…) are never
retried — resending a request the daemon *answered* cannot change the
answer.
"""

from __future__ import annotations

import json
import random
import socket
import time
from typing import Dict, List, Optional

from . import protocol
from .daemon import default_socket_path

#: error codes worth a retry after backoff (the daemon said "later",
#: not "no")
RETRYABLE_CODES = ("busy", "overloaded")


class ServeClientError(RuntimeError):
    """Connection-level failure talking to the daemon (the daemon's own
    typed errors come back as normal replies, not exceptions)."""

    #: True for failure shapes a daemon restart/overload produces —
    #: refused, reset, broken pipe, early close — where a retry against
    #: the (re)started daemon can succeed
    retryable = False


def roundtrip(requests: List[Dict], socket_path: Optional[str] = None,
              timeout: float = 600.0) -> List[Dict]:
    """Send request dicts over one connection; return one reply dict per
    request, in order."""
    path = socket_path or default_socket_path()
    connection = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    connection.settimeout(timeout)
    try:
        connection.connect(path)
    except OSError as error:
        connection.close()
        raise _transport_error(
            f"no daemon at {path} ({error}); start one with "
            f"`myth-tpu serve`", error) from error
    replies: List[Dict] = []
    try:
        with connection:
            wfile = connection.makefile("wb")
            rfile = connection.makefile("rb")
            for request in requests:
                wfile.write(protocol.encode(request).encode("utf-8"))
            wfile.flush()
            connection.shutdown(socket.SHUT_WR)
            for line in rfile:
                line = line.strip()
                if not line:
                    continue
                try:
                    replies.append(json.loads(line))
                except ValueError as error:
                    raise ServeClientError(
                        f"malformed reply from daemon: {error}")
    except socket.timeout as error:
        raise ServeClientError(
            f"daemon did not reply within {timeout:.0f}s") from error
    except OSError as error:
        raise _transport_error(
            f"connection to daemon failed mid-exchange ({error})",
            error) from error
    if len(replies) < len(requests):
        # a daemon dying (or restarting) mid-exchange closes early; the
        # surviving daemon can serve the retry
        error = ServeClientError(
            f"daemon closed the connection after {len(replies)} of "
            f"{len(requests)} replies")
        error.retryable = True
        raise error
    return replies


def _transport_error(message: str, cause: OSError) -> ServeClientError:
    """Wrap an OSError, classifying restart/overload shapes (broken
    pipe, connection reset, connection refused, missing socket) as
    retryable."""
    error = ServeClientError(message)
    error.retryable = isinstance(
        cause, (BrokenPipeError, ConnectionResetError,
                ConnectionRefusedError, ConnectionAbortedError,
                FileNotFoundError))
    return error


def request(payload: Dict, socket_path: Optional[str] = None,
            timeout: float = 600.0) -> Dict:
    """One request, one reply."""
    return roundtrip([payload], socket_path=socket_path,
                     timeout=timeout)[0]


def backoff_ms(attempt: int, retry_after_ms: Optional[float] = None,
               base_ms: float = 100.0, cap_ms: float = 30_000.0,
               rng=random) -> float:
    """Jittered exponential backoff before retry `attempt` (0-based).
    A daemon-supplied ``retry_after_ms`` floors the delay — the hint is
    the daemon's own p95-scaled estimate, so sleeping less just earns
    another shed. Full jitter on the exponential part keeps a burst of
    bounced clients from re-synchronizing into the next burst."""
    exp = min(base_ms * (2 ** attempt), cap_ms)
    delay = rng.uniform(0, exp)
    if retry_after_ms and retry_after_ms > 0:
        delay = max(delay, float(retry_after_ms))
    return min(delay, cap_ms)


def request_with_retry(payload: Dict, socket_path: Optional[str] = None,
                       timeout: float = 600.0, attempts: int = 4,
                       sleep=time.sleep) -> Dict:
    """One request with bounded retries: retryable transport failures
    and ``busy``/``overloaded`` replies back off (honoring the reply's
    ``retry_after_ms``) and try again, up to `attempts` total tries.
    Any other reply — success or typed error — returns as-is."""
    attempts = max(1, int(attempts))
    last_error: Optional[ServeClientError] = None
    for attempt in range(attempts):
        try:
            reply = request(payload, socket_path=socket_path,
                            timeout=timeout)
        except ServeClientError as error:
            if not error.retryable or attempt == attempts - 1:
                raise
            last_error = error
            sleep(backoff_ms(attempt) / 1000.0)
            continue
        error_doc = reply.get("error") or {}
        if reply.get("ok") or error_doc.get("code") not in RETRYABLE_CODES:
            return reply
        if attempt == attempts - 1:
            return reply  # out of attempts: surface the shed reply
        sleep(backoff_ms(attempt,
                         error_doc.get("retry_after_ms")) / 1000.0)
    raise last_error  # unreachable unless attempts exhausted on errors
