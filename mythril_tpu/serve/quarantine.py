"""Poison-contract quarantine sidecar for the serve worker pool.

A contract whose analysis keeps killing worker processes (a bytecode
that tickles an XLA segfault, an OOM, a pathological compile) must not
be allowed to crash-loop the pool — or, worse, to keep poisoning shared
fleet micro-batches. The supervisor records every worker death against
the victim request's bytecode hash; once a hash accumulates
``MYTHRIL_TPU_SERVE_QUARANTINE_AFTER`` deaths (default 2 — i.e. the
first dispatch *and* its one retry both died) the contract is
quarantined: further ``analyze`` requests for it are refused with a
typed ``quarantined`` protocol error before any worker is risked.

The store is a sidecar beside the warmset manifest
(``warmset.json`` → ``warmset.quarantine.json``) and follows the same
persistence rules as the manifest and the taint-summary store
(serve/warmset.py): versioned JSON, monotone union-merge on save (a
fleet of daemons sharing one sidecar only ever accumulates evidence),
fsync-atomic writes via ``support/checkpoint.fsync_replace``, and
tolerant loads that degrade to an empty store — a corrupt sidecar can
refuse nobody, never crash the daemon.

Store format::

    {"version": 1,
     "contracts": {"<sha256 of runtime hex>": {
         "crashes": 2, "classes": ["worker_segv"], "quarantined": true}}}

Stdlib-only (json/hashlib/os): the protocol unit tests load this
without paying an accelerator import.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from typing import Dict, Optional

from ..support.checkpoint import fsync_replace

log = logging.getLogger(__name__)

QUARANTINE_VERSION = 1


class QuarantinedContract(Exception):
    """Raised at admission for a contract in the poison sidecar; the
    service answers it with the typed ``quarantined`` protocol error."""

    def __init__(self, key: str, entry: Optional[dict] = None):
        self.key = key
        self.entry = dict(entry or {})
        crashes = self.entry.get("crashes", "?")
        classes = ",".join(self.entry.get("classes", [])) or "unknown"
        super().__init__(
            f"contract {key[:16]}… is quarantined after {crashes} worker "
            f"death(s) ({classes}); refusing to risk another worker")


def contract_key(code: Optional[str]) -> str:
    """Stable poison key for a request: sha256 of the normalized hex
    bytecode (case-folded, ``0x`` stripped) — the same identity under
    which the warmset stores taint summaries."""
    normalized = (code or "").strip().lower()
    normalized = normalized.removeprefix("0x")
    return hashlib.sha256(normalized.encode("utf-8")).hexdigest()


def quarantine_path_for(manifest_path: str) -> str:
    """The poison sidecar sits beside the shape manifest:
    ``warmset.json`` → ``warmset.quarantine.json``."""
    base, _ = os.path.splitext(manifest_path)
    return f"{base}.quarantine.json"


def load_quarantine(path: str) -> Dict[str, dict]:
    """Per-contract crash records keyed by bytecode hash; {} for
    missing, malformed, or unknown-version sidecars (logged, never
    raised)."""
    try:
        with open(path, "r", encoding="utf-8") as handle:
            doc = json.load(handle)
    except FileNotFoundError:
        return {}
    except (OSError, ValueError) as error:
        log.warning("quarantine sidecar %s unreadable (%s) — starting "
                    "with an empty poison list", path, error)
        return {}
    if not isinstance(doc, dict) or doc.get("version") != QUARANTINE_VERSION:
        log.warning("quarantine sidecar %s has unsupported version %r — "
                    "starting with an empty poison list", path,
                    doc.get("version") if isinstance(doc, dict) else None)
        return {}
    contracts = {}
    for key, entry in (doc.get("contracts") or {}).items():
        if isinstance(key, str) and isinstance(entry, dict):
            contracts[key] = {
                "crashes": int(entry.get("crashes", 0) or 0),
                "classes": sorted({str(c)
                                   for c in entry.get("classes", []) or []}),
                "quarantined": bool(entry.get("quarantined", False)),
            }
        else:
            log.warning("quarantine sidecar %s: skipping malformed entry "
                        "%r", path, key)
    return contracts


def _merge_entry(disk: dict, mem: dict) -> dict:
    """Union of two crash records: evidence only accumulates (max of
    crash counts — two daemons counting the same death must not double
    it — union of classes, OR of the quarantine verdict)."""
    return {
        "crashes": max(disk.get("crashes", 0), mem.get("crashes", 0)),
        "classes": sorted(set(disk.get("classes", []))
                          | set(mem.get("classes", []))),
        "quarantined": bool(disk.get("quarantined")
                            or mem.get("quarantined")),
    }


def save_quarantine(path: str, contracts: Dict[str, dict]) -> int:
    """Merge `contracts` into the sidecar at `path` (entry-wise union
    with what is already there) and write it fsync-atomically. Returns
    the merged entry count."""
    merged = load_quarantine(path)
    for key, entry in contracts.items():
        if isinstance(key, str) and isinstance(entry, dict):
            merged[key] = _merge_entry(merged.get(key, {}), entry)
    payload = {"version": QUARANTINE_VERSION,
               "contracts": {key: merged[key] for key in sorted(merged)}}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as handle:
        json.dump(payload, handle, indent=1)
    fsync_replace(tmp, path)
    return len(merged)


class QuarantineStore:
    """The supervisor's view of the poison list: check → record → flush.

    ``path=None`` disables persistence (crash accounting still works in
    memory, so a path-less daemon is protected for its own lifetime)."""

    def __init__(self, path: Optional[str] = None,
                 threshold: int = 2):
        self.path = path
        self.threshold = max(1, int(threshold))
        self._lock = threading.Lock()
        self._contracts: Dict[str, dict] = \
            load_quarantine(path) if path else {}

    def entry(self, key: str) -> Optional[dict]:
        with self._lock:
            found = self._contracts.get(key)
            return dict(found) if found else None

    def is_quarantined(self, key: str) -> bool:
        with self._lock:
            return bool(self._contracts.get(key, {}).get("quarantined"))

    def check(self, key: str) -> None:
        """Raise QuarantinedContract when `key` is poison (the
        admission-time gate)."""
        with self._lock:
            entry = self._contracts.get(key)
            if entry and entry.get("quarantined"):
                raise QuarantinedContract(key, entry)

    def record_crash(self, key: str, failure_class: str) -> bool:
        """Charge one worker death to `key`; returns True when this
        crash newly quarantined the contract. Persists on every call —
        deaths are rare and the sidecar must survive a daemon crash."""
        with self._lock:
            entry = self._contracts.setdefault(
                key, {"crashes": 0, "classes": [], "quarantined": False})
            entry["crashes"] += 1
            if failure_class not in entry["classes"]:
                entry["classes"] = sorted(set(entry["classes"])
                                          | {failure_class})
            newly = (not entry["quarantined"]
                     and entry["crashes"] >= self.threshold)
            if newly:
                entry["quarantined"] = True
                log.error(
                    "contract %s… QUARANTINED after %d worker death(s) "
                    "(%s): further requests are refused", key[:16],
                    entry["crashes"], ",".join(entry["classes"]))
            snapshot = {key: dict(entry)}
        self._flush(snapshot)
        return newly

    def _flush(self, contracts: Dict[str, dict]) -> None:
        if not self.path:
            return
        try:
            save_quarantine(self.path, contracts)
        except OSError as error:
            log.warning("could not persist quarantine sidecar %s: %s",
                        self.path, error)

    def quarantined_count(self) -> int:
        with self._lock:
            return sum(1 for entry in self._contracts.values()
                       if entry.get("quarantined"))

    def status(self) -> dict:
        with self._lock:
            return {
                "sidecar": self.path,
                "threshold": self.threshold,
                "tracked": len(self._contracts),
                "quarantined": sum(1 for e in self._contracts.values()
                                   if e.get("quarantined")),
            }
