"""Supervisor for the serve worker-process pool.

The daemon process keeps protocol, admission, and metrics in-process;
the *engine* runs in supervised worker processes
(``python -m mythril_tpu.serve.worker``) so one XLA segfault, OOM kill,
or wedged compile takes down a single request's sandbox instead of the
daemon, its warm caches, and every queued request (the Manticore /
DTVM sandbox argument, PAPERS.md). The supervisor owns:

* **The pool**: ``MYTHRIL_TPU_SERVE_WORKERS`` slots, each a warm worker
  that pre-compiled the warmset manifest at spawn. Dead slots respawn
  with exponential backoff (``MYTHRIL_TPU_SERVE_WORKER_BACKOFF_MS``
  base, doubled per consecutive death, capped at 30 s).
* **Death detection + taxonomy**: a worker death is detected by pipe
  EOF (exit-status classified via ``resilience.classify_exit_status``:
  SIGSEGV/SIGBUS/SIGABRT → WORKER_SEGV, SIGKILL → WORKER_OOM) or by
  heartbeat timeout (``MYTHRIL_TPU_SERVE_WORKER_HEARTBEAT_MS`` of
  silence → the supervisor kills the worker and classifies
  WORKER_HANG). Every death lands in ``serve.worker.deaths`` (labelled
  by class), a correlated slog record, and a trace instant.
* **Retry-once**: the victim request is retried on a fresh worker —
  resuming from its request-scoped host checkpoint when one was cut
  mid-flight, else restarting on the host-only backend ladder
  (engine=host, solver=cdcl). A second death fails the request with the
  typed worker exception instead of looping.
* **Quarantine**: each death is charged to the request's bytecode hash
  in the poison sidecar (serve/quarantine.py); once a contract reaches
  ``MYTHRIL_TPU_SERVE_QUARANTINE_AFTER`` deaths it is refused at
  admission with a ``quarantined`` error — one bad contract can never
  crash-loop the pool.
* **Deterministic fault injection**: the supervisor holds a *private*
  ``FaultPlan`` (``serve --inject-fault worker_segv:2`` or
  ``MYTHRIL_TPU_INJECT_FAULT``) and visits the ``worker`` site once per
  dispatched job; a firing entry is embedded in the job and the worker
  genuinely dies that way. Private, because the engine-side plan is
  reset per request (``resilience.reset``), which would wipe a daemon-
  lifetime schedule.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import queue
import select
import shutil
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from . import quarantine as quarantine_mod
from .warmset import default_manifest_path
from ..observe import metrics, slog, trace
from ..support import resilience, tpu_config
from ..support.checkpoint import request_checkpoint_path

log = logging.getLogger(__name__)

#: per-slot backoff ceiling — a permanently sick worker retries every
#: 30 s forever instead of growing an unbounded sleep
MAX_BACKOFF_S = 30.0
#: how long a spawned worker may take to warm up and report ready
READY_TIMEOUT_S = 600.0
#: how long run_job waits for a warm worker before giving up (covers
#: every slot being mid-backoff after a crash storm)
CHECKOUT_TIMEOUT_S = 600.0

WARM, BUSY, RESTARTING, BACKOFF, STOPPED = (
    "warm", "busy", "restarting", "backoff", "stopped")


class WorkerDeath(Exception):
    """Internal: one worker process died under a job."""

    def __init__(self, failure_class: str, detail: str = ""):
        self.failure_class = failure_class
        self.detail = detail
        super().__init__(f"{failure_class}: {detail}" if detail
                         else failure_class)


class WorkerAnalysisError(Exception):
    """An analysis exception *inside* a healthy worker (the sandbox
    survived; this is a clean per-request failure, never retried)."""

    def __init__(self, error_type: str, message: str):
        self.error_type = error_type
        super().__init__(f"{error_type}: {message}")


class WorkerUnavailable(Exception):
    """No warm worker could be checked out within the timeout."""


class _LineReader:
    """select()-driven line framing over a pipe fd. ``readline``
    returns a decoded line, ``""`` at EOF, or None on timeout — without
    a buffered wrapper that would hide pending lines from select()."""

    def __init__(self, fd: int):
        self.fd = fd
        self._buf = b""
        self._lines: deque = deque()
        self._eof = False

    def readline(self, timeout: float) -> Optional[str]:
        if self._lines:
            return self._lines.popleft()
        if self._eof:
            return ""
        try:
            ready, _, _ = select.select([self.fd], [], [], timeout)
        except (OSError, ValueError):
            self._eof = True
            return ""
        if not ready:
            return None
        try:
            chunk = os.read(self.fd, 1 << 16)
        except OSError:
            chunk = b""
        if not chunk:
            self._eof = True
            return ""
        self._buf += chunk
        while b"\n" in self._buf:
            line, self._buf = self._buf.split(b"\n", 1)
            self._lines.append(line.decode("utf-8", "replace"))
        return self._lines.popleft() if self._lines else None


class _WorkerHandle:
    """One pool slot: the live process (if any) plus its lifecycle
    bookkeeping. State transitions are guarded by the supervisor lock."""

    def __init__(self, slot: int):
        self.slot = slot
        self.state = RESTARTING
        self.proc: Optional[subprocess.Popen] = None
        self.reader: Optional[_LineReader] = None
        self.pid: Optional[int] = None
        self.jobs_done = 0
        self.deaths = 0              # lifetime deaths on this slot
        self.consecutive_deaths = 0  # resets on a completed job
        self.restarts = 0

    def snapshot(self) -> dict:
        return {"slot": self.slot, "state": self.state, "pid": self.pid,
                "jobs_done": self.jobs_done, "deaths": self.deaths,
                "restarts": self.restarts}


class Supervisor:
    """Owns the worker pool for one :class:`AnalysisService`."""

    def __init__(self, workers: int,
                 manifest_path: Optional[str] = None,
                 solver: str = "cdcl", engine: str = "host",
                 strategy: str = "bfs", warmup: bool = True,
                 inject_fault: Optional[str] = None,
                 heartbeat_ms: Optional[int] = None,
                 backoff_ms: Optional[int] = None,
                 quarantine_path: Optional[str] = None,
                 quarantine_after: Optional[int] = None,
                 worker_argv: Optional[List[str]] = None):
        self.workers = max(1, int(workers))
        self.manifest_path = manifest_path
        self.solver = solver
        self.engine = engine
        self.strategy = strategy
        self.warmup = warmup
        if heartbeat_ms is None:
            heartbeat_ms = tpu_config.get_int(
                "MYTHRIL_TPU_SERVE_WORKER_HEARTBEAT_MS")
        self.heartbeat_s = max(heartbeat_ms, 100) / 1000.0
        if backoff_ms is None:
            backoff_ms = tpu_config.get_int(
                "MYTHRIL_TPU_SERVE_WORKER_BACKOFF_MS")
        self.backoff_s = max(backoff_ms, 1) / 1000.0
        if quarantine_path is None and manifest_path:
            quarantine_path = quarantine_mod.quarantine_path_for(
                manifest_path)
        if quarantine_after is None:
            quarantine_after = tpu_config.get_int(
                "MYTHRIL_TPU_SERVE_QUARANTINE_AFTER")
        self.quarantine = quarantine_mod.QuarantineStore(
            quarantine_path, threshold=quarantine_after)
        # the supervisor's PRIVATE fault plan: the engine-side global
        # plan is reset per request, which would wipe a daemon-lifetime
        # injection schedule like worker_segv:2
        self._plan = resilience.FaultPlan(
            inject_fault
            or tpu_config.get_str("MYTHRIL_TPU_INJECT_FAULT"))
        self._worker_argv = worker_argv
        self._lock = threading.Lock()
        self._idle: "queue.Queue[_WorkerHandle]" = queue.Queue()
        self._handles = [_WorkerHandle(slot)
                         for slot in range(self.workers)]
        self._slot_seq = itertools.count(self.workers)
        self._seq = itertools.count(1)
        self._stopping = threading.Event()
        self._workdir = tempfile.mkdtemp(prefix="myth-tpu-serve-ckpt-")
        self._spawn_threads: List[threading.Thread] = []

    # -- pool lifecycle ----------------------------------------------------------------

    def start(self) -> None:
        log.info("starting worker pool: %d worker(s), heartbeat %.1fs, "
                 "quarantine sidecar %s", self.workers, self.heartbeat_s,
                 self.quarantine.path)
        slog.event("serve.worker.pool_start", workers=self.workers,
                   heartbeat_s=self.heartbeat_s,
                   quarantine=self.quarantine.path)
        for handle in self._handles:
            self._respawn_async(handle, delay_s=0.0, restart=False)

    def stop(self) -> None:
        self._stopping.set()
        with self._lock:
            handles = list(self._handles)
        for handle in handles:
            proc = handle.proc
            handle.state = STOPPED
            if proc is None or proc.poll() is not None:
                continue
            try:
                proc.stdin.write(b'{"kind": "shutdown"}\n')
                proc.stdin.flush()
            except (OSError, ValueError):
                pass
            try:
                proc.wait(timeout=2.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
        for thread in self._spawn_threads:
            thread.join(timeout=1.0)
        while True:
            try:
                self._idle.get_nowait()
            except queue.Empty:
                break
        metrics.set_gauge("serve.worker.pool", 0)
        shutil.rmtree(self._workdir, ignore_errors=True)
        slog.event("serve.worker.pool_stop", workers=self.workers)

    def _worker_command(self) -> List[str]:
        if self._worker_argv is not None:
            return list(self._worker_argv)
        argv = [sys.executable, "-m", "mythril_tpu.serve.worker",
                "--solver", self.solver, "--engine", self.engine,
                "--strategy", self.strategy,
                "--heartbeat-ms", str(int(self.heartbeat_s * 1000))]
        if self.manifest_path:
            argv += ["--manifest", self.manifest_path]
        if not self.warmup:
            argv.append("--no-warmup")
        return argv

    def _worker_env(self) -> Dict[str, str]:
        env = dict(os.environ)
        # the daemon owns the trace file and the metrics snapshot; a
        # worker exporting either would clobber them at exit
        env.pop("MYTHRIL_TPU_TRACE", None)
        env.pop("MYTHRIL_TPU_METRICS", None)
        # belt and braces: a worker must never spawn its own pool
        env["MYTHRIL_TPU_SERVE_WORKERS"] = "0"
        return env

    def _respawn_async(self, handle: _WorkerHandle, delay_s: float,
                       restart: bool) -> None:
        thread = threading.Thread(
            target=self._spawn_slot, args=(handle, delay_s, restart),
            name=f"serve-worker-spawn-{handle.slot}", daemon=True)
        self._spawn_threads = [t for t in self._spawn_threads
                               if t.is_alive()] + [thread]
        thread.start()

    def _spawn_slot(self, handle: _WorkerHandle, delay_s: float,
                    restart: bool) -> None:
        while not self._stopping.is_set():
            if delay_s > 0:
                with self._lock:
                    handle.state = BACKOFF
                slog.event("serve.worker.backoff", slot=handle.slot,
                           delay_s=round(delay_s, 3))
                if self._stopping.wait(delay_s):
                    return
            with self._lock:
                handle.state = RESTARTING
            try:
                proc = subprocess.Popen(
                    self._worker_command(), stdin=subprocess.PIPE,
                    stdout=subprocess.PIPE, stderr=None, bufsize=0,
                    env=self._worker_env())
            except OSError as error:
                log.error("cannot spawn worker for slot %d: %s",
                          handle.slot, error)
                delay_s = min(max(delay_s, self.backoff_s) * 2,
                              MAX_BACKOFF_S)
                continue
            reader = _LineReader(proc.stdout.fileno())
            if self._await_ready(proc, reader, handle):
                with self._lock:
                    handle.proc = proc
                    handle.reader = reader
                    handle.pid = proc.pid
                    handle.state = WARM
                    if restart:
                        handle.restarts += 1
                metrics.inc("serve.worker.spawns")
                if restart:
                    metrics.inc("serve.worker.restarts")
                metrics.set_gauge("serve.worker.pool", self._live_count())
                slog.event("serve.worker.ready", slot=handle.slot,
                           pid=proc.pid, restart=restart)
                trace.instant("serve.worker.ready", slot=handle.slot,
                              pid=proc.pid)
                self._idle.put(handle)
                return
            # spawn failed (died or hung before ready): clean up, back
            # off, and try again — the slot must eventually come back
            if proc.poll() is None:
                proc.kill()
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass
            failure_class = resilience.classify_exit_status(
                proc.returncode) or resilience.WORKER_CRASH
            self._count_death(handle, failure_class,
                              f"died during startup (exit "
                              f"{proc.returncode})", job_id=None)
            restart = True
            delay_s = self._backoff_for(handle)

    def _await_ready(self, proc: subprocess.Popen, reader: _LineReader,
                     handle: _WorkerHandle) -> bool:
        deadline = time.monotonic() + READY_TIMEOUT_S
        while time.monotonic() < deadline and not self._stopping.is_set():
            line = reader.readline(timeout=0.5)
            if line is None:
                continue
            if line == "":
                return False
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            if msg.get("event") == "ready":
                log.info("worker slot %d ready: pid %s, %s warm "
                         "bucket(s), %s executable-cache hit(s), %s "
                         "verdict(s) loaded", handle.slot, proc.pid,
                         msg.get("warmed", 0), msg.get("exec_hits", 0),
                         msg.get("verdicts_loaded", 0))
                self._fold_ready_metrics(msg)
                return True
        return False

    def _fold_ready_metrics(self, msg: dict) -> None:
        """Fold one worker's pre-warm deltas (shipped on its ready
        event) into the daemon's durable-warmth counters, so /healthz
        and /metrics report pool-wide deserialize-vs-compile coverage."""
        for name, value in (("cache.exec.hits", msg.get("exec_hits")),
                            ("cache.exec.misses",
                             msg.get("exec_misses")),
                            ("cache.verdict.loaded",
                             msg.get("verdicts_loaded"))):
            if isinstance(value, int) and value > 0:
                metrics.inc(name, value)

    def _live_count(self) -> int:
        with self._lock:
            return sum(1 for h in self._handles
                       if h.state in (WARM, BUSY))

    # -- elastic scaling ---------------------------------------------------------------

    def occupancy(self) -> Dict[str, int]:
        """Busy/live worker counts — the autoscaler's load signal."""
        with self._lock:
            busy = sum(1 for h in self._handles if h.state == BUSY)
            live = sum(1 for h in self._handles
                       if h.state in (WARM, BUSY))
        return {"busy": busy, "live": live}

    def scale_to(self, target: int) -> int:
        """Elastically resize the pool toward `target` slots (the
        autoscaler's lever). Growth spawns new slots immediately — they
        come up warm through the durable exec/verdict caches, not a
        cold compile. Shrink only retires *idle* workers: a busy worker
        is never killed mid-job, so when fewer idle workers are parked
        than the deficit, the remainder retires on a later tick.
        Returns the pool size after this call."""
        target = max(1, int(target))
        if self._stopping.is_set():
            return self.workers
        with self._lock:
            current = sum(1 for h in self._handles if h.state != STOPPED)
        while current < target:
            with self._lock:
                handle = _WorkerHandle(next(self._slot_seq))
                self._handles.append(handle)
            self._respawn_async(handle, delay_s=0.0, restart=False)
            current += 1
        while current > target:
            try:
                handle = self._idle.get_nowait()
            except queue.Empty:
                break  # nothing idle to retire — retry next tick
            if handle.proc is None or handle.proc.poll() is not None:
                # a corpse parked idle: retiring it IS the shrink —
                # count the death but do not respawn into a shrink
                self._count_death(handle,
                                  resilience.classify_exit_status(
                                      handle.proc.returncode
                                      if handle.proc else None)
                                  or resilience.WORKER_CRASH,
                                  "died while idle", job_id=None)
            self._retire(handle)
            current -= 1
        with self._lock:
            self.workers = max(
                1, sum(1 for h in self._handles if h.state != STOPPED))
            return self.workers

    def _retire(self, handle: _WorkerHandle) -> None:
        with self._lock:
            handle.state = STOPPED
            if handle in self._handles:
                self._handles.remove(handle)
            proc = handle.proc
            pid = handle.pid
            handle.proc = None
            handle.reader = None
            handle.pid = None
        if proc is not None and proc.poll() is None:
            try:
                proc.stdin.write(b'{"kind": "shutdown"}\n')
                proc.stdin.flush()
            except (OSError, ValueError):
                pass
            try:
                proc.wait(timeout=2.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
        metrics.set_gauge("serve.worker.pool", self._live_count())
        slog.event("serve.worker.retired", slot=handle.slot, pid=pid)
        log.info("worker slot %d (pid %s) retired by scale-down",
                 handle.slot, pid)

    def _backoff_for(self, handle: _WorkerHandle) -> float:
        exponent = max(handle.consecutive_deaths - 1, 0)
        return min(self.backoff_s * (2 ** exponent), MAX_BACKOFF_S)

    # -- job execution -----------------------------------------------------------------

    def run_job(self, params: Dict, cid: Optional[str] = None,
                kind: str = "analyze") -> Dict:
        """Execute one analyze (or optimize) request in a worker, with
        quarantine admission, retry-once-on-death, and checkpoint
        resume. Returns the payload dict; raises QuarantinedContract,
        the typed worker failure after a double death, or
        WorkerAnalysisError for a clean in-worker exception."""
        key = quarantine_mod.contract_key(params.get("code"))
        self._check_quarantine(key)
        job_id = next(self._seq)
        checkpoint = request_checkpoint_path(
            self._workdir, f"{key[:12]}-{job_id}")
        job = {"kind": kind, "job_id": job_id, "params": params,
               "cid": cid, "checkpoint": checkpoint}
        try:
            try:
                return self._attempt(job)
            except WorkerDeath as death:
                self._record_crash(key, death)
                return self._retry(job, death, resume_from=checkpoint,
                                   quarantine_key=key)
        finally:
            try:
                os.unlink(checkpoint)
            except OSError:
                pass

    def run_fleet(self, members: List[Dict],
                  cid: Optional[str] = None) -> List[Dict]:
        """Execute one fleet micro-batch in a worker; returns one
        outcome dict per member ({"ok": true, "payload": ...} or
        {"ok": false, "error_type": ..., "error": ...}). Deaths retry
        the whole batch once on the host ladder; crash accounting only
        charges a contract when it was alone in the batch (an innocent
        co-member must never inherit a poison record)."""
        key = (quarantine_mod.contract_key(members[0].get("code"))
               if len(members) == 1 else None)
        job = {"kind": "fleet", "job_id": next(self._seq),
               "members": members, "cid": cid}
        try:
            return self._attempt(job)["outcomes"]
        except WorkerDeath as death:
            if key is not None:
                self._record_crash(key, death)
            result = self._retry(job, death, resume_from=None,
                                 quarantine_key=key)
            return result["outcomes"]

    def _retry(self, job: Dict, death: WorkerDeath,
               resume_from: Optional[str],
               quarantine_key: Optional[str]) -> Dict:
        metrics.inc("serve.worker.retries")
        resuming = bool(resume_from and os.path.exists(resume_from))
        slog.event("serve.worker.retry", job_id=job["job_id"],
                   failure_class=death.failure_class, resume=resuming)
        log.warning("worker died under job %s (%s) — retrying on a "
                    "fresh worker (%s)", job["job_id"],
                    death.failure_class,
                    "checkpoint resume" if resuming else "host ladder")
        retry = dict(job)
        retry["retry"] = True
        if resuming:
            retry["resume"] = resume_from
        else:
            retry["ladder"] = True
        try:
            return self._attempt(retry)
        except WorkerDeath as second:
            if quarantine_key is not None:
                self._record_crash(quarantine_key, second)
            exc_class = resilience._EXCEPTION_FOR_CLASS.get(
                second.failure_class, resilience.DeviceWorkerCrash)
            raise exc_class(
                f"worker died twice under this request "
                f"({death.failure_class}, then {second.failure_class}); "
                "giving up after one retry") from second

    def _check_quarantine(self, key: str) -> None:
        try:
            self.quarantine.check(key)
        except quarantine_mod.QuarantinedContract:
            metrics.inc("serve.worker.quarantine_refusals")
            slog.event("serve.quarantine.refused", contract=key[:16])
            raise

    def _record_crash(self, key: str, death: WorkerDeath) -> None:
        if self.quarantine.record_crash(key, death.failure_class):
            metrics.inc("serve.worker.quarantined")
            slog.event("serve.quarantine.added", contract=key[:16],
                       failure_class=death.failure_class)
            trace.instant("serve.quarantine.added", contract=key[:16])

    def _attempt(self, job: Dict) -> Dict:
        """One dispatch to one worker. Visits the supervisor's fault-
        injection site, so CLASS[:NTH] specs count dispatch attempts
        (retries included) across the whole pool."""
        handle = self._checkout()
        fired = self._plan.visit("worker")
        if fired is not None:
            job = dict(job)
            job["inject"] = fired
            log.warning("fault injection: job %s carries %s (visit %d "
                        "of site 'worker')", job["job_id"], fired,
                        self._plan.site_counts["worker"])
        return self._dispatch(handle, job)

    def _checkout(self) -> _WorkerHandle:
        deadline = time.monotonic() + CHECKOUT_TIMEOUT_S
        while time.monotonic() < deadline:
            try:
                handle = self._idle.get(timeout=1.0)
            except queue.Empty:
                if self._stopping.is_set():
                    break
                continue
            # a worker can die while parked idle — skip the corpse, its
            # slot's respawn is triggered by the dispatch failure path
            if handle.proc is not None and handle.proc.poll() is None:
                return handle
            self._on_death(handle,
                           resilience.classify_exit_status(
                               handle.proc.returncode if handle.proc
                               else None) or resilience.WORKER_CRASH,
                           "died while idle", job_id=None)
        raise WorkerUnavailable(
            f"no warm worker within {CHECKOUT_TIMEOUT_S:.0f}s "
            f"({self.workers} slot(s) configured)")

    def _dispatch(self, handle: _WorkerHandle, job: Dict) -> Dict:
        with self._lock:
            handle.state = BUSY
        try:
            handle.proc.stdin.write(
                (json.dumps(job, default=repr) + "\n").encode("utf-8"))
            handle.proc.stdin.flush()
        except (OSError, ValueError):
            return self._die(handle, job,
                             resilience.classify_exit_status(
                                 handle.proc.poll())
                             or resilience.WORKER_CRASH,
                             "worker pipe closed at dispatch")
        deadline = time.monotonic() + self.heartbeat_s
        while True:
            line = handle.reader.readline(timeout=0.25)
            if line is None:
                if time.monotonic() > deadline:
                    handle.proc.kill()
                    try:
                        handle.proc.wait(timeout=5.0)
                    except subprocess.TimeoutExpired:
                        pass
                    return self._die(
                        handle, job, resilience.WORKER_HANG,
                        f"no heartbeat for {self.heartbeat_s:.1f}s")
                continue
            if line == "":
                try:
                    handle.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    handle.proc.kill()
                    handle.proc.wait(timeout=5.0)
                returncode = handle.proc.returncode
                return self._die(
                    handle, job,
                    resilience.classify_exit_status(returncode)
                    or resilience.WORKER_CRASH,
                    f"exit status {returncode}")
            try:
                msg = json.loads(line)
            except ValueError:
                continue  # stray output; stdout is claimed, but be safe
            deadline = time.monotonic() + self.heartbeat_s
            if msg.get("event") != "result" or \
                    msg.get("job_id") != job["job_id"]:
                continue  # heartbeat, or a stale result from a past job
            with self._lock:
                handle.state = WARM
                handle.jobs_done += 1
                handle.consecutive_deaths = 0
            self._idle.put(handle)
            if not msg.get("ok"):
                raise WorkerAnalysisError(
                    msg.get("error_type", "Exception"),
                    msg.get("error", "analysis failed in worker"))
            payload = msg.get("payload") or {}
            self._fold_worker_metrics(payload.pop("serve_metrics", None))
            return payload

    def _die(self, handle: _WorkerHandle, job: Dict, failure_class: str,
             detail: str) -> Dict:
        """Common death path during a dispatch: account, respawn the
        slot, raise WorkerDeath for the retry layer."""
        self._on_death(handle, failure_class, detail,
                       job_id=job.get("job_id"))
        raise WorkerDeath(failure_class, detail)

    def _count_death(self, handle: _WorkerHandle, failure_class: str,
                     detail: str, job_id) -> None:
        """Death accounting only (no respawn): the caller owns the
        slot's recovery — _spawn_slot's own retry loop, or _on_death's
        _respawn_async."""
        with self._lock:
            handle.deaths += 1
            handle.consecutive_deaths += 1
            handle.state = RESTARTING
            pid = handle.pid
            handle.proc = None
            handle.reader = None
            handle.pid = None
        metrics.observe("serve.worker.deaths", 1, label=failure_class)
        metrics.set_gauge("serve.worker.pool", self._live_count())
        slog.event("serve.worker.death", slot=handle.slot, pid=pid,
                   failure_class=failure_class, detail=detail,
                   job_id=job_id)
        trace.instant("serve.worker.death", slot=handle.slot,
                      failure_class=failure_class, detail=detail)
        log.error("worker slot %d (pid %s) died: %s (%s)", handle.slot,
                  pid, failure_class, detail)

    def _on_death(self, handle: _WorkerHandle, failure_class: str,
                  detail: str, job_id) -> None:
        self._count_death(handle, failure_class, detail, job_id)
        if not self._stopping.is_set():
            self._respawn_async(handle, delay_s=self._backoff_for(handle),
                                restart=True)

    def _fold_worker_metrics(self, deltas: Optional[Dict]) -> None:
        """Fold the worker's warm/cold/frontier deltas into the daemon's
        own counters, so the per-request accounting in
        ``AnalysisService._analyze`` (and /healthz) keeps working across
        the process boundary."""
        if not isinstance(deltas, dict):
            return
        for name, value in (("xla.bucket_compiles",
                             deltas.get("cold_buckets")),
                            ("xla.bucket_reuses",
                             deltas.get("warm_hits")),
                            ("cache.exec.hits",
                             deltas.get("exec_hits")),
                            ("cache.exec.misses",
                             deltas.get("exec_misses"))):
            if value:
                metrics.inc(name, value)
        frontier = deltas.get("frontier")
        if isinstance(frontier, dict):
            for counter, value in frontier.items():
                name = f"frontier.telemetry.{counter}"
                if value and metrics.declared(name):
                    metrics.inc(name, value)

    # -- introspection -----------------------------------------------------------------

    def status(self) -> Dict:
        """The worker-pool rollup for /healthz, the ``status`` op, and
        the chaos harness: per-worker state, restart/death totals, and
        the quarantine census."""
        with self._lock:
            workers = [handle.snapshot() for handle in self._handles]
        return {
            "pool": self.workers,
            "live": sum(1 for w in workers
                        if w["state"] in (WARM, BUSY)),
            "restarts": sum(w["restarts"] for w in workers),
            "deaths": sum(w["deaths"] for w in workers),
            "workers": workers,
            "quarantine": self.quarantine.status(),
            "injection": self._plan.spec,
        }
