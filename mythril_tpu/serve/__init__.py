"""`myth-tpu serve`: a persistent analysis service with AOT-warmed
executables.

The cold-start tax on the device path is XLA compilation: the first
solve per clause-shape bucket costs minutes, every later solve in the
same bucket costs milliseconds. A one-shot CLI run pays that tax every
invocation; this package amortizes it across a process lifetime instead:

* ``protocol``  — JSON-lines request framing + validation (stdlib-only)
* ``service``   — AnalysisService: admission gate, engine lock,
  per-request isolation, warm/cold accounting
* ``warmset``   — persisted manifest of hot clause-shape buckets +
  startup warmup (``serve.warmup`` trace span)
* ``daemon``    — stdio and unix-socket transport loops
* ``http_shim`` — thin POST shim over the same service
* ``client``    — socket client used by `myth-tpu client`

Submodules are imported lazily by the CLI so that client-side commands
never pay the engine import.
"""
