"""Serve worker process: the supervised analysis sandbox.

``python -m mythril_tpu.serve.worker`` is spawned by the supervisor
(serve/supervisor.py), pre-warms from the warmset manifest, then loops
over JSON-lines jobs on stdin — one ``analyze``, one ``optimize``, or
one fleet micro-batch per job — writing JSON-lines events back on
stdout:

* ``{"event": "ready", "pid": ..., "warmed": N, "exec_hits": ...,
  "exec_misses": ..., "verdicts_loaded": ...}`` — once, after the
  deserialize-first pre-warm (the supervisor folds the durable-warmth
  counters into the daemon's ``cache.exec.*`` / ``cache.verdict.*``);
* ``{"event": "heartbeat", "job_id": ...}`` — from a daemon thread
  while a job is running, so the supervisor can tell "slow" from
  "wedged" (a silent worker past the heartbeat timeout is killed and
  classified WORKER_HANG);
* ``{"event": "result", "job_id": ..., "ok": true, "payload": ...}`` or
  ``ok: false`` with ``error_type``/``error`` — an in-worker analysis
  exception is a *clean* failure (the sandbox survives; no retry), only
  a process death is a worker failure.

Stdout is reserved for this protocol: at startup the real stdout fd is
duplicated for the protocol writer and fd 1 is redirected to stderr, so
a chatty library can never corrupt the framing.

Jobs carry the request's correlation id across the process boundary:
the worker scopes ``slog.correlated(cid)`` around the run, and the slog
sink (``MYTHRIL_TPU_SLOG``, opened append-mode) interleaves supervisor
and worker records under one cid.

Fault injection (``--inject-fault worker_*``) is decided by the
*supervisor* (its private FaultPlan visits the ``worker`` site once per
dispatched job); when a job arrives with ``"inject"`` set the worker
genuinely dies that way — SIGSEGV to itself for ``worker_segv``,
SIGKILL (the kernel OOM killer's signature) for ``worker_oom``, or
going silent for ``worker_hang`` — so the supervisor's detection,
classification, restart, retry, and quarantine paths are exercised end
to end, not simulated.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import sys
import threading
import time
from typing import Optional, TextIO

from ..observe import metrics, slog
from ..support import resilience

log = logging.getLogger(__name__)


class _ProtocolWriter:
    """Line-framed JSON writer shared by the job loop and the heartbeat
    thread (one lock: a heartbeat must never tear a result line)."""

    def __init__(self, handle: TextIO):
        self._handle = handle
        self._lock = threading.Lock()

    def send(self, **record) -> None:
        line = json.dumps(record, sort_keys=True, default=repr)
        with self._lock:
            self._handle.write(line + "\n")
            self._handle.flush()


class _Heartbeat:
    """Emits ``heartbeat`` events for one job until stopped."""

    def __init__(self, writer: _ProtocolWriter, job_id: object,
                 interval_s: float):
        self._writer = writer
        self._job_id = job_id
        self._interval_s = max(interval_s, 0.05)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="worker-heartbeat", daemon=True)

    def __enter__(self) -> "_Heartbeat":
        self._thread.start()
        return self

    def __exit__(self, *exc) -> bool:
        self._stop.set()
        self._thread.join(timeout=2.0)
        return False

    def _run(self) -> None:
        while not self._stop.wait(self._interval_s):
            self._writer.send(event="heartbeat", job_id=self._job_id)


def _self_destruct(failure_class: str) -> None:
    """Die the way the injected class says a worker dies. Never
    returns (except for unknown classes, which are ignored so a newer
    supervisor cannot wedge an older worker)."""
    log.warning("worker %d: injected %s — dying for real", os.getpid(),
                failure_class)
    slog.event("serve.worker.injected", failure_class=failure_class,
               pid=os.getpid())
    if failure_class == resilience.WORKER_SEGV:
        signal.signal(signal.SIGSEGV, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGSEGV)
    elif failure_class == resilience.WORKER_OOM:
        # the kernel OOM killer's signature: uncatchable SIGKILL
        os.kill(os.getpid(), signal.SIGKILL)
    elif failure_class == resilience.WORKER_HANG:
        # go silent: no heartbeat, no result — the supervisor's
        # heartbeat timeout must detect and kill us
        while True:
            time.sleep(3600)


def _ladder_params(params: dict) -> dict:
    """Host-only backend ladder for a retry without a checkpoint: the
    death is presumed device-related, so the fresh worker restarts the
    request on the host engine with the native CDCL solver."""
    downgraded = dict(params)
    downgraded["engine"] = "host"
    if downgraded.get("solver") in (None, "jax"):
        downgraded["solver"] = "cdcl"
    return downgraded


def _run_analyze(service, job: dict) -> dict:
    from .service import _frontier_counters

    params = dict(job["params"])
    if job.get("ladder"):
        params = _ladder_params(params)
    cold_before = metrics.value("xla.bucket_compiles")
    warm_before = metrics.value("xla.bucket_reuses")
    exec_hits_before = metrics.value("cache.exec.hits")
    exec_misses_before = metrics.value("cache.exec.misses")
    frontier_before = _frontier_counters()
    payload = service._run_analysis_local(
        params, checkpoint_path=job.get("checkpoint"),
        resume_path=job.get("resume"))
    payload["serve_metrics"] = {
        "cold_buckets": metrics.value("xla.bucket_compiles") - cold_before,
        "warm_hits": metrics.value("xla.bucket_reuses") - warm_before,
        "exec_hits": metrics.value("cache.exec.hits") - exec_hits_before,
        "exec_misses":
            metrics.value("cache.exec.misses") - exec_misses_before,
        "frontier": {name: value - frontier_before[name]
                     for name, value in _frontier_counters().items()},
    }
    return payload


def _run_optimize(service, job: dict) -> dict:
    """One gas-superoptimization job: same ladder downgrade as analyze
    (a retried job after a device-side death proves on the host CDCL
    oracle), no checkpoint — superopt runs are short and restartable."""
    params = dict(job["params"])
    if job.get("ladder"):
        params = _ladder_params(params)
    return service._run_optimize_local(params)


def _run_fleet(service, job: dict) -> dict:
    """One fleet micro-batch: reuses the in-process batcher's engine
    body (service._FleetBatcher._run_batch_inner) on supervisor-shipped
    member params, demuxed into per-member outcome dicts."""
    from .service import _FleetBatcher, _FleetTicket

    members = job.get("members") or []
    cid = job.get("cid") or ""
    group = []
    for params in members:
        params = dict(params)
        if job.get("ladder"):
            params = _ladder_params(params)
        group.append(_FleetTicket(params, cid))
    if group:
        batcher = _FleetBatcher(service)
        try:
            batcher._run_batch_inner(group)
        except BaseException as error:  # noqa: BLE001 — demuxed per member
            for ticket in group:
                if not ticket.done.is_set():
                    ticket.error = error
                    ticket.done.set()
    outcomes = []
    for ticket in group:
        if ticket.error is not None:
            outcomes.append({"ok": False,
                             "error_type": type(ticket.error).__name__,
                             "error": str(ticket.error)})
        else:
            outcomes.append({"ok": True, "payload": ticket.payload})
    return {"outcomes": outcomes}


def _claim_stdout() -> TextIO:
    """Reserve the protocol channel: keep a private handle to the real
    stdout and point fd 1 (plus sys.stdout) at stderr so stray prints
    from the engine or its libraries cannot corrupt the framing."""
    protocol_out = os.fdopen(os.dup(sys.stdout.fileno()), "w",
                             encoding="utf-8")
    os.dup2(sys.stderr.fileno(), sys.stdout.fileno())
    sys.stdout = sys.stderr
    return protocol_out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mythril_tpu.serve.worker",
        description="supervised serve worker (spawned by the serve "
                    "supervisor; not a user-facing entry point)")
    parser.add_argument("--manifest", default=None)
    parser.add_argument("--solver", default="cdcl")
    parser.add_argument("--engine", default="host")
    parser.add_argument("--strategy", default="bfs")
    parser.add_argument("--no-warmup", action="store_true")
    parser.add_argument("--heartbeat-ms", type=int, default=30_000)
    args = parser.parse_args(argv)

    logging.basicConfig(
        stream=sys.stderr, level=logging.INFO,
        format=f"worker[{os.getpid()}] %(levelname)s %(name)s: %(message)s")
    writer = _ProtocolWriter(_claim_stdout())

    from .service import AnalysisService

    service = AnalysisService(
        solver=args.solver, engine=args.engine, strategy=args.strategy,
        manifest_path=args.manifest, warmup=False, max_inflight=1,
        fleet=False, workers=0)
    warmed = 0
    if not args.no_warmup:
        warmed = service.warmset.warmup()
    # deserialize-first pre-warm accounting rides the ready event: the
    # supervisor folds these into the daemon's cache.exec.* / verdict
    # counters, so /healthz shows pool-wide durable-warmth coverage
    writer.send(event="ready", pid=os.getpid(), warmed=warmed,
                exec_hits=int(metrics.value("cache.exec.hits")),
                exec_misses=int(metrics.value("cache.exec.misses")),
                verdicts_loaded=service.warmset.loaded_verdicts)
    log.info("worker ready (warmed %d buckets, %d from the executable "
             "cache, %d verdicts loaded)", warmed,
             int(metrics.value("cache.exec.hits")),
             service.warmset.loaded_verdicts)

    beat_s = max(args.heartbeat_ms, 200) / 4000.0
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            job = json.loads(line)
        except ValueError:
            log.error("worker: unparseable job line %r — skipping",
                      line[:120])
            continue
        kind = job.get("kind")
        if kind == "shutdown":
            break
        job_id = job.get("job_id")
        inject = job.get("inject")
        if inject:
            _self_destruct(inject)
        with slog.correlated(job.get("cid") or ""):
            slog.event("serve.worker.job", job_id=job_id, kind=kind,
                       pid=os.getpid(), retry=bool(job.get("retry")))
            with _Heartbeat(writer, job_id, beat_s):
                try:
                    if kind == "fleet":
                        payload = _run_fleet(service, job)
                    elif kind == "optimize":
                        payload = _run_optimize(service, job)
                    else:
                        payload = _run_analyze(service, job)
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as error:
                    log.exception("worker: job %s failed cleanly", job_id)
                    writer.send(event="result", job_id=job_id, ok=False,
                                error_type=type(error).__name__,
                                error=str(error))
                else:
                    writer.send(event="result", job_id=job_id, ok=True,
                                payload=payload)
        try:
            service.warmset.record_observed()
        except Exception:  # persistence is best-effort inside a worker
            log.exception("worker: could not persist warmset observations")
    return 0


if __name__ == "__main__":
    sys.exit(main())
