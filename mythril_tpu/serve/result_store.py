"""Content-addressed analysis-result store for the serve daemon.

Mainnet bytecode is heavily duplicated (the same proxy/implementation
bytes behind thousands of addresses — the DTVM result-commoditization
argument, PAPERS.md), so a daemon that re-analyzes every repeat codehash
wastes its scarcest resource. This store answers a repeat ``analyze``
request *at admission*, before the priority queue and before any worker
dispatch: the cheapest possible form of load shedding.

Keying: ``result_key`` is the sha256 of the normalized bytecode (the
same case-folded, ``0x``-stripped hex identity the quarantine sidecar
uses) **plus the effective analysis config** — modules, transaction
count, strategy, solver, engine, max_depth, bin_runtime, and a schema
version. Two requests for one codehash under different configs are
different keys (a config change must miss, never serve a stale verdict);
the request's ``deadline_ms`` and ``priority`` are deliberately *not* in
the key — they shape scheduling, not the analysis result.

Persistence follows the quarantine/verdict sidecar pattern
(serve/quarantine.py, serve/warmset.py): a versioned JSON sidecar beside
the warmset manifest (``warmset.json`` → ``warmset.results.json``),
union-merge on save under an exclusive flock (two daemons sharing the
sidecar accumulate each other's results, never clobber), fsync-atomic
writes via ``support/checkpoint.fsync_replace``, tolerant loads that
degrade to an empty store, and age-ordered eviction beyond
``MYTHRIL_TPU_RESULT_STORE_MAX``.

Two hard refusals in :meth:`ResultStore.put`:

* **incomplete payloads** — a deadline-drained partial report is a
  scheduling artifact, not the contract's analysis; caching it would
  serve truncated verdicts forever;
* **quarantined hashes** — a contract in the poison sidecar must never
  have a cached answer either (the cache would mask the quarantine and
  hide that the result predates the crashes that condemned it).

Stdlib-only (json/hashlib/os): protocol-level tests load this without
paying an accelerator import.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from typing import Dict, Optional

from .quarantine import contract_key
from ..support import tpu_config
from ..support.checkpoint import fsync_replace

log = logging.getLogger(__name__)

RESULTS_VERSION = 1

#: params that shape the analysis result (deadline/priority excluded:
#: they shape scheduling, not the verdict)
_CONFIG_FIELDS = ("bin_runtime", "transaction_count", "strategy",
                  "solver", "engine", "max_depth")


def result_key(params: Dict, solver: str = "cdcl", engine: str = "host",
               strategy: str = "bfs", op: str = "analyze") -> str:
    """Content address for one request: sha256 over the normalized
    bytecode hash plus the *effective* analysis config (the daemon
    defaults applied, so an explicit ``"solver": "cdcl"`` and an omitted
    solver under a cdcl daemon hash identically). The request ``op`` is
    part of the key material: an ``analyze`` verdict and an ``optimize``
    report for the same bytecode are different results and must never
    answer each other."""
    config = {
        "v": RESULTS_VERSION,
        "op": op,
        "code": contract_key(params.get("code")),
        "modules": sorted(params.get("modules") or []) or None,
        "bin_runtime": bool(params.get("bin_runtime", False)),
        "transaction_count": params.get("transaction_count"),
        "strategy": params.get("strategy") or strategy,
        "solver": params.get("solver") or solver,
        "engine": params.get("engine") or engine,
        "max_depth": params.get("max_depth"),
    }
    blob = json.dumps(config, sort_keys=True)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def results_path_for(manifest_path: str) -> str:
    """The result sidecar sits beside the shape manifest:
    ``warmset.json`` → ``warmset.results.json``."""
    base, _ = os.path.splitext(manifest_path)
    return f"{base}.results.json"


def load_results(path: str) -> Dict[str, dict]:
    """Entries keyed by result key, each ``{"seq": n, "payload": {...}}``;
    {} for missing, malformed, or unknown-version sidecars (logged,
    never raised — a corrupt sidecar serves nobody, but can never crash
    the daemon)."""
    try:
        with open(path, "r", encoding="utf-8") as handle:
            doc = json.load(handle)
    except FileNotFoundError:
        return {}
    except (OSError, ValueError) as error:
        log.warning("result sidecar %s unreadable (%s) — cold result "
                    "store", path, error)
        return {}
    if not isinstance(doc, dict) or doc.get("version") != RESULTS_VERSION:
        log.warning("result sidecar %s has unsupported version %r — cold "
                    "result store", path,
                    doc.get("version") if isinstance(doc, dict) else None)
        return {}
    entries: Dict[str, dict] = {}
    for key, entry in (doc.get("results") or {}).items():
        if (isinstance(key, str) and isinstance(entry, dict)
                and isinstance(entry.get("payload"), dict)):
            entries[key] = {"seq": int(entry.get("seq", 0) or 0),
                            "payload": entry["payload"]}
        else:
            log.warning("result sidecar %s: skipping malformed entry %r",
                        path, key)
    return entries


def save_results(path: str, entries: Dict[str, dict],
                 max_entries: Optional[int] = None) -> int:
    """Union-merge `entries` into the sidecar at `path` under an
    exclusive flock and write it fsync-atomically. On a key collision
    the entry with the higher ``seq`` wins (both daemons computed the
    same analysis; the newer write is at least as fresh). Age-ordered
    eviction (lowest seq first) keeps the store under the
    ``MYTHRIL_TPU_RESULT_STORE_MAX`` bound. Returns the entry count
    written."""
    from ..observe import metrics

    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    lock_handle = None
    try:
        import fcntl

        lock_handle = open(f"{path}.lock", "w", encoding="utf-8")
        fcntl.flock(lock_handle, fcntl.LOCK_EX)
    except (ImportError, OSError):
        lock_handle = None  # non-POSIX: rename atomicity still holds
    try:
        merged = load_results(path)
        top = max((e["seq"] for e in merged.values()), default=0)
        for key, entry in entries.items():
            disk = merged.get(key)
            if disk is None or entry["seq"] > disk["seq"]:
                top = max(top, entry["seq"])
                merged[key] = entry
        if max_entries is None:
            max_entries = tpu_config.get_int("MYTHRIL_TPU_RESULT_STORE_MAX")
        bound = max(1, int(max_entries))
        if len(merged) > bound:
            victims = sorted(merged, key=lambda k: merged[k]["seq"])
            evicted = len(merged) - bound
            for key in victims[:evicted]:
                del merged[key]
            metrics.inc("cache.result.evicted", evicted)
        payload = {"version": RESULTS_VERSION,
                   "results": {key: merged[key] for key in sorted(merged)}}
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as handle:
            json.dump(payload, handle, indent=1)
        fsync_replace(tmp, path)
        return len(merged)
    finally:
        if lock_handle is not None:
            lock_handle.close()


class ResultStore:
    """The daemon's view of the result sidecar: get → put → flush.

    ``path=None`` disables persistence (the in-memory map still
    short-circuits repeats within this daemon's lifetime). An optional
    ``quarantine`` (serve/quarantine.py QuarantineStore) enforces the
    poison interaction: a quarantined bytecode hash is never cached and
    never answered from cache."""

    def __init__(self, path: Optional[str] = None,
                 max_entries: Optional[int] = None,
                 quarantine=None):
        self.path = path
        if max_entries is None:
            max_entries = tpu_config.get_int("MYTHRIL_TPU_RESULT_STORE_MAX")
        self.max_entries = max(1, int(max_entries))
        self.quarantine = quarantine
        self._lock = threading.Lock()
        self._entries: Dict[str, dict] = \
            load_results(path) if path else {}
        self._seq = max((e["seq"] for e in self._entries.values()),
                        default=0)
        self.hits = 0
        self.misses = 0

    def get(self, key: str,
            contract_hash: Optional[str] = None) -> Optional[Dict]:
        """The cached payload for `key`, or None. Counts
        ``cache.result.hits``/``misses``; refuses to answer for a
        quarantined `contract_hash` (the caller's typed ``quarantined``
        refusal must win over a stale cached verdict)."""
        from ..observe import metrics

        if (contract_hash and self.quarantine is not None
                and self.quarantine.is_quarantined(contract_hash)):
            return None
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                metrics.inc("cache.result.misses")
                return None
            self.hits += 1
            metrics.inc("cache.result.hits")
            return json.loads(json.dumps(entry["payload"]))

    def put(self, key: str, payload: Dict,
            contract_hash: Optional[str] = None) -> bool:
        """Cache one *complete* analysis payload; returns True when
        stored. Refuses incomplete reports and quarantined hashes (see
        module docstring), and flushes the sidecar on every accepted
        put — results are expensive and must survive a daemon crash."""
        from ..observe import metrics

        if not isinstance(payload, dict) or payload.get("incomplete"):
            return False
        if (contract_hash and self.quarantine is not None
                and self.quarantine.is_quarantined(contract_hash)):
            log.info("result store: refusing to cache quarantined "
                     "contract %s…", (contract_hash or "")[:16])
            return False
        clean = {name: value for name, value in payload.items()
                 if name not in ("cached",)}
        with self._lock:
            self._seq += 1
            entry = {"seq": self._seq, "payload": clean}
            self._entries[key] = entry
            if len(self._entries) > self.max_entries:
                victims = sorted(self._entries,
                                 key=lambda k: self._entries[k]["seq"])
                evicted = len(self._entries) - self.max_entries
                for victim in victims[:evicted]:
                    del self._entries[victim]
                metrics.inc("cache.result.evicted", evicted)
            snapshot = {key: entry}
        metrics.inc("cache.result.stored")
        self._flush(snapshot)
        return True

    def _flush(self, entries: Dict[str, dict]) -> None:
        if not self.path:
            return
        try:
            save_results(self.path, entries, self.max_entries)
        except OSError as error:
            log.warning("could not persist result sidecar %s: %s",
                        self.path, error)

    def status(self) -> dict:
        with self._lock:
            entries = len(self._entries)
        total = self.hits + self.misses
        return {
            "sidecar": self.path,
            "entries": entries,
            "max_entries": self.max_entries,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hits / total, 4) if total else 0.0,
        }
