"""Transport loops for the serve daemon: stdio and unix socket.

Both speak the JSON-lines protocol (serve/protocol.py) and funnel every
frame through one shared :class:`~.service.AnalysisService` — transports
own bytes and connection lifecycle, the service owns admission,
isolation, and the engine. The socket server takes one reader thread per
connection (the service's in-flight gate bounds concurrent work), stdio
is a single foreground loop. Either exits cleanly when a ``shutdown``
request drains the service.
"""

from __future__ import annotations

import logging
import os
import socket
import sys
import threading
from typing import Optional

from . import protocol
from ..observe import slog
from ..support import tpu_config

log = logging.getLogger(__name__)


def install_sigterm_drain(service) -> None:
    """SIGTERM → graceful drain instead of a hard kill: admission stops
    (new analyzes get a typed ``shutting_down``), the transport loop
    exits, and ``service.shutdown()`` runs the
    ``MYTHRIL_TPU_SERVE_DRAIN_MS`` drain — in-flight and queued
    interactive work finishes, queued bulk is shed, stragglers are
    preempted into checkpoints. No-op off the main thread or on
    platforms without signals."""
    import signal

    def _drain(signum, frame):
        log.info("SIGTERM — draining")
        slog.event("serve.sigterm")
        service.shutting_down.set()

    try:
        signal.signal(signal.SIGTERM, _drain)
    except (ValueError, OSError, AttributeError, RuntimeError):
        pass


def default_socket_path() -> str:
    """MYTHRIL_TPU_SERVE_SOCKET, or ~/.mythril_tpu/serve.sock."""
    configured = tpu_config.get_str("MYTHRIL_TPU_SERVE_SOCKET")
    if configured:
        return configured
    base = tpu_config.get_str(
        "MYTHRIL_TPU_DIR",
        os.path.join(os.path.expanduser("~"), ".mythril_tpu"))
    return os.path.join(base, "serve.sock")


def serve_stream(service, rfile, wfile) -> int:
    """Serve one bidirectional byte stream until EOF or shutdown.
    Returns the number of frames answered. This is the whole protocol
    loop for stdio mode and for each socket connection."""
    answered = 0
    for item in protocol.iter_requests(rfile):
        reply = service.handle(item)
        wfile.write(protocol.encode(reply).encode("utf-8"))
        wfile.flush()
        answered += 1
        if service.shutting_down.is_set():
            break
    return answered


def serve_stdio(service, stdin=None, stdout=None) -> int:
    """Foreground stdio mode: requests on stdin, replies on stdout
    (logs must go to stderr — the CLI wires that up)."""
    rfile = stdin if stdin is not None else sys.stdin.buffer
    wfile = stdout if stdout is not None else sys.stdout.buffer
    service.startup()
    slog.event("serve.listening", transport="stdio")
    try:
        return serve_stream(service, rfile, wfile)
    finally:
        service.shutdown()
        slog.event("serve.stopped", transport="stdio")


def _connection_worker(service, connection) -> None:
    try:
        with connection:
            rfile = connection.makefile("rb")
            wfile = connection.makefile("wb")
            serve_stream(service, rfile, wfile)
    except (BrokenPipeError, ConnectionResetError):
        pass  # client went away mid-reply; nothing to clean up
    except Exception:
        log.exception("serve connection failed")


def serve_socket(service, socket_path: Optional[str] = None,
                 ready_event: Optional[threading.Event] = None) -> int:
    """Unix-socket mode: accept loop in this thread, one reader thread
    per connection. Blocks until a ``shutdown`` request (or
    KeyboardInterrupt) drains the service; returns the number of
    connections accepted. ``ready_event`` fires once the socket is bound
    and warmup has finished — tests and supervisors wait on it."""
    path = socket_path or default_socket_path()
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    # the probe/unlink/bind sequence below must be atomic across daemons:
    # two starting at once against the same stale socket could both probe
    # (dead), both unlink, and the second would silently unlink the
    # *first's* freshly bound socket. A held flock on a sidecar lockfile
    # serializes the whole reclaim-and-bind; the lock fd stays open for
    # the daemon's lifetime so a loser fails fast instead of stealing.
    lock_fd = os.open(path + ".lock", os.O_RDWR | os.O_CREAT, 0o600)
    try:
        try:
            import fcntl
            fcntl.flock(lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except ImportError:
            pass  # non-POSIX: fall back to the probe alone
        except OSError:
            raise RuntimeError(
                f"daemon already starting or listening on {path} "
                f"(lock {path}.lock is held)")
        if os.path.exists(path):
            # a live daemon would be reachable; a stale socket file from
            # a crashed one just blocks bind() — probe before unlinking
            probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                probe.connect(path)
            except OSError:
                log.warning("reclaiming stale socket %s", path)
                os.unlink(path)
            else:
                probe.close()
                raise RuntimeError(f"daemon already listening on {path}")
        server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        server.bind(path)
    except BaseException:
        os.close(lock_fd)
        raise
    accepted = 0
    try:
        os.chmod(path, 0o600)
        server.listen(8)
        server.settimeout(0.25)
        service.startup()
        if ready_event is not None:
            ready_event.set()
        log.info("serving on %s (max_inflight=%d)", path,
                 service.max_inflight)
        slog.event("serve.listening", transport="socket", path=path,
                   max_inflight=service.max_inflight)
        workers = []
        while not service.shutting_down.is_set():
            try:
                connection, _ = server.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            accepted += 1
            worker = threading.Thread(
                target=_connection_worker, args=(service, connection),
                name=f"serve-conn-{accepted}", daemon=True)
            worker.start()
            workers.append(worker)
        for worker in workers:
            worker.join(timeout=5.0)
    except KeyboardInterrupt:
        log.info("interrupted — draining")
    finally:
        service.shutdown()
        server.close()
        slog.event("serve.stopped", transport="socket",
                   connections=accepted)
        try:
            os.unlink(path)
        except OSError:
            pass
        os.close(lock_fd)  # releases the flock
        try:
            os.unlink(path + ".lock")
        except OSError:
            pass
    return accepted
