"""Thin HTTP shim over the serve protocol.

``POST /`` with one protocol request object as the JSON body returns the
reply as the JSON response body — the same validation, admission, and
isolation as the socket path, because every request still goes through
``AnalysisService.handle``. Overload semantics ride standard HTTP: an
``overloaded`` shed maps to 429 with a ``Retry-After`` header (rounded
up from the reply's ``retry_after_ms``), ``shutting_down`` to 503.
``GET /healthz`` answers a metrics summary (uptime, request counters,
queue depths, autoscaler state, warm buckets, frontier telemetry
rollup), ``GET /status`` the full status rollup, and ``GET /metrics``
Prometheus text exposition (observe/export.py) — all without touching
the engine, so a scrape during a long analyze never blocks. This is deliberately a shim, not a web framework:
stdlib ``http.server`` only, one process, no TLS — put a real proxy in
front if this ever leaves localhost.
"""

from __future__ import annotations

import json
import logging
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from . import protocol

log = logging.getLogger(__name__)

#: matches the protocol's per-line bound; requests beyond it are 413
MAX_BODY_BYTES = protocol.MAX_LINE_BYTES


class _Handler(BaseHTTPRequestHandler):
    service = None  # injected by serve_http via type()

    def log_message(self, fmt, *args):  # route access logs to logging
        log.debug("http: " + fmt, *args)

    def _reply(self, status: int, payload: dict,
               retry_after_s: Optional[int] = None) -> None:
        body = json.dumps(payload, sort_keys=True).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if retry_after_s is not None:
            self.send_header("Retry-After", str(retry_after_s))
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, status: int, body: str,
                    content_type: str) -> None:
        payload = body.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):
        if self.path == "/healthz":
            reply = self.service.handle(
                protocol.Request("healthz", "healthz", {}))
            self._reply(200, reply)
            return
        if self.path == "/status":
            reply = self.service.handle(
                protocol.Request("status", "status", {}))
            self._reply(200, reply)
            return
        if self.path == "/metrics":
            # Prometheus scrape: text exposition, not a JSON envelope
            reply = self.service.handle(
                protocol.Request("metrics", "metrics", {}))
            self._reply_text(200, reply["exposition"],
                             reply["content_type"])
            return
        self._reply(404, protocol.error_reply(
            None, "bad_request",
            "GET supports /healthz, /status, and /metrics"))

    def do_POST(self):
        try:
            length = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            length = -1
        if length < 0:
            self._reply(411, protocol.error_reply(
                None, "bad_request", "Content-Length required"))
            return
        if length > MAX_BODY_BYTES:
            self._reply(413, protocol.error_reply(
                None, "line_too_long",
                f"body exceeds {MAX_BODY_BYTES} bytes"))
            return
        body = self.rfile.read(length)
        try:
            request = protocol.parse_request(body)
        except protocol.ProtocolError as error:
            self._reply(400, protocol.error_reply(
                error.request_id, error.code, error.message))
            return
        reply = self.service.handle(request)
        retry_after_s: Optional[int] = None
        if reply.get("ok"):
            status = 200
        elif reply["error"]["code"] == "busy":
            status = 429  # Too Many Requests: back off and retry
        elif reply["error"]["code"] == "overloaded":
            status = 429  # shed by admission control
            retry_ms = reply["error"].get("retry_after_ms")
            if isinstance(retry_ms, (int, float)) and retry_ms > 0:
                # Retry-After is whole seconds; round up so a client
                # honoring the header never retries early
                retry_after_s = max(1, -(-int(retry_ms) // 1000))
        elif reply["error"]["code"] == "shutting_down":
            status = 503  # draining: this daemon is going away
        elif reply["error"]["code"] == "quarantined":
            status = 409  # Conflict: the resource itself is refused
        else:
            status = 400
        self._reply(status, reply, retry_after_s=retry_after_s)


def serve_http(service, host: str = "127.0.0.1", port: int = 8551,
               ready_event=None) -> int:
    """Serve HTTP until a ``shutdown`` request drains the service.
    Returns the bound port (useful with ``port=0`` in tests)."""
    handler = type("BoundHandler", (_Handler,), {"service": service})
    server = ThreadingHTTPServer((host, port), handler)
    server.timeout = 0.25
    server.daemon_threads = True
    bound_port = server.server_address[1]
    service.http_port = bound_port  # visible before the loop: port=0
    # callers (tests, supervisors) read the ephemeral port from here
    try:
        service.startup()
        if ready_event is not None:
            ready_event.set()
        log.info("serving HTTP on %s:%d", host, bound_port)
        while not service.shutting_down.is_set():
            server.handle_request()
    except KeyboardInterrupt:
        log.info("interrupted — draining")
    finally:
        service.shutdown()
        server.server_close()
    return bound_port
