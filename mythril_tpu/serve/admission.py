"""Admission control and backpressure for the serve daemon.

The original admission gate was a ``BoundedSemaphore`` that bounced any
request past ``max_inflight`` with a flat ``busy``. That fails the north
star two ways: a burst of bulk sweep traffic can starve the interactive
request that arrived a millisecond later, and a client has no idea
whether to retry in ten milliseconds or ten seconds.

:class:`AdmissionQueue` replaces it with a bounded two-class priority
queue:

* **Classes** — every request is ``interactive`` (the default) or
  ``bulk``; interactive always dequeues first, and within a class the
  earlier deadline wins, then arrival order (FIFO).
* **Backpressure** — up to ``MYTHRIL_TPU_SERVE_QUEUE_MAX`` requests may
  wait. Past the high-water mark the *lowest-priority oldest* waiter is
  shed with a typed ``overloaded`` error carrying ``retry_after_ms``
  (the configured base plus observed p95 service time scaled by queue
  depth — an honest hint, not a constant). A flood of bulk work
  therefore sheds bulk work; an interactive request is only ever shed
  by other interactive requests.
* **Early deadline triage** — a request whose ``deadline_ms`` cannot be
  met given queue depth × observed p95 service time is refused at
  admission instead of burning a slot to produce a guaranteed-late
  answer. Triage needs evidence: with no completed requests yet (no
  p95), everything is admitted.
* **Drain** — at shutdown the daemon sheds queued bulk work (typed
  ``shutting_down``), stops new admission, and waits for in-flight and
  queued-interactive requests via :meth:`wait_idle`.

The queue hands out *execution grants*: ``acquire`` blocks the serving
thread until one of the ``slots`` (= ``--max-inflight``) grants is
free, then the caller runs the analysis and must ``release`` in a
``finally``. All scheduling state lives under one condition variable —
grants are handed to the best waiter by ``_pump`` whenever a slot
frees, so no thread can barge past the queue.

Stdlib-only (threading/time): imported by protocol-level tests without
paying an accelerator import.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from .protocol import PRIORITIES
from ..support import tpu_config

_RANK = {name: rank for rank, name in enumerate(PRIORITIES)}

#: p95 service-time source for deadline triage and retry hints
SERVICE_HISTOGRAM = "serve.request_ms"


class Overloaded(Exception):
    """A request refused or shed by admission control.

    ``reason`` is ``"overload"`` (queue past high-water mark),
    ``"deadline"`` (triage: cannot meet the deadline at current depth),
    or ``"shutting_down"`` (shed during drain). ``retry_after_ms`` is
    the client's backoff hint."""

    def __init__(self, message: str, retry_after_ms: int,
                 reason: str = "overload"):
        super().__init__(message)
        self.retry_after_ms = int(retry_after_ms)
        self.reason = reason


class _Waiter:
    __slots__ = ("priority", "rank", "deadline_ms", "seq", "enqueued_at",
                 "granted", "shed_reason", "retry_after_ms")

    def __init__(self, priority: str, deadline_ms: Optional[int], seq: int):
        self.priority = priority
        self.rank = _RANK[priority]
        self.deadline_ms = deadline_ms
        self.seq = seq
        self.enqueued_at = time.monotonic()
        self.granted = False
        self.shed_reason: Optional[str] = None
        self.retry_after_ms = 0

    def sort_key(self):
        deadline = self.deadline_ms if self.deadline_ms else float("inf")
        return (self.rank, deadline, self.seq)


class AdmissionQueue:
    """Bounded two-class priority admission queue (see module doc)."""

    def __init__(self, slots: int, capacity: Optional[int] = None,
                 retry_after_ms: Optional[int] = None):
        self.slots = max(1, int(slots))
        if capacity is None:
            capacity = tpu_config.get_int("MYTHRIL_TPU_SERVE_QUEUE_MAX")
        self.capacity = max(1, int(capacity))
        if retry_after_ms is None:
            retry_after_ms = tpu_config.get_int(
                "MYTHRIL_TPU_SERVE_RETRY_AFTER_MS")
        self.retry_after_ms = max(1, int(retry_after_ms))
        self._cond = threading.Condition()
        self._waiters: list = []
        self._active = 0
        self._seq = 0
        self._closed = False
        self.shed_counts: Dict[str, int] = {name: 0 for name in PRIORITIES}
        self.deadline_rejections = 0

    # -- scheduling core (call with self._cond held) --------------------

    def _pump(self) -> None:
        """Hand free slots to the best waiters, best (rank, deadline,
        arrival) first."""
        handed = False
        while self._active < self.slots and self._waiters:
            best = min(self._waiters, key=_Waiter.sort_key)
            self._waiters.remove(best)
            best.granted = True
            self._active += 1
            handed = True
        if handed:
            self._cond.notify_all()

    def _gauge_depth(self) -> None:
        from ..observe import metrics

        metrics.set_gauge("serve.queue.depth", float(len(self._waiters)))

    def _p95_ms(self) -> Optional[float]:
        from ..observe import metrics

        try:
            p95 = metrics.quantile(SERVICE_HISTOGRAM, 0.95)
        except Exception:
            return None
        if p95 is None or p95 <= 0:
            return None
        return float(p95)

    def _retry_hint_ms(self, p95_ms: Optional[float]) -> int:
        """Backoff hint: base plus roughly one queue's worth of observed
        service time per slot — honest under load, minimal when idle."""
        hint = float(self.retry_after_ms)
        if p95_ms:
            depth = len(self._waiters) + 1
            hint += p95_ms * (depth / float(self.slots))
        return int(hint)

    def _shed(self, victim: "_Waiter", reason: str,
              retry_after_ms: int) -> None:
        from ..observe import metrics

        victim.shed_reason = reason
        victim.retry_after_ms = retry_after_ms
        self.shed_counts[victim.priority] += 1
        metrics.inc("serve.shed.overload")
        metrics.observe("serve.shed.by_class", 1.0, label=victim.priority)

    # -- public API -----------------------------------------------------

    def acquire(self, priority: str = "interactive",
                deadline_ms: Optional[int] = None) -> float:
        """Block until an execution grant is free; returns the time (ms)
        spent queued. Raises :class:`Overloaded` when this request is
        refused at triage, shed past the high-water mark, or shed by a
        drain."""
        from ..observe import metrics

        if priority not in _RANK:
            priority = "interactive"
        with self._cond:
            if self._closed:
                raise Overloaded("daemon is shutting down",
                                 self.retry_after_ms,
                                 reason="shutting_down")
            p95 = self._p95_ms()
            # early deadline triage: estimated completion is (everyone
            # queued ahead / slots + this request) p95 service times
            if deadline_ms and p95:
                est_ms = (len(self._waiters) / float(self.slots) + 1.0) * p95
                if est_ms > float(deadline_ms):
                    self.deadline_rejections += 1
                    metrics.inc("serve.shed.deadline")
                    raise Overloaded(
                        f"deadline {deadline_ms}ms cannot be met "
                        f"(estimated {int(est_ms)}ms at current depth)",
                        self._retry_hint_ms(p95), reason="deadline")
            self._seq += 1
            waiter = _Waiter(priority, deadline_ms, self._seq)
            self._waiters.append(waiter)
            if len(self._waiters) > self.capacity:
                # shed the lowest-priority oldest waiter — possibly the
                # newcomer itself if nothing queued outranks it
                victim = max(self._waiters,
                             key=lambda w: (w.rank, -w.seq))
                self._waiters.remove(victim)
                self._shed(victim, "overload", self._retry_hint_ms(p95))
                if victim is not waiter:
                    self._cond.notify_all()
            self._pump()
            self._gauge_depth()
            while not waiter.granted and waiter.shed_reason is None:
                self._cond.wait()
            self._gauge_depth()
            if waiter.shed_reason is not None:
                raise Overloaded("admission queue over capacity"
                                 if waiter.shed_reason == "overload"
                                 else "daemon is shutting down",
                                 waiter.retry_after_ms or self.retry_after_ms,
                                 reason=waiter.shed_reason)
            waited_ms = (time.monotonic() - waiter.enqueued_at) * 1000.0
        metrics.observe("serve.queue.wait_ms", waited_ms, label=priority)
        return waited_ms

    def release(self) -> None:
        with self._cond:
            if self._active > 0:
                self._active -= 1
            self._pump()
            self._gauge_depth()
            self._cond.notify_all()

    def try_acquire(self) -> bool:
        """Non-queueing grant for internal work (e.g. control ops that
        must not jump analyze traffic); False instead of waiting."""
        with self._cond:
            if self._closed or self._waiters or self._active >= self.slots:
                return False
            self._active += 1
            return True

    # -- drain ----------------------------------------------------------

    def close(self) -> None:
        """Stop admitting: subsequent ``acquire`` raises ``Overloaded``
        with reason ``shutting_down``. Queued waiters keep their place."""
        with self._cond:
            self._closed = True

    def shed_class(self, priority: str, reason: str = "shutting_down") -> int:
        """Shed every queued waiter of `priority` (drain path); returns
        how many were shed."""
        from ..observe import metrics

        with self._cond:
            victims = [w for w in self._waiters if w.priority == priority]
            for victim in victims:
                self._waiters.remove(victim)
                victim.shed_reason = reason
                victim.retry_after_ms = self.retry_after_ms
                self.shed_counts[victim.priority] += 1
                metrics.inc("serve.drain.shed")
            if victims:
                self._cond.notify_all()
            self._gauge_depth()
            return len(victims)

    def wait_idle(self, timeout_s: float) -> bool:
        """Wait up to `timeout_s` for every grant to be released and the
        queue to empty; True when fully idle."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._cond:
            while self._active > 0 or self._waiters:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True

    # -- introspection ---------------------------------------------------

    def depths(self) -> Dict[str, int]:
        with self._cond:
            counts = {name: 0 for name in PRIORITIES}
            for waiter in self._waiters:
                counts[waiter.priority] += 1
            return counts

    def active(self) -> int:
        with self._cond:
            return self._active

    def status(self) -> dict:
        with self._cond:
            depths = {name: 0 for name in PRIORITIES}
            for waiter in self._waiters:
                depths[waiter.priority] += 1
            return {
                "slots": self.slots,
                "capacity": self.capacity,
                "active": self._active,
                "depth": depths,
                "shed": dict(self.shed_counts),
                "deadline_rejections": self.deadline_rejections,
                "closed": self._closed,
            }
