"""WarmSet: the persisted registry of hot clause-shape buckets.

The cold-start problem (BENCH_r05, traceview per-shape accounting): the
first device solve per clause-shape bucket pays an XLA compile — ~112 s
of it before the first useful step on the TPU path. The serve daemon
kills it in two moves:

1. **Coarse canonicalization** (parallel/jax_solver.py, the default
   ``MYTHRIL_TPU_BUCKET_SCHEME=coarse``): tiles/vars/batch round to
   powers of four with a variable-axis floor, so real traffic lands in a
   handful of fat buckets instead of a long pow2 tail.
2. **Manifest-driven AOT warmup** (this module): every run records the
   shape keys its runners actually compiled
   (``jax_solver.observed_shape_keys()``, the same accounting behind the
   ``xla.bucket_compiles`` metric); the daemon replays the manifest
   through ``jax_solver.warm_shape_key`` at startup — inside the
   ``serve.warmup`` trace span — so requests arriving after warmup hit
   only warm buckets (asserted end to end via ``xla.bucket_reuses``).

Manifest format (JSON, versioned)::

    {"version": 1,
     "shapes": [["single", 1, 256, 5, 1, 1024, 32],
                ["batch", 256, 5, 1, 1024, 4, 32], ...]}

Shape entries are exactly the runner shape keys from
``parallel/jax_solver.py`` (kind, then the jit-cache dimensions). The
manifest merges monotonically: saving unions the shapes already on disk
with the ones observed this process, so a fleet of daemons sharing one
manifest only ever grows its warm set. Writes go through the fsync-atomic
``support/checkpoint.fsync_replace`` (PR 2), so a crashed daemon never
leaves a torn manifest behind. Unknown versions and malformed entries
load as empty/skipped — a stale manifest degrades to a cold start, never
a crash.

Beside the shape manifest lives the **taint-summary store**
(``<manifest>.summaries.json``): per-contract
``staticanalysis.ContractSummary`` JSON keyed by runtime-bytecode hash.
A warm daemon seeing a repeat corpus contract pre-seeds the persisted
summary onto its disassembly (``staticanalysis.install_summary``) before
the engine runs, so the taint fixpoint — like the XLA compiles — is paid
once per contract, not once per request. The store follows the same
rules as the manifest: monotone union-merge on save, fsync-atomic
writes, and tolerant loads that degrade to "rebuild the summary".

Two more durable-warmth stores complete the picture (ISSUE 16): the
**verdict sidecar** (``<manifest>.verdicts.json``) persists the
canonical-CNF SAT/UNSAT verdict cache (smt/solver/dispatch.py) — loaded
at worker spawn, union-merged at request end under a flock, bounded by
``MYTHRIL_TPU_VERDICT_SIDECAR_MAX`` — and the **executable cache**
(``parallel/exec_cache.py``, an ``exec_cache/`` directory beside the
manifest) persists the compiled runners themselves, so
:meth:`WarmSet.warmup` is deserialize-first and a respawned worker is
ready in seconds with zero ``xla.bucket_compiles``.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Dict, List, Optional, Tuple

from ..observe import metrics, trace
from ..support import tpu_config
from ..support.checkpoint import fsync_replace

log = logging.getLogger(__name__)

MANIFEST_VERSION = 1
SUMMARIES_VERSION = 1
VERDICTS_VERSION = 1


def default_manifest_path() -> str:
    """MYTHRIL_TPU_SERVE_MANIFEST, or ~/.mythril_tpu/warmset.json."""
    configured = tpu_config.get_str("MYTHRIL_TPU_SERVE_MANIFEST")
    if configured:
        return configured
    base = tpu_config.get_str(
        "MYTHRIL_TPU_DIR",
        os.path.join(os.path.expanduser("~"), ".mythril_tpu"))
    return os.path.join(base, "warmset.json")


def load_manifest(path: str) -> List[Tuple]:
    """Shape keys from a manifest file; [] for missing, malformed, or
    unknown-version manifests (each skip is logged, never raised)."""
    try:
        with open(path, "r", encoding="utf-8") as handle:
            doc = json.load(handle)
    except FileNotFoundError:
        return []
    except (OSError, ValueError) as error:
        log.warning("warmset manifest %s unreadable (%s) — cold start",
                    path, error)
        return []
    if not isinstance(doc, dict) or doc.get("version") != MANIFEST_VERSION:
        log.warning("warmset manifest %s has unsupported version %r — "
                    "cold start", path,
                    doc.get("version") if isinstance(doc, dict) else None)
        return []
    shapes = []
    for entry in doc.get("shapes") or []:
        if isinstance(entry, list) and entry \
                and isinstance(entry[0], str) \
                and all(isinstance(dim, int) for dim in entry[1:]):
            shapes.append(tuple(entry))
        else:
            log.warning("warmset manifest %s: skipping malformed entry %r",
                        path, entry)
    return shapes


def save_manifest(path: str, shapes: List[Tuple]) -> int:
    """Merge `shapes` into the manifest at `path` (union with what is
    already there) and write it fsync-atomically. Returns the merged
    shape count."""
    merged = sorted(set(load_manifest(path)) | {tuple(s) for s in shapes})
    payload = {"version": MANIFEST_VERSION,
               "shapes": [list(shape) for shape in merged]}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as handle:
        json.dump(payload, handle, indent=1)
    fsync_replace(tmp, path)
    return len(merged)


def summaries_path_for(manifest_path: str) -> str:
    """The taint-summary store sits beside the shape manifest:
    ``warmset.json`` → ``warmset.summaries.json``."""
    base, _ = os.path.splitext(manifest_path)
    return f"{base}.summaries.json"


def load_summaries(path: str) -> Dict[str, dict]:
    """Per-contract summary JSON keyed by bytecode hash; {} for missing,
    malformed, or unknown-version stores (logged, never raised). Entries
    are returned verbatim — ``ContractSummary.from_json`` does its own
    version/shape validation at install time."""
    try:
        with open(path, "r", encoding="utf-8") as handle:
            doc = json.load(handle)
    except FileNotFoundError:
        return {}
    except (OSError, ValueError) as error:
        log.warning("summary store %s unreadable (%s) — summaries will "
                    "be rebuilt", path, error)
        return {}
    if not isinstance(doc, dict) or doc.get("version") != SUMMARIES_VERSION:
        log.warning("summary store %s has unsupported version %r — "
                    "summaries will be rebuilt", path,
                    doc.get("version") if isinstance(doc, dict) else None)
        return {}
    summaries = {}
    for key, entry in (doc.get("summaries") or {}).items():
        if isinstance(key, str) and isinstance(entry, dict):
            summaries[key] = entry
        else:
            log.warning("summary store %s: skipping malformed entry %r",
                        path, key)
    return summaries


def save_summaries(path: str, summaries: Dict[str, dict]) -> int:
    """Merge `summaries` into the store at `path` (union by bytecode
    hash, this process's entries winning ties) and write it
    fsync-atomically. Returns the merged entry count."""
    merged = load_summaries(path)
    merged.update({k: v for k, v in summaries.items()
                   if isinstance(k, str) and isinstance(v, dict)})
    payload = {"version": SUMMARIES_VERSION,
               "summaries": {key: merged[key] for key in sorted(merged)}}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as handle:
        json.dump(payload, handle, indent=1)
    fsync_replace(tmp, path)
    return len(merged)


def verdicts_path_for(manifest_path: str) -> str:
    """The verdict sidecar sits beside the shape manifest:
    ``warmset.json`` → ``warmset.verdicts.json``."""
    base, _ = os.path.splitext(manifest_path)
    return f"{base}.verdicts.json"


def verdict_sidecar_enabled() -> bool:
    """MYTHRIL_TPU_VERDICT_SIDECAR (default on)."""
    return tpu_config.get_flag("MYTHRIL_TPU_VERDICT_SIDECAR")


def _verdict_key(entry: list) -> str:
    """Dedup key for one sidecar entry: the canonical CNF itself (the
    verdict is a property of the clause set, so colliding entries are
    interchangeable)."""
    return json.dumps([entry[0], entry[1]])


def load_verdicts(path: str) -> List[list]:
    """Sidecar entries (JSON-shaped, see ``dispatch.export_verdicts``);
    [] for missing, malformed, or unknown-version sidecars (logged,
    never raised). Entries are shallow-checked here — deep validation
    happens at ``dispatch.import_verdicts`` time."""
    try:
        with open(path, "r", encoding="utf-8") as handle:
            doc = json.load(handle)
    except FileNotFoundError:
        return []
    except (OSError, ValueError) as error:
        log.warning("verdict sidecar %s unreadable (%s) — cold verdict "
                    "cache", path, error)
        return []
    if not isinstance(doc, dict) or doc.get("version") != VERDICTS_VERSION:
        log.warning("verdict sidecar %s has unsupported version %r — "
                    "cold verdict cache", path,
                    doc.get("version") if isinstance(doc, dict) else None)
        return []
    entries = []
    for entry in doc.get("verdicts") or []:
        if isinstance(entry, list) and len(entry) == 4:
            entries.append(entry)
        else:
            log.warning("verdict sidecar %s: skipping malformed entry %r",
                        path, entry)
    return entries


def save_verdicts(path: str, entries: List[list]) -> int:
    """Union-merge `entries` into the sidecar at `path` and write it
    fsync-atomically: what is on disk loads first, this process's
    entries append (disk-order = age-order, so eviction under the
    ``MYTHRIL_TPU_VERDICT_SIDECAR_MAX`` bound drops the OLDEST entries).
    The load-merge-write runs under an exclusive flock on a ``.lock``
    file beside the sidecar, so two workers flushing concurrently
    serialize and neither's entries are lost (the lock guards the
    read-modify-write; the fsync-atomic rename guards readers). Returns
    the entry count written."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    lock_handle = None
    try:
        import fcntl

        lock_handle = open(f"{path}.lock", "w", encoding="utf-8")
        fcntl.flock(lock_handle, fcntl.LOCK_EX)
    except (ImportError, OSError):
        lock_handle = None  # non-POSIX: rename atomicity still holds
    try:
        merged: Dict[str, list] = {}
        for entry in load_verdicts(path):
            merged[_verdict_key(entry)] = entry
        fresh = 0
        for entry in entries:
            key = _verdict_key(entry)
            if key not in merged:
                fresh += 1
            merged[key] = entry
        if fresh:
            metrics.inc("cache.verdict.merged", fresh)
        ordered = list(merged.values())
        bound = max(1,
                    tpu_config.get_int("MYTHRIL_TPU_VERDICT_SIDECAR_MAX"))
        if len(ordered) > bound:
            metrics.inc("cache.verdict.evicted", len(ordered) - bound)
            ordered = ordered[len(ordered) - bound:]
        payload = {"version": VERDICTS_VERSION, "verdicts": ordered}
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as handle:
            json.dump(payload, handle, indent=1)
        fsync_replace(tmp, path)
        return len(ordered)
    finally:
        if lock_handle is not None:
            lock_handle.close()


class WarmSet:
    """The daemon's view of the warm buckets: load → warm → record.

    ``path=None`` disables persistence (warmup still works off whatever
    shapes the caller seeds via :meth:`warm`)."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.warmed: List[Tuple] = []
        self.failed: List[Tuple] = []
        #: verdict-cache entries loaded from the sidecar at warmup
        self.loaded_verdicts = 0
        # taint summaries recorded this process, pending persistence
        self._pending_summaries: Dict[str, dict] = {}
        # lazy-loaded view of the on-disk store (None = not loaded yet)
        self._stored_summaries: Optional[Dict[str, dict]] = None

    def _summaries_path(self) -> Optional[str]:
        return summaries_path_for(self.path) if self.path else None

    def _verdicts_path(self) -> Optional[str]:
        return verdicts_path_for(self.path) if self.path else None

    def summary_for(self, code_hash: str) -> Optional[dict]:
        """The persisted ContractSummary JSON for a bytecode hash, if any
        (this process's fresh records take precedence over disk)."""
        if code_hash in self._pending_summaries:
            return self._pending_summaries[code_hash]
        if self._stored_summaries is None:
            path = self._summaries_path()
            self._stored_summaries = load_summaries(path) if path else {}
        return self._stored_summaries.get(code_hash)

    def record_summary(self, code_hash: str, summary_json: dict) -> None:
        """Queue a freshly built summary for persistence (flushed by
        :meth:`record_observed` after each request and at shutdown)."""
        if code_hash and isinstance(summary_json, dict):
            self._pending_summaries[code_hash] = summary_json

    def warmup(self) -> int:
        """Pre-compile every manifest bucket, inside one ``serve.warmup``
        span (traceview attributes the compile cliff to warmup, not to
        the first request). Returns the bucket count actually warmed."""
        shapes = load_manifest(self.path) if self.path else []
        # the span is emitted even for an empty manifest: traceview's
        # serve section attributes warmup separately from request time,
        # and "0 buckets warmed" is a finding, not an absence
        with trace.span("serve.warmup", buckets=len(shapes)) as span:
            if shapes:
                from ..parallel import jax_solver

                for shape in shapes:
                    if jax_solver.warm_shape_key(shape):
                        self.warmed.append(shape)
                        metrics.inc("serve.warmed_buckets")
                    else:
                        self.failed.append(shape)
            self.loaded_verdicts = self._load_verdicts()
            span.set(warmed=len(self.warmed), failed=len(self.failed),
                     exec_hits=int(metrics.value("cache.exec.hits")),
                     exec_misses=int(metrics.value("cache.exec.misses")),
                     verdicts_loaded=self.loaded_verdicts)
        if self.failed:
            log.warning("warmup skipped %d un-warmable manifest shapes "
                        "(different mesh or malformed): %s",
                        len(self.failed), self.failed[:4])
        log.info("warmup pre-compiled %d clause-shape buckets "
                 "(%d from the executable cache), loaded %d verdicts",
                 len(self.warmed), int(metrics.value("cache.exec.hits")),
                 self.loaded_verdicts)
        return len(self.warmed)

    def _load_verdicts(self) -> int:
        """Seed the dispatch verdict cache from the persisted sidecar
        (worker spawn / daemon warmup). Best-effort: an unreadable or
        stale sidecar is a cold cache, never a failed startup."""
        path = self._verdicts_path()
        if not path or not verdict_sidecar_enabled():
            return 0
        from ..smt.solver import dispatch

        return dispatch.import_verdicts(load_verdicts(path))

    def _flush_verdicts(self) -> None:
        """Union-merge this process's verdict cache into the sidecar."""
        path = self._verdicts_path()
        if not path or not verdict_sidecar_enabled():
            return
        from ..smt.solver import dispatch

        entries = dispatch.export_verdicts()
        if not entries:
            return
        try:
            save_verdicts(path, entries)
        except OSError as error:
            log.warning("could not persist verdict sidecar %s: %s",
                        path, error)

    def record_observed(self) -> int:
        """Persist every shape this process has compiled so far (warmup
        plus live traffic) back into the manifest. Called after each
        request and at shutdown — the next daemon starts at least this
        warm. No-op (returning 0) without a manifest path."""
        if not self.path:
            return 0
        self._flush_summaries()
        self._flush_verdicts()
        from ..parallel import jax_solver

        observed = jax_solver.observed_shape_keys()
        if not observed:
            return 0
        try:
            return save_manifest(self.path, observed)
        except OSError as error:
            log.warning("could not persist warmset manifest %s: %s",
                        self.path, error)
            return 0

    def _flush_summaries(self) -> None:
        if not self._pending_summaries:
            return
        path = self._summaries_path()
        try:
            save_summaries(path, self._pending_summaries)
        except OSError as error:
            log.warning("could not persist summary store %s: %s",
                        path, error)
            return
        # fold into the in-memory view so summary_for keeps answering
        # without a re-read, then clear the queue
        if self._stored_summaries is not None:
            self._stored_summaries.update(self._pending_summaries)
        self._pending_summaries.clear()

    def status(self) -> dict:
        from ..parallel import jax_solver

        if self._stored_summaries is None:
            path = self._summaries_path()
            self._stored_summaries = load_summaries(path) if path else {}
        return {
            "manifest": self.path,
            "warmed_buckets": len(self.warmed),
            "unwarmable_buckets": len(self.failed),
            "observed_buckets": len(jax_solver.observed_shape_keys()),
            "taint_summaries": len(set(self._stored_summaries)
                                   | set(self._pending_summaries)),
            "exec_cache": {
                "hits": int(metrics.value("cache.exec.hits")),
                "misses": int(metrics.value("cache.exec.misses")),
            },
            "verdicts_loaded": self.loaded_verdicts,
        }
