"""WarmSet: the persisted registry of hot clause-shape buckets.

The cold-start problem (BENCH_r05, traceview per-shape accounting): the
first device solve per clause-shape bucket pays an XLA compile — ~112 s
of it before the first useful step on the TPU path. The serve daemon
kills it in two moves:

1. **Coarse canonicalization** (parallel/jax_solver.py, the default
   ``MYTHRIL_TPU_BUCKET_SCHEME=coarse``): tiles/vars/batch round to
   powers of four with a variable-axis floor, so real traffic lands in a
   handful of fat buckets instead of a long pow2 tail.
2. **Manifest-driven AOT warmup** (this module): every run records the
   shape keys its runners actually compiled
   (``jax_solver.observed_shape_keys()``, the same accounting behind the
   ``xla.bucket_compiles`` metric); the daemon replays the manifest
   through ``jax_solver.warm_shape_key`` at startup — inside the
   ``serve.warmup`` trace span — so requests arriving after warmup hit
   only warm buckets (asserted end to end via ``xla.bucket_reuses``).

Manifest format (JSON, versioned)::

    {"version": 1,
     "shapes": [["single", 1, 256, 5, 1, 1024, 32],
                ["batch", 256, 5, 1, 1024, 4, 32], ...]}

Shape entries are exactly the runner shape keys from
``parallel/jax_solver.py`` (kind, then the jit-cache dimensions). The
manifest merges monotonically: saving unions the shapes already on disk
with the ones observed this process, so a fleet of daemons sharing one
manifest only ever grows its warm set. Writes go through the fsync-atomic
``support/checkpoint.fsync_replace`` (PR 2), so a crashed daemon never
leaves a torn manifest behind. Unknown versions and malformed entries
load as empty/skipped — a stale manifest degrades to a cold start, never
a crash.
"""

from __future__ import annotations

import json
import logging
import os
from typing import List, Optional, Tuple

from ..observe import metrics, trace
from ..support import tpu_config
from ..support.checkpoint import fsync_replace

log = logging.getLogger(__name__)

MANIFEST_VERSION = 1


def default_manifest_path() -> str:
    """MYTHRIL_TPU_SERVE_MANIFEST, or ~/.mythril_tpu/warmset.json."""
    configured = tpu_config.get_str("MYTHRIL_TPU_SERVE_MANIFEST")
    if configured:
        return configured
    base = tpu_config.get_str(
        "MYTHRIL_TPU_DIR",
        os.path.join(os.path.expanduser("~"), ".mythril_tpu"))
    return os.path.join(base, "warmset.json")


def load_manifest(path: str) -> List[Tuple]:
    """Shape keys from a manifest file; [] for missing, malformed, or
    unknown-version manifests (each skip is logged, never raised)."""
    try:
        with open(path, "r", encoding="utf-8") as handle:
            doc = json.load(handle)
    except FileNotFoundError:
        return []
    except (OSError, ValueError) as error:
        log.warning("warmset manifest %s unreadable (%s) — cold start",
                    path, error)
        return []
    if not isinstance(doc, dict) or doc.get("version") != MANIFEST_VERSION:
        log.warning("warmset manifest %s has unsupported version %r — "
                    "cold start", path,
                    doc.get("version") if isinstance(doc, dict) else None)
        return []
    shapes = []
    for entry in doc.get("shapes") or []:
        if isinstance(entry, list) and entry \
                and isinstance(entry[0], str) \
                and all(isinstance(dim, int) for dim in entry[1:]):
            shapes.append(tuple(entry))
        else:
            log.warning("warmset manifest %s: skipping malformed entry %r",
                        path, entry)
    return shapes


def save_manifest(path: str, shapes: List[Tuple]) -> int:
    """Merge `shapes` into the manifest at `path` (union with what is
    already there) and write it fsync-atomically. Returns the merged
    shape count."""
    merged = sorted(set(load_manifest(path)) | {tuple(s) for s in shapes})
    payload = {"version": MANIFEST_VERSION,
               "shapes": [list(shape) for shape in merged]}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as handle:
        json.dump(payload, handle, indent=1)
    fsync_replace(tmp, path)
    return len(merged)


class WarmSet:
    """The daemon's view of the warm buckets: load → warm → record.

    ``path=None`` disables persistence (warmup still works off whatever
    shapes the caller seeds via :meth:`warm`)."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.warmed: List[Tuple] = []
        self.failed: List[Tuple] = []

    def warmup(self) -> int:
        """Pre-compile every manifest bucket, inside one ``serve.warmup``
        span (traceview attributes the compile cliff to warmup, not to
        the first request). Returns the bucket count actually warmed."""
        shapes = load_manifest(self.path) if self.path else []
        # the span is emitted even for an empty manifest: traceview's
        # serve section attributes warmup separately from request time,
        # and "0 buckets warmed" is a finding, not an absence
        with trace.span("serve.warmup", buckets=len(shapes)) as span:
            if shapes:
                from ..parallel import jax_solver

                for shape in shapes:
                    if jax_solver.warm_shape_key(shape):
                        self.warmed.append(shape)
                        metrics.inc("serve.warmed_buckets")
                    else:
                        self.failed.append(shape)
            span.set(warmed=len(self.warmed), failed=len(self.failed))
        if self.failed:
            log.warning("warmup skipped %d un-warmable manifest shapes "
                        "(different mesh or malformed): %s",
                        len(self.failed), self.failed[:4])
        log.info("warmup pre-compiled %d clause-shape buckets",
                 len(self.warmed))
        return len(self.warmed)

    def record_observed(self) -> int:
        """Persist every shape this process has compiled so far (warmup
        plus live traffic) back into the manifest. Called after each
        request and at shutdown — the next daemon starts at least this
        warm. No-op (returning 0) without a manifest path."""
        if not self.path:
            return 0
        from ..parallel import jax_solver

        observed = jax_solver.observed_shape_keys()
        if not observed:
            return 0
        try:
            return save_manifest(self.path, observed)
        except OSError as error:
            log.warning("could not persist warmset manifest %s: %s",
                        self.path, error)
            return 0

    def status(self) -> dict:
        from ..parallel import jax_solver

        return {
            "manifest": self.path,
            "warmed_buckets": len(self.warmed),
            "unwarmable_buckets": len(self.failed),
            "observed_buckets": len(jax_solver.observed_shape_keys()),
        }
