"""AnalysisService: the daemon's request executor.

One instance owns the warm set, the admission queue, and the engine
lock; the stdio loop, the unix-socket server, and the HTTP shim all
funnel into :meth:`handle`, so every transport shares one behavior:

* **Admission** is a bounded two-class priority queue
  (serve/admission.py): ``MYTHRIL_TPU_SERVE_MAX_INFLIGHT`` execution
  grants, up to ``MYTHRIL_TPU_SERVE_QUEUE_MAX`` waiting requests
  ordered (priority, deadline, arrival). Past the high-water mark the
  lowest-priority oldest waiter is shed with a typed ``overloaded``
  error carrying ``retry_after_ms``; a request whose deadline cannot
  be met given queue depth × observed p95 service time is refused at
  admission (early triage). Before any of that, a repeat
  (bytecode, config) request is answered straight from the
  content-addressed result store (serve/result_store.py) without
  consuming a grant or touching a worker.
* **Execution** is serialized on one engine lock — the symbolic engine,
  the solver pipeline, and the dispatch queue are all single-threaded
  process singletons. Admitted requests wait on the lock; the in-flight
  bound caps how many can wait.
* **Isolation**: each analyze request starts from
  ``reset_solver_backend(keep_verdicts=True)`` — fresh incremental
  pipeline, fresh breaker/fault state (a quarantine belongs to the
  request that suffered it), reset callback modules — while the
  canonical-CNF verdict cache and every compiled XLA executable stay
  warm (that is the whole point of the daemon).
* **Deadlines** ride the engine's deadline-drain substrate (PR 2): the
  request's ``deadline_ms`` becomes the analysis execution timeout, so
  an over-budget contract yields ``incomplete: true`` plus coverage
  stats, never a wedged queue.
* **Accounting**: every request runs inside a ``serve.request`` trace
  span carrying the request id and its warm/cold dispatch counts
  (``xla.bucket_compiles``/``bucket_reuses`` deltas), which is what
  ``tools/traceview.py``'s per-request rollup renders.
* **Worker isolation** (``MYTHRIL_TPU_SERVE_WORKERS`` / ``serve
  --workers N``): with a pool configured, the engine never runs in the
  daemon process — each analyze (or fleet micro-batch) is dispatched to
  a supervised, manifest-warmed worker process
  (serve/supervisor.py), so a segfault/OOM/hang kills one sandbox, the
  victim request is retried once, and repeat offenders land in the
  poison-quarantine sidecar (answered with a typed ``quarantined``
  error). The engine lock is bypassed in this mode: the pool itself is
  the execution-capacity gate. With ``MYTHRIL_TPU_SERVE_WORKERS_MAX``
  set, an autoscaler (serve/autoscale.py) elastically resizes the pool
  from the admission-depth and occupancy gauges.
* **QoS preemption** (fleet mode): the micro-batcher composes batches
  in (priority, deadline, arrival) order, and an interactive arrival
  preempts a running all-bulk batch through the engine's per-contract
  deadline-drain machinery — the preempted members checkpoint
  (namespaced per contract) and re-run solo from their checkpoints
  instead of being aborted.
* **Graceful drain**: ``shutdown``/SIGTERM stops admission (typed
  ``shutting_down``), sheds queued bulk work, and gives in-flight and
  queued-interactive requests ``MYTHRIL_TPU_SERVE_DRAIN_MS`` to finish
  before the remaining fleet batches are preempted into checkpoints.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import shutil
import tempfile
import threading
import time
from typing import Dict, Optional

from . import protocol
from .admission import AdmissionQueue, Overloaded
from .quarantine import QuarantinedContract, contract_key
from .result_store import ResultStore, result_key, results_path_for
from .warmset import WarmSet
from ..observe import export, metrics, slog, trace
from ..support import tpu_config

log = logging.getLogger(__name__)

#: frontier telemetry counters rolled up per request and in /healthz
#: (declared in observe/metrics.py, fed by parallel/frontier.py's
#: per-chunk decode of the device counter plane)
_FRONTIER_COUNTERS = ("executed", "forks", "escapes", "reseeds", "deaths",
                      "cold_sload_pauses")


def _frontier_counters() -> Dict[str, int]:
    return {name: int(metrics.value(f"frontier.telemetry.{name}"))
            for name in _FRONTIER_COUNTERS}


def _shard_capacity_factor() -> int:
    """Shard count a fleet frontier would run with right now: the forced
    MYTHRIL_TPU_FLEET_SHARD when set, else the device count on a real
    multi-device mesh (the same auto rule parallel/frontier.py applies).
    The micro-batcher multiplies its per-batch capacity by it — N shard
    blocks sweep N contracts' lanes concurrently."""
    forced = tpu_config.get_int("MYTHRIL_TPU_FLEET_SHARD")
    if forced > 1:
        return forced
    if forced == 0:
        try:
            import jax

            devices = jax.devices()
            if len(devices) > 1 and devices[0].platform != "cpu":
                return len(devices)
        except Exception:  # no backend yet: solo capacity
            log.debug("shard capacity probe failed", exc_info=True)
    return 1


def _shard_rollup() -> Dict[str, object]:
    """Sharded-fleet gauges for /healthz (declared in observe/metrics.py,
    fed by the frontier's per-chunk shard-block decode)."""
    return {
        "devices": int(metrics.value("frontier.shard.devices")),
        "steal_rows": int(metrics.value("frontier.shard.steal_rows")),
        "steal_passes": int(metrics.value("frontier.shard.steal_passes")),
        "imbalance": int(metrics.value("frontier.shard.imbalance")),
        "fairness": float(metrics.value("frontier.shard.fairness")),
    }


def execution_timeout_s(deadline_ms: Optional[int]) -> float:
    """A request's ``deadline_ms`` as the engine execution timeout in
    seconds, clamped to the ``MYTHRIL_TPU_SERVE_MAX_DEADLINE_MS``
    ceiling; a request without a deadline gets the full ceiling (one
    day by default — "no deadline" still must not wedge a worker
    forever). Shared by the solo path, the fleet batcher, and the
    worker process, so every execution route prices a deadline the same
    way."""
    max_ms = tpu_config.get_int("MYTHRIL_TPU_SERVE_MAX_DEADLINE_MS")
    if deadline_ms:
        if max_ms and deadline_ms > max_ms:
            deadline_ms = max_ms
        return max(deadline_ms / 1000.0, 0.001)
    return max(max_ms / 1000.0, 0.001) if max_ms else 86400.0


class _RequestArgs:
    """Namespace handed to MythrilAnalyzer as cmd_args (it getattr()s
    every field with a default, so only overrides need to exist)."""


#: batch-composition order: priority class first, then deadline
_PRIORITY_RANK = {name: rank
                  for rank, name in enumerate(protocol.PRIORITIES)}


class _FleetTicket:
    """One analyze request waiting on (or leading) a fleet micro-batch."""

    _seq = itertools.count(1)

    def __init__(self, params: Dict, cid: str):
        self.params = params
        self.cid = cid
        self.seq = next(self._seq)
        self.done = threading.Event()
        self.payload: Optional[Dict] = None
        self.error: Optional[BaseException] = None
        #: set when this member was preempted by an interactive arrival:
        #: the request thread re-runs it solo from `resume_path`
        self.preempted = False
        self.resume_path: Optional[str] = None

    def sort_key(self):
        deadline = self.params.get("deadline_ms") or float("inf")
        return (_PRIORITY_RANK.get(
            self.params.get("priority") or "interactive", 0),
            deadline, self.seq)


class _FleetBatcher:
    """Micro-batching admission for `analyze` (opt-in: `serve --fleet` /
    MYTHRIL_TPU_FLEET_SERVE).

    Instead of queueing on the engine lock one-by-one, concurrent
    compatible requests form a batch: the first arrival for a parameter
    key becomes the LEADER, waits MYTHRIL_TPU_FLEET_WINDOW_MS for
    followers, then runs every member's contract as ONE fleet
    (MythrilAnalyzer.fleet_contract_results — one shared device frontier,
    merged solver flushes) and demuxes per-contract results back into
    per-request replies. Followers just park on their ticket. Requests
    whose parameters differ (another key) lead their own batch.

    QoS: the leader composes the batch in (priority, deadline, arrival)
    order, and an interactive arrival at admission preempts any running
    all-bulk batch (``preempt_for_interactive``) via the engine's
    deadline-drain machinery — preempted members checkpoint under a
    batch-scoped namespace and their request threads re-run them solo
    from the checkpoint once the interactive work has the engine."""

    #: params that must agree for two requests to share one fleet step
    _KEY_FIELDS = ("engine", "solver", "strategy", "max_depth",
                   "transaction_count", "bin_runtime", "deadline_ms")

    def __init__(self, service: "AnalysisService"):
        self.service = service
        self._lock = threading.Lock()
        self._waiting: Dict[tuple, list] = {}
        self._batch_seq = itertools.count(1)
        #: running engine-lock batches: {"preempt": Event, "tickets": []}
        #: (worker-mode batches are not preemptible across the process
        #: boundary — the pool's parallelism is their QoS lever)
        self._inflight: list = []

    def _key(self, params: Dict) -> tuple:
        key = [params.get(field) for field in self._KEY_FIELDS]
        modules = params.get("modules")
        key.append(tuple(modules) if modules else None)
        return tuple(key)

    def run(self, params: Dict, cid: str) -> Dict:
        """Join (or lead) the micro-batch for this request's parameter
        key; returns this request's own payload."""
        window_s = max(
            tpu_config.get_float("MYTHRIL_TPU_FLEET_WINDOW_MS"), 0.0) / 1000.0
        max_batch = max(tpu_config.get_int("MYTHRIL_TPU_FLEET_MAX_BATCH"), 1)
        # a sharded fleet frontier sweeps one lane block per shard, so the
        # micro-batch capacity scales with the shard count (devices on a
        # real mesh, MYTHRIL_TPU_FLEET_SHARD when forced)
        max_batch *= max(_shard_capacity_factor(), 1)
        key = self._key(params)
        ticket = _FleetTicket(params, cid)
        with self._lock:
            group = self._waiting.get(key)
            if group is not None and len(group) < max_batch:
                group.append(ticket)
                leader = False
            else:
                self._waiting[key] = [ticket]
                leader = True
        if leader:
            if window_s:
                time.sleep(window_s)
            with self._lock:
                group = self._waiting.pop(key)
            # batch composition is (priority, deadline, arrival), so a
            # mixed batch runs its interactive members first
            group.sort(key=_FleetTicket.sort_key)
            if self.service._supervisor is not None:
                # worker mode: the batch runs in a supervised worker
                # process; the pool is the capacity gate, not the
                # daemon's engine lock
                self._run_batch_workers(group)
            else:
                with self.service._engine_lock:
                    self._run_batch(group)
        ticket.done.wait()
        if ticket.preempted:
            return self._rerun_preempted(ticket)
        if ticket.error is not None:
            raise ticket.error
        return ticket.payload

    def preempt_for_interactive(self) -> int:
        """Preempt every running all-bulk batch (an interactive request
        just arrived and wants the engine): sets the batch's preempt
        event, so the next deadline-drain sweep abandons its members —
        they checkpoint and re-run solo. Returns batches preempted."""
        with self._lock:
            batches = list(self._inflight)
        hit = 0
        for batch in batches:
            if batch["preempt"].is_set():
                continue
            if all((t.params.get("priority") or "interactive") == "bulk"
                   for t in batch["tickets"]):
                batch["preempt"].set()
                hit += 1
                metrics.inc("serve.fleet.preempted")
                slog.event("serve.fleet.preempt",
                           members=len(batch["tickets"]))
                log.info("preempting a running bulk fleet batch "
                         "(%d member(s)) for an interactive arrival",
                         len(batch["tickets"]))
        return hit

    def _rerun_preempted(self, ticket: _FleetTicket) -> Dict:
        """The request thread's continuation after its member was
        preempted: one solo engine-lock run, resuming from the member's
        batch-scoped checkpoint when one was written (a drain before
        the first periodic save restarts from scratch). Solo means no
        batcher and no preempt event — a re-run cannot be preempted
        again, so bulk work always completes."""
        resume = ticket.resume_path
        if resume and not os.path.exists(resume):
            resume = None
        slog.event("serve.fleet.requeued", resume=bool(resume))
        try:
            with self.service._engine_lock:
                payload = self.service._run_analysis_local(
                    ticket.params, resume_path=resume)
        finally:
            if ticket.resume_path:
                try:
                    os.unlink(ticket.resume_path)
                except OSError:
                    pass
        payload["fleet_preempted"] = True
        return payload

    def _run_batch(self, group: list) -> None:
        """Leader-side: run every ticket's contract as one fleet and
        complete the tickets. Always completes every ticket (with an
        error when the batch itself fails) — followers must never hang."""
        try:
            self._run_batch_inner(group)
        except BaseException as error:  # noqa: BLE001 — demuxed per ticket
            for ticket in group:
                if not ticket.done.is_set():
                    ticket.error = error
                    ticket.done.set()
            raise

    def _run_batch_workers(self, group: list) -> None:
        """Leader-side, worker mode: quarantined members are refused
        individually (an innocent co-member must not lose its slot to a
        poison contract), then the surviving members ship to one worker
        as a single fleet job — death retry and ladder fallback are the
        supervisor's job. Always completes every ticket."""
        from . import quarantine
        from .supervisor import WorkerAnalysisError

        supervisor = self.service._supervisor
        live = []
        for ticket in group:
            try:
                supervisor._check_quarantine(
                    quarantine.contract_key(ticket.params.get("code")))
            except quarantine.QuarantinedContract as error:
                ticket.error = error
                ticket.done.set()
                continue
            live.append(ticket)
        if not live:
            return
        if len(live) >= 2:
            metrics.inc("serve.fleet.windows")
            metrics.inc("serve.fleet.batched", len(live))
            slog.event("serve.fleet.batch", requests=len(live),
                       workers=True)
        try:
            outcomes = supervisor.run_fleet(
                [ticket.params for ticket in live], cid=live[0].cid)
        except BaseException as error:  # noqa: BLE001 — demuxed per ticket
            for ticket in live:
                if not ticket.done.is_set():
                    ticket.error = error
                    ticket.done.set()
            raise
        for ticket, outcome in zip(live, outcomes):
            if isinstance(outcome, dict) and outcome.get("ok"):
                ticket.payload = outcome.get("payload") or {}
            else:
                outcome = outcome if isinstance(outcome, dict) else {}
                ticket.error = WorkerAnalysisError(
                    outcome.get("error_type", "Exception"),
                    outcome.get("error", "fleet member failed in worker"))
            ticket.done.set()

    def _run_batch_inner(self, group: list) -> None:
        from ..analysis.report import Report
        from ..analysis.security import reset_callback_modules
        from ..mythril import MythrilAnalyzer, MythrilDisassembler
        from ..smt.solver.solver import reset_solver_backend

        if len(group) >= 2:
            metrics.inc("serve.fleet.windows")
            metrics.inc("serve.fleet.batched", len(group))
            slog.event("serve.fleet.batch", requests=len(group))
        # one isolation reset per BATCH (the batch is the unit of engine
        # occupancy, exactly like one solo request on the legacy path)
        reset_solver_backend(keep_verdicts=True)
        reset_callback_modules()
        params = group[0].params
        preempt = threading.Event()
        ckpt_base = os.path.join(self.service._fleet_ckpt_dir(),
                                 f"fleet-{next(self._batch_seq)}")
        cmd = _RequestArgs()
        cmd.solver = params.get("solver") or self.service.solver
        cmd.engine = params.get("engine") or self.service.engine
        cmd.max_depth = params["max_depth"]
        cmd.fleet = True
        cmd.fleet_preempt = preempt
        # batch-scoped checkpoint namespace: each member periodically
        # saves to {base}.{contract_id}, which is exactly what a
        # preempted member's solo re-run resumes from
        cmd.checkpoint = ckpt_base
        cmd.execution_timeout = execution_timeout_s(
            params.get("deadline_ms"))
        disassembler = MythrilDisassembler()
        address = None
        live: list = []
        for ticket in group:
            try:
                address, contract = disassembler.load_from_bytecode(
                    ticket.params["code"], ticket.params["bin_runtime"])
                self.service._seed_summary(contract)
                live.append((ticket, contract))
            except Exception as error:  # bad input fails ITS request only
                ticket.error = error
                ticket.done.set()
        if not live:
            return
        analyzer = MythrilAnalyzer(
            disassembler, cmd_args=cmd,
            strategy=params.get("strategy") or self.service.strategy,
            address=address)
        batch = {"preempt": preempt,
                 "tickets": [ticket for ticket, _ in live]}
        with self._lock:
            self._inflight.append(batch)
        try:
            results = analyzer.fleet_contract_results(
                modules=params.get("modules"),
                transaction_count=params["transaction_count"])
        finally:
            with self._lock:
                if batch in self._inflight:
                    self._inflight.remove(batch)
        preempted = preempt.is_set() \
            and not self.service.shutting_down.is_set()
        for (ticket, contract), entry in zip(live, results):
            if preempted and entry["timed_out"]:
                # preempted mid-flight: hand the member back to its own
                # request thread to re-run solo from its checkpoint —
                # re-enqueue, not abort
                ticket.preempted = True
                ticket.resume_path = f"{ckpt_base}.{entry['contract_id']}"
                ticket.done.set()
                continue
            report = Report(contracts=[contract],
                            exceptions=entry["exceptions"])
            report.source = [getattr(contract, "input_file", contract.name)]
            report.incomplete = entry["timed_out"]
            report.coverage = entry["coverage"]
            for issue in entry["issues"]:
                report.append_issue(issue)
            self.service._record_summary(contract)
            ticket.payload = {
                "issue_count": len(report.issues),
                "incomplete": bool(report.incomplete),
                "coverage": report.coverage or {},
                "report": json.loads(report.as_json()),
                "fleet_batched": len(results),
            }
            ticket.done.set()


class AnalysisService:
    def __init__(self, solver: str = "cdcl", engine: str = "host",
                 strategy: str = "bfs",
                 manifest_path: Optional[str] = None,
                 warmup: Optional[bool] = None,
                 max_inflight: Optional[int] = None,
                 fleet: Optional[bool] = None,
                 workers: Optional[int] = None,
                 inject_fault: Optional[str] = None):
        self.solver = solver
        self.engine = engine
        self.strategy = strategy
        if fleet is None:
            fleet = tpu_config.get_flag("MYTHRIL_TPU_FLEET_SERVE")
        self.fleet = bool(fleet)
        self._fleet_batcher = _FleetBatcher(self) if self.fleet else None
        self.warmset = WarmSet(manifest_path)
        if warmup is None:
            warmup = tpu_config.get_flag("MYTHRIL_TPU_SERVE_WARMUP")
        self.warmup_enabled = warmup
        if max_inflight is None:
            max_inflight = tpu_config.get_int("MYTHRIL_TPU_SERVE_MAX_INFLIGHT")
        self.max_inflight = max(1, max_inflight)
        if workers is None:
            workers = tpu_config.get_int("MYTHRIL_TPU_SERVE_WORKERS")
        self.workers = max(0, int(workers or 0))
        self._supervisor = None
        if self.workers > 0:
            from .supervisor import Supervisor

            self._supervisor = Supervisor(
                self.workers, manifest_path=manifest_path,
                solver=self.solver, engine=self.engine,
                strategy=self.strategy, warmup=self.warmup_enabled,
                inject_fault=inject_fault)
        self._admission = AdmissionQueue(self.max_inflight)
        # the result sidecar lives beside the warmset manifest, so the
        # store follows the manifest: no manifest, no result store (a
        # memory-only cache would silently diverge between daemons)
        self.result_store: Optional[ResultStore] = None
        if manifest_path and tpu_config.get_flag("MYTHRIL_TPU_RESULT_STORE"):
            self.result_store = ResultStore(
                path=results_path_for(manifest_path),
                quarantine=(self._supervisor.quarantine
                            if self._supervisor is not None else None))
        self._autoscaler = None
        if self._supervisor is not None:
            from .autoscale import Autoscaler

            self._autoscaler = Autoscaler(self._supervisor,
                                          self._admission)
        self._engine_lock = threading.Lock()
        self._fleet_workdir: Optional[str] = None
        self._started = time.monotonic()
        self._requests_done = 0
        self.shutting_down = threading.Event()

    # -- lifecycle ---------------------------------------------------------------------

    def startup(self) -> None:
        """Warm the solver buckets from the manifest (when enabled) and
        stamp the trace manifest. Runs before the first request."""
        # enable the span tracer now, not at first analyze: the warmup
        # span must land in the trace for traceview's serve rollup
        trace_out = tpu_config.get_str("MYTHRIL_TPU_TRACE")
        if trace_out and not trace.enabled():
            trace.enable(trace_out)
        trace.set_manifest(serve_solver=self.solver,
                           serve_engine=self.engine)
        if self._supervisor is not None:
            # worker mode: each worker pre-warms from the manifest at
            # spawn; warming the daemon process too would pay the
            # compile cliff twice for an engine that never runs here
            self._supervisor.start()
        elif self.warmup_enabled:
            self.warmset.warmup()
            self.warmset.record_observed()
        if self._autoscaler is not None:
            self._autoscaler.start()

    def shutdown(self, drain_ms: Optional[int] = None) -> None:
        """Graceful drain, then stop: admission closes (new analyzes get
        ``shutting_down``), queued *bulk* work is shed, in-flight and
        queued-interactive requests get ``MYTHRIL_TPU_SERVE_DRAIN_MS``
        to finish, and whatever is still running after the budget is
        preempted into its checkpoints instead of being cut."""
        if drain_ms is None:
            drain_ms = tpu_config.get_int("MYTHRIL_TPU_SERVE_DRAIN_MS")
        self.shutting_down.set()
        if self._autoscaler is not None:
            self._autoscaler.stop()
        self._admission.close()
        shed = self._admission.shed_class("bulk")
        slog.event("serve.drain", drain_ms=drain_ms, bulk_shed=shed)
        drained = self._admission.wait_idle(max(0, drain_ms) / 1000.0)
        if not drained:
            log.warning("drain budget (%d ms) expired with work still "
                        "in flight — preempting into checkpoints",
                        drain_ms)
            if self._fleet_batcher is not None:
                with self._fleet_batcher._lock:
                    batches = list(self._fleet_batcher._inflight)
                for batch in batches:
                    batch["preempt"].set()
        if self._supervisor is not None:
            self._supervisor.stop()
        self.warmset.record_observed()
        trace.export()
        if self._fleet_workdir is not None:
            shutil.rmtree(self._fleet_workdir, ignore_errors=True)
            self._fleet_workdir = None

    def _fleet_ckpt_dir(self) -> str:
        if self._fleet_workdir is None:
            self._fleet_workdir = tempfile.mkdtemp(
                prefix="myth-tpu-fleet-ckpt-")
        return self._fleet_workdir

    def uptime_s(self) -> float:
        return time.monotonic() - self._started

    # -- request handling --------------------------------------------------------------

    def handle(self, request) -> Dict:
        """One reply dict for one parsed request (or for the
        ProtocolError a transport's parser produced)."""
        if isinstance(request, protocol.ProtocolError):
            metrics.inc("serve.request_errors")
            return protocol.error_reply(request.request_id, request.code,
                                        request.message)
        if self.shutting_down.is_set() and request.op != "shutdown":
            return protocol.error_reply(request.id, "shutting_down",
                                        "daemon is draining")
        if request.op == "ping":
            return protocol.ok_reply(request.id, pong=True,
                                     uptime_s=round(self.uptime_s(), 3))
        if request.op == "healthz":
            return self._healthz(request)
        if request.op == "metrics":
            return self._metrics(request)
        if request.op == "status":
            return self._status(request)
        if request.op == "shutdown":
            self.shutting_down.set()
            return protocol.ok_reply(request.id, shutdown=True,
                                     requests_served=self._requests_done)
        # analyze/optimize: result-store short-circuit, then queued
        # admission, then execution. The correlation id is minted here,
        # at admission — a shed reply gets one too, so its log line and
        # reply still correlate.
        cid = slog.new_correlation_id()
        params = request.params
        priority = params.get("priority") or "interactive"
        cached = self._cached_reply(request, cid)
        if cached is not None:
            return cached
        if self._fleet_batcher is not None and priority == "interactive":
            # an interactive arrival evicts running all-bulk batches
            # BEFORE queueing, so the grant it waits on frees promptly
            self._fleet_batcher.preempt_for_interactive()
        try:
            self._admission.acquire(priority, params.get("deadline_ms"))
        except Overloaded as shed:
            with slog.correlated(cid):
                metrics.inc("serve.requests")
                metrics.inc("serve.busy_rejections")
                slog.event("serve.shed", request_id=str(request.id),
                           priority=priority, reason=shed.reason,
                           retry_after_ms=shed.retry_after_ms)
            code = ("shutting_down" if shed.reason == "shutting_down"
                    else "overloaded")
            reply = protocol.error_reply(request.id, code, str(shed))
            if code == "overloaded":
                reply["error"]["retry_after_ms"] = shed.retry_after_ms
            reply["correlation_id"] = cid
            return reply
        try:
            with slog.correlated(cid):
                slog.event("serve.admitted", request_id=str(request.id),
                           op=request.op, priority=priority)
                if request.op == "optimize":
                    # superopt rides the same admission queue and worker
                    # pool as analyze but never micro-batches: its own
                    # proof obligations already share one dispatch flush
                    if self._supervisor is not None:
                        return self._optimize(request, cid)
                    with self._engine_lock:
                        return self._optimize(request, cid)
                if self._fleet_batcher is not None and \
                        (params.get("engine") or self.engine) == "tpu":
                    # micro-batching path: the batch LEADER takes the
                    # engine lock for the whole fleet step; followers
                    # park on their ticket instead of queueing here
                    return self._analyze(request, cid, fleet=True)
                if self._supervisor is not None:
                    # worker mode: execution capacity is the pool, not
                    # the in-process engine — no engine lock, so two
                    # workers genuinely run two requests in parallel
                    return self._analyze(request, cid)
                with self._engine_lock:
                    return self._analyze(request, cid)
        finally:
            self._admission.release()

    def _cached_reply(self, request, cid: str) -> Optional[Dict]:
        """Content-addressed short-circuit: a repeat (bytecode, config)
        request is answered from the result store before admission —
        zero queueing, zero worker dispatch (the cheapest shedding)."""
        if self.result_store is None:
            return None
        params = request.params
        key = result_key(params, solver=self.solver, engine=self.engine,
                         strategy=self.strategy, op=request.op)
        payload = self.result_store.get(
            key, contract_hash=contract_key(params.get("code")))
        if payload is None:
            return None
        with slog.correlated(cid):
            metrics.inc("serve.requests")
            self._requests_done += 1
            slog.event("serve.reply", request_id=str(request.id),
                       ok=True, cached=True, op=request.op,
                       issues=payload.get("issue_count", 0))
        return protocol.ok_reply(request.id, correlation_id=cid,
                                 cached=True, elapsed_ms=0.0, **payload)

    def _healthz(self, request) -> Dict:
        """Liveness probe with a metrics summary (GET /healthz): uptime,
        request counters, warm-bucket totals, and the lifetime frontier
        telemetry rollup — a dashboard scrape's worth, without the full
        ``status`` payload (metrics snapshot, verdict cache)."""
        return protocol.ok_reply(
            request.id,
            healthy=True,
            uptime_s=round(self.uptime_s(), 3),
            requests_served=self._requests_done,
            busy_rejections=int(metrics.value("serve.busy_rejections")),
            request_errors=int(metrics.value("serve.request_errors")),
            warm={"cold_buckets": int(metrics.value("xla.bucket_compiles")),
                  "warm_hits": int(metrics.value("xla.bucket_reuses")),
                  "exec_hits": int(metrics.value("cache.exec.hits")),
                  "exec_misses": int(metrics.value("cache.exec.misses")),
                  "verdicts_loaded":
                      int(metrics.value("cache.verdict.loaded")),
                  "warmset": self.warmset.status()},
            frontier=_frontier_counters(),
            shard=_shard_rollup(),
            queue=self._admission.status(),
            autoscaler=(self._autoscaler.status()
                        if self._autoscaler is not None else None),
            result_store=(self.result_store.status()
                          if self.result_store is not None else None),
            workers=(self._supervisor.status()
                     if self._supervisor is not None else None))

    def _metrics(self, request) -> Dict:
        """Scrape (the `metrics` op / GET /metrics): the full registry
        as Prometheus text exposition plus the snapshot-ring tail.
        Handled *before* admission — a scrape during a long analyze
        (engine lock held) must answer immediately, never block."""
        metrics.inc("serve.metrics_scrapes")
        export.collect_device_memory()
        ring = export.ring()
        ring.record(scrape=str(request.id))
        return protocol.ok_reply(
            request.id,
            exposition=export.render_prometheus(),
            content_type=export.CONTENT_TYPE,
            ring={"capacity": ring.capacity, "entries": ring.tail(8)})

    def _status(self, request) -> Dict:
        from ..smt.solver import dispatch

        return protocol.ok_reply(
            request.id,
            uptime_s=round(self.uptime_s(), 3),
            requests_served=self._requests_done,
            solver=self.solver, engine=self.engine,
            fleet=self.fleet,
            max_inflight=self.max_inflight,
            queue=self._admission.status(),
            autoscaler=(self._autoscaler.status()
                        if self._autoscaler is not None else None),
            result_store=(self.result_store.status()
                          if self.result_store is not None else None),
            warmset=self.warmset.status(),
            workers=(self._supervisor.status()
                     if self._supervisor is not None else None),
            cached_verdicts=dispatch.cached_verdicts(),
            metrics=metrics.snapshot())

    def _analyze(self, request, cid: str, fleet: bool = False) -> Dict:
        params = request.params
        started = time.monotonic()
        cold_before = metrics.value("xla.bucket_compiles")
        warm_before = metrics.value("xla.bucket_reuses")
        exec_hits_before = metrics.value("cache.exec.hits")
        exec_misses_before = metrics.value("cache.exec.misses")
        frontier_before = _frontier_counters()
        with trace.span("serve.request", request_id=str(request.id),
                        correlation_id=cid) as span:
            try:
                if fleet:
                    payload = self._fleet_batcher.run(params, cid)
                else:
                    payload = self._run_analysis(params)
            except (KeyboardInterrupt, SystemExit):
                raise
            except QuarantinedContract as error:
                log.warning("refusing quarantined contract for request "
                            "%r: %s", request.id, error)
                metrics.inc("serve.requests")
                metrics.inc("serve.request_errors")
                span.set(error="quarantined")
                slog.event("serve.reply", request_id=str(request.id),
                           ok=False, error="quarantined")
                reply = protocol.error_reply(request.id, "quarantined",
                                             str(error))
                reply["correlation_id"] = cid
                return reply
            except Exception as error:
                log.exception("analysis failed for request %r", request.id)
                metrics.inc("serve.requests")
                metrics.inc("serve.request_errors")
                span.set(error=repr(error))
                slog.event("serve.reply", request_id=str(request.id),
                           ok=False, error=repr(error))
                reply = protocol.error_reply(
                    request.id, "analysis_failed",
                    f"{type(error).__name__}: {error}")
                reply["correlation_id"] = cid
                return reply
            cold = metrics.value("xla.bucket_compiles") - cold_before
            warm = metrics.value("xla.bucket_reuses") - warm_before
            exec_hits = metrics.value("cache.exec.hits") - exec_hits_before
            exec_misses = \
                metrics.value("cache.exec.misses") - exec_misses_before
            frontier = {name: value - frontier_before[name]
                        for name, value in _frontier_counters().items()}
            span.set(cold_buckets=cold, warm_hits=warm,
                     exec_hits=exec_hits, exec_misses=exec_misses,
                     issues=payload["issue_count"],
                     frontier_executed=frontier["executed"],
                     frontier_forks=frontier["forks"])
        elapsed_ms = (time.monotonic() - started) * 1000.0
        metrics.inc("serve.requests")
        metrics.observe("serve.request_ms", elapsed_ms)
        self._requests_done += 1
        if self.result_store is not None:
            # put() itself refuses incomplete payloads and quarantined
            # hashes — a deadline-drained partial must never be replayed
            self.result_store.put(
                result_key(params, solver=self.solver,
                           engine=self.engine, strategy=self.strategy),
                payload, contract_hash=contract_key(params.get("code")))
        self.warmset.record_observed()
        # one snapshot-ring tick per finished request: the "periodic"
        # cadence of a daemon is its request stream
        export.record_snapshot(request_id=str(request.id),
                               correlation_id=cid)
        slog.event("serve.reply", request_id=str(request.id), ok=True,
                   issues=payload["issue_count"],
                   elapsed_ms=round(elapsed_ms, 3),
                   cold_buckets=cold, warm_hits=warm,
                   exec_hits=exec_hits, exec_misses=exec_misses)
        return protocol.ok_reply(
            request.id,
            correlation_id=cid,
            elapsed_ms=round(elapsed_ms, 3),
            warm={"cold_buckets": cold, "warm_hits": warm,
                  "exec_hits": exec_hits, "exec_misses": exec_misses},
            frontier=frontier,
            **payload)

    def _run_analysis(self, params: Dict) -> Dict:
        """Route one request to the engine: in worker mode the supervisor
        dispatches it to a pooled sandbox process (with death detection,
        retry, and quarantine); otherwise it runs in-process."""
        if self._supervisor is not None:
            return self._supervisor.run_job(params,
                                            cid=slog.correlation_id())
        return self._run_analysis_local(params)

    def _run_analysis_local(self, params: Dict,
                            checkpoint_path: Optional[str] = None,
                            resume_path: Optional[str] = None) -> Dict:
        """The per-request engine run: isolate, load, fire lasers.
        `checkpoint_path`/`resume_path` are worker-mode extras: the
        request-scoped checkpoint the supervisor assigns so a killed
        worker's one retry can resume mid-analysis."""
        from ..analysis.security import reset_callback_modules
        from ..mythril import MythrilAnalyzer, MythrilDisassembler
        from ..smt.solver.solver import reset_solver_backend

        # fresh pipeline/breaker/clock per request; verdict cache and the
        # compiled executables survive (DispatchQueue.reset keep_verdicts)
        reset_solver_backend(keep_verdicts=True)
        reset_callback_modules()

        cmd = _RequestArgs()
        cmd.solver = params.get("solver") or self.solver
        cmd.engine = params.get("engine") or self.engine
        cmd.max_depth = params["max_depth"]
        cmd.execution_timeout = execution_timeout_s(params.get("deadline_ms"))
        if checkpoint_path:
            cmd.checkpoint = checkpoint_path
        if resume_path:
            cmd.resume = resume_path
        disassembler = MythrilDisassembler()
        address, contract = disassembler.load_from_bytecode(
            params["code"], params["bin_runtime"])
        self._seed_summary(contract)
        analyzer = MythrilAnalyzer(
            disassembler, cmd_args=cmd,
            strategy=params.get("strategy") or self.strategy,
            address=address)
        report = analyzer.fire_lasers(
            modules=params.get("modules"),
            transaction_count=params["transaction_count"])
        self._record_summary(contract)
        return {
            "issue_count": len(report.issues),
            "incomplete": bool(getattr(report, "incomplete", False)),
            "coverage": getattr(report, "coverage", {}) or {},
            "report": json.loads(report.as_json()),
        }

    def _optimize(self, request, cid: str) -> Dict:
        """The `optimize` op: gas superoptimization of one runtime
        bytecode, same accounting shell as `_analyze` (trace span,
        request metrics, result-store put under the op-discriminated
        key) around `superopt.optimize_bytecode`."""
        params = request.params
        started = time.monotonic()
        with trace.span("serve.request", request_id=str(request.id),
                        correlation_id=cid, op="optimize") as span:
            try:
                payload = self._run_optimize(params)
            except (KeyboardInterrupt, SystemExit):
                raise
            except QuarantinedContract as error:
                log.warning("refusing quarantined contract for request "
                            "%r: %s", request.id, error)
                metrics.inc("serve.requests")
                metrics.inc("serve.request_errors")
                span.set(error="quarantined")
                slog.event("serve.reply", request_id=str(request.id),
                           ok=False, error="quarantined")
                reply = protocol.error_reply(request.id, "quarantined",
                                             str(error))
                reply["correlation_id"] = cid
                return reply
            except Exception as error:
                log.exception("optimization failed for request %r",
                              request.id)
                metrics.inc("serve.requests")
                metrics.inc("serve.request_errors")
                span.set(error=repr(error))
                slog.event("serve.reply", request_id=str(request.id),
                           ok=False, error=repr(error))
                reply = protocol.error_reply(
                    request.id, "analysis_failed",
                    f"{type(error).__name__}: {error}")
                reply["correlation_id"] = cid
                return reply
            span.set(rewrites=len(payload.get("rewrites") or ()),
                     gas_saved=payload.get("gas_saved", 0))
        elapsed_ms = (time.monotonic() - started) * 1000.0
        metrics.inc("serve.requests")
        metrics.observe("serve.request_ms", elapsed_ms)
        self._requests_done += 1
        if self.result_store is not None:
            # keyed with op="optimize": an analyze verdict for the same
            # bytecode must never answer an optimize request (and vice
            # versa) — see result_store.result_key
            self.result_store.put(
                result_key(params, solver=self.solver,
                           engine=self.engine, strategy=self.strategy,
                           op="optimize"),
                payload, contract_hash=contract_key(params.get("code")))
        export.record_snapshot(request_id=str(request.id),
                               correlation_id=cid)
        slog.event("serve.reply", request_id=str(request.id), ok=True,
                   op="optimize",
                   rewrites=len(payload.get("rewrites") or ()),
                   gas_saved=payload.get("gas_saved", 0),
                   elapsed_ms=round(elapsed_ms, 3))
        return protocol.ok_reply(
            request.id,
            correlation_id=cid,
            elapsed_ms=round(elapsed_ms, 3),
            **payload)

    def _run_optimize(self, params: Dict) -> Dict:
        """Route one optimize request: worker mode dispatches to a
        pooled sandbox (death detection, retry, quarantine — same as
        analyze), otherwise it runs in-process."""
        if self._supervisor is not None:
            return self._supervisor.run_job(params,
                                            cid=slog.correlation_id(),
                                            kind="optimize")
        return self._run_optimize_local(params)

    def _run_optimize_local(self, params: Dict) -> Dict:
        """One in-process superopt run: same per-request isolation reset
        as analyze (fresh pipeline/breaker state, warm verdict cache and
        executables), then the engine walk + batched proofs."""
        from ..smt.solver.solver import reset_solver_backend
        from ..superopt import optimize_bytecode

        reset_solver_backend(keep_verdicts=True)
        report = optimize_bytecode(
            params["code"], solver=params.get("solver") or self.solver)
        return report.to_json()

    def _seed_summary(self, contract) -> None:
        """Pre-seed a persisted taint summary onto the contract's
        disassembly so a repeat corpus contract skips the fixpoint.
        Runtime code only — creation requests execute constructor code
        the summary never modeled."""
        if not getattr(contract, "code", None):
            return
        from ..staticanalysis import ContractSummary, install_summary

        cached = self.warmset.summary_for(contract.bytecode_hash)
        if cached is None:
            return
        summary = ContractSummary.from_json(cached)
        if summary is not None and summary.code_length * 2 == len(
                contract.code.removeprefix("0x")):
            install_summary(contract.disassembly, summary)
            metrics.inc("serve.summary_seeded")

    def _record_summary(self, contract) -> None:
        """Queue this contract's summary (fresh or seeded) for the
        warmset's summary store; flushed with the shape manifest."""
        if not getattr(contract, "code", None):
            return
        from ..staticanalysis import get_summary

        summary = get_summary(contract.disassembly)
        if summary is not None:
            self.warmset.record_summary(contract.bytecode_hash,
                                        summary.to_json())
