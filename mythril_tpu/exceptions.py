"""Framework-wide exception hierarchy (capability parity: mythril/exceptions.py)."""


class MythrilTpuBaseException(Exception):
    """Base for all framework exceptions."""


class CompilerError(MythrilTpuBaseException):
    """Solidity compiler (solc) invocation failed or solc unavailable."""


class UnsatError(MythrilTpuBaseException):
    """Constraint system proven unsatisfiable (or no model found in budget)."""


class SolverTimeOutException(UnsatError):
    """Solver exceeded its per-query time budget."""


class NoContractFoundError(MythrilTpuBaseException):
    """Input did not contain a contract."""


class CriticalError(MythrilTpuBaseException):
    """Unrecoverable user-facing error (bad arguments, missing inputs)."""


class AddressNotFoundError(MythrilTpuBaseException):
    """On-chain address lookup failed."""


class DetectorNotFoundError(MythrilTpuBaseException):
    """Unknown detection-module name."""


class IllegalArgumentError(ValueError, MythrilTpuBaseException):
    """Bad argument to a framework API."""
