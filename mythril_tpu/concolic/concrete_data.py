"""ConcreteData: the JSON schema shared by witness reports and concolic input
(capability parity: mythril/concolic/concrete_data.py — the TypedDict schema of
initialState + steps; analysis/solver.get_transaction_sequence emits it, the
concolic CLI consumes it)."""

from __future__ import annotations

from typing import Dict, List, TypedDict


class AccountData(TypedDict):
    nonce: int
    code: str
    storage: Dict[str, str]
    balance: str


class InitialState(TypedDict):
    accounts: Dict[str, AccountData]


class TransactionData(TypedDict, total=False):
    address: str
    input: str
    origin: str
    value: str
    gasLimit: str
    gasPrice: str
    name: str
    calldata: str


class ConcreteData(TypedDict):
    initialState: InitialState
    steps: List[TransactionData]


def validate_concrete_data(data: dict) -> None:
    if "initialState" not in data or "steps" not in data:
        raise ValueError("ConcreteData needs initialState and steps")
    if "accounts" not in data["initialState"]:
        raise ValueError("initialState needs accounts")
    for step in data["steps"]:
        if "input" not in step:
            raise ValueError("every step needs an input field")
