"""Concolic driver: record a concrete trace, then flip chosen branches
(capability parity: mythril/concolic/concolic_execution.py —
concolic_execution:67, flip_branches:22).

Flow (SURVEY §3.5): concrete_execution replays the ConcreteData steps and
records (pc, tx) per executed instruction; flip_branches re-runs the same
transaction sequence with SYMBOLIC calldata under ConcolicStrategy, which
follows the recorded trace and, at each requested JUMPI address, solves the
deviating branch's path constraints into a fresh ConcreteData input set."""

from __future__ import annotations

import binascii
import logging
from copy import deepcopy
from typing import Dict, List

from ..core.strategy.concolic import ConcolicStrategy
from ..core.svm import LaserEVM
from ..core.transaction.symbolic import execute_message_call
from ..smt import symbol_factory
from .concrete_data import ConcreteData
from .find_trace import concrete_execution, setup_concrete_initial_state

log = logging.getLogger(__name__)


def flip_branches(init_state, concrete_data: ConcreteData,
                  jump_addresses: List[str], trace) -> List[Dict]:
    """Symbolic re-execution along `trace`, flipping `jump_addresses`
    (reference concolic_execution.py:22)."""
    output_list: List[Dict] = []
    laser_evm = LaserEVM(execution_timeout=600, use_reachability_check=False,
                         transaction_count=len(concrete_data["steps"]),
                         requires_statespace=False,
                         strategy=ConcolicStrategy)
    laser_evm.open_states = [deepcopy(init_state)]
    laser_evm.strategy = ConcolicStrategy(
        laser_evm.work_list, laser_evm.max_depth,
        trace=[entry for tx_trace in trace for entry in tx_trace],
        flip_branch_addresses=jump_addresses)

    from ..core.time_handler import time_handler
    from datetime import datetime

    time_handler.start_execution(laser_evm.execution_timeout)
    laser_evm.time = datetime.now()
    for transaction in concrete_data["steps"]:
        address = transaction.get("address", "")
        if not address:
            continue  # creation steps replayed concretely in init_state
        execute_message_call(
            laser_evm, symbol_factory.BitVecVal(int(address, 16), 256))

    for branch_address, sequence in laser_evm.strategy.results.items():
        flipped = deepcopy(concrete_data)
        steps = sequence.get("steps", [])
        for i, step in enumerate(flipped["steps"]):
            if i < len(steps):
                step["input"] = steps[i]["input"]
                step["calldata"] = steps[i]["input"]
        output_list.append({"branch": branch_address, "input": flipped})
    return output_list


def concolic_execution(concrete_data: ConcreteData, jump_addresses: List,
                       engine: str = "oracle") -> List[Dict]:
    """Record the trace of `concrete_data`, then flip `jump_addresses`
    (reference concolic_execution.py:67)."""
    jump_addresses = [hex(a) if isinstance(a, int) else a
                      for a in jump_addresses]
    init_state, trace = concrete_execution(concrete_data)
    if engine == "lockstep":
        # trace recording already validated against the lockstep engine by
        # tests/test_parallel_lockstep.py; the flip run itself is symbolic and
        # stays on the oracle either way
        log.info("concrete replay verified against the lockstep engine")
    output_list = flip_branches(init_state=init_state,
                                concrete_data=concrete_data,
                                jump_addresses=jump_addresses, trace=trace)
    return output_list
