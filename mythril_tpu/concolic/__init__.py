"""Concolic mode: concrete trace recording + branch flipping
(capability parity: mythril/concolic/ — concolic_execution.py:67,
find_trace.py:45, concrete_data.py)."""

from .concolic_execution import concolic_execution
from .find_trace import concrete_execution

__all__ = ["concolic_execution", "concrete_execution"]
