"""Concrete replay + trace recording (capability parity:
mythril/concolic/find_trace.py:45 — setup_concrete_initial_state:24,
concrete_execution with the TraceFinder plugin).

`engine="lockstep"` replays single-call steps through the TPU batched
interpreter (parallel/lockstep.py) instead of the host oracle — same
ConcreteData in, same trace format out — and falls back to the oracle for
steps the lockstep engine escapes on."""

from __future__ import annotations

from copy import deepcopy
from typing import List, Tuple

from ..core.plugin.loader import LaserPluginLoader
from ..core.plugin.plugins.trace import TraceFinderBuilder
from ..core.state.world_state import WorldState
from ..core.svm import LaserEVM
from ..core.transaction.concolic import (execute_contract_creation,
                                         execute_message_call)
from ..frontends.disassembler import Disassembly
from ..smt import symbol_factory
from .concrete_data import ConcreteData, validate_concrete_data


def setup_concrete_initial_state(concrete_data: ConcreteData) -> WorldState:
    """initialState.accounts -> WorldState (reference find_trace.py:24)."""
    world_state = WorldState()
    for address_hex, details in concrete_data["initialState"]["accounts"].items():
        account = world_state.create_account(
            balance=int(details.get("balance", "0x0"), 16),
            address=int(address_hex, 16),
            concrete_storage=True,
            nonce=details.get("nonce", 0))
        code = details.get("code", "")
        account.code = Disassembly(code[2:] if code.startswith("0x") else code)
        for slot_hex, value_hex in details.get("storage", {}).items():
            account.storage[symbol_factory.BitVecVal(int(slot_hex, 16), 256)] = \
                symbol_factory.BitVecVal(int(value_hex, 16), 256)
    return world_state


def concrete_execution(concrete_data: ConcreteData
                       ) -> Tuple[WorldState, List[List[Tuple[int, str]]]]:
    """Replay all steps concretely; returns (initial world state, trace).
    trace is a list per transaction of (pc_address, tx_id) pairs."""
    validate_concrete_data(concrete_data)
    init_state = setup_concrete_initial_state(concrete_data)
    laser_evm = LaserEVM(execution_timeout=1000, requires_statespace=False)
    laser_evm.open_states = [deepcopy(init_state)]

    plugin_loader = LaserPluginLoader()
    plugin_loader.reset()
    trace_plugin_builder = TraceFinderBuilder()
    plugin = trace_plugin_builder()
    plugin.initialize(laser_evm)

    for transaction in concrete_data["steps"]:
        input_hex = transaction["input"]
        data = bytes.fromhex(input_hex[2:] if input_hex.startswith("0x")
                             else input_hex)
        target = transaction.get("address", "")
        caller = int(transaction.get("origin", "0x" + "a" * 40), 16)
        value = int(transaction.get("value", "0x0"), 16)
        gas_limit = int(transaction.get("gasLimit", hex(8_000_000)), 16)
        gas_price = int(transaction.get("gasPrice", "0x0"), 16)
        if target in ("", None):
            execute_contract_creation(
                laser_evm, callee_address="",
                caller_address=caller, origin_address=caller,
                code=input_hex[2:] if input_hex.startswith("0x") else input_hex,
                data=list(data), gas_limit=gas_limit, gas_price=gas_price,
                value=value)
        else:
            execute_message_call(
                laser_evm, callee_address=int(target, 16),
                caller_address=caller, origin_address=caller,
                data=list(data), gas_limit=gas_limit, gas_price=gas_price,
                value=value)
    return init_state, plugin.tx_trace
