"""mythril_tpu — a TPU-native symbolic-execution framework for EVM bytecode.

Capability surface modeled on Mythril (reference: /root/reference, see SURVEY.md):
symbolic execution + SMT solving + taint-style annotation tracking detecting
SWC-classified vulnerabilities, exposed through a `myth`-compatible CLI.

Architecture (TPU-first, not a port):
  - ``mythril_tpu.smt``      — own term IR + bit-vector solver stack (no z3 in this
                               environment; a from-scratch bit-blasting CDCL solver with a
                               C++ core is the decision procedure; a batched JAX
                               unit-propagation solver discharges frontier feasibility
                               checks on TPU).
  - ``mythril_tpu.core``     — the LASER-equivalent symbolic EVM (object interpreter:
                               the semantic oracle) plus engine services.
  - ``mythril_tpu.parallel`` — the TPU execution backend: SoA StateBatch, lockstep
                               jitted opcode stepping, mask-forking, sharded frontier
                               over a jax.sharding.Mesh.
  - ``mythril_tpu.analysis`` — detection modules, witness extraction, reports.
"""

__version__ = "0.1.0"
