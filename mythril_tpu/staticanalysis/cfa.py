"""Static control-flow analysis of EVM bytecode (the cfa pass).

One pass over ``frontends/disassembler.py`` output that recovers basic
blocks, resolves jump targets with an abstract stack/constant dataflow
(push-constant tracking through DUP/SWAP/arithmetic/AND-mask idioms),
builds the CFG, computes reachability and dominator/post-dominator trees
(iterative CHK, see :mod:`.domtree`), and emits dense device-consumable
tables:

* ``pc_to_block`` — byte address -> block id (immediates inherit their
  PUSH's block);
* ``block_merge_pc`` — block id -> pc of the nearest post-dominating
  block (-1 when none): the veritesting merge point for branch blocks
  (ROADMAP item 3) and the reconvergence pc every lane in the block is
  heading to;
* ``valid_target_bitmap`` / ``valid_targets`` — the JUMPDEST bitmap
  refined to *reachable* JUMPDESTs;
* ``dead_mask`` — bytes proven statically unreachable.

Soundness direction: the CFG **over-approximates** real control flow —
an unresolved jump conservatively fans out to every JUMPDEST (plus the
virtual exit, so post-dominator claims shrink rather than grow). Hence
"statically dead" implies genuinely unreachable, and a jump site
"resolved to T" means every execution of that site jumps to T: both are
safe to act on without a solver. Jump targets pushed inside their own
block (the solc idiom) stay resolved even when unknown-stack states fan
in, so resolution survives the conservative edges.

This module is stdlib-only (plus the in-package opcode table and the
stdlib-only ``support/tpu_config`` / ``observe`` registries): tools such
as ``tools/cfaview.py`` and the lint framework can load it without jax.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..ops.opcodes import OPCODES, STACK
from . import domtree

log = logging.getLogger(__name__)

_WORD_MASK = (1 << 256) - 1

#: opcodes that end a block with no fall-through
TERMINATORS = {"STOP", "RETURN", "REVERT", "INVALID", "SELFDESTRUCT"}

#: abstract-stack slots tracked per block entry (deeper slots are UNKNOWN);
#: must cover DUP16/SWAP16 reach — see MYTHRIL_TPU_CFA_STACK_DEPTH
_DEFAULT_TRACKED_DEPTH = 32

#: block-count bail-out guard — see MYTHRIL_TPU_CFA_MAX_BLOCKS
_DEFAULT_MAX_BLOCKS = 16384


class _Underflow(Exception):
    """Abstract execution popped below a KNOWN-height stack: the real
    machine would throw, so the block exits exceptionally."""


@dataclass
class BasicBlock:
    """One basic block: a maximal straight-line instruction run."""

    block_id: int
    start_pc: int            #: byte address of the first instruction
    end_pc: int              #: byte address AFTER the last instruction's bytes
    first_index: int         #: index into Disassembly.instruction_list
    last_index: int          #: inclusive
    terminator: str          #: op_code of the last instruction ("" = fallthrough)
    successors: Set[int] = field(default_factory=set)  #: block ids (+ exit id)
    entry_height: Optional[int] = None  #: abstract stack height on entry


@dataclass
class CfaResult:
    """The CFA verdict for one Disassembly: CFG + dense tables."""

    blocks: List[BasicBlock]
    exit_id: int                       #: virtual exit node (== len(blocks))
    code_length: int
    pc_to_block: List[int]             #: per byte, -1 when code is empty
    block_merge_pc: List[int]          #: per block, -1 when no postdom merge
    branch_merge_pc: Dict[int, int]    #: branch-site pc -> merge pc
    valid_targets: Set[int]            #: reachable JUMPDEST pcs
    valid_target_bitmap: bytearray     #: per byte, 1 = reachable JUMPDEST
    dead_mask: bytearray               #: per byte, 1 = statically unreachable
    jump_targets: Dict[int, Tuple[int, ...]]  #: resolved site pc -> targets
    unresolved_jumps: Tuple[int, ...]  #: site pcs the dataflow could not pin
    reachable: Set[int]                #: reachable block ids
    idom: List[Optional[int]]          #: dominator tree (entry block 0)
    ipostdom: List[Optional[int]]      #: post-dominator tree (virtual exit)
    n_edges: int

    # -- queries (the consumer surface) ------------------------------------------
    def block_at(self, pc: int) -> Optional[int]:
        if 0 <= pc < len(self.pc_to_block):
            block = self.pc_to_block[pc]
            return block if block >= 0 else None
        return None

    def is_valid_target(self, pc: int) -> bool:
        return 0 <= pc < len(self.valid_target_bitmap) \
            and bool(self.valid_target_bitmap[pc])

    def is_dead(self, pc: int) -> bool:
        return 0 <= pc < len(self.dead_mask) and bool(self.dead_mask[pc])

    def merge_pc_at(self, pc: int) -> Optional[int]:
        """The reconvergence pc the block containing `pc` flows into, or
        None when the block has no real post-dominator."""
        block = self.block_at(pc)
        if block is None:
            return None
        merge = self.block_merge_pc[block]
        return merge if merge >= 0 else None

    def resolved_targets(self, pc: int) -> Optional[Tuple[int, ...]]:
        """Resolved target pcs of the jump site at `pc`; () when the site
        provably throws (constant non-JUMPDEST target); None when the
        site is unresolved or not a reachable jump site."""
        return self.jump_targets.get(pc)

    @property
    def n_jump_sites(self) -> int:
        return len(self.jump_targets) + len(self.unresolved_jumps)

    @property
    def fully_resolved(self) -> bool:
        return not self.unresolved_jumps

    @property
    def merge_points(self) -> Set[int]:
        return set(self.branch_merge_pc.values())

    @property
    def dead_bytes(self) -> int:
        return sum(self.dead_mask)


# -- abstract stack ------------------------------------------------------------------
# A value is an int (known constant) or None (unknown). A state is
# (height, vals): total stack height (None = conflicting/unknown) plus the
# top `tracked_depth` values, top of stack LAST. Slots below the tracked
# window are implicitly unknown.

_AbsState = Tuple[Optional[int], Tuple[Optional[int], ...]]


def _merge_states(a: _AbsState, b: _AbsState) -> _AbsState:
    height = a[0] if a[0] == b[0] else None
    vals_a, vals_b = a[1], b[1]
    keep = min(len(vals_a), len(vals_b))
    merged = tuple(
        x if x == y else None
        for x, y in zip(vals_a[len(vals_a) - keep:],
                        vals_b[len(vals_b) - keep:]))
    return (height, merged)


def _fold_binary(op: str, a: Optional[int],
                 b: Optional[int]) -> Optional[int]:
    """Constant-fold op(µ0=a, µ1=b); None when either operand is unknown.
    Only the pure word ops the solc jump idioms flow targets through."""
    if a is None or b is None:
        return None
    if op == "ADD":
        return (a + b) & _WORD_MASK
    if op == "SUB":
        return (a - b) & _WORD_MASK
    if op == "MUL":
        return (a * b) & _WORD_MASK
    if op == "DIV":
        return 0 if b == 0 else a // b
    if op == "AND":
        return a & b
    if op == "OR":
        return a | b
    if op == "XOR":
        return a ^ b
    if op == "SHL":
        return (b << a) & _WORD_MASK if a < 256 else 0
    if op == "SHR":
        return b >> a if a < 256 else 0
    if op == "EQ":
        return int(a == b)
    if op == "LT":
        return int(a < b)
    if op == "GT":
        return int(a > b)
    return None


_UNARY_FOLDS = {"ISZERO", "NOT"}
_BINARY_FOLDS = {"ADD", "SUB", "MUL", "DIV", "AND", "OR", "XOR",
                 "SHL", "SHR", "EQ", "LT", "GT"}


def push_immediate(ins) -> Optional[int]:
    """The concrete immediate of a PUSH instruction (PUSH0 and an empty
    argument decode to 0), or None when the hex argument is unparsable.
    The one shared decode site (R9): every consumer outside this package
    — the superoptimizer's block layout, future peepholes — reads PUSH
    immediates through here instead of re-implementing the fold."""
    if ins.op_code == "PUSH0" or not ins.argument:
        return 0
    try:
        return int(ins.argument, 16)
    except ValueError:
        return None


class _Stack:
    """Mutable abstract stack for simulating one block."""

    __slots__ = ("vals", "below", "tracked")

    def __init__(self, state: _AbsState, tracked: int):
        height, vals = state
        self.vals: List[Optional[int]] = list(vals)
        #: unknown slots beneath the tracked window; None = unbounded
        self.below: Optional[int] = None if height is None \
            else height - len(vals)
        self.tracked = tracked

    def pop(self) -> Optional[int]:
        if self.vals:
            return self.vals.pop()
        if self.below is None:
            return None
        if self.below <= 0:
            raise _Underflow
        self.below -= 1
        return None

    def push(self, value: Optional[int]) -> None:
        self.vals.append(value)
        if len(self.vals) > self.tracked:
            del self.vals[0]
            if self.below is not None:
                self.below += 1

    def peek(self, depth: int) -> Optional[int]:
        """Value `depth` slots below the top (0 = top), None when outside
        the tracked window."""
        if depth < len(self.vals):
            return self.vals[-1 - depth]
        if self.below is not None and self.below < depth - len(self.vals) + 1:
            raise _Underflow
        return None

    def swap(self, depth: int) -> None:
        """SWAPn: exchange top with the slot `depth` below it."""
        while len(self.vals) <= depth:
            if self.below is not None:
                if self.below <= 0:
                    raise _Underflow
                self.below -= 1
            self.vals.insert(0, None)
        self.vals[-1], self.vals[-1 - depth] = \
            self.vals[-1 - depth], self.vals[-1]

    def state(self) -> _AbsState:
        height = None if self.below is None else self.below + len(self.vals)
        return (height, tuple(self.vals))


def _simulate(block: BasicBlock, instructions, entry: _AbsState,
              tracked: int):
    """Abstractly execute a block body (everything up to, but excluding,
    the control effect of its terminator).

    Returns (exit_state, jump_dest) where jump_dest is the abstract value
    on top of the stack *consumed by* a JUMP/JUMPI terminator (already
    popped, condition included), or None for other terminators. Raises
    _Underflow when the block provably underflows a known-height stack."""
    stack = _Stack(entry, tracked)
    jump_dest: Optional[int] = None
    for index in range(block.first_index, block.last_index + 1):
        ins = instructions[index]
        op = ins.op_code
        if op.startswith("PUSH"):
            stack.push(push_immediate(ins))
        elif op.startswith("DUP"):
            stack.push(stack.peek(int(op[3:]) - 1))
        elif op.startswith("SWAP"):
            stack.swap(int(op[4:]))
        elif op == "POP":
            stack.pop()
        elif op == "PC":
            stack.push(ins.address)
        elif op == "JUMPDEST":
            pass
        elif op == "JUMP":
            jump_dest = stack.pop()
        elif op == "JUMPI":
            jump_dest = stack.pop()
            stack.pop()  # condition
        elif op in _UNARY_FOLDS:
            value = stack.pop()
            if value is None:
                stack.push(None)
            elif op == "ISZERO":
                stack.push(int(value == 0))
            else:  # NOT
                stack.push(~value & _WORD_MASK)
        elif op in _BINARY_FOLDS:
            a, b = stack.pop(), stack.pop()
            stack.push(_fold_binary(op, a, b))
        elif op in OPCODES:
            pops, pushes = OPCODES[op][STACK]
            for _ in range(pops):
                stack.pop()
            for _ in range(pushes):
                stack.push(None)
        else:
            # unassigned opcode: the machine throws; treated as a
            # terminator at block-construction time, nothing to simulate
            break
    return stack.state(), jump_dest


# -- CFG construction ----------------------------------------------------------------

def _recover_blocks(instructions, code_length: int) -> List[BasicBlock]:
    """Split the linear-sweep decode into basic blocks: leaders are pc 0,
    every JUMPDEST, and every instruction following a JUMP/JUMPI or a
    terminator (including unassigned opcodes, which throw)."""
    if not instructions:
        return []
    leaders = {0}
    for index, ins in enumerate(instructions):
        if ins.op_code == "JUMPDEST":
            leaders.add(index)
        if (ins.op_code in ("JUMP", "JUMPI") or ins.op_code in TERMINATORS
                or ins.op_code not in OPCODES) \
                and index + 1 < len(instructions):
            leaders.add(index + 1)
    ordered = sorted(leaders)
    blocks: List[BasicBlock] = []
    for block_id, first in enumerate(ordered):
        last = (ordered[block_id + 1] - 1 if block_id + 1 < len(ordered)
                else len(instructions) - 1)
        end_pc = (instructions[last + 1].address
                  if last + 1 < len(instructions) else code_length)
        last_op = instructions[last].op_code
        terminator = last_op if (last_op in ("JUMP", "JUMPI")
                                 or last_op in TERMINATORS
                                 or last_op not in OPCODES) else ""
        blocks.append(BasicBlock(
            block_id=block_id, start_pc=instructions[first].address,
            end_pc=end_pc, first_index=first, last_index=last,
            terminator=terminator))
    return blocks


def build_cfa(disassembly, tracked_depth: Optional[int] = None,
              max_blocks: Optional[int] = None) -> Optional[CfaResult]:
    """Run the full pass over a ``frontends.disassembler.Disassembly``.

    Returns None when the contract exceeds the block budget (the screen
    and all consumers treat None as "no verdict" and keep their dynamic
    paths)."""
    from ..support import tpu_config

    if tracked_depth is None:
        tracked_depth = tpu_config.get_int("MYTHRIL_TPU_CFA_STACK_DEPTH")
    if max_blocks is None:
        max_blocks = tpu_config.get_int("MYTHRIL_TPU_CFA_MAX_BLOCKS")

    instructions = disassembly.instruction_list
    code_length = len(getattr(disassembly, "raw_code", b"")) or (
        instructions[-1].address + 1 if instructions else 0)
    blocks = _recover_blocks(instructions, code_length)
    if not blocks:
        return None
    if len(blocks) > max_blocks:
        log.info("cfa: %d blocks exceeds MYTHRIL_TPU_CFA_MAX_BLOCKS=%d — "
                 "skipping static analysis", len(blocks), max_blocks)
        return None

    exit_id = len(blocks)
    block_of_pc = {block.start_pc: block.block_id for block in blocks}
    jumpdest_blocks = [block.block_id for block in blocks
                       if instructions[block.first_index].op_code
                       == "JUMPDEST"]

    # -- worklist dataflow: entry states + dynamically discovered edges ----------
    entry_states: Dict[int, _AbsState] = {0: (0, ())}
    succs: List[Set[int]] = [set() for _ in blocks]
    fanned_out: Set[int] = set()       # jump-site block ids already fanned out
    # site pc -> every abstract dest observed across (re-)simulations; a
    # site re-simulated under merged entry states can yield different
    # constants, and ALL of them are feasible targets
    jump_value: Dict[int, Set[Optional[int]]] = {}
    worklist = [0]

    def propagate(target: int, state: _AbsState) -> None:
        old = entry_states.get(target)
        new = state if old is None else _merge_states(old, state)
        if new != old:
            entry_states[target] = new
            if target not in worklist:
                worklist.append(target)

    def fan_out(block: BasicBlock) -> None:
        """Unresolved jump: conservative edges to every JUMPDEST block,
        plus the virtual exit so post-dominator claims stay sound."""
        if block.block_id in fanned_out:
            return
        fanned_out.add(block.block_id)
        succs[block.block_id].add(exit_id)
        unknown: _AbsState = (None, ())
        for target in jumpdest_blocks:
            succs[block.block_id].add(target)
            propagate(target, unknown)

    iterations = 0
    iteration_cap = max(64, 8 * len(blocks) * (tracked_depth + 2))
    while worklist:
        iterations += 1
        if iterations > iteration_cap:  # defensive: lattice guarantees
            log.warning("cfa: dataflow did not converge in %d iterations — "
                        "skipping static analysis", iteration_cap)
            return None
        block = blocks[worklist.pop()]
        entry = entry_states[block.block_id]
        try:
            exit_state, jump_dest = _simulate(
                block, instructions, entry, tracked_depth)
        except _Underflow:
            succs[block.block_id].add(exit_id)  # provable throw
            continue
        term = block.terminator
        next_id = block.block_id + 1 if block.block_id + 1 < len(blocks) \
            else exit_id

        if term == "":
            succs[block.block_id].add(next_id)
            if next_id != exit_id:
                propagate(next_id, exit_state)
        elif term == "JUMPI":
            succs[block.block_id].add(next_id)
            if next_id != exit_id:
                propagate(next_id, exit_state)
            site = instructions[block.last_index].address
            jump_value.setdefault(site, set()).add(jump_dest)
            if jump_dest is None:
                fan_out(block)
            elif jump_dest in block_of_pc and \
                    instructions[blocks[block_of_pc[jump_dest]]
                                 .first_index].op_code == "JUMPDEST":
                target = block_of_pc[jump_dest]
                succs[block.block_id].add(target)
                propagate(target, exit_state)
            else:
                succs[block.block_id].add(exit_id)  # constant invalid target
        elif term == "JUMP":
            site = instructions[block.last_index].address
            jump_value.setdefault(site, set()).add(jump_dest)
            if jump_dest is None:
                fan_out(block)
            elif jump_dest in block_of_pc and \
                    instructions[blocks[block_of_pc[jump_dest]]
                                 .first_index].op_code == "JUMPDEST":
                target = block_of_pc[jump_dest]
                succs[block.block_id].add(target)
                propagate(target, exit_state)
            else:
                succs[block.block_id].add(exit_id)
        else:  # STOP/RETURN/REVERT/SELFDESTRUCT/INVALID/unassigned
            succs[block.block_id].add(exit_id)

    # -- final tables over the fixpoint -------------------------------------------
    reachable = set(entry_states)
    for block in blocks:
        block.entry_height = entry_states.get(block.block_id, (None, ()))[0] \
            if block.block_id in reachable else None
        block.successors = succs[block.block_id] if block.block_id \
            in reachable else set()

    # classify reachable jump sites from their fixpoint dest values
    jump_targets: Dict[int, Tuple[int, ...]] = {}
    unresolved: List[int] = []
    for block in blocks:
        if block.block_id not in reachable \
                or block.terminator not in ("JUMP", "JUMPI"):
            continue
        site = instructions[block.last_index].address
        if block.block_id in fanned_out:
            unresolved.append(site)
            continue
        dests = jump_value.get(site)
        if not dests:
            # simulated only via an underflowing entry: provable throw
            jump_targets[site] = ()
        else:
            jump_targets[site] = tuple(sorted(
                dest for dest in dests
                if dest is not None and dest in block_of_pc
                and instructions[blocks[block_of_pc[dest]].first_index]
                .op_code == "JUMPDEST"))

    # dense byte tables
    pc_to_block = [-1] * code_length
    for block in blocks:
        for pc in range(block.start_pc, min(block.end_pc, code_length)):
            pc_to_block[pc] = block.block_id
    dead_mask = bytearray(code_length)
    for block in blocks:
        if block.block_id not in reachable:
            for pc in range(block.start_pc, min(block.end_pc, code_length)):
                dead_mask[pc] = 1
    valid_targets = {block.start_pc for block in blocks
                     if block.block_id in reachable
                     and instructions[block.first_index].op_code
                     == "JUMPDEST"}
    valid_target_bitmap = bytearray(code_length)
    for pc in valid_targets:
        valid_target_bitmap[pc] = 1

    # dominators / post-dominators over reachable blocks + virtual exit
    graph: List[List[int]] = [sorted(block.successors) for block in blocks]
    graph.append([])                      # the virtual exit has no successors
    idom = domtree.compute_idoms(graph, entry=0)
    reverse: List[List[int]] = [[] for _ in range(len(graph))]
    for node, nexts in enumerate(graph):
        for nxt in nexts:
            reverse[nxt].append(node)
    ipostdom = domtree.compute_idoms(reverse, entry=exit_id)

    block_merge_pc = [-1] * len(blocks)
    branch_merge_pc: Dict[int, int] = {}
    n_edges = sum(len(block.successors) for block in blocks)
    for block in blocks:
        pdom = ipostdom[block.block_id]
        if pdom is not None and pdom != exit_id:
            block_merge_pc[block.block_id] = blocks[pdom].start_pc
        real_succs = [s for s in block.successors if s != exit_id]
        if len(real_succs) >= 2 and block_merge_pc[block.block_id] >= 0:
            site = instructions[block.last_index].address
            branch_merge_pc[site] = block_merge_pc[block.block_id]

    return CfaResult(
        blocks=blocks, exit_id=exit_id, code_length=code_length,
        pc_to_block=pc_to_block, block_merge_pc=block_merge_pc,
        branch_merge_pc=branch_merge_pc, valid_targets=valid_targets,
        valid_target_bitmap=valid_target_bitmap, dead_mask=dead_mask,
        jump_targets=jump_targets, unresolved_jumps=tuple(unresolved),
        reachable=reachable, idom=idom, ipostdom=ipostdom, n_edges=n_edges)
