"""Value-range + memory write-region abstract interpretation (absint).

The third stdlib-only static pass beside the CFA (cfa.py) and the taint
summary (taint.py): a memoized fixpoint interpreter over the CFA's CFG
with two abstract domains —

* a **stride-interval value domain** for abstract stack cells: every
  cell is ``(lo, hi, stride)`` meaning ``{lo + k*stride} ∩ [lo, hi]``
  (``stride == 0`` is the singleton constant). Entry states join at
  CFG merges and **widen at natural-loop headers** (summary.py's
  LoopInfo) so the fixpoint terminates on counting loops;
* a **memory write-region domain**: per basic block (and, derived, per
  post-dominator join point) the ``[offset, offset + len)`` byte ranges
  the block may write — ⊤ as soon as a write offset is unbounded or
  past ``OFFSET_CAP``.

Three consumer surfaces ride on the fixpoint tables:

* ``join_regions`` / ``word_windows`` — per join pc, the statically
  proven byte regions either diamond arm may have written. The device
  merge kernel (parallel/symstep.py merge_pass) ships these as a
  32-byte-window mask so lane pairs whose memory planes diverge ONLY
  inside the mask can still ITE-blend and merge (frontier item 4a);
* ``loop_bounds`` — proven per-loop header-arrival counts from
  abstractly executing constant-entry loops to their exit, consumed by
  core/strategy/bounded_loops.py in place of the flat default;
* ``const_jumpis`` — JUMPI sites whose condition interval is provably
  always-zero / always-nonzero (out-of-range CALLDATALOAD selectors
  fold here through ``SHR``/``EQ``), consumed by the cfa screen to
  skip the infeasible side before any constraint or solver work.

Soundness direction mirrors the CFA: states propagate along every CFG
edge including the conservative fan-out edges, so every interval and
region **over-approximates** the concrete values/writes — the
randomized concrete-differential harness in tests/test_absint.py holds
the pass to exactly that contract. Consumers reach the tables through
``smt/solver/cfa_screen.py`` (the counted adapter); ``--no-absint`` /
``MYTHRIL_TPU_ABSINT=0`` gates the whole surface for A/B runs.
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..ops.opcodes import OPCODES, STACK
from .cfa import CfaResult, TERMINATORS, BasicBlock

log = logging.getLogger(__name__)

#: bump when the JSON layout changes; from_json rejects other versions
ABSINT_VERSION = 1

_WORD_MASK = (1 << 256) - 1

#: an interval is (lo, hi, stride); stride 0 <=> singleton constant
Interval = Tuple[int, int, int]

TOP: Interval = (0, _WORD_MASK, 1)
#: 160-bit address-class ops (CALLER/ADDRESS/...) push at most this
_ADDR_TOP: Interval = (0, (1 << 160) - 1, 1)

#: write offsets at/above this are treated as ⊤ (the device memory
#: plane is far smaller; a frontier-side filter re-checks its own cap)
OFFSET_CAP = 1 << 24
#: one write spanning more than this many bytes is ⊤
SPAN_CAP = 4096
#: per-block write-region list cap before collapsing to ⊤
_BLOCK_REGION_CAP = 16
#: joins switch from join() to widen() after this many block visits,
#: loop headers widen from the first revisit (termination guard for
#: slowly-ascending chains through conservative fan-out edges)
_WIDEN_AFTER = 8

#: ops that write memory with (dest, ..., length) operand layouts;
#: value = (dest operand index from top, length operand index, fixed
#: size when length is implicit)
_COPY_WRITERS = {
    "CALLDATACOPY": (0, 2),
    "CODECOPY": (0, 2),
    "RETURNDATACOPY": (0, 2),
    "MCOPY": (0, 2),
    "EXTCODECOPY": (1, 3),
}
#: ops whose memory effect is unbounded for this pass (return-data
#: writes at dynamic offsets; conservatively ⊤)
_TOP_WRITERS = frozenset(
    {"CALL", "CALLCODE", "DELEGATECALL", "STATICCALL"})

_ADDR_OPS = frozenset({"ADDRESS", "ORIGIN", "CALLER", "COINBASE"})

_BINARY_OPS = frozenset({
    "ADD", "SUB", "MUL", "DIV", "MOD", "AND", "OR", "XOR",
    "SHL", "SHR", "EQ", "LT", "GT", "EXP"})


# -- the stride-interval domain ------------------------------------------------------

def make_interval(lo: int, hi: int, stride: int) -> Interval:
    """Canonicalize: clamp to the word range, singletons get stride 0,
    hi is pulled down onto the stride lattice so it is attainable."""
    lo = max(0, lo)
    hi = min(_WORD_MASK, hi)
    if hi < lo:
        return TOP  # defensive: an empty interval is a bug upstream
    if lo == hi:
        return (lo, lo, 0)
    stride = max(1, stride)
    hi = lo + ((hi - lo) // stride) * stride
    if lo == hi:
        return (lo, lo, 0)
    return (lo, hi, stride)


def const(value: int) -> Interval:
    value &= _WORD_MASK
    return (value, value, 0)


def is_const(iv: Interval) -> bool:
    return iv[0] == iv[1]


def contains(iv: Interval, value: int) -> bool:
    lo, hi, stride = iv
    if not lo <= value <= hi:
        return False
    return stride == 0 or (value - lo) % stride == 0


def join_iv(a: Interval, b: Interval) -> Interval:
    if a == b:
        return a
    stride = math.gcd(math.gcd(a[2], b[2]), abs(a[0] - b[0]))
    return make_interval(min(a[0], b[0]), max(a[1], b[1]), stride)


def widen_iv(old: Interval, new: Interval) -> Interval:
    """Jump unstable bounds to the lattice extremes (strides still
    descend by gcd, a finite divisor chain, so widening terminates)."""
    joined = join_iv(old, new)
    if joined == old:
        return old
    lo = old[0] if joined[0] >= old[0] else 0
    hi = old[1] if joined[1] <= old[1] else _WORD_MASK
    return make_interval(lo, hi, joined[2])


def _definitely_nonzero(iv: Interval) -> bool:
    return not contains(iv, 0)


def _definitely_zero(iv: Interval) -> bool:
    return iv == (0, 0, 0)


def interval_binary(op: str, a: Interval, b: Interval) -> Interval:
    """Abstract transfer for op(µ0=a, µ1=b) — same operand convention as
    cfa._fold_binary (a is the top-of-stack pop)."""
    la, ha, sa = a
    lb, hb, sb = b
    if op == "ADD":
        if ha + hb <= _WORD_MASK:
            return make_interval(la + lb, ha + hb, math.gcd(sa, sb))
        return TOP  # may wrap
    if op == "SUB":
        if la >= hb:
            return make_interval(la - hb, ha - lb, math.gcd(sa, sb))
        return TOP  # may underflow-wrap
    if op == "MUL":
        if ha * hb > _WORD_MASK:
            return TOP
        # (la+i·sa)(lb+j·sb) − la·lb is a multiple of this gcd
        stride = math.gcd(math.gcd(sa * lb, sb * la), sa * sb)
        return make_interval(la * lb, ha * hb, stride)
    if op == "DIV":
        if lb == 0:  # divisor may be 0: EVM yields 0, which min covers
            return make_interval(0, ha // max(lb, 1), 1)
        stride = sa // lb if is_const(b) and lb and sa % lb == 0 else 1
        return make_interval(la // hb, ha // lb, stride)
    if op == "MOD":
        if is_const(b) and lb > 0 and ha < lb:
            return a  # in-range: identity
        if hb == 0:
            return const(0)  # x mod 0 == 0 on the EVM
        return make_interval(0, hb - 1, 1)
    if op == "AND":
        if is_const(b) and (lb + 1) & lb == 0 and ha <= lb:
            return a  # power-of-two mask that doesn't clip
        if is_const(a) and (la + 1) & la == 0 and hb <= la:
            return b
        return make_interval(0, min(ha, hb), 1)
    if op in ("OR", "XOR"):
        bits = max(ha.bit_length(), hb.bit_length())
        return make_interval(0, (1 << bits) - 1, 1)
    if op == "SHL":  # shift = µ0, value = µ1
        if is_const(a):
            if la >= 256:
                return const(0)
            if (hb << la) <= _WORD_MASK:
                return make_interval(lb << la, hb << la, sb << la)
        return TOP
    if op == "SHR":  # monotone decreasing in the shift amount
        lo = lb >> min(ha, 256)
        hi = hb >> min(la, 256)
        return make_interval(lo, hi, 0 if lo == hi else 1)
    if op == "EQ":
        if ha < lb or hb < la:
            return const(0)  # disjoint
        if is_const(a) and is_const(b):
            return const(int(la == lb))
        if is_const(a) and not contains(b, la):
            return const(0)  # off-stride constant (selector screening)
        if is_const(b) and not contains(a, lb):
            return const(0)
        return (0, 1, 1)
    if op == "LT":
        if ha < lb:
            return const(1)
        if la >= hb:
            return const(0)
        return (0, 1, 1)
    if op == "GT":
        if la > hb:
            return const(1)
        if ha <= lb:
            return const(0)
        return (0, 1, 1)
    if op == "EXP":  # base = µ0, exponent = µ1; fold small constants
        if is_const(a) and is_const(b) and lb <= 256 \
                and la.bit_length() * max(lb, 1) <= 257:
            return const(pow(la, lb) & _WORD_MASK)
        return TOP
    return TOP


# -- abstract machine state ----------------------------------------------------------
# Mirrors cfa._AbsState / cfa._Stack with intervals for values: a state
# is (height, vals) — total stack height (None = unknown) plus the top
# `tracked` cells, top of stack LAST; deeper slots are implicitly TOP.

AbsState = Tuple[Optional[int], Tuple[Interval, ...]]

_ENTRY_STATE: AbsState = (0, ())
_UNKNOWN_STATE: AbsState = (None, ())


class _Underflow(Exception):
    """Abstract execution popped below a known-height stack."""


def merge_states(a: AbsState, b: AbsState,
                 widen: bool = False) -> AbsState:
    height = a[0] if a[0] == b[0] else None
    vals_a, vals_b = a[1], b[1]
    keep = min(len(vals_a), len(vals_b))
    combine = widen_iv if widen else join_iv
    merged = tuple(
        combine(x, y)
        for x, y in zip(vals_a[len(vals_a) - keep:],
                        vals_b[len(vals_b) - keep:]))
    return (height, merged)


class _IStack:
    """Mutable interval stack for simulating one block."""

    __slots__ = ("vals", "below", "tracked")

    def __init__(self, state: AbsState, tracked: int):
        height, vals = state
        self.vals: List[Interval] = list(vals)
        self.below: Optional[int] = None if height is None \
            else height - len(vals)
        self.tracked = tracked

    def pop(self) -> Interval:
        if self.vals:
            return self.vals.pop()
        if self.below is None:
            return TOP
        if self.below <= 0:
            raise _Underflow
        self.below -= 1
        return TOP

    def push(self, value: Interval) -> None:
        self.vals.append(value)
        if len(self.vals) > self.tracked:
            del self.vals[0]
            if self.below is not None:
                self.below += 1

    def peek(self, depth: int) -> Interval:
        if depth < len(self.vals):
            return self.vals[-1 - depth]
        if self.below is not None \
                and self.below < depth - len(self.vals) + 1:
            raise _Underflow
        return TOP

    def swap(self, depth: int) -> None:
        while len(self.vals) <= depth:
            if self.below is not None:
                if self.below <= 0:
                    raise _Underflow
                self.below -= 1
            self.vals.insert(0, TOP)
        self.vals[-1], self.vals[-1 - depth] = \
            self.vals[-1 - depth], self.vals[-1]

    def state(self) -> AbsState:
        height = None if self.below is None \
            else self.below + len(self.vals)
        return (height, tuple(self.vals))


#: one abstract memory write: (start, end) byte region, or None = ⊤
_Write = Optional[Tuple[int, int]]


def _bounded_write(offset: Interval, size: int) -> _Write:
    """Region an [offset, offset+size) write may touch; None when the
    offset is unbounded or the span blows the caps."""
    lo, hi, _stride = offset
    if hi + size > OFFSET_CAP or (hi + size) - lo > SPAN_CAP:
        return None
    return (lo, hi + size)


def simulate_block(block: BasicBlock, instructions, entry: AbsState,
                   tracked: int,
                   writes: Optional[List[_Write]] = None
                   ) -> Tuple[AbsState, Optional[Interval],
                              Optional[Interval]]:
    """Abstractly execute one block body over the interval domain.

    Returns (exit_state, jump_dest, jumpi_cond) — the dest/cond
    intervals a JUMP/JUMPI terminator consumed (already popped), None
    otherwise. Appends every abstract memory write to `writes` when
    given. Raises _Underflow like cfa._simulate."""
    stack = _IStack(entry, tracked)
    jump_dest: Optional[Interval] = None
    jumpi_cond: Optional[Interval] = None

    def record(write: _Write) -> None:
        if writes is not None:
            writes.append(write)

    for index in range(block.first_index, block.last_index + 1):
        ins = instructions[index]
        op = ins.op_code
        if op.startswith("PUSH"):
            if op == "PUSH0":
                stack.push(const(0))
            else:
                try:
                    stack.push(const(int(ins.argument, 16)
                                     if ins.argument else 0))
                except ValueError:
                    stack.push(TOP)
        elif op.startswith("DUP"):
            stack.push(stack.peek(int(op[3:]) - 1))
        elif op.startswith("SWAP"):
            stack.swap(int(op[4:]))
        elif op == "POP":
            stack.pop()
        elif op == "PC":
            stack.push(const(ins.address))
        elif op == "JUMPDEST":
            pass
        elif op == "JUMP":
            jump_dest = stack.pop()
        elif op == "JUMPI":
            jump_dest = stack.pop()
            jumpi_cond = stack.pop()
        elif op == "ISZERO":
            value = stack.pop()
            if _definitely_zero(value):
                stack.push(const(1))
            elif _definitely_nonzero(value):
                stack.push(const(0))
            else:
                stack.push((0, 1, 1))
        elif op == "NOT":  # NOT x == MASK - x: bounds flip, stride kept
            lo, hi, stride = stack.pop()
            stack.push(make_interval(
                _WORD_MASK - hi, _WORD_MASK - lo, stride))
        elif op in _BINARY_OPS:
            a, b = stack.pop(), stack.pop()
            stack.push(interval_binary(op, a, b))
        elif op == "MSTORE":
            offset = stack.pop()
            stack.pop()
            record(_bounded_write(offset, 32))
        elif op == "MSTORE8":
            offset = stack.pop()
            stack.pop()
            record(_bounded_write(offset, 1))
        elif op in _COPY_WRITERS:
            dest_at, len_at = _COPY_WRITERS[op]
            pops, _pushes = OPCODES[op][STACK]
            operands = [stack.pop() for _ in range(pops)]
            dest, length = operands[dest_at], operands[len_at]
            if is_const(length) and length[0] == 0:
                pass  # zero-length copy writes nothing
            elif is_const(length) and length[0] <= SPAN_CAP:
                record(_bounded_write(dest, length[0]))
            else:
                record(None)
        elif op in _TOP_WRITERS:
            pops, pushes = OPCODES[op][STACK]
            for _ in range(pops):
                stack.pop()
            record(None)
            for _ in range(pushes):
                stack.push((0, 1, 1))  # call status word
        elif op in _ADDR_OPS:
            stack.push(_ADDR_TOP)
        elif op in OPCODES:
            pops, pushes = OPCODES[op][STACK]
            for _ in range(pops):
                stack.pop()
            for _ in range(pushes):
                stack.push(TOP)
        else:
            break  # unassigned opcode: the machine throws here
    return stack.state(), jump_dest, jumpi_cond


def _merge_regions(regions: List[Tuple[int, int]]
                   ) -> Tuple[Tuple[int, int], ...]:
    """Sort + coalesce overlapping/adjacent [start, end) regions."""
    merged: List[Tuple[int, int]] = []
    for start, end in sorted(regions):
        if merged and start <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], end))
        else:
            merged.append((start, end))
    return tuple(merged)


# -- result --------------------------------------------------------------------------

@dataclass
class AbsintResult:
    """The absint verdict for one Disassembly (block ids refer to the
    contract's CfaResult)."""

    code_length: int
    #: reachable block id -> (entry height, entry cell intervals)
    entry_intervals: Dict[int, AbsState]
    #: reachable block id -> merged write regions; None = ⊤
    block_writes: Dict[int, Optional[Tuple[Tuple[int, int], ...]]]
    #: join pc -> proven byte regions either diamond arm may write
    #: (absent join pc = ⊤ / untracked)
    join_regions: Dict[int, Tuple[Tuple[int, int], ...]]
    #: loop header pc -> proven header-arrival bound
    loop_bounds: Dict[int, int]
    #: JUMPI site pc -> True (always taken) / False (never taken)
    const_jumpis: Dict[int, bool]
    widenings: int = 0
    iterations: int = 0
    mem_regions_cap: int = 8
    #: lazily-built word-window memo per (join pc)
    _windows: Dict[int, Optional[Tuple[int, ...]]] = \
        field(default_factory=dict, repr=False)

    # -- queries (the consumer surface) ------------------------------------------
    @property
    def regions_proven(self) -> int:
        return len(self.join_regions)

    def jumpi_verdict(self, site_pc: int) -> Optional[bool]:
        """True = always taken, False = never taken, None = no claim."""
        return self.const_jumpis.get(site_pc)

    def loop_bound(self, header_pc: int) -> Optional[int]:
        return self.loop_bounds.get(header_pc)

    def word_windows(self, join_pc: int) -> Optional[Tuple[int, ...]]:
        """Non-overlapping 32-byte window start offsets covering the
        join's proven regions, or None when the join is untracked or
        needs more than `mem_regions_cap` windows (⊤ for the kernel)."""
        if join_pc not in self._windows:
            self._windows[join_pc] = self._build_windows(join_pc)
        return self._windows[join_pc]

    def _build_windows(self, join_pc: int) -> Optional[Tuple[int, ...]]:
        regions = self.join_regions.get(join_pc)
        if regions is None:
            return None
        windows: List[int] = []
        cursor = 0
        for start, end in regions:
            offset = max(start, cursor)
            while offset < end:
                windows.append(offset)
                cursor = offset + 32
                offset = cursor
                if len(windows) > self.mem_regions_cap:
                    return None
        return tuple(windows)

    # -- persistence (serve warm path / cfaview --json) --------------------------
    def to_json(self) -> dict:
        return {
            "version": ABSINT_VERSION,
            "code_length": self.code_length,
            "blocks": {
                str(bid): {"height": state[0],
                           "vals": [list(iv) for iv in state[1]]}
                for bid, state in sorted(self.entry_intervals.items())},
            "writes": {
                str(bid): (None if regions is None
                           else [list(region) for region in regions])
                for bid, regions in sorted(self.block_writes.items())},
            "joins": {
                str(pc): [list(region) for region in regions]
                for pc, regions in sorted(self.join_regions.items())},
            "loop_bounds": {str(pc): bound for pc, bound
                            in sorted(self.loop_bounds.items())},
            "const_jumpis": {str(pc): verdict for pc, verdict
                             in sorted(self.const_jumpis.items())},
            "widenings": self.widenings,
            "iterations": self.iterations,
            "mem_regions_cap": self.mem_regions_cap,
        }

    @classmethod
    def from_json(cls, data: dict) -> Optional["AbsintResult"]:
        if not isinstance(data, dict) \
                or data.get("version") != ABSINT_VERSION:
            return None
        return cls(
            code_length=int(data["code_length"]),
            entry_intervals={
                int(bid): (entry["height"],
                           tuple(tuple(iv) for iv in entry["vals"]))
                for bid, entry in data["blocks"].items()},
            block_writes={
                int(bid): (None if regions is None
                           else tuple(tuple(r) for r in regions))
                for bid, regions in data["writes"].items()},
            join_regions={
                int(pc): tuple(tuple(r) for r in regions)
                for pc, regions in data["joins"].items()},
            loop_bounds={int(pc): int(bound) for pc, bound
                         in data["loop_bounds"].items()},
            const_jumpis={int(pc): bool(verdict) for pc, verdict
                          in data["const_jumpis"].items()},
            widenings=int(data.get("widenings", 0)),
            iterations=int(data.get("iterations", 0)),
            mem_regions_cap=int(data.get("mem_regions_cap", 8)),
        )


# -- fixpoint driver -----------------------------------------------------------------

def _successor_states(cfa: CfaResult, block: BasicBlock, instructions,
                      exit_state: AbsState, jump_dest: Optional[Interval]
                      ) -> List[Tuple[int, AbsState]]:
    """(target block, propagated state) pairs for one simulated block —
    the same edge classification build_cfa derived, driven from its
    tables (jump_targets / unresolved_jumps) instead of re-resolving."""
    out: List[Tuple[int, AbsState]] = []
    term = block.terminator
    next_id = block.block_id + 1 \
        if block.block_id + 1 < len(cfa.blocks) else cfa.exit_id
    if term == "" and next_id != cfa.exit_id:
        out.append((next_id, exit_state))
        return out
    if term not in ("JUMP", "JUMPI"):
        return out
    if term == "JUMPI" and next_id != cfa.exit_id:
        out.append((next_id, exit_state))
    site = instructions[block.last_index].address
    targets = cfa.jump_targets.get(site)
    if targets is not None:
        for target_pc in targets:
            target = cfa.block_at(target_pc)
            if target is not None:
                out.append((target, exit_state))
    else:
        # unresolved site: the cfa fanned out to every JUMPDEST block —
        # propagate the unknown state along those conservative edges
        for succ in block.successors:
            if succ != cfa.exit_id and succ != next_id:
                out.append((succ, _UNKNOWN_STATE))
    return out


def _prove_loop_bound(cfa: CfaResult, instructions, loop,
                      entry: AbsState, tracked: int,
                      max_iters: int) -> Optional[int]:
    """Abstractly execute the loop from its outside entry state; when
    every branch decision folds to a constant and the loop exits within
    `max_iters` header arrivals, the arrival count is a proven bound."""
    body = set(loop.blocks)
    current = loop.header_block
    state = entry
    visits = 0
    for _step in range(max_iters * 64):
        if current == loop.header_block:
            visits += 1
            if visits > max_iters:
                return None
        block = cfa.blocks[current]
        try:
            state, jump_dest, jumpi_cond = simulate_block(
                block, instructions, state, tracked)
        except _Underflow:
            return None
        term = block.terminator
        if term in TERMINATORS or (term not in ("", "JUMP", "JUMPI")):
            return visits  # execution ended inside the loop body
        next_id = current + 1 if current + 1 < len(cfa.blocks) \
            else cfa.exit_id
        if term == "":
            target = next_id
        else:
            if term == "JUMPI":
                if jumpi_cond is None:
                    return None
                if _definitely_zero(jumpi_cond):
                    target = next_id
                elif _definitely_nonzero(jumpi_cond):
                    target = _const_jump_block(cfa, jump_dest)
                else:
                    return None  # data-dependent branch: no proof
            else:  # JUMP
                target = _const_jump_block(cfa, jump_dest)
            if target is None:
                return None
        if target == cfa.exit_id:
            return visits
        if target not in body:
            return visits  # left the loop: bound proven
        current = target
    return None


def _const_jump_block(cfa: CfaResult,
                      dest: Optional[Interval]) -> Optional[int]:
    """Target block of a constant jump dest, None when not provable."""
    if dest is None or not is_const(dest):
        return None
    pc = dest[0]
    if pc not in cfa.valid_targets:
        return None
    block = cfa.block_at(pc)
    if block is None or cfa.blocks[block].start_pc != pc:
        return None
    return block


def build_absint(disassembly, cfa: Optional[CfaResult] = None,
                 tracked_depth: Optional[int] = None,
                 max_iters: Optional[int] = None,
                 mem_regions: Optional[int] = None
                 ) -> Optional[AbsintResult]:
    """Run the interval/region fixpoint over a Disassembly's CFA.

    Returns None when there is no CFA (pass disabled or bailed) — every
    consumer treats None as "no verdict" and keeps its dynamic path."""
    from ..support import tpu_config
    from .summary import recover_loops

    if cfa is None:
        from .cfa import build_cfa

        cfa = build_cfa(disassembly)
    if cfa is None:
        return None
    if tracked_depth is None:
        tracked_depth = tpu_config.get_int("MYTHRIL_TPU_CFA_STACK_DEPTH")
    if max_iters is None:
        max_iters = tpu_config.get_int("MYTHRIL_TPU_ABSINT_MAX_ITERS")
    if mem_regions is None:
        mem_regions = tpu_config.get_int("MYTHRIL_TPU_ABSINT_MEM_REGIONS")

    instructions = disassembly.instruction_list
    loops, _loop_header_of = recover_loops(cfa, instructions)
    #: header block id -> its loop's body block set (for back-edge
    #: classification during propagation)
    loop_body_of: Dict[int, Set[int]] = {
        loop.header_block: set(loop.blocks) for loop in loops}

    entry_states: Dict[int, AbsState] = {0: _ENTRY_STATE}
    #: loop header -> entry state merged over NON-back-edge preds only
    #: (the state trip-count proving must start from)
    outside_entry: Dict[int, AbsState] = {}
    visits: Dict[int, int] = {}
    #: JUMPI site pc -> branch-direction observations across visits
    jumpi_obs: Dict[int, Set[str]] = {}
    widenings = 0
    iterations = 0
    worklist: List[int] = [0]
    # defensive convergence cap (widening guarantees termination; the
    # cap turns a domain bug into a bail instead of a hang)
    iteration_cap = max(256, 32 * len(cfa.blocks))

    def propagate(src: int, target: int, state: AbsState) -> None:
        nonlocal widenings
        body = loop_body_of.get(target)
        back_edge = body is not None and src in body
        old = entry_states.get(target)
        if not back_edge:
            prev = outside_entry.get(target)
            if body is not None:
                outside_entry[target] = state if prev is None \
                    else merge_states(prev, state)
        if old is None:
            new = state
        else:
            widen = back_edge or visits.get(target, 0) >= _WIDEN_AFTER
            new = merge_states(old, state, widen=widen)
            if widen and new != old:
                widenings += 1
        if new != old:
            entry_states[target] = new
            if target not in worklist:
                worklist.append(target)

    while worklist:
        iterations += 1
        if iterations > iteration_cap:
            log.warning("absint: fixpoint did not converge in %d "
                        "iterations — skipping value-range analysis",
                        iteration_cap)
            return None
        block_id = worklist.pop()
        visits[block_id] = visits.get(block_id, 0) + 1
        block = cfa.blocks[block_id]
        entry = entry_states[block_id]
        try:
            exit_state, jump_dest, jumpi_cond = simulate_block(
                block, instructions, entry, tracked_depth)
        except _Underflow:
            continue  # provable throw; cfa already routed to exit
        if block.terminator == "JUMPI" and jumpi_cond is not None:
            site = instructions[block.last_index].address
            if _definitely_nonzero(jumpi_cond):
                direction = "taken"
            elif _definitely_zero(jumpi_cond):
                direction = "fall"
            else:
                direction = "both"
            jumpi_obs.setdefault(site, set()).add(direction)
        for target, state in _successor_states(
                cfa, block, instructions, exit_state, jump_dest):
            propagate(block_id, target, state)

    # -- per-block write effects over the fixpoint entry states ------------------
    block_writes: Dict[int, Optional[Tuple[Tuple[int, int], ...]]] = {}
    for block_id in sorted(entry_states):
        writes: List[_Write] = []
        try:
            simulate_block(cfa.blocks[block_id], instructions,
                           entry_states[block_id], tracked_depth,
                           writes=writes)
        except _Underflow:
            writes = []
        if any(write is None for write in writes):
            block_writes[block_id] = None
        else:
            merged = _merge_regions(
                [write for write in writes if write is not None])
            block_writes[block_id] = merged \
                if len(merged) <= _BLOCK_REGION_CAP else None

    # -- diamond write regions per post-dominator join ---------------------------
    # For each branch site, the blocks strictly between the branch and
    # its join (DFS from the branch's successors, stopping at the join)
    # bound what either arm may have written when two siblings meet
    # there. Several sites can share a join; their regions union.
    join_acc: Dict[int, Optional[List[Tuple[int, int]]]] = {}
    for site, merge_pc in cfa.branch_merge_pc.items():
        branch_block = cfa.block_at(site)
        join_block = cfa.block_at(merge_pc)
        if branch_block is None or join_block is None:
            continue
        regions = join_acc.setdefault(merge_pc, [])
        if regions is None:
            continue  # an earlier site already forced ⊤
        stack = [succ for succ in cfa.blocks[branch_block].successors
                 if succ != cfa.exit_id and succ != join_block]
        diamond: Set[int] = set()
        while stack:
            node = stack.pop()
            if node in diamond:
                continue
            diamond.add(node)
            for succ in cfa.blocks[node].successors:
                if succ != cfa.exit_id and succ != join_block \
                        and succ not in diamond:
                    stack.append(succ)
        for node in diamond:
            if node not in entry_states:
                continue  # unreachable: cannot execute, cannot write
            effect = block_writes.get(node)
            if effect is None:
                join_acc[merge_pc] = None
                break
            regions.extend(effect)
    join_regions = {
        merge_pc: _merge_regions(regions)
        for merge_pc, regions in join_acc.items() if regions is not None}

    # -- proven loop bounds ------------------------------------------------------
    loop_bounds: Dict[int, int] = {}
    for loop in loops:
        entry = outside_entry.get(loop.header_block)
        if entry is None:
            continue
        bound = _prove_loop_bound(cfa, instructions, loop, entry,
                                  tracked_depth, max_iters)
        if bound is not None:
            loop_bounds[loop.header_pc] = bound

    const_jumpis = {
        site: observations == {"taken"}
        for site, observations in jumpi_obs.items()
        if observations in ({"taken"}, {"fall"})}

    return AbsintResult(
        code_length=cfa.code_length,
        entry_intervals=dict(entry_states),
        block_writes=block_writes,
        join_regions=join_regions,
        loop_bounds=loop_bounds,
        const_jumpis=const_jumpis,
        widenings=widenings,
        iterations=iterations,
        mem_regions_cap=mem_regions,
    )
