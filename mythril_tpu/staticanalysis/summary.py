"""Per-contract analysis summaries: functions, loops, sink taints.

Packages the :mod:`.taint` fixpoint with two cheap structural passes
over the same ``CfaResult`` into one memoizable, JSON-serializable
``ContractSummary``:

* **functions** — the public selectors the disassembler already
  recovered from the dispatcher idiom (``Disassembly.func_hashes``),
  cross-checked against reachable JUMPDESTs and expanded to per-function
  block cover sets by forward DFS from each entry block. Blocks reached
  from exactly one selector are "owned" by it (shared runtime helpers
  stay unowned), giving fleet scheduling a per-function work partition.
* **loops** — natural loops from the dominator tree: a back edge is a
  CFG edge ``u -> h`` where ``h`` dominates ``u``; the loop body is the
  reverse-reachable set from ``u`` that stays below ``h``. Emitted as
  per-loop-header hint tables (header pc, back-edge sites, body, nesting
  depth) for bounded-unroll lane budgeting in the device frontier.
* **sinks** — the taint pass's per-sink-site operand verdicts plus the
  reachable opcode set the module screen consults.

Consumers go through ``analysis/module_screen.py`` (the counted adapter,
mirroring ``smt/solver/cfa_screen.py`` for the cfa tables); the serve
daemon persists summaries by code hash via ``to_json``/``from_json``.
Stdlib-only.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .cfa import CfaResult
from .taint import SinkSite, TaintResult, build_taint

log = logging.getLogger(__name__)

#: bump when the JSON layout changes; from_json rejects other versions
SUMMARY_VERSION = 1


@dataclass
class FunctionInfo:
    """One public function recovered from the dispatcher."""

    name: str                     #: signature or _function_0x<selector>
    selector: Optional[str]       #: 0x-prefixed 4-byte hash, None = fallback
    entry_pc: int
    blocks: Tuple[int, ...]       #: block ids reachable from the entry
    ops: FrozenSet[str]           #: opcodes appearing in those blocks

    def to_json(self) -> dict:
        return {"name": self.name, "selector": self.selector,
                "entry_pc": self.entry_pc, "blocks": list(self.blocks),
                "ops": sorted(self.ops)}

    @classmethod
    def from_json(cls, data: dict) -> "FunctionInfo":
        return cls(name=str(data["name"]), selector=data.get("selector"),
                   entry_pc=int(data["entry_pc"]),
                   blocks=tuple(int(b) for b in data["blocks"]),
                   ops=frozenset(data["ops"]))


@dataclass
class LoopInfo:
    """One natural loop (per-loop-header hint table row)."""

    header_pc: int
    header_block: int
    back_edge_pcs: Tuple[int, ...]   #: pc of each back-edge jump site
    blocks: Tuple[int, ...]          #: body block ids, header included
    depth: int                       #: nesting depth, outermost = 1

    def to_json(self) -> dict:
        return {"header_pc": self.header_pc,
                "header_block": self.header_block,
                "back_edge_pcs": list(self.back_edge_pcs),
                "blocks": list(self.blocks), "depth": self.depth}

    @classmethod
    def from_json(cls, data: dict) -> "LoopInfo":
        return cls(header_pc=int(data["header_pc"]),
                   header_block=int(data["header_block"]),
                   back_edge_pcs=tuple(int(p)
                                       for p in data["back_edge_pcs"]),
                   blocks=tuple(int(b) for b in data["blocks"]),
                   depth=int(data["depth"]))


@dataclass
class ContractSummary:
    """The per-contract static summary the screens and the serve daemon
    consume. Block ids refer to the contract's ``CfaResult``."""

    code_length: int
    functions: Tuple[FunctionInfo, ...]
    loops: Tuple[LoopInfo, ...]
    sink_sites: Dict[int, SinkSite]       #: site pc -> operand taints
    reachable_ops: FrozenSet[str]
    rounds: int                           #: storage rounds the fixpoint ran
    converged: bool
    loop_header_of: Dict[int, int] = field(default_factory=dict)
    #: block id -> innermost loop header pc
    function_of: Dict[int, int] = field(default_factory=dict)
    #: block id -> index into `functions` (uniquely-owned blocks only)

    # -- queries (the consumer surface) ------------------------------------------
    def sink_at(self, pc: int) -> Optional[SinkSite]:
        return self.sink_sites.get(pc)

    def function_order(self) -> Tuple[int, ...]:
        """Function entry pcs in dispatcher order (selector functions
        first, by entry pc)."""
        return tuple(f.entry_pc for f in self.functions)

    @property
    def n_sink_sites(self) -> int:
        return len(self.sink_sites)

    def to_json(self) -> dict:
        return {
            "version": SUMMARY_VERSION,
            "code_length": self.code_length,
            "functions": [f.to_json() for f in self.functions],
            "loops": [l.to_json() for l in self.loops],
            "sink_sites": {str(pc): site.to_json()
                           for pc, site in sorted(self.sink_sites.items())},
            "reachable_ops": sorted(self.reachable_ops),
            "rounds": self.rounds,
            "converged": self.converged,
            "loop_header_of": {str(b): pc for b, pc
                               in sorted(self.loop_header_of.items())},
            "function_of": {str(b): i for b, i
                            in sorted(self.function_of.items())},
        }

    @classmethod
    def from_json(cls, data: dict) -> Optional["ContractSummary"]:
        """Rebuild a summary from its JSON form; None when the payload is
        malformed or from another summary version (callers fall back to a
        fresh build)."""
        try:
            if int(data["version"]) != SUMMARY_VERSION:
                return None
            return cls(
                code_length=int(data["code_length"]),
                functions=tuple(FunctionInfo.from_json(f)
                                for f in data["functions"]),
                loops=tuple(LoopInfo.from_json(l) for l in data["loops"]),
                sink_sites={int(pc): SinkSite.from_json(site)
                            for pc, site in data["sink_sites"].items()},
                reachable_ops=frozenset(data["reachable_ops"]),
                rounds=int(data["rounds"]),
                converged=bool(data["converged"]),
                loop_header_of={int(b): int(pc) for b, pc
                                in data["loop_header_of"].items()},
                function_of={int(b): int(i) for b, i
                             in data["function_of"].items()},
            )
        except (KeyError, TypeError, ValueError, AttributeError):
            return None


# -- structural passes ---------------------------------------------------------------

def _function_cover(cfa: CfaResult, entry_block: int) -> List[int]:
    """Block ids reachable from `entry_block` along CFG edges (virtual
    exit excluded), sorted."""
    seen: Set[int] = {entry_block}
    stack = [entry_block]
    while stack:
        block = cfa.blocks[stack.pop()]
        for succ in block.successors:
            if succ != cfa.exit_id and succ not in seen:
                seen.add(succ)
                stack.append(succ)
    return sorted(seen)


def recover_functions(disassembly,
                      cfa: CfaResult) -> Tuple[Tuple[FunctionInfo, ...],
                                               Dict[int, int]]:
    """Cross-check the disassembler's dispatcher table against the CFA
    and expand each entry to its block cover; returns (functions,
    block id -> unique owner index)."""
    instructions = disassembly.instruction_list
    name_to_hash = getattr(disassembly, "function_name_to_hash", {}) or {}
    entries = sorted(
        (getattr(disassembly, "function_name_to_address", {}) or {}).items(),
        key=lambda kv: kv[1])
    functions: List[FunctionInfo] = []
    covers: List[List[int]] = []
    for name, entry_pc in entries:
        block = cfa.block_at(entry_pc)
        if block is None or block not in cfa.reachable \
                or not cfa.is_valid_target(entry_pc):
            continue  # dispatcher pattern matched dead/invalid code
        cover = _function_cover(cfa, block)
        ops = frozenset(
            instructions[index].op_code
            for bid in cover
            for index in range(cfa.blocks[bid].first_index,
                               cfa.blocks[bid].last_index + 1))
        functions.append(FunctionInfo(
            name=name, selector=name_to_hash.get(name), entry_pc=entry_pc,
            blocks=tuple(cover), ops=ops))
        covers.append(cover)
    function_of: Dict[int, int] = {}
    owner_count: Dict[int, int] = {}
    for index, cover in enumerate(covers):
        for bid in cover:
            owner_count[bid] = owner_count.get(bid, 0) + 1
            function_of[bid] = index
    function_of = {bid: index for bid, index in function_of.items()
                   if owner_count[bid] == 1}
    return tuple(functions), function_of


def recover_loops(cfa: CfaResult, instructions) -> Tuple[Tuple[LoopInfo, ...],
                                                         Dict[int, int]]:
    """Natural loops from the dominator tree; returns (loops, block id ->
    innermost loop header pc)."""
    instructions_pc = {block.block_id: block.start_pc
                       for block in cfa.blocks}

    def dominates(a: int, b: int) -> bool:
        node: Optional[int] = b
        while node is not None:
            if node == a:
                return True
            if node == 0:
                return False
            node = cfa.idom[node] if node < len(cfa.idom) else None
        return False

    preds: Dict[int, List[int]] = {}
    for block in cfa.blocks:
        if block.block_id not in cfa.reachable:
            continue
        for succ in block.successors:
            if succ != cfa.exit_id:
                preds.setdefault(succ, []).append(block.block_id)

    bodies: Dict[int, Set[int]] = {}       # header block -> body
    back_sites: Dict[int, List[int]] = {}  # header block -> back-edge pcs
    for block in cfa.blocks:
        if block.block_id not in cfa.reachable:
            continue
        for succ in block.successors:
            if succ == cfa.exit_id or succ not in cfa.reachable:
                continue
            if not dominates(succ, block.block_id):
                continue
            header = succ
            body = bodies.setdefault(header, {header})
            # the back-edge site is the block's jump instruction; for
            # fallthrough back edges report the block start
            if block.terminator in ("JUMP", "JUMPI"):
                site_pc = instructions[block.last_index].address
            else:
                site_pc = block.start_pc
            back_sites.setdefault(header, []).append(site_pc)
            stack = [block.block_id]
            if block.block_id != header:
                body.add(block.block_id)
            while stack:
                node = stack.pop()
                if node == header:
                    continue
                for pred in preds.get(node, ()):
                    if pred not in body:
                        body.add(pred)
                        stack.append(pred)
    loops: List[LoopInfo] = []
    for header in sorted(bodies):
        depth = 1 + sum(1 for other, body in bodies.items()
                        if other != header and header in body)
        loops.append(LoopInfo(
            header_pc=instructions_pc[header], header_block=header,
            back_edge_pcs=tuple(sorted(set(back_sites[header]))),
            blocks=tuple(sorted(bodies[header])), depth=depth))
    loop_header_of: Dict[int, int] = {}
    for loop in sorted(loops, key=lambda l: -len(l.blocks)):
        for bid in loop.blocks:
            loop_header_of[bid] = loop.header_pc  # smallest body wins
    return tuple(loops), loop_header_of


def build_summary(disassembly,
                  cfa: Optional[CfaResult]) -> Optional[ContractSummary]:
    """Build the full summary for one contract over its CfaResult; None
    when the cfa tables are unavailable or the taint fixpoint bailed."""
    if cfa is None:
        return None
    instructions = disassembly.instruction_list
    taint: Optional[TaintResult] = build_taint(cfa, instructions)
    if taint is None:
        return None
    functions, function_of = recover_functions(disassembly, cfa)
    loops, loop_header_of = recover_loops(cfa, instructions)
    return ContractSummary(
        code_length=cfa.code_length,
        functions=functions, loops=loops,
        sink_sites=taint.sink_sites, reachable_ops=taint.reachable_ops,
        rounds=taint.rounds, converged=taint.converged,
        loop_header_of=loop_header_of, function_of=function_of)
