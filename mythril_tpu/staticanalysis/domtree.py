"""Iterative dominator-tree construction (Cooper–Harvey–Kennedy).

"A Simple, Fast Dominance Algorithm" (Cooper, Harvey, Kennedy 2001):
process nodes in reverse postorder, intersecting the current immediate
dominators of each node's processed predecessors, until a fixed point.
No Lengauer–Tarjan machinery, no recursion, no external deps — the CFGs
this runs on are EVM contracts (hundreds to low thousands of blocks), and
CHK is near-linear there.

The same routine computes POST-dominators: call it on the reversed edge
set with the virtual exit node as the entry.
"""

from __future__ import annotations

from typing import List, Optional, Sequence


def postorder(succs: Sequence[Sequence[int]], entry: int) -> List[int]:
    """Iterative DFS postorder over the nodes reachable from `entry`."""
    seen = [False] * len(succs)
    order: List[int] = []
    # (node, iterator over its successors) — explicit stack, no recursion
    stack = [(entry, iter(succs[entry]))]
    seen[entry] = True
    while stack:
        node, it = stack[-1]
        advanced = False
        for nxt in it:
            if not seen[nxt]:
                seen[nxt] = True
                stack.append((nxt, iter(succs[nxt])))
                advanced = True
                break
        if not advanced:
            order.append(node)
            stack.pop()
    return order


def compute_idoms(succs: Sequence[Sequence[int]],
                  entry: int) -> List[Optional[int]]:
    """Immediate dominator of every node, or None for nodes unreachable
    from `entry` (the entry dominates itself: idom[entry] == entry)."""
    n = len(succs)
    preds: List[List[int]] = [[] for _ in range(n)]
    for node in range(n):
        for nxt in succs[node]:
            preds[nxt].append(node)

    order = postorder(succs, entry)          # postorder
    rpo_index = [-1] * n                     # node -> reverse-postorder rank
    for rank, node in enumerate(reversed(order)):
        rpo_index[node] = rank

    idom: List[Optional[int]] = [None] * n
    idom[entry] = entry

    def intersect(a: int, b: int) -> int:
        # walk the two dominator chains up (toward the entry = lower rank)
        while a != b:
            while rpo_index[a] > rpo_index[b]:
                a = idom[a]  # type: ignore[assignment]
            while rpo_index[b] > rpo_index[a]:
                b = idom[b]  # type: ignore[assignment]
        return a

    changed = True
    while changed:
        changed = False
        for node in reversed(order):         # reverse postorder
            if node == entry:
                continue
            new_idom: Optional[int] = None
            for pred in preds[node]:
                if idom[pred] is None:
                    continue                 # not processed / unreachable
                new_idom = pred if new_idom is None \
                    else intersect(pred, new_idom)
            if new_idom is not None and idom[node] != new_idom:
                idom[node] = new_idom
                changed = True
    return idom


def dominator_depth(idom: Sequence[Optional[int]], entry: int) -> List[int]:
    """Depth of every node in the dominator tree (-1 when unreachable)."""
    depth = [-1] * len(idom)
    depth[entry] = 0
    for start in range(len(idom)):
        if depth[start] >= 0 or idom[start] is None:
            continue
        chain = []
        node = start
        while depth[node] < 0 and idom[node] is not None:
            chain.append(node)
            node = idom[node]  # type: ignore[assignment]
        base = depth[node]
        if base < 0:
            continue
        for offset, member in enumerate(reversed(chain), start=1):
            depth[member] = base + offset
    return depth
