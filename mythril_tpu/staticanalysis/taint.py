"""Source->sink taint dataflow over the CFA CFG (the taint pass).

A forward may-taint analysis on top of :mod:`.cfa`'s ``CfaResult``: each
abstract value is a ``(const, taint)`` pair — the cfa constant lattice
joined with a set of source tags — propagated through the same
stack-machine simulation the cfa pass uses, plus three abstract cells
the cfa pass does not track:

* one **memory** summary cell (every MSTORE/*COPY unions in, every
  MLOAD/SHA3 reads it — symbolic offsets make per-offset tracking
  unsound, so one cell over-approximates all of memory);
* a bounded map of **concrete storage slots** (weak updates; reads of a
  tracked slot see its write taints), budgeted by
  ``MYTHRIL_TPU_TAINT_SLOTS``;
* one **symbolic-storage** summary cell for writes through unknown slot
  keys (every SLOAD includes it).

Sources: calldata (CALLDATALOAD/CALLDATACOPY/CALLDATASIZE), CALLER,
ORIGIN, CALLVALUE, block/chain environment opcodes, external-call
returndata, and persistent storage itself (a prior transaction may have
written anything, so SLOAD always carries the STORAGE tag). Storage
write taints are additionally folded back into the entry state and the
fixpoint re-run (``MYTHRIL_TPU_TAINT_MAX_ITERS`` rounds) so
cross-transaction flows — tx1 stores calldata, tx2 jumps on it — show
the original source tag, not just STORAGE.

Soundness invariant (what the module screen relies on):
**an empty taint set means the value is a deterministic function of the
bytecode alone** — every unmodeled opcode pushes the UNKNOWN tag,
untracked stack slots read as fully tainted, and unresolved jump edges
propagate an unknown stack, mirroring the cfa pass's conservative
fan-out. The analysis only ever over-approximates: a sink operand
reported untainted provably cannot depend on attacker input.

Stdlib-only, like the rest of ``staticanalysis/``.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ..ops.opcodes import OPCODES, STACK
from .cfa import (CfaResult, _BINARY_FOLDS, _UNARY_FOLDS, _Underflow,
                  _WORD_MASK, _fold_binary)

log = logging.getLogger(__name__)

# -- the taint lattice ---------------------------------------------------------------

#: source tags; a taint set is a frozenset of these
TAG_CALLDATA = "calldata"      #: CALLDATALOAD / CALLDATACOPY / CALLDATASIZE
TAG_CALLER = "caller"          #: msg.sender
TAG_ORIGIN = "origin"          #: tx.origin
TAG_CALLVALUE = "callvalue"    #: msg.value
TAG_ENV = "env"                #: block/chain environment (TIMESTAMP, NUMBER, ...)
TAG_RETURNDATA = "returndata"  #: external-call return data
TAG_STORAGE = "storage"        #: persistent storage (writable in prior txs)
TAG_UNKNOWN = "unknown"        #: unmodeled opcode / untracked slot

ALL_TAGS = (TAG_CALLDATA, TAG_CALLER, TAG_ORIGIN, TAG_CALLVALUE,
            TAG_ENV, TAG_RETURNDATA, TAG_STORAGE, TAG_UNKNOWN)

Taint = FrozenSet[str]
EMPTY: Taint = frozenset()
TOP: Taint = frozenset(ALL_TAGS)

#: (const, taint): the cfa constant lattice joined with a tag set.
#: Invariant: const is not None => taint == EMPTY (a proven constant is
#: deterministic no matter what its operands were).
Value = Tuple[Optional[int], Taint]

UNKNOWN_VALUE: Value = (None, TOP)


def _mk(const: Optional[int], taint: Taint) -> Value:
    return (const, EMPTY) if const is not None else (None, taint)


def _merge_value(a: Value, b: Value) -> Value:
    const = a[0] if a[0] == b[0] else None
    return _mk(const, a[1] | b[1])


# -- source / effect tables ----------------------------------------------------------

#: opcodes that push one fresh value carrying a fixed tag (popped
#: operands' taints union in on top)
_SOURCE_PUSH = {
    "CALLDATALOAD": TAG_CALLDATA, "CALLDATASIZE": TAG_CALLDATA,
    "CALLER": TAG_CALLER, "ORIGIN": TAG_ORIGIN,
    "CALLVALUE": TAG_CALLVALUE,
    "TIMESTAMP": TAG_ENV, "NUMBER": TAG_ENV, "DIFFICULTY": TAG_ENV,
    "PREVRANDAO": TAG_ENV, "COINBASE": TAG_ENV, "GASLIMIT": TAG_ENV,
    "CHAINID": TAG_ENV, "BASEFEE": TAG_ENV, "BLOCKHASH": TAG_ENV,
    "GAS": TAG_ENV, "GASPRICE": TAG_ENV, "ADDRESS": TAG_ENV,
    "BALANCE": TAG_ENV, "SELFBALANCE": TAG_ENV,
    "EXTCODESIZE": TAG_ENV, "EXTCODEHASH": TAG_ENV,
    "RETURNDATASIZE": TAG_RETURNDATA,
}

#: pure word functions beyond the cfa fold set: output taint is exactly
#: the union of input taints (deterministic in, deterministic out)
_PURE_EXTRA = {"MOD", "SMOD", "SDIV", "ADDMOD", "MULMOD", "EXP",
               "SIGNEXTEND", "SLT", "SGT", "BYTE", "SAR"}

#: external-call family: pushes a RETURNDATA-tagged status word and
#: writes returndata into memory
_CALL_OPS = {"CALL", "CALLCODE", "DELEGATECALL", "STATICCALL"}

#: sink opcodes and how many top-of-stack operands the summary records
#: for each site (operand 0 = top of stack at the site)
SINK_OPERANDS = {
    "JUMP": 1, "JUMPI": 2,            # dest; dest, cond
    "SSTORE": 2,                      # key, value
    "CALL": 3, "CALLCODE": 3,         # gas, to, value
    "DELEGATECALL": 2, "STATICCALL": 2,   # gas, to
    "SELFDESTRUCT": 1,                # beneficiary
    "CREATE": 3, "CREATE2": 4,        # value, offset, length[, salt]
}


@dataclass
class SinkSite:
    """Merged taint verdicts for one sink instruction (may-taint over
    every abstract path reaching it)."""

    pc: int
    op: str
    operand_taint: Tuple[Taint, ...]   #: operand 0 = top of stack

    def to_json(self) -> dict:
        return {"pc": self.pc, "op": self.op,
                "operands": [sorted(t) for t in self.operand_taint]}

    @classmethod
    def from_json(cls, data: dict) -> "SinkSite":
        return cls(pc=int(data["pc"]), op=str(data["op"]),
                   operand_taint=tuple(frozenset(t)
                                       for t in data["operands"]))


@dataclass
class TaintResult:
    """The taint fixpoint for one contract's reachable code."""

    sink_sites: Dict[int, SinkSite]    #: site pc -> merged operand taints
    reachable_ops: FrozenSet[str]      #: opcodes in reachable blocks
    rounds: int                        #: cross-transaction storage rounds run
    converged: bool                    #: False = saturated at the round cap


# -- abstract machine ----------------------------------------------------------------

#: stack half of a block-entry state, mirroring cfa._AbsState: total
#: height (None = unknown) plus the top tracked values, top LAST
_StackState = Tuple[Optional[int], Tuple[Value, ...]]

#: full block-entry state: stack, memory cell, storage slots, symbolic
#: storage cell
_State = Tuple[_StackState, Taint, Dict[int, Taint], Taint]

_UNKNOWN_STACK: _StackState = (None, ())


def _merge_stack(a: _StackState, b: _StackState) -> _StackState:
    height = a[0] if a[0] == b[0] else None
    vals_a, vals_b = a[1], b[1]
    keep = min(len(vals_a), len(vals_b))
    merged = tuple(
        _merge_value(x, y)
        for x, y in zip(vals_a[len(vals_a) - keep:],
                        vals_b[len(vals_b) - keep:]))
    return (height, merged)


def _merge_store(a: Dict[int, Taint], b: Dict[int, Taint]) -> Dict[int, Taint]:
    out = dict(a)
    for slot, taint in b.items():
        out[slot] = out.get(slot, EMPTY) | taint
    return out


def _merge_state(a: _State, b: _State) -> _State:
    return (_merge_stack(a[0], b[0]), a[1] | b[1],
            _merge_store(a[2], b[2]), a[3] | b[3])


class _TStack:
    """Mutable (const, taint) stack for simulating one block; slots below
    the tracked window read as fully tainted (UNKNOWN_VALUE)."""

    __slots__ = ("vals", "below", "tracked")

    def __init__(self, state: _StackState, tracked: int):
        height, vals = state
        self.vals: List[Value] = list(vals)
        self.below: Optional[int] = None if height is None \
            else height - len(vals)
        self.tracked = tracked

    def pop(self) -> Value:
        if self.vals:
            return self.vals.pop()
        if self.below is None:
            return UNKNOWN_VALUE
        if self.below <= 0:
            raise _Underflow
        self.below -= 1
        return UNKNOWN_VALUE

    def push(self, value: Value) -> None:
        self.vals.append(value)
        if len(self.vals) > self.tracked:
            del self.vals[0]
            if self.below is not None:
                self.below += 1

    def peek(self, depth: int) -> Value:
        if depth < len(self.vals):
            return self.vals[-1 - depth]
        if self.below is not None and self.below < depth - len(self.vals) + 1:
            raise _Underflow
        return UNKNOWN_VALUE

    def swap(self, depth: int) -> None:
        while len(self.vals) <= depth:
            if self.below is not None:
                if self.below <= 0:
                    raise _Underflow
                self.below -= 1
            self.vals.insert(0, UNKNOWN_VALUE)
        self.vals[-1], self.vals[-1 - depth] = \
            self.vals[-1 - depth], self.vals[-1]

    def state(self) -> _StackState:
        height = None if self.below is None else self.below + len(self.vals)
        return (height, tuple(self.vals))


def _simulate(block, instructions, entry: _State, tracked: int,
              slot_budget: int, sink_cb=None) -> _State:
    """Abstractly execute one block under `entry`, returning the exit
    state (terminator stack effects included, control effects not).
    `sink_cb(pc, op, operands)` observes each sink site's operand values
    before the op consumes them. Raises _Underflow on a provable
    underflow of a known-height stack (the block throws)."""
    stack = _TStack(entry[0], tracked)
    mem: Taint = entry[1]
    store: Dict[int, Taint] = dict(entry[2])
    sym: Taint = entry[3]

    for index in range(block.first_index, block.last_index + 1):
        ins = instructions[index]
        op = ins.op_code
        if sink_cb is not None and op in SINK_OPERANDS:
            try:
                operands = tuple(stack.peek(i)
                                 for i in range(SINK_OPERANDS[op]))
            except _Underflow:
                pass  # the site throws before executing; pops raise below
            else:
                sink_cb(ins.address, op, operands)
        if op.startswith("PUSH"):
            if op == "PUSH0":
                stack.push((0, EMPTY))
            else:
                try:
                    stack.push((int(ins.argument, 16) if ins.argument
                                else 0, EMPTY))
                except ValueError:
                    stack.push((None, EMPTY))  # truncated push: still fixed
        elif op.startswith("DUP"):
            stack.push(stack.peek(int(op[3:]) - 1))
        elif op.startswith("SWAP"):
            stack.swap(int(op[4:]))
        elif op == "POP":
            stack.pop()
        elif op == "PC":
            stack.push((ins.address, EMPTY))
        elif op == "JUMPDEST":
            pass
        elif op == "JUMP":
            stack.pop()
        elif op == "JUMPI":
            stack.pop()
            stack.pop()
        elif op in _UNARY_FOLDS:
            const, taint = stack.pop()
            if const is None:
                stack.push((None, taint))
            elif op == "ISZERO":
                stack.push((int(const == 0), EMPTY))
            else:  # NOT
                stack.push((~const & _WORD_MASK, EMPTY))
        elif op in _BINARY_FOLDS:
            a, b = stack.pop(), stack.pop()
            stack.push(_mk(_fold_binary(op, a[0], b[0]), a[1] | b[1]))
        elif op in _PURE_EXTRA:
            pops, _ = OPCODES[op][STACK]
            taint = EMPTY
            for _ in range(pops):
                taint |= stack.pop()[1]
            stack.push((None, taint))
        elif op in _SOURCE_PUSH:
            pops, _ = OPCODES[op][STACK]
            taint = frozenset((_SOURCE_PUSH[op],))
            for _ in range(pops):
                taint |= stack.pop()[1]
            stack.push((None, taint))
        elif op == "SHA3":
            a, b = stack.pop(), stack.pop()
            stack.push((None, mem | a[1] | b[1]))
        elif op == "MLOAD":
            off = stack.pop()
            stack.push((None, mem | off[1]))
        elif op in ("MSTORE", "MSTORE8"):
            off, val = stack.pop(), stack.pop()
            mem |= off[1] | val[1]
        elif op in ("CALLDATACOPY", "RETURNDATACOPY", "CODECOPY",
                    "EXTCODECOPY", "MCOPY"):
            pops, _ = OPCODES[op][STACK]
            taint = EMPTY
            for _ in range(pops):
                taint |= stack.pop()[1]
            if op == "CALLDATACOPY":
                taint |= frozenset((TAG_CALLDATA,))
            elif op == "RETURNDATACOPY":
                taint |= frozenset((TAG_RETURNDATA,))
            elif op == "EXTCODECOPY":
                taint |= frozenset((TAG_ENV,))
            # CODECOPY copies deterministic bytes; MCOPY shuffles what
            # memory already holds — offsets still union in
            mem |= taint
        elif op == "SLOAD":
            key = stack.pop()
            base = sym | frozenset((TAG_STORAGE,)) | key[1]
            if key[0] is not None:
                stack.push((None, base | store.get(key[0], EMPTY)))
            else:
                everything = EMPTY
                for taint in store.values():
                    everything |= taint
                stack.push((None, base | everything))
        elif op == "SSTORE":
            key, val = stack.pop(), stack.pop()
            written = val[1] | key[1]
            if key[0] is not None and (key[0] in store
                                       or len(store) < slot_budget):
                store[key[0]] = store.get(key[0], EMPTY) | written
            else:
                sym |= written
        elif op in _CALL_OPS:
            pops, _ = OPCODES[op][STACK]
            for _ in range(pops):
                stack.pop()
            mem |= frozenset((TAG_RETURNDATA,))
            stack.push((None, frozenset((TAG_RETURNDATA,))))
        elif op in ("CREATE", "CREATE2"):
            pops, _ = OPCODES[op][STACK]
            for _ in range(pops):
                stack.pop()
            stack.push((None, frozenset((TAG_RETURNDATA,))))
        elif op in OPCODES:
            pops, pushes = OPCODES[op][STACK]
            for _ in range(pops):
                stack.pop()
            for _ in range(pushes):
                stack.push((None, frozenset((TAG_UNKNOWN,))))
        else:
            # unassigned opcode: throws; block construction already made
            # it a terminator
            break
    return (stack.state(), mem, store, sym)


# -- the fixpoint --------------------------------------------------------------------

def _run_fixpoint(cfa: CfaResult, instructions, tracked: int,
                  slot_budget: int, entry_store: Dict[int, Taint],
                  entry_sym: Taint) -> Optional[Dict[int, _State]]:
    """One intra-transaction fixpoint over the CFA CFG, starting from an
    empty stack/memory and the given cross-round storage state. Returns
    block id -> entry state, or None if the (defensively capped)
    iteration budget blows."""
    blocks = cfa.blocks
    unresolved = set(cfa.unresolved_jumps)
    entry_states: Dict[int, _State] = {
        0: ((0, ()), EMPTY, dict(entry_store), entry_sym)}
    worklist = [0]

    def propagate(target: int, state: _State) -> None:
        old = entry_states.get(target)
        new = state if old is None else _merge_state(old, state)
        if new != old:
            entry_states[target] = new
            if target not in worklist:
                worklist.append(target)

    iterations = 0
    iteration_cap = max(64, 8 * len(blocks) * (tracked + 2))
    while worklist:
        iterations += 1
        if iterations > iteration_cap:
            log.warning("taint: dataflow did not converge in %d iterations "
                        "— skipping taint analysis", iteration_cap)
            return None
        block = blocks[worklist.pop()]
        entry = entry_states[block.block_id]
        try:
            exit_state = _simulate(block, instructions, entry, tracked,
                                   slot_budget)
        except _Underflow:
            continue  # provable throw; cfa routed the edge to exit
        term = block.terminator
        next_id = block.block_id + 1 if block.block_id + 1 < len(blocks) \
            else cfa.exit_id
        if term in ("JUMP", "JUMPI") \
                and instructions[block.last_index].address in unresolved:
            # mirror the cfa fan-out: jump successors get an unknown
            # stack (the dynamic dest could arrive at any height), but
            # memory/storage flow through untouched
            fanned = (_UNKNOWN_STACK,) + exit_state[1:]
            for succ in block.successors:
                if succ == cfa.exit_id:
                    continue
                if term == "JUMPI" and succ == next_id:
                    propagate(succ, exit_state)
                else:
                    propagate(succ, fanned)
        else:
            for succ in block.successors:
                if succ != cfa.exit_id:
                    propagate(succ, exit_state)
    return entry_states


def build_taint(cfa: CfaResult, instructions,
                tracked_depth: Optional[int] = None,
                max_iters: Optional[int] = None,
                slot_budget: Optional[int] = None) -> Optional[TaintResult]:
    """Run the taint pass over an existing ``CfaResult``.

    Returns None when the dataflow blows its defensive iteration cap
    (consumers treat None as "no verdict")."""
    from ..support import tpu_config

    if tracked_depth is None:
        tracked_depth = tpu_config.get_int("MYTHRIL_TPU_CFA_STACK_DEPTH")
    if max_iters is None:
        max_iters = tpu_config.get_int("MYTHRIL_TPU_TAINT_MAX_ITERS")
    if slot_budget is None:
        slot_budget = tpu_config.get_int("MYTHRIL_TPU_TAINT_SLOTS")
    max_iters = max(1, max_iters)

    # cross-transaction rounds: fold every round's storage writes back
    # into the entry storage until stable (or saturate at the cap)
    entry_store: Dict[int, Taint] = {}
    entry_sym: Taint = EMPTY
    entry_states: Optional[Dict[int, _State]] = None
    converged = False
    rounds = 0
    while rounds < max_iters:
        rounds += 1
        entry_states = _run_fixpoint(cfa, instructions, tracked_depth,
                                     slot_budget, entry_store, entry_sym)
        if entry_states is None:
            return None
        next_store, next_sym = dict(entry_store), entry_sym
        for block in cfa.blocks:
            if block.block_id not in entry_states:
                continue
            try:
                _, _, store, sym = _simulate(
                    block, instructions, entry_states[block.block_id],
                    tracked_depth, slot_budget)
            except _Underflow:
                continue
            next_store = _merge_store(next_store, store)
            next_sym |= sym
        if next_store == entry_store and next_sym == entry_sym:
            converged = True
            break
        entry_store, entry_sym = next_store, next_sym
    if not converged:
        # round cap hit: saturate storage so the final pass stays sound
        entry_sym = TOP
        entry_states = _run_fixpoint(cfa, instructions, tracked_depth,
                                     slot_budget, entry_store, entry_sym)
        if entry_states is None:
            return None

    # final pass: record per-sink-site operand taints under the fixpoint
    sink_sites: Dict[int, SinkSite] = {}

    def record(pc: int, op: str, operands: Tuple[Value, ...]) -> None:
        taints = tuple(_mk(*v)[1] for v in operands)
        known = sink_sites.get(pc)
        if known is None:
            sink_sites[pc] = SinkSite(pc=pc, op=op, operand_taint=taints)
        else:
            sink_sites[pc] = SinkSite(
                pc=pc, op=op, operand_taint=tuple(
                    a | b for a, b in zip(known.operand_taint, taints)))

    reachable_ops: Set[str] = set()
    for block in cfa.blocks:
        if block.block_id not in entry_states:
            continue
        for index in range(block.first_index, block.last_index + 1):
            reachable_ops.add(instructions[index].op_code)
        try:
            _simulate(block, instructions, entry_states[block.block_id],
                      tracked_depth, slot_budget, sink_cb=record)
        except _Underflow:
            pass

    return TaintResult(sink_sites=sink_sites,
                       reachable_ops=frozenset(reachable_ops),
                       rounds=rounds, converged=converged)
