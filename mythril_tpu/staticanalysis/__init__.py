"""Static control-flow analysis (cfa) of EVM bytecode.

Stdlib-only: block recovery, jump-target resolution via abstract
stack/constant dataflow, CFG + dominator/post-dominator trees, and the
dense device-consumable tables (pc->block, merge-pc, refined JUMPDEST
bitmap, dead-code mask) that frontier pruning and on-device state
merging (ROADMAP item 3) consume.

Entry point for consumers: :func:`get_cfa` — memoized per Disassembly,
returns None when analysis is disabled or bails (over the block budget),
in which case callers keep their dynamic paths.

On top of the cfa tables, :mod:`.taint` + :mod:`.summary` add a
source->sink taint dataflow, selector/function partitioning, and
natural-loop hint tables; :func:`get_summary` is the memoized entry
point with the same None-means-no-verdict contract. :mod:`.absint`
adds the value-range / memory-region abstract interpretation (interval
stack cells, diamond write regions, proven loop bounds, constant-JUMPI
verdicts) behind :func:`get_absint`, same contract again.
"""

from __future__ import annotations

from typing import Optional

from .absint import AbsintResult, build_absint
from .cfa import BasicBlock, CfaResult, TERMINATORS, build_cfa
from .domtree import compute_idoms, dominator_depth, postorder
from .summary import ContractSummary, FunctionInfo, LoopInfo, build_summary
from .taint import SinkSite, TaintResult, build_taint

__all__ = [
    "AbsintResult",
    "BasicBlock",
    "CfaResult",
    "ContractSummary",
    "FunctionInfo",
    "LoopInfo",
    "SinkSite",
    "TERMINATORS",
    "TaintResult",
    "build_absint",
    "build_cfa",
    "build_summary",
    "build_taint",
    "compute_idoms",
    "dominator_depth",
    "get_absint",
    "get_cfa",
    "get_summary",
    "install_summary",
    "postorder",
]

_MISS = object()  # memo sentinel: distinguishes "not built" from "bailed"


def get_cfa(disassembly) -> Optional[CfaResult]:
    """Build (once) and return the CFA tables for a Disassembly.

    Memoized on the Disassembly instance itself (`_cfa_result`), so every
    consumer of the same contract shares one build. Returns None when the
    pass is disabled via MYTHRIL_TPU_CFA or bailed out; the None verdict
    is memoized too, so a bailing contract pays the bail check once.
    """
    from ..observe import metrics, trace
    from ..support import tpu_config

    cached = getattr(disassembly, "_cfa_result", _MISS)
    if cached is not _MISS:
        return cached

    if not tpu_config.get_flag("MYTHRIL_TPU_CFA"):
        disassembly._cfa_result = None
        return None

    with trace.span("cfa.build") as span:
        result = build_cfa(disassembly)
        if result is None:
            span.set(bailed=True)
        else:
            span.set(
                blocks=len(result.blocks),
                edges=result.n_edges,
                resolved=len(result.jump_targets),
                unresolved=len(result.unresolved_jumps),
                merge_points=len(result.merge_points),
            )
            metrics.inc("cfa.blocks", len(result.blocks))
            metrics.inc("cfa.jumps_resolved", len(result.jump_targets))
            metrics.inc("cfa.jumps_unresolved",
                        len(result.unresolved_jumps))
            metrics.inc("cfa.merge_points", len(result.merge_points))
            metrics.inc("cfa.dead_bytes", result.dead_bytes)
    disassembly._cfa_result = result
    return result


def get_summary(disassembly) -> Optional[ContractSummary]:
    """Build (once) and return the taint/function/loop summary for a
    Disassembly.

    Memoized on the Disassembly instance (`_taint_summary`), like
    :func:`get_cfa`. Returns None when MYTHRIL_TPU_TAINT is off, the cfa
    tables are unavailable, or the taint fixpoint bailed — consumers
    treat None as "no verdict" and keep their dynamic paths.
    """
    from ..observe import metrics, trace
    from ..support import tpu_config

    cached = getattr(disassembly, "_taint_summary", _MISS)
    if cached is not _MISS:
        return cached

    if not tpu_config.get_flag("MYTHRIL_TPU_TAINT"):
        disassembly._taint_summary = None
        return None

    cfa = get_cfa(disassembly)
    if cfa is None:
        disassembly._taint_summary = None
        return None

    with trace.span("taint.build") as span:
        result = build_summary(disassembly, cfa)
        if result is None:
            span.set(bailed=True)
        else:
            span.set(
                functions=len(result.functions),
                loops=len(result.loops),
                sinks=len(result.sink_sites),
                rounds=result.rounds,
            )
            metrics.inc("taint.functions", len(result.functions))
            metrics.inc("taint.loops", len(result.loops))
    disassembly._taint_summary = result
    return result


def install_summary(disassembly, summary: Optional[ContractSummary]) -> None:
    """Pre-seed the summary memo (serve warm path: summaries persisted by
    code hash skip the rebuild on repeat contracts)."""
    disassembly._taint_summary = summary


def get_absint(disassembly) -> Optional[AbsintResult]:
    """Build (once) and return the value-range/memory-region tables for
    a Disassembly.

    Memoized on the Disassembly instance (`_absint_result`), like
    :func:`get_cfa`. Returns None when MYTHRIL_TPU_ABSINT is off, the
    cfa tables are unavailable, or the fixpoint bailed — consumers
    treat None as "no verdict" and keep their dynamic paths.
    """
    import time

    from ..observe import metrics, trace
    from ..support import tpu_config

    cached = getattr(disassembly, "_absint_result", _MISS)
    if cached is not _MISS:
        return cached

    if not tpu_config.get_flag("MYTHRIL_TPU_ABSINT"):
        disassembly._absint_result = None
        return None

    cfa = get_cfa(disassembly)
    if cfa is None:
        disassembly._absint_result = None
        return None

    with trace.span("absint.build") as span:
        start = time.perf_counter()
        result = build_absint(disassembly, cfa)
        if result is None:
            span.set(bailed=True)
        else:
            span.set(
                blocks=len(result.entry_intervals),
                widenings=result.widenings,
                regions=result.regions_proven,
                loop_bounds=len(result.loop_bounds),
                const_jumpis=len(result.const_jumpis),
            )
            metrics.observe(
                "absint.build_ms",
                (time.perf_counter() - start) * 1000.0)
            metrics.inc("absint.widenings", result.widenings)
            metrics.inc("absint.regions_proven", result.regions_proven)
    disassembly._absint_result = result
    return result
