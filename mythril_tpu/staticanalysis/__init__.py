"""Static control-flow analysis (cfa) of EVM bytecode.

Stdlib-only: block recovery, jump-target resolution via abstract
stack/constant dataflow, CFG + dominator/post-dominator trees, and the
dense device-consumable tables (pc->block, merge-pc, refined JUMPDEST
bitmap, dead-code mask) that frontier pruning and on-device state
merging (ROADMAP item 3) consume.

Entry point for consumers: :func:`get_cfa` — memoized per Disassembly,
returns None when analysis is disabled or bails (over the block budget),
in which case callers keep their dynamic paths.
"""

from __future__ import annotations

from typing import Optional

from .cfa import BasicBlock, CfaResult, TERMINATORS, build_cfa
from .domtree import compute_idoms, dominator_depth, postorder

__all__ = [
    "BasicBlock",
    "CfaResult",
    "TERMINATORS",
    "build_cfa",
    "compute_idoms",
    "dominator_depth",
    "postorder",
    "get_cfa",
]

_MISS = object()  # memo sentinel: distinguishes "not built" from "bailed"


def get_cfa(disassembly) -> Optional[CfaResult]:
    """Build (once) and return the CFA tables for a Disassembly.

    Memoized on the Disassembly instance itself (`_cfa_result`), so every
    consumer of the same contract shares one build. Returns None when the
    pass is disabled via MYTHRIL_TPU_CFA or bailed out; the None verdict
    is memoized too, so a bailing contract pays the bail check once.
    """
    from ..observe import metrics, trace
    from ..support import tpu_config

    cached = getattr(disassembly, "_cfa_result", _MISS)
    if cached is not _MISS:
        return cached

    if not tpu_config.get_flag("MYTHRIL_TPU_CFA"):
        disassembly._cfa_result = None
        return None

    with trace.span("cfa.build") as span:
        result = build_cfa(disassembly)
        if result is None:
            span.set(bailed=True)
        else:
            span.set(
                blocks=len(result.blocks),
                edges=result.n_edges,
                resolved=len(result.jump_targets),
                unresolved=len(result.unresolved_jumps),
                merge_points=len(result.merge_points),
            )
            metrics.inc("cfa.blocks", len(result.blocks))
            metrics.inc("cfa.jumps_resolved", len(result.jump_targets))
            metrics.inc("cfa.jumps_unresolved",
                        len(result.unresolved_jumps))
            metrics.inc("cfa.merge_points", len(result.merge_points))
            metrics.inc("cfa.dead_bytes", result.dead_bytes)
    disassembly._cfa_result = result
    return result
