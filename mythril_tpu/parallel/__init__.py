"""TPU execution lane: batched lockstep EVM interpretation + batched solving.

This package is the reason the framework exists (SURVEY.md §2.3, §7 stages 7-9):
the per-state worklist of the host engine (`core/svm.py`) becomes a dense,
padded StateBatch pytree stepped in lockstep by one jitted function, sharded
over a `jax.sharding.Mesh` for multi-chip scale.

Modules:
  words     — 256-bit EVM words as 16x16-bit limbs in uint32 (native TPU lanes)
  keccak    — batched keccak-256 sponge entirely on device
  batch     — the StateBatch structure-of-arrays pytree + host converters
  concrete  — the lockstep concrete interpreter (conformance + concolic replay)
  jax_solver— batched CNF unit-propagation/DPLL over dense clause matrices
  frontier  — symbolic frontier stepping (mask-fork JUMPI, lane compaction)

Everything here is JAX; `jax_enable_x64` is switched on at import because gas
counters exceed 2^32 (word arithmetic itself never needs 64-bit lanes).
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

# The lockstep step function is a large graph (division ladders, keccak rounds)
# that takes ~2 min to compile on a remote-compile TPU path; persist compiled
# executables so repeat runs (bench, CLI) skip straight to execution.


def _enable_persistent_cache() -> None:
    from ..support import tpu_config

    cache_dir = tpu_config.get_str(
        "MYTHRIL_TPU_JAX_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "mythril_tpu_jax"))
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache EVERY executable: the frontier's service helpers (row gather/
        # scatter, arena-delta fetch) compile per power-of-two bucket shape,
        # and each sub-2s compile re-paid on every process added up to
        # ~20s/run on the remote-TPU path
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:  # cache is an optimization, never a hard requirement
        pass  # allowlisted in tools/check_excepts.py


_enable_persistent_cache()

# mesh plumbing re-export: the validated logical-shard count used by the
# frontier (lane-axis blocks), the fleet driver and the serve capacity math
from .batch import shard_count  # noqa: E402,F401
