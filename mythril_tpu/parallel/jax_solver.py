"""Batched SAT on device: lockstep DPLL over a dense clause matrix.

This is the `--solver jax` backend (SURVEY §7 stage 8). The CNF comes from the
same Tseitin bit-blaster that feeds the native CDCL core
(smt/solver/bitblast.py — every gate clause has <= 3 literals, so the dense
clause matrix is [n_clauses, 3] int32 with 0 padding), and verdicts are
differentially tested against it.

Search shape (cube-and-conquer in lockstep): P probe lanes each run complete
chronological-backtracking DPLL, with their first `log2(P)` decision phases
forced to the bits of the lane index. Decision-variable selection is a
deterministic function of the assignment (static frequency order), so the
forced prefixes form a perfect binary tree of subspaces: UNSAT iff every lane
proves its cube UNSAT, SAT as soon as one lane completes an assignment —
sound and complete, and every lane's unit propagation is one dense
[P, C, 3] gather/compare that maps straight onto the TPU vector units.

Model extraction returns the satisfying lane's assignment, consumed by
smt/solver/solver.py exactly like a CDCL model.

Termination: a step budget bounds device time; still-searching lanes at the
budget yield "unknown" and the caller falls back to the native CDCL core.
"""

from __future__ import annotations

from functools import partial
from typing import List, NamedTuple, Optional, Tuple

import numpy as np

SAT, UNSAT, UNKNOWN = 1, 0, -1

# probe status
SEARCHING, S_SAT, S_UNSAT = 0, 1, 2


class _Problem(NamedTuple):
    lits: "jnp.ndarray"      # int32[C, L] DIMACS literals, 0-padded
    order: "jnp.ndarray"     # int32[V+1] decision rank per var (lower = earlier)
    n_vars: int


class _SolverState(NamedTuple):
    assign: "jnp.ndarray"     # int8[P, V+1]: 0 unassigned, 1 true, 2 false
    trail: "jnp.ndarray"      # int32[P, V+1] literals in assignment order
    tag: "jnp.ndarray"        # int8[P, V+1]: 0 implied, 1 decision, 2 exhausted
    trail_len: "jnp.ndarray"  # int32[P]
    status: "jnp.ndarray"     # int8[P]


def _build_problem(clauses: List[List[int]], n_vars: int,
                   max_len: int = 3) -> _Problem:
    import jax.numpy as jnp

    long_clauses = [c for c in clauses if len(c) > max_len]
    if long_clauses:
        # split long clauses with fresh connector variables (rare: the blaster
        # emits <=3-literal gate clauses; asserts are units)
        rebuilt = []
        for clause in clauses:
            while len(clause) > max_len:
                n_vars += 1
                rebuilt.append(clause[:max_len - 1] + [n_vars])
                clause = [-n_vars] + clause[max_len - 1:]
            rebuilt.append(clause)
        clauses = rebuilt

    lits = np.zeros((max(1, len(clauses)), max_len), dtype=np.int32)
    for i, clause in enumerate(clauses):
        lits[i, :len(clause)] = clause

    counts = np.zeros(n_vars + 1, dtype=np.int64)
    for clause in clauses:
        for lit in clause:
            counts[abs(lit)] += 1
    order = np.zeros(n_vars + 1, dtype=np.int32)
    by_freq = np.argsort(-counts[1:], kind="stable") + 1
    order[by_freq] = np.arange(1, n_vars + 1, dtype=np.int32)
    order[0] = n_vars + 2  # var 0 never decided
    return _Problem(jnp.asarray(lits), jnp.asarray(order), n_vars)


def make_stepper(problem: _Problem, forced_depth: int):
    """Build the jitted single-step transition for this problem."""
    import jax
    import jax.numpy as jnp

    lits, order = problem.lits, problem.order

    def step(state: _SolverState) -> _SolverState:
        n_probes, v1 = state.assign.shape
        searching = state.status == SEARCHING
        probe_idx = jnp.arange(n_probes)[:, None]

        var = jnp.abs(lits)
        is_pos = lits > 0
        is_pad = lits == 0
        av = state.assign[:, var]
        val_true = jnp.where(is_pos, av == 1, av == 2) & ~is_pad
        val_unassigned = (av == 0) & ~is_pad
        clause_sat = jnp.any(val_true, axis=-1)
        n_un = jnp.sum(val_unassigned, axis=-1)
        conflict = jnp.any(~clause_sat & (n_un == 0), axis=-1)
        unit_clause = ~clause_sat & (n_un == 1)
        has_units = jnp.any(unit_clause, axis=-1)

        # ---- branch 1: assert all unit literals -------------------------------------
        unit_slot = jnp.argmax(val_unassigned, axis=-1)
        unit_lit = jnp.take_along_axis(
            jnp.broadcast_to(lits, (n_probes,) + lits.shape),
            unit_slot[..., None], axis=-1)[..., 0]
        unit_lit = jnp.where(unit_clause, unit_lit, 0)
        unit_var = jnp.abs(unit_lit)
        unit_phase = jnp.where(unit_lit > 0, 1, 2).astype(jnp.int8)
        u_assign = state.assign.at[probe_idx, unit_var].set(
            jnp.where(unit_clause, unit_phase,
                      state.assign[probe_idx, unit_var]))
        u_assign = u_assign.at[:, 0].set(0)
        newly = (u_assign != state.assign) & (u_assign != 0)
        new_rank = jnp.cumsum(newly, axis=-1) - 1
        append_pos = jnp.clip(state.trail_len[:, None] + new_rank, 0, v1 - 1)
        signed = jnp.where(u_assign == 1, 1, -1) * jnp.arange(v1)
        u_trail = state.trail.at[probe_idx, append_pos].set(
            jnp.where(newly, signed.astype(jnp.int32),
                      state.trail[probe_idx, append_pos]))
        u_tag = state.tag.at[probe_idx, append_pos].set(
            jnp.where(newly, jnp.int8(0), state.tag[probe_idx, append_pos]))
        u_len = state.trail_len + jnp.sum(newly, axis=-1).astype(jnp.int32)

        # ---- branch 2: backtrack ----------------------------------------------------
        pos = jnp.arange(v1)[None, :]
        in_trail = pos < state.trail_len[:, None]
        flippable = (state.tag == 1) & in_trail
        has_flip = jnp.any(flippable, axis=-1)
        flip_pos = (v1 - 1) - jnp.argmax(flippable[:, ::-1], axis=-1)
        flip_pos = jnp.where(has_flip, flip_pos, 0).astype(jnp.int32)
        # unassign everything at positions > flip_pos
        kill = in_trail & (pos > flip_pos[:, None])
        kill_var = jnp.abs(state.trail)
        b_assign = state.assign.at[probe_idx, jnp.where(kill, kill_var, 0)].set(
            jnp.where(kill, jnp.int8(0),
                      state.assign[probe_idx, jnp.where(kill, kill_var, 0)]))
        b_assign = b_assign.at[:, 0].set(0)
        # flip the decision literal in place, now exhausted
        flip_lit = jnp.take_along_axis(state.trail, flip_pos[:, None],
                                       axis=-1)[:, 0]
        flip_var = jnp.abs(flip_lit)
        new_phase = jnp.where(flip_lit > 0, 2, 1).astype(jnp.int8)  # opposite
        b_assign = b_assign.at[jnp.arange(n_probes), flip_var].set(
            jnp.where(has_flip, new_phase,
                      b_assign[jnp.arange(n_probes), flip_var]))
        b_trail = state.trail.at[jnp.arange(n_probes), flip_pos].set(-flip_lit)
        b_tag = state.tag.at[jnp.arange(n_probes), flip_pos].set(2)
        b_len = jnp.where(has_flip, flip_pos + 1, state.trail_len)
        b_status = jnp.where(has_flip, jnp.int8(SEARCHING), jnp.int8(S_UNSAT))

        # ---- branch 3: decide -------------------------------------------------------
        free = state.assign == 0
        free = free.at[:, 0].set(False)
        any_free = jnp.any(free, axis=-1)
        pick_rank = jnp.where(free, order[None, :], jnp.int32(1 << 30))
        d_var = jnp.argmin(pick_rank, axis=-1).astype(jnp.int32)
        level = jnp.sum((state.tag >= 1) & in_trail, axis=-1)
        in_prefix = level < forced_depth
        probe_bit = (jnp.arange(n_probes) >> jnp.clip(level, 0, 30)) & 1
        d_phase_true = jnp.where(in_prefix, probe_bit == 1, False)
        d_assign_val = jnp.where(d_phase_true, jnp.int8(1), jnp.int8(2))
        d_tag_val = jnp.where(in_prefix, jnp.int8(2), jnp.int8(1))
        d_lit = jnp.where(d_phase_true, d_var, -d_var)
        d_assign = state.assign.at[jnp.arange(n_probes), d_var].set(d_assign_val)
        d_pos = jnp.clip(state.trail_len, 0, v1 - 1)
        d_trail = state.trail.at[jnp.arange(n_probes), d_pos].set(d_lit)
        d_tag = state.tag.at[jnp.arange(n_probes), d_pos].set(d_tag_val)
        d_len = state.trail_len + 1

        # ---- combine: conflict > units > all-assigned(SAT) > decide -----------------
        take_b = searching & conflict
        take_u = searching & ~conflict & has_units
        take_sat = searching & ~conflict & ~has_units & ~any_free
        take_d = searching & ~conflict & ~has_units & any_free

        def mix(bt, un, de, old):
            m_b, m_u, m_d = take_b, take_u, take_d
            while m_b.ndim < bt.ndim:
                m_b, m_u, m_d = m_b[..., None], m_u[..., None], m_d[..., None]
            out = jnp.where(m_b, bt, old)
            out = jnp.where(m_u, un, out)
            return jnp.where(m_d, de, out)

        assign = mix(b_assign, u_assign, d_assign, state.assign)
        trail = mix(b_trail, u_trail, d_trail, state.trail)
        tag = mix(b_tag, u_tag, d_tag, state.tag)
        trail_len = mix(b_len, u_len, d_len, state.trail_len)
        status = jnp.where(take_b, b_status, state.status)
        status = jnp.where(take_sat, jnp.int8(S_SAT), status)
        return _SolverState(assign, trail, tag, trail_len, status)

    return step


def solve_cnf_device(clauses: List[List[int]], n_vars: int,
                     n_probes: int = 32, max_steps: int = 20_000,
                     chunk: int = 256
                     ) -> Tuple[int, Optional[List[bool]]]:
    """Solve CNF on the JAX backend. Same contract as sat.solve_cnf:
    (status, model) with model[v-1] the value of DIMACS var v."""
    import jax
    import jax.numpy as jnp

    for clause in clauses:
        if not clause:
            return UNSAT, None

    problem = _build_problem(clauses, n_vars)
    n_vars = problem.n_vars
    forced_depth = max(0, int(np.log2(max(1, n_probes))))
    step = make_stepper(problem, forced_depth)

    v1 = n_vars + 1
    state = _SolverState(
        assign=jnp.zeros((n_probes, v1), dtype=jnp.int8),
        trail=jnp.zeros((n_probes, v1), dtype=jnp.int32),
        tag=jnp.zeros((n_probes, v1), dtype=jnp.int8),
        trail_len=jnp.zeros(n_probes, dtype=jnp.int32),
        status=jnp.zeros(n_probes, dtype=jnp.int8),
    )

    @partial(jax.jit, static_argnames=("n",))
    def run_chunk(s, n):
        return jax.lax.fori_loop(
            0, n, lambda _, st: step(st), s)

    steps = 0
    while steps < max_steps:
        state = run_chunk(state, chunk)
        steps += chunk
        status = np.asarray(state.status)
        if (status == S_SAT).any() or (status != SEARCHING).all():
            break

    status = np.asarray(state.status)
    sat_lanes = np.nonzero(status == S_SAT)[0]
    if len(sat_lanes):
        assign = np.asarray(state.assign[int(sat_lanes[0])])
        return SAT, [bool(assign[v] == 1) for v in range(1, n_vars + 1)]
    if (status == S_UNSAT).all():
        return UNSAT, None
    return UNKNOWN, None
