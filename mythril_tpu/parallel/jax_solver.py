"""Batched SAT on device: lockstep DPLL over a tiled clause matrix.

This is the `--solver jax` backend (SURVEY §7 stage 8). The CNF comes from the
same Tseitin bit-blaster that feeds the native CDCL core
(smt/solver/bitblast.py — every gate clause has <= 3 literals, so the dense
clause matrix is [n_clauses, 3] int32 with 0 padding), and verdicts are
differentially tested against it (tests/test_jax_solver.py replays real
queries captured from analyses through both backends).

Search shape (cube-and-conquer in lockstep): P probe lanes each run complete
chronological-backtracking DPLL, with their first `log2(P)` decision phases
forced to the bits of the lane index. Decision-variable selection is a
deterministic function of the assignment (static frequency order), so the
forced prefixes form a perfect binary tree of subspaces: UNSAT iff every lane
proves its cube UNSAT, SAT as soon as one lane completes an assignment —
sound and complete.

Unit propagation is tiled: the clause matrix is reshaped to
[n_tiles, TILE, 3] and scanned tile-by-tile, so device memory per step is
O(P * TILE) regardless of clause count (a single monolithic [P, C, 3] gather
killed the TPU worker on realistic bit-blasted queries — a 256-bit multiply
alone emits ~1e5 clauses). Problems above `clause_cap` return UNKNOWN
immediately; the caller falls back to the native CDCL core and counts the
event (SolverStatistics.device_fallbacks) so the fallback is never silent.

Shapes are bucketed to powers of two (variables and clause tiles) and the
problem tensors are *arguments* of one module-cached jitted runner, so
successive queries of similar size reuse the compiled executable — path
constraints grow a conjunct at a time, and per-query recompilation would
dwarf the solve itself.

Bucketing comes in two schemes (MYTHRIL_TPU_BUCKET_SCHEME): the default
``coarse`` scheme rounds clause tiles, the variable axis, and the batch
query axis to powers of FOUR (with a variable-axis floor), trading up to
4x padded compute per step for a warm set small enough that `myth-tpu
serve` can pre-compile every hot bucket at startup; ``fine`` keeps the
original per-pow2 buckets for A/B measurement. The serve warm hooks at
the bottom (observed_shape_keys / warm_shape_key) export and replay the
shape keys this process has compiled.

Model extraction returns the satisfying lane's assignment, consumed by
smt/solver/solver.py exactly like a CDCL model.
"""

from __future__ import annotations

import logging
from functools import lru_cache
from typing import List, NamedTuple, Optional, Tuple

import numpy as np

from ..observe import metrics, trace

log = logging.getLogger(__name__)

SAT, UNSAT, UNKNOWN = 1, 0, -1

# probe status
SEARCHING, S_SAT, S_UNSAT = 0, 1, 2

#: clause tile width for the scanned unit-propagation pass
TILE = 2048

#: default PER-DEVICE clause cap for device solving: step time grows
#: linearly with the local tile count — refuse early and let the caller
#: fall back loudly. The effective cap multiplies by the mesh size when the
#: clause matrix shards across devices (a 256-bit multiply bit-blasts to
#: ~1e5 clauses; one device now holds it, a mesh holds several). Raised from
#: 1<<18 alongside the word-level simplifier: post-simplification
#: killbilly-class queries land in the 3-5e5 range, and routing them to the
#: device instead of counting a fallback is the whole point of shrinking them.
DEFAULT_CLAUSE_CAP = 1 << 19

#: unassigned / true / false assignment codes
_UNASSIGNED, _TRUE, _FALSE = 0, 1, 2

#: shape keys whose runner has been invoked at least once this process —
#: XLA compiles (or loads from the persistent cache) at the FIRST call per
#: argument shape, not when lru_cache builds the jitted callable
_SHAPES_RUN: set = set()

#: shape key -> AOT ``jax.stages.Compiled`` executable, either
#: deserialized from the persistent executable cache (exec_cache.py) or
#: compiled here and persisted for the next process. Preferred over the
#: jitted runner at every invocation, so a deserialize-first warm worker
#: never touches the jit compile path at all.
_AOT_EXECUTABLES: dict = {}


def _run_accounted(runner, shape_key, state, lits, valid, order):
    """One runner invocation with XLA compile accounting.

    The first call per (runner kind, arg-shape) key consults the
    persistent executable cache: a deserialize hit counts
    ``cache.exec.hits`` + ``xla.bucket_reuses`` (warmth was durable — no
    compile happened); a miss pays an AOT compile under an
    ``xla.compile`` span (traceview attributes the latency cliff to its
    clause-shape bucket), then persists the executable so the NEXT
    process's first call is a cache read. Later calls reuse the AOT
    executable (or the jit cache for uncacheable sharded keys) and count
    as bucket reuses."""
    if shape_key in _SHAPES_RUN:
        metrics.inc("xla.bucket_reuses")
        aot = _AOT_EXECUTABLES.get(shape_key)
        if aot is not None:
            try:
                return aot(state, lits, valid, order)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception:
                # arg-layout drift (e.g. weak-type mismatch with a
                # deserialized executable): drop it and let jit recover
                log.warning("AOT executable rejected args for %s — "
                            "reverting to the jit path", shape_key)
                _AOT_EXECUTABLES.pop(shape_key, None)
        return runner(state, lits, valid, order)

    from . import exec_cache

    loaded = exec_cache.load(shape_key)
    if loaded is not None:
        try:
            result = loaded(state, lits, valid, order)
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception:
            log.warning("deserialized executable rejected args for %s — "
                        "compiling instead", shape_key)
        else:
            _SHAPES_RUN.add(shape_key)
            _AOT_EXECUTABLES[shape_key] = loaded
            metrics.inc("xla.bucket_reuses")
            return result
    _SHAPES_RUN.add(shape_key)
    metrics.inc("xla.bucket_compiles")
    with trace.span("xla.compile", shape=str(shape_key)):
        compiled = exec_cache.compile_and_store(
            runner, shape_key, (state, lits, valid, order))
        if compiled is not None:
            _AOT_EXECUTABLES[shape_key] = compiled
            return compiled(state, lits, valid, order)
        return runner(state, lits, valid, order)


class _Problem(NamedTuple):
    lits: np.ndarray       # int32[n_tiles, TILE, 3] DIMACS literals, 0-padded
    valid: np.ndarray      # bool[n_tiles, TILE] true where a real clause lives
    order: np.ndarray      # int32[V1] decision rank per var (lower = earlier)
    init_assign: np.ndarray  # int8[V1] 0 for real vars, _FALSE for pad vars
    n_vars: int            # real variable count (pre-padding)


#: coarse-scheme floor for the padded variable axis: every query with
#: fewer vars shares one bucket (the v1-wide per-step ops are cheap next
#: to the tile scan, so a fat floor costs little and folds the long tail
#: of small queries into a single pre-bakeable executable)
COARSE_VARS_FLOOR = 1 << 10


def _next_pow2(value: int) -> int:
    from .batch import next_pow2

    return next_pow2(value)


def _next_pow4(value: int) -> int:
    bucket = 1
    while bucket < value:
        bucket <<= 2
    return bucket


def _coarse_buckets() -> bool:
    """Call-time scheme read: 'coarse' (default) unless the A/B knob says
    'fine'."""
    from ..support import tpu_config

    return tpu_config.get_str("MYTHRIL_TPU_BUCKET_SCHEME") != "fine"


def _bucket_tiles(tiles_needed: int) -> int:
    if _coarse_buckets():
        return _next_pow4(tiles_needed)
    return _next_pow2(tiles_needed)


def _bucket_vars(vars_needed: int) -> int:
    if _coarse_buckets():
        return max(COARSE_VARS_FLOOR, _next_pow4(vars_needed))
    return _next_pow2(vars_needed)


def _bucket_batch(queries_needed: int) -> int:
    if _coarse_buckets():
        return _next_pow4(queries_needed)
    return _next_pow2(queries_needed)


def _build_problem(clauses: List[List[int]], n_vars: int,
                   max_len: int = 3) -> _Problem:
    long_clauses = [c for c in clauses if len(c) > max_len]
    if long_clauses:
        # split long clauses with fresh connector variables (rare: the blaster
        # emits <=3-literal gate clauses; asserts are units)
        rebuilt = []
        for clause in clauses:
            while len(clause) > max_len:
                n_vars += 1
                rebuilt.append(clause[:max_len - 1] + [n_vars])
                clause = [-n_vars] + clause[max_len - 1:]
            rebuilt.append(clause)
        clauses = rebuilt

    n_clauses = len(clauses)
    n_tiles = _bucket_tiles(max(1, -(-n_clauses // TILE)))
    lits = np.zeros((n_tiles * TILE, max_len), dtype=np.int32)
    for i, clause in enumerate(clauses):
        lits[i, :len(clause)] = clause
    valid = np.zeros(n_tiles * TILE, dtype=bool)
    valid[:n_clauses] = True

    # bucket the variable axis; padded vars start pre-assigned (false, not on
    # the trail) so they are never decided and never block the SAT check
    v1 = _bucket_vars(n_vars + 1)
    counts = np.zeros(v1, dtype=np.int64)
    for clause in clauses:
        for lit in clause:
            counts[abs(lit)] += 1
    order = np.full(v1, 1 << 30, dtype=np.int32)
    by_freq = np.argsort(-counts[1:n_vars + 1], kind="stable") + 1
    order[by_freq] = np.arange(1, n_vars + 1, dtype=np.int32)
    init_assign = np.zeros(v1, dtype=np.int8)
    init_assign[n_vars + 1:] = _FALSE
    return _Problem(lits.reshape(n_tiles, TILE, max_len),
                    valid.reshape(n_tiles, TILE), order, init_assign, n_vars)


class _SolverState(NamedTuple):
    assign: "jnp.ndarray"     # int8[P, V1]: 0 unassigned, 1 true, 2 false
    trail: "jnp.ndarray"      # int32[P, V1] literals in assignment order
    tag: "jnp.ndarray"        # int8[P, V1]: 0 implied, 1 decision, 2 exhausted
    trail_len: "jnp.ndarray"  # int32[P]
    status: "jnp.ndarray"     # int8[P]


def _step(state: _SolverState, lits, valid, order, forced_depth: int,
          axis_name: Optional[str] = None) -> _SolverState:
    """One DPLL transition for every probe lane (pure; traced under jit).

    With `axis_name`, the clause-tile axis is SHARDED across a device mesh
    (shard_map): each device scans only its clause shard and the verdicts
    combine with collectives — conflict flags by any-of, implied phases by
    elementwise max (opposite-phase races are benign exactly as within one
    device: the losing clause falsifies and conflicts next step). This is
    the SURVEY §2.3 "tensor parallelism" analogue: the clause matrix is the
    weight matrix, unit propagation the matmul, psum/pmax the reduction."""
    import jax
    import jax.numpy as jnp

    n_probes, v1 = state.assign.shape
    searching = state.status == SEARCHING
    probe_idx = jnp.arange(n_probes)[:, None]

    # ---- tiled unit propagation ------------------------------------------------
    # Opposite implications of the same variable race benignly: whichever phase
    # lands, the losing clause becomes falsified and the conflict is detected
    # on the next step.
    def tile_body(carry, tile):
        conflict, implied = carry
        tile_lits, tile_valid = tile        # [T, 3], [T]
        var = jnp.abs(tile_lits)
        is_pos = tile_lits > 0
        is_pad = tile_lits == 0
        av = state.assign[:, var]                                # [P, T, 3]
        val_true = jnp.where(is_pos, av == _TRUE, av == _FALSE) & ~is_pad
        val_unassigned = (av == _UNASSIGNED) & ~is_pad
        clause_sat = jnp.any(val_true, axis=-1) | ~tile_valid    # [P, T]
        n_un = jnp.sum(val_unassigned, axis=-1)
        conflict = conflict | jnp.any(~clause_sat & (n_un == 0), axis=-1)
        unit_clause = ~clause_sat & (n_un == 1)                  # [P, T]
        unit_slot = jnp.argmax(val_unassigned, axis=-1)
        unit_lit = jnp.take_along_axis(
            jnp.broadcast_to(tile_lits, (n_probes,) + tile_lits.shape),
            unit_slot[..., None], axis=-1)[..., 0]
        # route non-unit rows to a dropped out-of-bounds write
        unit_var = jnp.where(unit_clause, jnp.abs(unit_lit), v1)
        unit_phase = jnp.where(unit_lit > 0, _TRUE, _FALSE).astype(jnp.int8)
        implied = implied.at[probe_idx, unit_var].set(unit_phase, mode="drop")
        return (conflict, implied), None

    init = (jnp.zeros(n_probes, dtype=bool),
            jnp.zeros((n_probes, v1), dtype=jnp.int8))
    (conflict, implied), _ = jax.lax.scan(tile_body, init, (lits, valid))
    if axis_name is not None:
        conflict = jax.lax.pmax(conflict.astype(jnp.int8), axis_name) > 0
        implied = jax.lax.pmax(implied, axis_name)
    implied = implied.at[:, 0].set(0)
    newly = (implied != 0) & (state.assign == _UNASSIGNED)       # [P, V1]
    has_units = jnp.any(newly, axis=-1)

    # ---- branch 1: assert all unit literals -------------------------------------
    u_assign = jnp.where(newly, implied, state.assign)
    # collision-free trail append: every non-newly column routes to the dropped
    # out-of-bounds slot v1 instead of aliasing a live position (duplicate-index
    # scatter order is undefined and implied literals would vanish from the
    # trail, surviving backtracking — ADVICE r2 high finding)
    new_rank = jnp.cumsum(newly, axis=-1) - 1
    append_pos = jnp.where(newly, state.trail_len[:, None] + new_rank, v1)
    signed = jnp.where(implied == _TRUE, 1, -1) * jnp.arange(v1)
    u_trail = state.trail.at[probe_idx, append_pos].set(
        signed.astype(jnp.int32), mode="drop")
    u_tag = state.tag.at[probe_idx, append_pos].set(jnp.int8(0), mode="drop")
    u_len = state.trail_len + jnp.sum(newly, axis=-1).astype(jnp.int32)

    # ---- branch 2: backtrack ----------------------------------------------------
    pos = jnp.arange(v1)[None, :]
    in_trail = pos < state.trail_len[:, None]
    flippable = (state.tag == 1) & in_trail
    has_flip = jnp.any(flippable, axis=-1)
    flip_pos = (v1 - 1) - jnp.argmax(flippable[:, ::-1], axis=-1)
    flip_pos = jnp.where(has_flip, flip_pos, 0).astype(jnp.int32)
    # unassign everything at positions > flip_pos (collision-free: masked
    # entries route to the dropped slot, not onto var 0)
    kill = in_trail & (pos > flip_pos[:, None])
    kill_var = jnp.where(kill, jnp.abs(state.trail), v1)
    b_assign = state.assign.at[probe_idx, kill_var].set(
        jnp.int8(0), mode="drop")
    # flip the decision literal in place, now exhausted
    flip_lit = jnp.take_along_axis(state.trail, flip_pos[:, None], axis=-1)[:, 0]
    flip_var = jnp.abs(flip_lit)
    new_phase = jnp.where(flip_lit > 0, jnp.int8(_FALSE), jnp.int8(_TRUE))
    b_assign = b_assign.at[jnp.arange(n_probes), flip_var].set(
        jnp.where(has_flip, new_phase,
                  b_assign[jnp.arange(n_probes), flip_var]))
    b_trail = state.trail.at[jnp.arange(n_probes), flip_pos].set(-flip_lit)
    b_tag = state.tag.at[jnp.arange(n_probes), flip_pos].set(2)
    b_len = jnp.where(has_flip, flip_pos + 1, state.trail_len)
    b_status = jnp.where(has_flip, jnp.int8(SEARCHING), jnp.int8(S_UNSAT))

    # ---- branch 3: decide -------------------------------------------------------
    free = state.assign == _UNASSIGNED
    free = free.at[:, 0].set(False)
    any_free = jnp.any(free, axis=-1)
    pick_rank = jnp.where(free, order[None, :], jnp.int32(1 << 30))
    d_var = jnp.argmin(pick_rank, axis=-1).astype(jnp.int32)
    level = jnp.sum((state.tag >= 1) & in_trail, axis=-1)
    in_prefix = level < forced_depth
    probe_bit = (jnp.arange(n_probes) >> jnp.clip(level, 0, 30)) & 1
    d_phase_true = jnp.where(in_prefix, probe_bit == 1, False)
    d_assign_val = jnp.where(d_phase_true, jnp.int8(_TRUE), jnp.int8(_FALSE))
    d_tag_val = jnp.where(in_prefix, jnp.int8(2), jnp.int8(1))
    d_lit = jnp.where(d_phase_true, d_var, -d_var)
    d_assign = state.assign.at[jnp.arange(n_probes), d_var].set(d_assign_val)
    d_pos = jnp.clip(state.trail_len, 0, v1 - 1)
    d_trail = state.trail.at[jnp.arange(n_probes), d_pos].set(d_lit)
    d_tag = state.tag.at[jnp.arange(n_probes), d_pos].set(d_tag_val)
    d_len = state.trail_len + 1

    # ---- combine: conflict > units > all-assigned(SAT) > decide -----------------
    take_b = searching & conflict
    take_u = searching & ~conflict & has_units
    take_sat = searching & ~conflict & ~has_units & ~any_free
    take_d = searching & ~conflict & ~has_units & any_free

    def mix(bt, un, de, old):
        m_b, m_u, m_d = take_b, take_u, take_d
        while m_b.ndim < bt.ndim:
            m_b, m_u, m_d = m_b[..., None], m_u[..., None], m_d[..., None]
        out = jnp.where(m_b, bt, old)
        out = jnp.where(m_u, un, out)
        return jnp.where(m_d, de, out)

    assign = mix(b_assign, u_assign, d_assign, state.assign)
    trail = mix(b_trail, u_trail, d_trail, state.trail)
    tag = mix(b_tag, u_tag, d_tag, state.tag)
    trail_len = mix(b_len, u_len, d_len, state.trail_len)
    status = jnp.where(take_b, b_status, state.status)
    status = jnp.where(take_sat, jnp.int8(S_SAT), status)
    return _SolverState(assign, trail, tag, trail_len, status)


@lru_cache(maxsize=64)
def _get_runner(chunk: int, forced_depth: int):
    """One compiled executable per (chunk, forced_depth); problem tensors are
    arguments, so every query in the same shape bucket reuses it."""
    import jax

    def run(state, lits, valid, order):
        return jax.lax.fori_loop(
            0, chunk,
            lambda _, st: _step(st, lits, valid, order, forced_depth), state)

    return jax.jit(run)


@lru_cache(maxsize=16)
def _get_sharded_runner(chunk: int, forced_depth: int, n_devices: int):
    """Clause-matrix-sharded runner: lits/valid partition over the mesh's
    "clauses" axis, solver state replicates, verdicts combine per step with
    pmax collectives inside the fused loop."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    mesh = Mesh(np.array(jax.devices()[:n_devices]), ("clauses",))

    def run(state, lits, valid, order):
        def body(_, st):
            return _step(st, lits, valid, order, forced_depth,
                         axis_name="clauses")

        return jax.lax.fori_loop(0, chunk, body, state)

    sharded = shard_map(
        run, mesh=mesh,
        in_specs=(_SolverState(*([P()] * 5)), P("clauses"), P("clauses"),
                  P()),
        out_specs=_SolverState(*([P()] * 5)),
        check_rep=False)
    return jax.jit(sharded), mesh


def solve_cnf_device(clauses: List[List[int]], n_vars: int,
                     n_probes: int = 32, max_steps: int = 20_000,
                     chunk: int = 256, clause_cap: int = DEFAULT_CLAUSE_CAP
                     ) -> Tuple[int, Optional[List[bool]]]:
    """Solve CNF on the JAX backend. Same contract as sat.solve_cnf:
    (status, model) with model[v-1] the value of DIMACS var v.

    Returns UNKNOWN (never raises, never guesses) when the problem exceeds
    `clause_cap` — the caller falls back to the native CDCL core."""
    import jax.numpy as jnp

    if not clauses:
        # trivially satisfiable — padding with a zero row would fabricate an
        # empty (always-false) clause (ADVICE r2 medium finding)
        return SAT, [False] * n_vars
    for clause in clauses:
        if not clause:
            return UNSAT, None

    # clause-matrix sharding across the mesh (SURVEY §2.3 TP analogue):
    # the cap scales with the device count — each device scans only its
    # tile shard per step. Same gating as the frontier's lane sharding:
    # MYTHRIL_TPU_SHARD=1 forces on, =0 off, default on for real
    # accelerator meshes only.
    import jax

    from ..support import tpu_config

    devices = jax.devices()
    flag = tpu_config.get_raw("MYTHRIL_TPU_SHARD")
    n_devices = 1
    if len(devices) > 1 and flag != "0" \
            and (flag == "1" or devices[0].platform != "cpu"):
        n_devices = len(devices)
    if len(clauses) > clause_cap * n_devices:
        return UNKNOWN, None

    problem = _build_problem(clauses, n_vars)
    forced_depth = max(0, int(np.log2(max(1, n_probes))))
    if n_devices > 1 and problem.lits.shape[0] % n_devices == 0 \
            and problem.lits.shape[0] >= n_devices:
        runner, mesh = _get_sharded_runner(chunk, forced_depth, n_devices)
    else:
        if len(clauses) > clause_cap:
            # the mesh-scaled cap only holds when the tiles actually shard;
            # refuse loudly rather than run n_devices x the per-device
            # budget on one device
            return UNKNOWN, None
        runner = _get_runner(chunk, forced_depth)

    v1 = problem.order.shape[0]
    lits = jnp.asarray(problem.lits)
    valid = jnp.asarray(problem.valid)
    order = jnp.asarray(problem.order)
    state = _SolverState(
        assign=jnp.broadcast_to(jnp.asarray(problem.init_assign),
                                (n_probes, v1)),
        trail=jnp.zeros((n_probes, v1), dtype=jnp.int32),
        tag=jnp.zeros((n_probes, v1), dtype=jnp.int8),
        trail_len=jnp.zeros(n_probes, dtype=jnp.int32),
        status=jnp.zeros(n_probes, dtype=jnp.int8),
    )

    shape_key = ("single", n_devices, chunk, forced_depth,
                 problem.lits.shape[0], v1, n_probes)
    steps = 0
    while steps < max_steps:
        state = _run_accounted(runner, shape_key, state, lits, valid, order)
        steps += chunk
        status = np.asarray(state.status)
        if (status == S_SAT).any() or (status != SEARCHING).all():
            break

    status = np.asarray(state.status)
    sat_lanes = np.nonzero(status == S_SAT)[0]
    if len(sat_lanes):
        assign = np.asarray(state.assign[int(sat_lanes[0])])
        return SAT, [bool(assign[v] == _TRUE)
                     for v in range(1, problem.n_vars + 1)]
    if (status == S_UNSAT).all():
        return UNSAT, None
    return UNKNOWN, None


@lru_cache(maxsize=32)
def _get_batch_runner(chunk: int, forced_depth: int):
    """Query-vmapped runner: one compiled executable per (chunk,
    forced_depth); the query axis, like the problem tensors, is an argument
    shape, so every batch in the same (n_tiles, v1, padded_batch) bucket
    reuses it. Decided queries freeze in place (per-query active mask) so
    one long solve does not burn steps re-deciding its finished siblings."""
    import jax
    import jax.numpy as jnp

    def run_one(state, lits, valid, order):
        def body(_, st):
            decided = jnp.any(st.status == S_SAT) \
                | jnp.all(st.status != SEARCHING)
            advanced = _step(st, lits, valid, order, forced_depth)
            return jax.tree_util.tree_map(
                lambda new, old: jnp.where(decided, old, new), advanced, st)

        return jax.lax.fori_loop(0, chunk, body, state)

    return jax.jit(jax.vmap(run_one))


def solve_cnf_device_batch(queries: List[Tuple[List[List[int]], int]],
                           n_probes: int = 32, max_steps: int = 20_000,
                           chunk: int = 256,
                           clause_cap: Optional[int] = None
                           ) -> List[Tuple[int, Optional[List[bool]]]]:
    """Solve many independent CNFs in shape-bucketed device batches.

    `queries` is a list of (clauses, n_vars); returns one (status, model)
    per query, aligned, with the same per-query contract as
    solve_cnf_device: trivial cases (empty CNF, empty clause) answer on the
    host, oversize queries return UNKNOWN (caller falls back to CDCL), and
    no query ever raises past the caller's classification layer.

    Problems bucket by their padded (n_tiles, v1) shape — already bucketed
    by _build_problem (pow2, or the coarse pow4 scheme) — and the query
    axis pads the same way by repeating the last problem, so the vmapped
    runner's compile cache stays as small as the single-query one's. The
    host loop early-exits a bucket once every REAL query in it has a
    verdict (pad lanes never gate progress).

    `clause_cap=None` reads DEFAULT_CLAUSE_CAP at call time, so the
    dispatch layer (and tests) can tune the module global."""
    import jax.numpy as jnp

    if clause_cap is None:
        clause_cap = DEFAULT_CLAUSE_CAP
    results: List[Optional[Tuple[int, Optional[List[bool]]]]] = \
        [None] * len(queries)
    buckets: dict = {}  # (n_tiles, v1) -> [(query index, _Problem)]
    for index, (clauses, n_vars) in enumerate(queries):
        if not clauses:
            results[index] = (SAT, [False] * n_vars)
            continue
        if any(not clause for clause in clauses):
            results[index] = (UNSAT, None)
            continue
        if len(clauses) > clause_cap:
            results[index] = (UNKNOWN, None)
            continue
        problem = _build_problem(clauses, n_vars)
        key = (problem.lits.shape[0], problem.order.shape[0])
        buckets.setdefault(key, []).append((index, problem))

    forced_depth = max(0, int(np.log2(max(1, n_probes))))
    for (n_tiles, v1), group in buckets.items():
        n_real = len(group)
        n_padded = _bucket_batch(n_real)
        problems = [problem for _, problem in group]
        problems += [problems[-1]] * (n_padded - n_real)
        try:
            from ..smt.solver.solver_statistics import SolverStatistics

            SolverStatistics().batch_bucket_shapes.add(
                (n_tiles, v1, n_padded))
        except ImportError:  # stats are observability, never a solve gate
            pass

        lits = jnp.asarray(np.stack([p.lits for p in problems]))
        valid = jnp.asarray(np.stack([p.valid for p in problems]))
        order = jnp.asarray(np.stack([p.order for p in problems]))
        assign0 = np.stack([np.broadcast_to(p.init_assign, (n_probes, v1))
                            for p in problems])
        state = _SolverState(
            assign=jnp.asarray(assign0),
            trail=jnp.zeros((n_padded, n_probes, v1), dtype=jnp.int32),
            tag=jnp.zeros((n_padded, n_probes, v1), dtype=jnp.int8),
            trail_len=jnp.zeros((n_padded, n_probes), dtype=jnp.int32),
            status=jnp.zeros((n_padded, n_probes), dtype=jnp.int8),
        )
        runner = _get_batch_runner(chunk, forced_depth)
        shape_key = ("batch", chunk, forced_depth, n_tiles, v1, n_padded,
                     n_probes)

        steps = 0
        while steps < max_steps:
            state = _run_accounted(runner, shape_key, state, lits, valid,
                                   order)
            steps += chunk
            status = np.asarray(state.status)[:n_real]
            if ((status == S_SAT).any(axis=1)
                    | (status != SEARCHING).all(axis=1)).all():
                break

        status = np.asarray(state.status)
        for slot, (index, problem) in enumerate(group):
            sat_lanes = np.nonzero(status[slot] == S_SAT)[0]
            if len(sat_lanes):
                assign = np.asarray(state.assign[slot, int(sat_lanes[0])])
                results[index] = (SAT, [bool(assign[v] == _TRUE)
                                        for v in range(1, problem.n_vars + 1)])
            elif (status[slot] == S_UNSAT).all():
                results[index] = (UNSAT, None)
            else:
                results[index] = (UNKNOWN, None)
    return results


# -- serve warm hooks (mythril_tpu/serve/warmset.py) ---------------------------------

#: sanity bounds for manifest-sourced shape keys — a corrupt or hostile
#: manifest must not allocate arbitrary device memory at daemon startup
_WARM_MAX_TILES = 1 << 12
_WARM_MAX_VARS = 1 << 22
_WARM_MAX_PROBES = 1 << 10
_WARM_MAX_BATCH = 1 << 12
_WARM_MAX_CHUNK = 1 << 12


def observed_shape_keys() -> List[tuple]:
    """Snapshot of every runner shape key invoked this process — the
    serve warm-set exports these to the warmup manifest so the next
    daemon can pre-compile them before taking traffic."""
    return sorted(_SHAPES_RUN)


def warm_shape_key(key) -> bool:
    """Warm one runner shape bucket: deserialize-first, compile-on-miss.

    The synthetic zero-clause problem below has exactly the bucket's
    padded shapes/dtypes, and the invocation routes through
    ``_run_accounted`` — so a persisted executable is deserialized into
    ``_AOT_EXECUTABLES`` (the cache real queries hit first) and a miss
    pays its AOT compile inside the warmup span, not the first request,
    then persists the executable for the next spawn. Returns False
    (never raises) for malformed keys, out-of-bounds shapes, or sharded
    keys the current mesh cannot host — a stale manifest must not take
    the daemon down."""
    import jax
    import jax.numpy as jnp

    try:
        key = tuple(key)
        kind = key[0]
        if kind == "single":
            _, n_devices, chunk, forced_depth, n_tiles, v1, n_probes = key
            n_padded = 0
        elif kind == "batch":
            _, chunk, forced_depth, n_tiles, v1, n_padded, n_probes = key
            n_devices = 1
        else:
            return False
        dims = [n_devices, chunk, forced_depth, n_tiles, v1, n_probes]
        if kind == "batch":
            dims.append(n_padded)
        if not all(isinstance(d, int) and d >= 0 for d in dims):
            return False
        if not (0 < n_tiles <= _WARM_MAX_TILES
                and 0 < v1 <= _WARM_MAX_VARS
                and 0 < n_probes <= _WARM_MAX_PROBES
                and 0 < chunk <= _WARM_MAX_CHUNK
                and forced_depth <= 30
                and (kind != "batch" or 0 < n_padded <= _WARM_MAX_BATCH)):
            return False
    except (TypeError, ValueError, IndexError):
        return False
    if key in _SHAPES_RUN:
        return True

    if n_devices > 1:
        if len(jax.devices()) < n_devices or n_tiles % n_devices:
            return False
        runner, _ = _get_sharded_runner(chunk, forced_depth, n_devices)
    elif kind == "batch":
        runner = _get_batch_runner(chunk, forced_depth)
    else:
        runner = _get_runner(chunk, forced_depth)

    # a zero-clause problem: every tile row is padding, so the lanes just
    # decide variables for `chunk` steps — same shapes/dtypes as a real
    # query (the jit cache key), trivial work
    lits = np.zeros((n_tiles, TILE, 3), dtype=np.int32)
    valid = np.zeros((n_tiles, TILE), dtype=bool)
    order = np.arange(v1, dtype=np.int32)
    assign = np.zeros((n_probes, v1), dtype=np.int8)
    if kind == "batch":
        state = _SolverState(
            assign=jnp.asarray(np.broadcast_to(
                assign, (n_padded, n_probes, v1))),
            trail=jnp.zeros((n_padded, n_probes, v1), dtype=jnp.int32),
            tag=jnp.zeros((n_padded, n_probes, v1), dtype=jnp.int8),
            trail_len=jnp.zeros((n_padded, n_probes), dtype=jnp.int32),
            status=jnp.zeros((n_padded, n_probes), dtype=jnp.int8),
        )
        lits_dev = jnp.asarray(np.broadcast_to(lits, (n_padded,) + lits.shape))
        valid_dev = jnp.asarray(np.broadcast_to(
            valid, (n_padded,) + valid.shape))
        order_dev = jnp.asarray(np.broadcast_to(order, (n_padded, v1)))
    else:
        state = _SolverState(
            assign=jnp.broadcast_to(jnp.asarray(assign[0]), (n_probes, v1)),
            trail=jnp.zeros((n_probes, v1), dtype=jnp.int32),
            tag=jnp.zeros((n_probes, v1), dtype=jnp.int8),
            trail_len=jnp.zeros(n_probes, dtype=jnp.int32),
            status=jnp.zeros(n_probes, dtype=jnp.int8),
        )
        lits_dev, valid_dev, order_dev = (jnp.asarray(lits),
                                          jnp.asarray(valid),
                                          jnp.asarray(order))
    try:
        _run_accounted(runner, key, state, lits_dev, valid_dev, order_dev)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception:
        # warming is an optimization: an uncompilable key (e.g. a manifest
        # from a different mesh) must not take the daemon down
        _SHAPES_RUN.discard(key)
        return False
    return True
