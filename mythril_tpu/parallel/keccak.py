"""Batched keccak-256 entirely on device.

The sponge state is 25 64-bit lanes held as uint32 (lo, hi) pairs so every
operation is a native 32-bit rotate/xor — no emulated 64-bit arithmetic, which
keeps the permutation on the TPU's vector units. Rotation amounts are all
static, so each round compiles to a fixed xor/or/shift DAG that XLA fuses.

Used by the lockstep interpreter for SHA3/CREATE2 (reference semantics:
mythril/laser/ethereum/instructions.py sha3_:1018 concretizes via eth-hash on
host; here concrete lanes hash on device, batched).

Variable-length batched hashing: each lane carries its own byte length; padding
(0x01 … 0x80) is materialized arithmetically per lane and absorption of block
`b` is masked by `b < nblocks(lane)`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

U32 = jnp.uint32
RATE = 136  # keccak-256 rate in bytes
LANES = RATE // 8  # 17 input lanes per block

_ROUND_CONSTANTS = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]
_RC_LO = np.array([c & 0xFFFFFFFF for c in _ROUND_CONSTANTS], dtype=np.uint32)
_RC_HI = np.array([c >> 32 for c in _ROUND_CONSTANTS], dtype=np.uint32)

# rotation offsets r[x][y], flattened index = x + 5*y
_ROTATIONS = np.zeros(25, dtype=np.int32)
_ROT_TABLE = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]
for _x in range(5):
    for _y in range(5):
        _ROTATIONS[_x + 5 * _y] = _ROT_TABLE[_x][_y]
# plain-int view for use inside traced code: _rotl64's shift amount is a
# static Python int, and an int(np_scalar) conversion inside the traced
# round function reads as a device sync to tpu-lint R3
_ROTATIONS_PY = [int(_r) for _r in _ROTATIONS]


def _rotl64(lo, hi, n):
    """Rotate a 64-bit value given as uint32 (lo, hi) left by static n."""
    n %= 64
    if n == 0:
        return lo, hi
    if n == 32:
        return hi, lo
    if n < 32:
        new_lo = ((lo << U32(n)) | (hi >> U32(32 - n)))
        new_hi = ((hi << U32(n)) | (lo >> U32(32 - n)))
        return new_lo, new_hi
    m = n - 32
    new_lo = ((hi << U32(m)) | (lo >> U32(32 - m)))
    new_hi = ((lo << U32(m)) | (hi >> U32(32 - m)))
    return new_lo, new_hi


_RC_LO_T = jnp.asarray(_RC_LO)
_RC_HI_T = jnp.asarray(_RC_HI)


def _keccak_round(lo, hi, rc_lo, rc_hi):
    """One keccak-f round (rc_* may be traced scalars). Rotation amounts stay
    static, so the round body is a fixed xor/or/shift DAG; keccak_f rolls the
    24 rounds into a fori_loop so the DAG is compiled ONCE, not 24x per
    absorbed block — the unrolled version dominated the whole interpreter's
    XLA program (~87% of sym_step's HLO) and pushed TPU compile past 2 min."""
    # theta
    c_lo = [lo[..., x] ^ lo[..., x + 5] ^ lo[..., x + 10]
            ^ lo[..., x + 15] ^ lo[..., x + 20] for x in range(5)]
    c_hi = [hi[..., x] ^ hi[..., x + 5] ^ hi[..., x + 10]
            ^ hi[..., x + 15] ^ hi[..., x + 20] for x in range(5)]
    d_lo, d_hi = [], []
    for x in range(5):
        rot_lo, rot_hi = _rotl64(c_lo[(x + 1) % 5], c_hi[(x + 1) % 5], 1)
        d_lo.append(c_lo[(x + 4) % 5] ^ rot_lo)
        d_hi.append(c_hi[(x + 4) % 5] ^ rot_hi)
    lo = jnp.stack([lo[..., i] ^ d_lo[i % 5] for i in range(25)], axis=-1)
    hi = jnp.stack([hi[..., i] ^ d_hi[i % 5] for i in range(25)], axis=-1)

    # rho + pi
    b_lo = [None] * 25
    b_hi = [None] * 25
    for x in range(5):
        for y in range(5):
            src = x + 5 * y
            dst = y + 5 * ((2 * x + 3 * y) % 5)
            b_lo[dst], b_hi[dst] = _rotl64(
                lo[..., src], hi[..., src], _ROTATIONS_PY[src])

    # chi
    new_lo, new_hi = [], []
    for y in range(5):
        for x in range(5):
            i = x + 5 * y
            i1 = (x + 1) % 5 + 5 * y
            i2 = (x + 2) % 5 + 5 * y
            new_lo.append(b_lo[i] ^ ((~b_lo[i1]) & b_lo[i2]))
            new_hi.append(b_hi[i] ^ ((~b_hi[i1]) & b_hi[i2]))
    lo = jnp.stack(new_lo, axis=-1)
    hi = jnp.stack(new_hi, axis=-1)

    # iota
    lo = lo.at[..., 0].set(lo[..., 0] ^ rc_lo)
    hi = hi.at[..., 0].set(hi[..., 0] ^ rc_hi)
    return lo, hi


def keccak_f(lo: jnp.ndarray, hi: jnp.ndarray):
    """keccak-f[1600] permutation. lo/hi: uint32[..., 25]."""

    def body(round_index, carry):
        lo, hi = carry
        return _keccak_round(lo, hi, _RC_LO_T[round_index],
                             _RC_HI_T[round_index])

    return jax.lax.fori_loop(0, 24, body, (lo, hi))


def keccak256(data: jnp.ndarray, length: jnp.ndarray) -> jnp.ndarray:
    """Batched keccak-256.

    data:   uint8[..., max_len] message buffer (bytes past `length` ignored)
    length: int32[...] per-lane message length in bytes, 0 <= length <= max_len
    returns uint8[..., 32] digests.
    """
    batch_shape = data.shape[:-1]
    max_len = data.shape[-1]
    n_blocks = (max_len + 1 + RATE - 1) // RATE
    padded_size = n_blocks * RATE

    j = jnp.arange(padded_size)
    padded_len = ((length + 1 + RATE - 1) // RATE) * RATE
    base = jnp.where(j < length[..., None],
                     jnp.pad(data, [(0, 0)] * len(batch_shape)
                             + [(0, padded_size - max_len)]),
                     0).astype(jnp.uint8)
    base = jnp.where(j == length[..., None], jnp.uint8(0x01), base)
    base = jnp.where(j == padded_len[..., None] - 1,
                     base | jnp.uint8(0x80), base)

    # bytes -> 64-bit lanes (little-endian within each lane)
    blocks = base.reshape(batch_shape + (n_blocks, LANES, 8)).astype(U32)
    weights = (U32(1) << (8 * jnp.arange(4, dtype=U32)))
    block_lo = jnp.sum(blocks[..., 0:4] * weights, axis=-1, dtype=U32)
    block_hi = jnp.sum(blocks[..., 4:8] * weights, axis=-1, dtype=U32)

    lo = jnp.zeros(batch_shape + (25,), dtype=U32)
    hi = jnp.zeros(batch_shape + (25,), dtype=U32)
    lane_blocks = padded_len // RATE
    pad_lanes = jnp.zeros(batch_shape + (25 - LANES,), dtype=U32)

    def absorb(b, carry):
        lo, hi = carry
        absorb_lo = jnp.concatenate(
            [jax.lax.dynamic_index_in_dim(block_lo, b, axis=len(batch_shape),
                                          keepdims=False), pad_lanes], axis=-1)
        absorb_hi = jnp.concatenate(
            [jax.lax.dynamic_index_in_dim(block_hi, b, axis=len(batch_shape),
                                          keepdims=False), pad_lanes], axis=-1)
        new_lo, new_hi = keccak_f(lo ^ absorb_lo, hi ^ absorb_hi)
        active = (b < lane_blocks)[..., None]
        return (jnp.where(active, new_lo, lo),
                jnp.where(active, new_hi, hi))

    lo, hi = jax.lax.fori_loop(0, n_blocks, absorb, (lo, hi))

    # squeeze 32 bytes from lanes 0..3
    out_lanes_lo = lo[..., 0:4]
    out_lanes_hi = hi[..., 0:4]
    shifts = 8 * jnp.arange(4, dtype=U32)
    lo_bytes = ((out_lanes_lo[..., None] >> shifts) & 0xFF).astype(jnp.uint8)
    hi_bytes = ((out_lanes_hi[..., None] >> shifts) & 0xFF).astype(jnp.uint8)
    return jnp.concatenate([lo_bytes, hi_bytes], axis=-1) \
        .reshape(batch_shape + (32,))
