"""Persistent executable cache: solver runners as files, not compiles.

BENCH_r05 measured the cold path at ~112 s of XLA compilation before the
first useful device step, and the serve worker pool (PR 15) pays it *per
spawned worker*. The warmset manifest already tells a fresh process WHAT
to warm (the shape keys); this module makes the warming itself a cache
read: every compiled solver runner is serialized with JAX's AOT
machinery (``jax.experimental.serialize_executable``) into a content-
addressed file, and the next process deserializes it instead of
compiling — the DTVM deterministic-JIT argument (PAPERS.md) applied to
the solver tier.

Cache key (one file per entry, filename = sha256 of the key JSON):

* jax + jaxlib versions — serialized executables are not ABI-stable
  across releases;
* device platform + device kind — an executable compiled for one
  accelerator is garbage on another;
* the runner shape key (``jax_solver._run_accounted``'s bucket key) —
  kind, chunk, forced depth, and every padded dimension;
* a program fingerprint (sha256 of ``jax_solver.py``'s source plus
  :data:`SCHEMA_VERSION`) — editing the kernel invalidates every entry
  without any manual versioning.

Only single-device runners are cached (``single`` with ``n_devices ==
1`` and every ``batch`` key): sharded executables embed mesh/topology
state that does not survive a process boundary, so those keys fall back
to ordinary compilation (which still hits the persistent *XLA* cache
enabled in ``parallel/__init__``).

Writes are fsync-atomic (tmp + fsync + rename via
``support/checkpoint.fsync_replace``) beside the warmset manifest, and
loads are corruption-tolerant: a truncated, garbled, wrong-schema, or
wrong-version file silently degrades to a compile — never a crash.
Hit/miss/latency land in the ``cache.exec.*`` metrics.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import time
from typing import Optional, Tuple

from ..observe import metrics
from ..support import tpu_config
from ..support.checkpoint import fsync_replace

log = logging.getLogger(__name__)

#: bump to invalidate every persisted executable (folded into both the
#: entry filename and the payload header, so old files are simply never
#: found and a hash collision still fails the header check)
SCHEMA_VERSION = 1

#: pickled payloads beyond this size are refused at load time — a
#: corrupt length field must not balloon into an allocation bomb
MAX_ENTRY_BYTES = 1 << 30


def enabled() -> bool:
    """MYTHRIL_TPU_EXEC_CACHE (default on)."""
    return tpu_config.get_flag("MYTHRIL_TPU_EXEC_CACHE")


def cache_dir() -> str:
    """MYTHRIL_TPU_EXEC_CACHE_DIR, or an ``exec_cache/`` directory
    beside the warmset manifest (so the executable store, the shape
    manifest, and the verdict/summary/quarantine sidecars travel
    together)."""
    configured = tpu_config.get_str("MYTHRIL_TPU_EXEC_CACHE_DIR")
    if configured:
        return configured
    from ..serve.warmset import default_manifest_path

    return os.path.join(os.path.dirname(default_manifest_path()),
                        "exec_cache")


def cacheable(shape_key: Tuple) -> bool:
    """Only single-device runners serialize portably: ``batch`` keys and
    ``single`` keys with ``n_devices == 1``. Sharded runners embed mesh
    state and fall back to ordinary compilation."""
    try:
        if shape_key[0] == "batch":
            return True
        return shape_key[0] == "single" and shape_key[1] == 1
    except (IndexError, TypeError):
        return False


_FINGERPRINT: Optional[str] = None


def program_fingerprint() -> str:
    """sha256 of the solver kernel source + schema version: any edit to
    ``jax_solver.py`` orphans every persisted executable."""
    global _FINGERPRINT
    if _FINGERPRINT is None:
        from . import jax_solver

        digest = hashlib.sha256()
        digest.update(f"schema:{SCHEMA_VERSION}".encode("utf-8"))
        try:
            with open(jax_solver.__file__, "rb") as handle:
                digest.update(handle.read())
        except OSError:
            digest.update(b"source-unavailable")
        _FINGERPRINT = digest.hexdigest()
    return _FINGERPRINT


def _backend_tag() -> str:
    import jax

    device = jax.devices()[0]
    jaxlib_version = ""
    try:
        import jaxlib.version

        jaxlib_version = jaxlib.version.__version__
    except ImportError:
        pass
    return json.dumps([jax.__version__, jaxlib_version, device.platform,
                       getattr(device, "device_kind", "")])


def entry_key(shape_key: Tuple) -> str:
    """The full cache key, JSON-shaped (hashed into the filename AND
    stored in the payload header for a post-load equality check)."""
    return json.dumps([SCHEMA_VERSION, _backend_tag(),
                       program_fingerprint(), list(shape_key)],
                      default=str)


def entry_path(shape_key: Tuple) -> str:
    digest = hashlib.sha256(entry_key(shape_key).encode("utf-8"))
    return os.path.join(cache_dir(), f"{digest.hexdigest()}.jexec")


def store(shape_key: Tuple, compiled) -> bool:
    """Serialize one ``jax.stages.Compiled`` runner fsync-atomically.
    Best-effort: any failure (unserializable executable, full disk,
    read-only cache dir) logs and returns False — persistence is an
    optimization, never a gate on the solve that just happened."""
    if not enabled() or not cacheable(shape_key):
        return False
    try:
        from jax.experimental import serialize_executable

        payload, in_tree, out_tree = serialize_executable.serialize(
            compiled)
        blob = pickle.dumps({"key": entry_key(shape_key),
                             "payload": payload,
                             "in_tree": in_tree,
                             "out_tree": out_tree},
                            protocol=pickle.HIGHEST_PROTOCOL)
        path = entry_path(shape_key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as handle:
            handle.write(blob)
        fsync_replace(tmp, path)
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as error:
        log.warning("could not persist executable for %s: %r",
                    shape_key, error)
        return False
    log.info("persisted executable for %s (%d bytes)", shape_key,
             len(blob))
    return True


def load(shape_key: Tuple):
    """Deserialize the persisted runner for a shape key, or None.

    Counts ``cache.exec.hits`` + ``cache.exec.deserialize_ms`` on
    success and ``cache.exec.misses`` on any enabled-but-unusable
    outcome (absent, truncated, garbage, schema/version/fingerprint
    mismatch, deserialization failure) — the caller falls back to
    compiling, which re-persists a fresh entry."""
    if not enabled() or not cacheable(shape_key):
        return None
    path = entry_path(shape_key)
    started = time.perf_counter()
    try:
        if os.path.getsize(path) > MAX_ENTRY_BYTES:
            raise ValueError("entry exceeds MAX_ENTRY_BYTES")
        with open(path, "rb") as handle:
            doc = pickle.loads(handle.read())
        if not isinstance(doc, dict) or doc.get("key") != \
                entry_key(shape_key):
            raise ValueError("cache key mismatch")
        from jax.experimental import serialize_executable

        compiled = serialize_executable.deserialize_and_load(
            doc["payload"], doc["in_tree"], doc["out_tree"])
    except FileNotFoundError:
        metrics.inc("cache.exec.misses")
        return None
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as error:
        # corruption-tolerant by contract: a torn or stale entry is a
        # compile, never a crash
        log.warning("unusable persisted executable for %s at %s: %r — "
                    "falling back to compile", shape_key, path, error)
        metrics.inc("cache.exec.misses")
        return None
    elapsed_ms = (time.perf_counter() - started) * 1000.0
    metrics.inc("cache.exec.hits")
    metrics.observe("cache.exec.deserialize_ms", elapsed_ms)
    log.info("deserialized executable for %s in %.1f ms", shape_key,
             elapsed_ms)
    return compiled


def compile_and_store(runner, shape_key: Tuple, args: Tuple):
    """AOT-compile `runner` for `args` via lower().compile(), persist
    the executable, and return the ``Compiled`` — or None when the key
    is uncacheable or AOT lowering fails (the caller then runs the
    plain jitted path; with the persistent XLA cache on, the backend
    compile below is shared either way)."""
    if not enabled() or not cacheable(shape_key):
        return None
    try:
        compiled = runner.lower(*args).compile()
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as error:
        log.warning("AOT compile failed for %s: %r — using the jit "
                    "path", shape_key, error)
        return None
    store(shape_key, compiled)
    return compiled


def stats() -> dict:
    """Current hit/miss counters (serve ready events and /healthz)."""
    return {"hits": int(metrics.value("cache.exec.hits")),
            "misses": int(metrics.value("cache.exec.misses"))}
