"""Symbolic lockstep: the batched interpreter stepping SYMBOLIC words.

This replaces the reference hot loop (mythril/laser/ethereum/svm.py:325-401 —
one Python GlobalState per instruction, JUMPI forking via deepcopy at
instructions.py:1633,1658) with a vmapped frontier: symbolic words live as
int32 arena node ids riding in planes parallel to the concrete StateBatch
(SymPlanes), new expressions are scatter-allocated arena rows
(parallel/arena.py), and a symbolic JUMPI pauses the lane (status=FORKING)
for the driver to duplicate — fork = lane copy + one constraint id per side,
never a deepcopy.

Division of labor per step:
  1. `_decide` (pre-pass): fetch each lane's opcode, look at which operands
     are symbolic, and classify — device-representable (arith/cmp/bitwise/
     memory round-trips/storage with concrete keys), FORK (symbolic JUMPI
     condition), or ESCAPE (CALL family, keccak over symbolic bytes, symbolic
     offsets/keys — everything the host oracle owns).
  2. `lockstep.step(state, force_escape, force_fork)` executes the concrete
     semantics; forced-out lanes take no effects.
  3. `_apply_sym_effects` (post-pass): allocate arena nodes for symbolic
     results and mirror the stack/memory/storage effects onto the planes.

Lanes escape exactly AT the instruction they cannot execute, so the host
engine (and its detector hooks) resumes them with full fidelity
(parallel/frontier.py materializes the GlobalState)."""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import arena as A
from . import lockstep
from . import words
from .batch import (DEAD, ERRORED, ESCAPED, FORKING, RUNNING,
                    StateBatch)

I32 = jnp.int32

O = lockstep.O
POPS_T = lockstep.POPS_T

# ops whose result is representable as an arena node when operands are
# symbolic (everything else with a symbolic operand escapes or forks)
_SYM_OK = np.zeros(256, dtype=bool)
for _name in ["ADD", "MUL", "SUB", "DIV", "SDIV", "MOD", "SMOD",
              "LT", "GT", "SLT", "SGT", "EQ", "ISZERO", "AND", "OR", "XOR",
              "NOT", "BYTE", "SHL", "SHR", "SAR"]:
    _SYM_OK[O[_name]] = True
# SIGNEXTEND deliberately absent: a symbolic size needs the host's 31-way
# If-chain (instructions.py); with a symbolic operand the lane escapes
SYM_OK_T = jnp.asarray(_SYM_OK)

# ops that never need symbolic handling: stack shuffling and constants flow
# the plane through _sym_stack_update instead
_PLUMBING = np.zeros(256, dtype=bool)
for _byte in range(0x5F, 0xA0):  # PUSH0-32, DUP1-16, SWAP1-16
    _PLUMBING[_byte] = True
_PLUMBING[O["POP"]] = True
_PLUMBING[O["JUMPDEST"]] = True
_PLUMBING[O["JUMP"]] = True
_PLUMBING[O["JUMPI"]] = True
_PLUMBING[O["PC"]] = True
_PLUMBING[O["MSIZE"]] = True
_PLUMBING[O["GAS"]] = True
_PLUMBING[O["STOP"]] = True
PLUMBING_T = jnp.asarray(_PLUMBING)

#: env opcode byte -> arena var class (symbolic-env lanes)
_ENV_CLASS = np.zeros(256, dtype=np.int32)
for _name, _cls in [("CALLER", A.V_CALLER), ("ORIGIN", A.V_ORIGIN),
                    ("CALLVALUE", A.V_CALLVALUE), ("GASPRICE", A.V_GASPRICE),
                    ("TIMESTAMP", A.V_TIMESTAMP), ("NUMBER", A.V_NUMBER),
                    ("COINBASE", A.V_COINBASE),
                    ("PREVRANDAO", A.V_PREVRANDAO),
                    ("BASEFEE", A.V_BASEFEE),
                    ("CALLDATASIZE", A.V_CALLDATASIZE)]:
    _ENV_CLASS[O[_name]] = _cls
ENV_CLASS_T = jnp.asarray(_ENV_CLASS)


# ---- telemetry plane ------------------------------------------------------------
# A small block of device-resident counters accumulated inside the fused
# step and piggybacked onto the per-chunk summary download (zero extra
# host syncs). `DeviceScheduler.telemetry is None` is a *static* Python
# branch under jit: the telemetry-off program contains no telemetry ops
# at all, so the A/B flag compares genuinely different executables.

#: opcode byte -> execution-histogram class
OP_CLASS_NAMES = ("arith", "cmp", "keccak", "env", "block", "mem",
                  "storage", "jump", "push", "dup", "swap", "log", "call",
                  "halt", "other")
N_OP_CLASSES = len(OP_CLASS_NAMES)
OP_CLASS = np.full(256, OP_CLASS_NAMES.index("other"), dtype=np.int32)
OP_CLASS[0x01:0x0C] = OP_CLASS_NAMES.index("arith")
OP_CLASS[0x10:0x1E] = OP_CLASS_NAMES.index("cmp")
OP_CLASS[0x20] = OP_CLASS_NAMES.index("keccak")
OP_CLASS[0x30:0x40] = OP_CLASS_NAMES.index("env")
OP_CLASS[0x5A] = OP_CLASS_NAMES.index("env")        # GAS
OP_CLASS[0x40:0x4B] = OP_CLASS_NAMES.index("block")
for _byte in (0x50, 0x51, 0x52, 0x53, 0x59, 0x5E):  # POP, M*, MSIZE, MCOPY
    OP_CLASS[_byte] = OP_CLASS_NAMES.index("mem")
for _byte in (0x54, 0x55, 0x5C, 0x5D):              # SLOAD/SSTORE/TLOAD/TSTORE
    OP_CLASS[_byte] = OP_CLASS_NAMES.index("storage")
for _byte in (0x56, 0x57, 0x58, 0x5B):              # JUMP/JUMPI/PC/JUMPDEST
    OP_CLASS[_byte] = OP_CLASS_NAMES.index("jump")
OP_CLASS[0x5F:0x80] = OP_CLASS_NAMES.index("push")
OP_CLASS[0x80:0x90] = OP_CLASS_NAMES.index("dup")
OP_CLASS[0x90:0xA0] = OP_CLASS_NAMES.index("swap")
OP_CLASS[0xA0:0xA5] = OP_CLASS_NAMES.index("log")
OP_CLASS[0xF0:0xFB] = OP_CLASS_NAMES.index("call")
for _byte in (0x00, 0xF3, 0xFD, 0xFE, 0xFF):  # STOP/RETURN/REVERT/INVALID/SD
    OP_CLASS[_byte] = OP_CLASS_NAMES.index("halt")
OP_CLASS_T = jnp.asarray(OP_CLASS)

#: lane lifecycle transition counters (LIVE→DEAD/FORKING/ESCAPED + pauses)
LIFECYCLE_NAMES = ("reseeds", "err_deaths", "overflow_kills",
                   "bad_jump_deaths", "esc_buffered", "esc_frozen",
                   "fork_waits", "cold_sloads", "forks_claimed",
                   "forks_pushed", "forks_spilled", "frozen_revived")
N_LIFECYCLE = len(LIFECYCLE_NAMES)

#: why lanes escaped to the host, priority-ordered most-specific-last
ESC_CAUSE_NAMES = ("halt", "sym_jump_dest", "detector_branch",
                   "sym_mem_off", "dirty_mload", "sym_storage_key",
                   "sym_mem_region", "host_op")
N_ESC_CAUSES = len(ESC_CAUSE_NAMES)


class Telemetry(NamedTuple):
    """Device-resident frontier counters (cumulative across chunks)."""

    op_hist: jnp.ndarray    # i64[N_OP_CLASSES] executed per opcode class
    lifecycle: jnp.ndarray  # i64[N_LIFECYCLE]
    esc_cause: jnp.ndarray  # i64[N_ESC_CAUSES]
    occupancy: jnp.ndarray  # i64[2] — (running-lane-step sum, steps)
    hwm: jnp.ndarray        # i64[2] — (stack_top high-water, esc_count hw)
    tag_pcs: jnp.ndarray    # i32[K] static merge/loop-header pcs (-1 empty)
    tag_occ: jnp.ndarray    # i64[K] running-lane-steps at each tagged pc
    fleet_slots: jnp.ndarray  # i32[C] static seeding-context -> fleet slot
    fleet_occ: jnp.ndarray    # i64[F] running-lane-steps per fleet slot


#: summary words contributed before the variable-length tag_occ block
TELEMETRY_FIXED_WORDS = N_OP_CLASSES + N_LIFECYCLE + N_ESC_CAUSES + 2 + 2


def new_telemetry(tag_pcs=None, fleet_slots=None, n_fleet=0) -> Telemetry:
    """Zeroed counter plane. `tag_pcs` is a host-side int sequence of
    merge-point / loop-header byte addresses to track occupancy at.
    `fleet_slots` maps each seeding-context index to one of `n_fleet`
    fleet slots (one slot per packed contract); when omitted the fleet
    occupancy block is empty and contributes no summary words."""
    pcs = np.asarray([] if tag_pcs is None else list(tag_pcs),
                     dtype=np.int32)
    slots = np.asarray([] if fleet_slots is None else list(fleet_slots),
                       dtype=np.int32)
    i64 = jnp.int64
    return Telemetry(
        op_hist=jnp.zeros(N_OP_CLASSES, dtype=i64),
        lifecycle=jnp.zeros(N_LIFECYCLE, dtype=i64),
        esc_cause=jnp.zeros(N_ESC_CAUSES, dtype=i64),
        occupancy=jnp.zeros(2, dtype=i64),
        hwm=jnp.zeros(2, dtype=i64),
        tag_pcs=jnp.asarray(pcs),
        tag_occ=jnp.zeros(pcs.shape[0], dtype=i64),
        fleet_slots=jnp.asarray(slots),
        fleet_occ=jnp.zeros(int(n_fleet), dtype=i64),
    )


def telemetry_words(tel: Telemetry) -> jnp.ndarray:
    """Flatten the counters into the i64 vector appended to the per-chunk
    summary (layout: op_hist | lifecycle | esc_cause | occupancy | hwm |
    tag_occ | fleet_occ; tag_pcs / fleet_slots are static and never
    downloaded)."""
    return jnp.concatenate([tel.op_hist, tel.lifecycle, tel.esc_cause,
                            tel.occupancy, tel.hwm, tel.tag_occ,
                            tel.fleet_occ])


class SymPlanes(NamedTuple):
    """Symbolic shadow of the concrete StateBatch (0 = concrete everywhere)."""

    stack_sym: jnp.ndarray     # int32[B, S] arena node per stack slot
    mem_sym: jnp.ndarray       # int32[B, M] (node << 5 | byte_index), 0=concrete
    storage_sym: jnp.ndarray   # int32[B, K] arena node per storage slot value
    storage_dirty: jnp.ndarray  # bool[B, K] slot written (not just faulted in)
    storage_base_sym: jnp.ndarray  # bool[B] storage base array is symbolic
    conds: jnp.ndarray         # int32[B, KC] signed node ids (neg = negated)
    cond_count: jnp.ndarray    # int32[B]
    fork_cond: jnp.ndarray     # int32[B] node id pending at a FORKING lane
    symbolic_env: jnp.ndarray  # bool[B] env/calldata are symbolic
    ctx_id: jnp.ndarray        # int32[B] seeding-context index (rides forks)
    branches: jnp.ndarray      # int32[B] JUMPI branches taken (host depth
    #                            parity: the host increments mstate.depth
    #                            per surviving JUMPI branch, concrete or
    #                            symbolic — materialization adds this)
    last_jump: jnp.ndarray     # int32[B] byte address of the last JUMP taken
    #                            (0 = none) — materializes as the exceptions
    #                            detector's LastJumpAnnotation source hint

    @classmethod
    def empty(cls, batch: int, stack_slots: int, mem_bytes: int,
              storage_slots: int, max_conds: int = 64) -> "SymPlanes":
        return cls(
            stack_sym=jnp.zeros((batch, stack_slots), dtype=I32),
            mem_sym=jnp.zeros((batch, mem_bytes), dtype=I32),
            storage_sym=jnp.zeros((batch, storage_slots), dtype=I32),
            storage_dirty=jnp.zeros((batch, storage_slots), dtype=bool),
            storage_base_sym=jnp.zeros(batch, dtype=bool),
            conds=jnp.zeros((batch, max_conds), dtype=I32),
            cond_count=jnp.zeros(batch, dtype=I32),
            fork_cond=jnp.zeros(batch, dtype=I32),
            symbolic_env=jnp.ones(batch, dtype=bool),
            ctx_id=jnp.full(batch, -1, dtype=I32),
            branches=jnp.zeros(batch, dtype=I32),
            last_jump=jnp.zeros(batch, dtype=I32),
        )


class DeviceScheduler(NamedTuple):
    """The frontier's worklist machine, resident in HBM (the tunnel charges
    ~100 ms per host-argument upload and ~30 ms + 35 MB/s per download, so
    scheduling decisions cannot touch the host):

      - `stack_*` is a DFS sibling stack: a forking lane that finds no DEAD
        lane to claim PUSHES its fall-through sibling here and continues down
        the taken side; DEAD lanes POP the deepest sibling at the next step.
        This replaces round-4's freeze-and-wait (which deadlocked the batch
        at tree depth log2(n_lanes) and handed everything to the host).
      - `esc_*` is the escape buffer: a lane that halts or reaches a
        host-owned instruction has its row copied here and is freed
        immediately; the host bulk-drains rows in bandwidth-sized batches
        instead of per-service gathers.
      - counters accumulate on device; the host reads them in the per-chunk
        summary fetch."""

    stack_state: StateBatch    # [P] sibling rows
    stack_planes: "SymPlanes"
    stack_top: jnp.ndarray     # i32 scalar, or i32[D] per-shard rows used
    esc_state: StateBatch      # [E] escaped rows
    esc_planes: "SymPlanes"
    esc_count: jnp.ndarray     # i32 scalar, or i32[D] per-shard rows used
    executed: jnp.ndarray      # i64 — instruction-states stepped
    forks: jnp.ndarray         # i64 — fork events (claims + pushes)
    pushes: jnp.ndarray        # i64 — siblings pushed to the stack
    pops: jnp.ndarray          # i64 — siblings reseeded from the stack
    enabled: jnp.ndarray       # bool — False = legacy freeze/escape semantics
    telemetry: Optional[Telemetry] = None  # None = telemetry compiled out
    # work-stealing counters (sharded schedulers only; None when n_shards=1):
    steals_sent: Optional[jnp.ndarray] = None      # i64[D] rows donated
    steals_received: Optional[jnp.ndarray] = None  # i64[D] rows adopted
    steal_rows: Optional[jnp.ndarray] = None       # i64 total rows moved


def new_scheduler(state: StateBatch, planes: SymPlanes, stack_rows: int,
                  esc_rows: int, disabled: bool = False,
                  telemetry: Optional[Telemetry] = None,
                  n_shards: int = 1) -> DeviceScheduler:
    """Allocate scheduler pools shaped like (state, planes) rows. With
    `disabled`, pushes/buffering/reseeds never engage — the legacy
    freeze-and-escape semantics for callers without a driver.

    With `n_shards` > 1 the pools are logically segmented: shard d owns
    pool rows [d*P/D, (d+1)*P/D) and the tops become i32[D] vectors, so
    reseeds/pushes/spills stay shard-local and the steal pass can move
    rows between segments. `stack_rows`/`esc_rows` must divide evenly."""
    if n_shards > 1:
        if stack_rows % n_shards or esc_rows % n_shards:
            raise ValueError(
                f"pool rows ({stack_rows}, {esc_rows}) must divide "
                f"n_shards={n_shards}")

    def rows(leaf, n):
        return jnp.zeros((n,) + tuple(leaf.shape[1:]), dtype=leaf.dtype)

    def top():
        if n_shards > 1:
            return jnp.zeros(n_shards, dtype=I32)
        return jnp.asarray(0, dtype=I32)

    return DeviceScheduler(
        stack_state=StateBatch(*[rows(leaf, stack_rows) for leaf in state]),
        stack_planes=SymPlanes(*[rows(leaf, stack_rows) for leaf in planes]),
        stack_top=top(),
        esc_state=StateBatch(*[rows(leaf, esc_rows) for leaf in state]),
        esc_planes=SymPlanes(*[rows(leaf, esc_rows) for leaf in planes]),
        esc_count=top(),
        executed=jnp.asarray(0, dtype=jnp.int64),
        forks=jnp.asarray(0, dtype=jnp.int64),
        pushes=jnp.asarray(0, dtype=jnp.int64),
        pops=jnp.asarray(0, dtype=jnp.int64),
        enabled=jnp.asarray(not disabled),
        telemetry=telemetry,
        steals_sent=(jnp.zeros(n_shards, dtype=jnp.int64)
                     if n_shards > 1 else None),
        steals_received=(jnp.zeros(n_shards, dtype=jnp.int64)
                         if n_shards > 1 else None),
        steal_rows=(jnp.asarray(0, dtype=jnp.int64)
                    if n_shards > 1 else None),
    )


def _where_rows(mask, rows, leaf):
    """Per-lane row select with mask broadcast over trailing dims."""
    return jnp.where(mask.reshape(mask.shape + (1,) * (leaf.ndim - 1)),
                     rows, leaf)


def _seg_rank(mask, n_seg):
    """Segment-local 0-based rank of True lanes: the lane axis is split
    into n_seg equal contiguous blocks (one per shard) and ranks restart
    at each block boundary. n_seg=1 degenerates to the global rank."""
    return (mask.astype(I32).reshape(n_seg, -1).cumsum(axis=1).reshape(-1)
            - 1)


def _seg_sum(mask, n_seg):
    """i32[n_seg] count of True lanes per contiguous lane block."""
    return mask.reshape(n_seg, -1).sum(axis=1, dtype=I32)


def _per_lane(vec, batch):
    """Broadcast an i32[n_seg] per-shard value to per-lane (i32[batch])."""
    return jnp.repeat(vec, batch // vec.shape[0])


def _operand_syms(state: StateBatch, planes: SymPlanes, n: int):
    """Arena node of the n-th-from-top stack slot (0 where concrete)."""
    idx = jnp.clip(state.sp - n, 0, planes.stack_sym.shape[1] - 1)
    return jnp.take_along_axis(planes.stack_sym, idx[:, None].astype(I32),
                               axis=1)[:, 0]


def _range_has_sym(plane_row_any, off, size, cap):
    """bool[B]: any symbolic byte in [off, off+size) of mem_sym."""
    j = jnp.arange(cap)
    in_range = (j[None, :] >= off[:, None]) & (j[None, :] < (off + size)[:, None])
    return jnp.any(in_range & (plane_row_any != 0), axis=1)


def sym_step(state: StateBatch, planes: SymPlanes, arena: A.Arena,
             sched: DeviceScheduler
             ) -> Tuple[StateBatch, SymPlanes, A.Arena, DeviceScheduler]:
    """One symbolic lockstep step for the whole batch."""
    batch, slots = planes.stack_sym.shape
    mem_cap = planes.mem_sym.shape[1]
    lane = jnp.arange(batch)

    # error-terminated lanes are done (the device escapes INVALID and
    # transaction-end opcodes explicitly; ERRORED here covers stack
    # under/overflow and out-of-gas bookkeeping, matching the round-4
    # service's reap) — free them so forks/reseeds can claim the slot
    if sched.telemetry is not None:
        n_err_freed = jnp.sum(state.status == ERRORED, dtype=jnp.int64)
    state = state._replace(status=jnp.where(
        state.status == ERRORED, I32(DEAD), state.status))

    # ---- reseed DEAD lanes from the sibling stack (deepest = top first) -------------
    # Sharded schedulers (stack_top i32[D]) treat the lane axis as D equal
    # contiguous blocks, each owning its own pool segment; ranks, sources
    # and top updates are all segment-local so no cross-shard gathers
    # appear in the step. D=1 reduces to the exact scalar math.
    pool_rows = sched.stack_state.status.shape[0]
    sharded = sched.stack_top.ndim == 1
    top_vec = jnp.atleast_1d(sched.stack_top)
    n_seg = top_vec.shape[0]
    seg_pool = pool_rows // n_seg
    top_l = _per_lane(top_vec, batch)
    base_l = _per_lane(jnp.arange(n_seg, dtype=I32) * seg_pool, batch)
    dead0 = state.status == DEAD
    rrank = _seg_rank(dead0, n_seg)
    take = dead0 & (rrank < top_l) & sched.enabled
    src = jnp.clip(base_l + top_l - 1 - rrank, 0,
                   max(pool_rows - 1, 0)).astype(I32)
    state = StateBatch(*[
        _where_rows(take, pool_leaf[src], leaf)
        for leaf, pool_leaf in zip(state, sched.stack_state)])
    planes = SymPlanes(*[
        _where_rows(take, pool_leaf[src], leaf)
        for leaf, pool_leaf in zip(planes, sched.stack_planes)])
    n_taken = _seg_sum(take, n_seg)
    new_top = top_vec - n_taken
    sched = sched._replace(
        stack_top=new_top if sharded else new_top[0],
        pops=sched.pops + jnp.sum(n_taken).astype(jnp.int64))

    running = state.status == RUNNING
    # instruction-state accounting ON device: reseeded lanes, claimed fork
    # targets and revived forkers all step inside the fused loop where
    # host-side status diffs cannot see them
    sched = sched._replace(executed=sched.executed + jnp.sum(
        running.astype(jnp.int64)))

    # ---- fetch (same as lockstep) ---------------------------------------------------
    in_code = state.pc < state.code_len
    op = jnp.where(
        in_code,
        jnp.take_along_axis(state.code,
                            jnp.clip(state.pc, 0, state.code.shape[1] - 1)
                            [:, None], axis=1)[:, 0].astype(I32),
        I32(O["STOP"]))

    def is_op(name):
        return op == O[name]

    sym1 = _operand_syms(state, planes, 1)
    sym2 = _operand_syms(state, planes, 2)
    sym3 = _operand_syms(state, planes, 3)
    pops = POPS_T[op]
    has1 = (pops >= 1) & (sym1 != 0)
    has2 = (pops >= 2) & (sym2 != 0)
    has3 = (pops >= 3) & (sym3 != 0)
    any_operand_sym = has1 | has2 | has3

    a_limbs = lockstep._peek(state, 1)
    b_limbs = lockstep._peek(state, 2)

    off_i, off_fits = lockstep._word_to_i64(a_limbs)

    symbolic_env = planes.symbolic_env
    env_class = ENV_CLASS_T[op]
    env_var_op = running & symbolic_env & (env_class != 0)
    cdl_op = running & symbolic_env & is_op("CALLDATALOAD")
    cdl_sym_off = cdl_op & (sym1 != 0)
    cdl_var = cdl_op & (sym1 == 0) & off_fits & (off_i < (1 << 30))

    # memory round-trip classification
    mstore_sym_val = running & is_op("MSTORE") & (sym1 == 0) & (sym2 != 0)
    mload_mask = running & is_op("MLOAD") & (sym1 == 0)
    mload_first = jnp.take_along_axis(
        planes.mem_sym, jnp.clip(off_i, 0, mem_cap - 1).astype(I32)[:, None],
        axis=1)[:, 0]
    # int32 so scattered plane values never promote to int64 (x64 is on;
    # an int64 value into the int32 mem_sym plane is a future hard error)
    j32 = jnp.arange(32, dtype=I32)
    mload_idx = jnp.clip(off_i[:, None] + j32, 0, mem_cap - 1).astype(I32)
    mload_cells = jnp.take_along_axis(planes.mem_sym, mload_idx, axis=1)
    mload_any_sym = jnp.any(mload_cells != 0, axis=1)
    # the clean round-trip: 32 cells hold (node, 0..31) in order
    expected = jnp.where((mload_first != 0)[:, None],
                         ((mload_first >> 5) << 5)[:, None] + j32[None, :], 0)
    mload_clean = mload_any_sym & (mload_first != 0) \
        & ((mload_first & 31) == 0) & jnp.all(mload_cells == expected, axis=1)
    mload_node = jnp.where(mload_clean, mload_first >> 5, 0)
    mload_dirty = mload_mask & mload_any_sym & ~mload_clean

    # storage
    sload_mask = running & is_op("SLOAD")
    sstore_mask = running & is_op("SSTORE")
    storage_match = state.storage_used & jnp.all(
        state.storage_keys == a_limbs[:, None, :], axis=-1)
    storage_found = jnp.any(storage_match, axis=-1)
    storage_slot = jnp.argmax(storage_match, axis=-1)
    sload_node = jnp.where(
        sload_mask & storage_found,
        planes.storage_sym[lane, storage_slot], 0)

    # ---- classify: FORK / PAUSE -----------------------------------------------------
    jumpi_sym_cond = running & is_op("JUMPI") & (sym2 != 0) & (sym1 == 0)
    # conditions whose taint cone contains origin/block-attribute classes
    # must visit the host at the JUMPI (dependence detectors hook it); all
    # other symbolic conditions fork ON DEVICE below — no host service, no
    # batch round-trip through the tunnel (the round-3 bench stall was
    # per-fork full-batch transfers)
    cond_cls = arena.cls[jnp.clip(sym2, 0, arena.capacity - 1)]
    cond_room = planes.cond_count + 1 <= planes.conds.shape[1]
    jumpi_host = jumpi_sym_cond & (((cond_cls & A.PREDICTABLE_MASK) != 0)
                                   | ~cond_room)
    jumpi_fork = jumpi_sym_cond & ~jumpi_host
    # saturated forkers WAIT frozen (status FORKING) and are revived here
    # once escapes free lanes: their pc still sits on the JUMPI, so the
    # same decode re-classifies them each step
    frozen_fork = (state.status == FORKING) & is_op("JUMPI") \
        & (sym2 != 0) & (sym1 == 0) & cond_room \
        & ((cond_cls & A.PREDICTABLE_MASK) == 0)
    # cold SLOAD on a symbolic-base storage: the key is concrete but absent
    # from the device table — pause the lane (status FORKING, pc still at the
    # SLOAD) so the driver can fault the slot in as a Select(base, key)
    # host-term leaf and resume the lane on device (the reference's lazy
    # Storage fault-in, mythril/laser/ethereum/state/account.py:43-76,
    # re-expressed as a host service)
    sload_cold = sload_mask & (sym1 == 0) & planes.storage_base_sym \
        & ~storage_found
    force_fork = jumpi_fork | sload_cold

    # ---- classify: ESCAPE -----------------------------------------------------------
    sym_representable = SYM_OK_T[op] | PLUMBING_T[op]
    # transaction-end opcodes ALWAYS go to the host in symbolic mode: the
    # TransactionEndSignal machinery (open-state add, potential-issue checks)
    # and the exceptions detector's INVALID hook live there
    esc_always = running & (is_op("STOP") | is_op("RETURN") | is_op("REVERT")
                            | is_op("INVALID"))
    # symbolic operand feeding an op the device cannot represent
    esc = any_operand_sym & ~sym_representable & ~mstore_sym_val \
        & ~(sload_mask | sstore_mask)
    # memory ops with symbolic offsets/sizes
    esc = esc | (running & is_op("JUMP") & (sym1 != 0))
    esc = esc | (running & is_op("JUMPI") & (sym1 != 0))   # symbolic dest
    esc = esc | jumpi_host  # detector-relevant branch condition
    esc = esc | (running & is_op("MSTORE") & (sym1 != 0))
    esc = esc | (running & is_op("MLOAD") & (sym1 != 0))
    esc = esc | cdl_sym_off
    esc = esc | mload_dirty
    # storage with symbolic key
    esc = esc | ((sload_mask | sstore_mask) & (sym1 != 0))
    # SHA3 / RETURN / REVERT over symbolic memory bytes go to the host (the
    # keccak function manager and return-data semantics live there)
    size_for_read = jnp.where(is_op("SHA3") | is_op("RETURN")
                              | is_op("REVERT"),
                              lockstep._word_to_i64(b_limbs)[0], 0)
    mem_region_sym = _range_has_sym(planes.mem_sym, off_i,
                                    jnp.clip(size_for_read, 0, mem_cap),
                                    mem_cap)
    esc = esc | (running & (is_op("SHA3") | is_op("RETURN") | is_op("REVERT"))
                 & (sym1 == 0) & (sym2 == 0) & mem_region_sym)
    # symbolic-calldata lanes cannot run byte-copies from calldata, and
    # balances are symbolic arrays only the host models
    esc = esc | (running & symbolic_env & is_op("CALLDATACOPY"))
    esc = esc | (running & symbolic_env & is_op("SELFBALANCE"))
    # concrete copies landing on symbolically-marked bytes would need the
    # marks cleared byte-accurately; hand those to the host instead
    copy_size_i = lockstep._word_to_i64(
        lockstep._peek(state, 3))[0]
    esc = esc | (running & (is_op("CODECOPY") | is_op("RETURNDATACOPY"))
                 & _range_has_sym(planes.mem_sym, off_i,
                                  jnp.clip(copy_size_i, 0, mem_cap), mem_cap))
    # MCOPY with any symbolic memory in the lane (byte-accurate plane moves
    # are not worth the complexity at this tier)
    esc = esc | (running & is_op("MCOPY")
                 & jnp.any(planes.mem_sym != 0, axis=1))
    force_escape = (esc | esc_always) & ~force_fork

    # ---- concrete semantics (forced-out lanes untouched) ----------------------------
    new_state = lockstep.step(state, force_escape=force_escape,
                              force_fork=force_fork)

    # ---- allocate nodes -------------------------------------------------------------
    advanced = running & ~force_escape & ~force_fork \
        & (new_state.status == RUNNING)

    # const wraps for concrete operands of symbolic ops
    sym_compute = advanced & any_operand_sym & SYM_OK_T[op]
    need_const_a = sym_compute & (sym1 == 0) & (pops >= 1)
    arena, const_a, ovf_a = A.alloc_consts(arena, need_const_a, a_limbs)
    need_const_b = sym_compute & (sym2 == 0) & (pops >= 2)
    arena, const_b, ovf_b = A.alloc_consts(arena, need_const_b, b_limbs)
    node_a = jnp.where(sym1 != 0, sym1, const_a)
    node_b = jnp.where(sym2 != 0, sym2, const_b)

    # MSTORE of a symbolic value: value node is operand 2
    # SSTORE of a symbolic value with concrete key: store node directly
    sstore_sym_val = advanced & sstore_mask & (sym1 == 0) & (sym2 != 0)

    # result nodes for computations; imm2 records the instruction's byte
    # address — host-side conversion reconstructs the integer detector's
    # OverUnderflowAnnotation (operator + site) from it
    arena, result_node, ovf_r = A.alloc_rows(
        arena, sym_compute, op, node_a, node_b, jnp.zeros_like(node_a),
        jnp.zeros_like(node_a), state.pc.astype(I32))

    # env var nodes
    env_alloc = advanced & (env_var_op | cdl_var)
    var_class = jnp.where(cdl_var, A.V_CALLDATA_WORD, env_class)
    var_qual = jnp.where(cdl_var, off_i.astype(I32), 0)
    arena, env_node, ovf_e = A.alloc_rows(
        arena, env_alloc, jnp.full_like(op, A.VAR), jnp.zeros_like(op),
        jnp.zeros_like(op), jnp.zeros_like(op), var_class, var_qual)

    overflow = ovf_a | ovf_b | ovf_r | ovf_e
    # arena exhaustion: the state already advanced with a zero (=concrete)
    # node, which would silently corrupt — kill the lane. The driver keeps
    # head-room per chunk (frontier.ARENA_HEADROOM) so this is a last-resort
    # guard, and killed lanes are counted, never silent.
    new_state = new_state._replace(
        status=jnp.where(overflow, DEAD, new_state.status))

    # ---- mirror plane effects -------------------------------------------------------
    new_top_node = jnp.where(sym_compute, result_node,
                             jnp.where(env_alloc, env_node,
                                       jnp.where(mload_mask & mload_clean,
                                                 mload_node, sload_node)))

    new_planes = _sym_stack_update(state, new_state, planes, op, advanced,
                                   new_top_node)

    # MSTORE symbolic value: mark 32 bytes (node<<5 | byte_index)
    mstore_adv = advanced & mstore_sym_val
    mem_sym = new_planes.mem_sym
    write_idx = jnp.where(mstore_adv[:, None],
                          jnp.clip(off_i[:, None] + j32, 0, mem_cap - 1),
                          mem_cap).astype(I32)
    mem_sym = mem_sym.at[lane[:, None], write_idx].set(
        jnp.where(mstore_adv[:, None], (sym2[:, None] << 5) + j32[None, :], 0),
        mode="drop")
    # concrete MSTORE over previously-symbolic bytes clears the marks
    mstore_concrete = advanced & is_op("MSTORE") & (sym1 == 0) & (sym2 == 0)
    clear_idx = jnp.where(mstore_concrete[:, None],
                          jnp.clip(off_i[:, None] + j32, 0, mem_cap - 1),
                          mem_cap).astype(I32)
    mem_sym = mem_sym.at[lane[:, None], clear_idx].set(0, mode="drop")
    # concrete MSTORE8 clears its single byte's mark (a stale mark would let
    # a later MLOAD resurrect the overwritten symbolic word)
    mstore8_concrete = advanced & is_op("MSTORE8") & (sym1 == 0) & (sym2 == 0)
    clear8_idx = jnp.where(mstore8_concrete,
                           jnp.clip(off_i, 0, mem_cap - 1),
                           mem_cap).astype(I32)
    mem_sym = mem_sym.at[lane, clear8_idx].set(0, mode="drop")

    # storage plane: symbolic SSTORE sets the slot's node, concrete clears it
    storage_sym = new_planes.storage_sym
    new_match = new_state.storage_used & jnp.all(
        new_state.storage_keys == a_limbs[:, None, :], axis=-1)
    new_slot = jnp.argmax(new_match, axis=-1)
    sstore_any = advanced & sstore_mask & (sym1 == 0) \
        & jnp.any(new_match, axis=-1)
    storage_sym = storage_sym.at[
        jnp.where(sstore_any, lane, batch),
        jnp.where(sstore_any, new_slot, 0)].set(
        jnp.where(sstore_any, sym2, 0), mode="drop")
    # every SSTORE marks its slot dirty: materialization writes back only
    # dirty slots (faulted-in reads and seeds are already in the template)
    storage_dirty = new_planes.storage_dirty.at[
        jnp.where(sstore_any, lane, batch),
        jnp.where(sstore_any, new_slot, 0)].set(
        jnp.where(sstore_any, True, False), mode="drop")

    # fork condition marks WAITING forkers for the driver; a cold-SLOAD
    # pause must CLEAR it (a stale node from the lane's previous fork would
    # misclassify the pause and strand the lane — the driver dispatches on
    # fork_cond == 0 for the fault-in service)
    fork_cond = jnp.where(
        (state.status == RUNNING) & jumpi_fork, sym2,
        jnp.where((state.status == RUNNING) & sload_cold, 0,
                  new_planes.fork_cond))

    new_planes = new_planes._replace(
        mem_sym=mem_sym, storage_sym=storage_sym,
        storage_dirty=storage_dirty, fork_cond=fork_cond,
        # a CONCRETE-condition JUMPI executes on device (plumbing) and
        # counts one branch, matching host jumpi_'s depth increment;
        # symbolic forks count via the fork block below (both sides
        # inherit the forker's counter + 1)
        branches=jnp.where(advanced & is_op("JUMPI"),
                           new_planes.branches + 1,
                           new_planes.branches).astype(I32),
        last_jump=jnp.where(advanced & is_op("JUMP"), state.pc,
                            new_planes.last_jump).astype(I32))

    # ---- escape buffering (before forking: freed lanes are claimable) ---------------
    # Halting / host-owned lanes move their row into the escape buffer and
    # free the lane immediately; the host bulk-drains the buffer in light
    # packed transfers. Buffer full -> the lane stays frozen ESCAPED and
    # the next summary sends the driver down the direct-materialize
    # fallback.
    esc_rows = sched.esc_state.status.shape[0]
    ecount_vec = jnp.atleast_1d(sched.esc_count)
    seg_esc = esc_rows // n_seg
    ecount_l = _per_lane(ecount_vec, batch)
    ebase_l = _per_lane(jnp.arange(n_seg, dtype=I32) * seg_esc, batch)
    esc_now = (new_state.status == ESCAPED) & sched.enabled
    erank = _seg_rank(esc_now, n_seg)
    put = esc_now & (erank < (seg_esc - ecount_l))
    eslot = jnp.where(put, ebase_l + ecount_l + erank, esc_rows).astype(I32)
    esc_state = StateBatch(*[
        pool_leaf.at[eslot].set(leaf, mode="drop")
        for pool_leaf, leaf in zip(sched.esc_state, new_state)])
    esc_planes = SymPlanes(*[
        pool_leaf.at[eslot].set(leaf, mode="drop")
        for pool_leaf, leaf in zip(sched.esc_planes, new_planes)])
    esc_used_vec = ecount_vec + _seg_sum(put, n_seg)
    esc_used = esc_used_vec if sharded else esc_used_vec[0]
    sched = sched._replace(esc_state=esc_state, esc_planes=esc_planes,
                           esc_count=esc_used)
    new_state = new_state._replace(
        status=jnp.where(put, I32(DEAD), new_state.status))

    # ---- on-device JUMPI forking ----------------------------------------------------
    # A forking lane takes the jump and its fall-through sibling goes to
    # ONE of three places, all inside the fused loop (reference forks at
    # instructions.py:1633,1658 via deepcopy; here a fork is a row copy and
    # one signed condition id per side):
    #   claim — a DEAD lane exists: the sibling runs in parallel (width);
    #   push  — batch saturated: the sibling row is pushed onto the
    #           scheduler's DFS stack and reseeds a lane later (depth);
    #   spill — stack ALSO full: the sibling row goes into the ESCAPE
    #           buffer — it drains to the host as a light packed row and
    #           the host explores that subtree within its own budget.
    # Only with every tier full does the forker freeze (FORKING +
    # fork_cond marker) for the driver. Feasibility is NOT checked here:
    # lanes explore optimistically, exactly like the host engine's jumpi_.
    max_conds = planes.conds.shape[1]
    want = jumpi_fork | frozen_fork  # cond_room baked into both
    # claims, pushes and spills are all segment-local when sharded: a
    # sibling lands in its own block's dead lanes / pool segment / escape
    # segment, preserving per-shard member affinity
    lane_base_l = _per_lane(jnp.arange(n_seg, dtype=I32) * (batch // n_seg),
                            batch)
    is_dead = new_state.status == DEAD
    dead_rank = _seg_rank(is_dead, n_seg)
    dead_map = jnp.zeros(batch, dtype=I32).at[
        jnp.where(is_dead, lane_base_l + dead_rank, batch)].set(
        lane.astype(I32), mode="drop")
    fork_rank = _seg_rank(want, n_seg)
    n_dead_l = _per_lane(_seg_sum(is_dead, n_seg), batch)
    have_target = want & (fork_rank < n_dead_l)
    target = jnp.where(have_target,
                       dead_map[jnp.clip(lane_base_l + fork_rank, 0,
                                         batch - 1)],
                       batch).astype(I32)
    # saturated forkers push their sibling onto the DFS stack
    top2_vec = jnp.atleast_1d(sched.stack_top)
    top2_l = _per_lane(top2_vec, batch)
    push_want = want & ~have_target & sched.enabled
    push_rank = _seg_rank(push_want, n_seg)
    push = push_want & (push_rank < (seg_pool - top2_l))
    # stack full: the sibling spills into the escape buffer instead
    eused_l = _per_lane(esc_used_vec, batch)
    spill_want = push_want & ~push
    spill_rank = _seg_rank(spill_want, n_seg)
    spill = spill_want & (spill_rank < (seg_esc - eused_l))
    act = have_target | push | spill

    # taken-side destination validity (dest = concrete stack top)
    code_cap = state.code.shape[1]
    dest_in = off_fits & (off_i >= 0) & (off_i < state.code_len)
    dest_bitmap = jnp.take_along_axis(
        state.jumpdest, jnp.clip(off_i, 0, code_cap - 1)[:, None].astype(I32),
        axis=1)[:, 0]
    dest_ok = dest_in & dest_bitmap

    count = jnp.clip(planes.cond_count, 0, max_conds - 1)

    # 1. prepare the forker row as the shared post-fork template: sp -= 2,
    #    gas charged, +cond appended, dead stack_sym slots cleared
    sp_fork = jnp.where(act, state.sp - 2, new_state.sp)
    gas_fork = jnp.where(act,
                         state.gas_used + lockstep.GAS_MIN_T[op],
                         new_state.gas_used)
    conds_fork = new_planes.conds.at[
        jnp.where(act, lane, batch), count].set(sym2, mode="drop")
    ccount_fork = jnp.where(act, planes.cond_count + 1,
                            new_planes.cond_count)
    branches_fork = jnp.where(act, planes.branches + 1,
                              new_planes.branches).astype(I32)
    j_slots = jnp.arange(slots)
    cleared = act[:, None] & (j_slots[None, :] >= sp_fork[:, None])
    ssym_fork = jnp.where(cleared, 0, new_planes.stack_sym)
    state_a = new_state._replace(sp=sp_fork, gas_used=gas_fork)
    planes_a = new_planes._replace(conds=conds_fork, cond_count=ccount_fork,
                                   stack_sym=ssym_fork,
                                   branches=branches_fork)

    # 2. the fall-through SIBLING rows: pc+1, flipped condition sign,
    #    RUNNING, no wait marker
    sib_conds = conds_fork.at[
        jnp.where(act, lane, batch), count].set(-sym2, mode="drop")
    sib_state = state_a._replace(
        pc=jnp.where(act, state.pc + 1, state_a.pc).astype(I32),
        status=jnp.where(act, I32(RUNNING), state_a.status))
    sib_planes = planes_a._replace(
        conds=sib_conds,
        fork_cond=jnp.where(act, 0, planes_a.fork_cond))

    # 3a. claim: copy sibling rows into the claimed DEAD lanes
    state_b = StateBatch(*[
        field.at[target].set(sib, mode="drop")
        for field, sib in zip(state_a, sib_state)])
    planes_b = SymPlanes(*[
        field.at[target].set(sib, mode="drop")
        for field, sib in zip(planes_a, sib_planes)])

    # 3b. push: scatter sibling rows onto the scheduler stack
    dst = jnp.where(push, base_l + top2_l + push_rank,
                    pool_rows).astype(I32)
    stack_state = StateBatch(*[
        pool_leaf.at[dst].set(sib, mode="drop")
        for pool_leaf, sib in zip(sched.stack_state, sib_state)])
    stack_planes = SymPlanes(*[
        pool_leaf.at[dst].set(sib, mode="drop")
        for pool_leaf, sib in zip(sched.stack_planes, sib_planes)])
    n_push = _seg_sum(push, n_seg)

    # 3c. spill: scatter sibling rows into the escape buffer (after any
    #     rows buffered by this step's escapes)
    sdst = jnp.where(spill, ebase_l + eused_l + spill_rank,
                     esc_rows).astype(I32)
    esc_state = StateBatch(*[
        pool_leaf.at[sdst].set(sib, mode="drop")
        for pool_leaf, sib in zip(sched.esc_state, sib_state)])
    esc_planes = SymPlanes(*[
        pool_leaf.at[sdst].set(sib, mode="drop")
        for pool_leaf, sib in zip(sched.esc_planes, sib_planes)])
    n_spill = _seg_sum(spill, n_seg)
    top3_vec = top2_vec + n_push
    esc3_vec = esc_used_vec + n_spill
    sched = sched._replace(
        stack_state=stack_state, stack_planes=stack_planes,
        stack_top=top3_vec if sharded else top3_vec[0],
        esc_state=esc_state, esc_planes=esc_planes,
        esc_count=esc3_vec if sharded else esc3_vec[0],
        pushes=sched.pushes + jnp.sum(n_push).astype(jnp.int64),
        forks=sched.forks + jnp.sum(act).astype(jnp.int64))

    # 4. forker divergence: take the jump (or die on an invalid dest)
    pc_final = jnp.where(act, off_i.astype(I32), state_b.pc)
    status_final = jnp.where(
        act, jnp.where(dest_ok, RUNNING, DEAD), state_b.status)
    # the fork is consumed: clear the waiting marker (a stale marker would
    # misclassify this lane's next pause as a fork-wait); non-act waiters
    # keep theirs and freeze until capacity appears
    fcond_final = jnp.where(act, 0, planes_b.fork_cond)

    new_state = state_b._replace(pc=pc_final, status=status_final)
    new_planes = planes_b._replace(fork_cond=fcond_final)

    # ---- telemetry accumulation (statically compiled out when off) ------------------
    tel = sched.telemetry
    if tel is not None:
        one = jnp.int64(1)
        op_hist = tel.op_hist.at[
            jnp.where(running, OP_CLASS_T[op], N_OP_CLASSES)].add(
            one, mode="drop")

        # escape cause: where-chain generic -> specific, so the most
        # specific matching cause wins; scatter-add over escaping lanes
        cause = jnp.full(batch, N_ESC_CAUSES, dtype=I32)
        # cause names live in a local so the tuple below is (mask, name)
        # pairs of NAMES — not a literal the opcode-parity lint would
        # read as mnemonic references
        cause_masks = (
            (force_escape, "host_op"),
            ((running & (is_op("SHA3") | is_op("RETURN")
                         | is_op("REVERT"))
              & (sym1 == 0) & (sym2 == 0) & mem_region_sym)
             | (running & (is_op("CODECOPY") | is_op("RETURNDATACOPY"))
                & _range_has_sym(planes.mem_sym, off_i,
                                 jnp.clip(copy_size_i, 0, mem_cap),
                                 mem_cap))
             | (running & is_op("MCOPY")
                & jnp.any(planes.mem_sym != 0, axis=1)),
             "sym_mem_region"),
            ((sload_mask | sstore_mask) & (sym1 != 0),
             "sym_storage_key"),
            (mload_dirty, "dirty_mload"),
            ((running & (is_op("MSTORE") | is_op("MLOAD"))
              & (sym1 != 0)) | cdl_sym_off, "sym_mem_off"),
            (jumpi_host, "detector_branch"),
            (running & (is_op("JUMP") | is_op("JUMPI")) & (sym1 != 0),
             "sym_jump_dest"),
            (esc_always, "halt"))
        for mask, name in cause_masks:
            cause = jnp.where(mask, I32(ESC_CAUSE_NAMES.index(name)), cause)
        esc_cause = tel.esc_cause.at[
            jnp.where(force_escape, cause, N_ESC_CAUSES)].add(
            one, mode="drop")

        lc_deltas = jnp.stack([
            jnp.sum(n_taken, dtype=jnp.int64),                # reseeds
            n_err_freed,                                      # err_deaths
            jnp.sum(overflow, dtype=jnp.int64),               # overflow_kills
            jnp.sum(act & ~dest_ok, dtype=jnp.int64),         # bad_jump_deaths
            jnp.sum(put, dtype=jnp.int64),                    # esc_buffered
            jnp.sum(esc_now & ~put, dtype=jnp.int64),         # esc_frozen
            jnp.sum(want & ~act, dtype=jnp.int64),            # fork_waits
            jnp.sum(sload_cold, dtype=jnp.int64),             # cold_sloads
            jnp.sum(have_target, dtype=jnp.int64),            # forks_claimed
            jnp.sum(push, dtype=jnp.int64),                   # forks_pushed
            jnp.sum(spill, dtype=jnp.int64),                  # forks_spilled
            jnp.sum(frozen_fork & act, dtype=jnp.int64),      # frozen_revived
        ])

        occupancy = tel.occupancy + jnp.stack(
            [jnp.sum(running, dtype=jnp.int64), one])
        # vector tops (sharded) report the global rows-in-use high water
        hwm = jnp.maximum(tel.hwm, jnp.stack(
            [jnp.sum(sched.stack_top).astype(jnp.int64),
             jnp.sum(sched.esc_count).astype(jnp.int64)]))
        # per merge-tag / loop-header occupancy: running lanes whose fetch
        # pc sits at a tagged address (state.pc is the pre-step pc here)
        if tel.tag_pcs.shape[0]:
            tag_occ = tel.tag_occ + jnp.sum(
                running[:, None]
                & (state.pc[:, None] == tel.tag_pcs[None, :]),
                axis=0, dtype=jnp.int64)
        else:
            tag_occ = tel.tag_occ
        # per-contract fleet occupancy: running lanes bucketed by the
        # fleet slot their seeding context belongs to (scatter-add with
        # out-of-range drop, same shape as the op_hist accumulation)
        if tel.fleet_occ.shape[0]:
            n_ctx = tel.fleet_slots.shape[0]
            lane_slot = tel.fleet_slots[
                jnp.clip(planes.ctx_id, 0, n_ctx - 1)]
            fleet_occ = tel.fleet_occ.at[
                jnp.where(running, lane_slot, tel.fleet_occ.shape[0])].add(
                one, mode="drop")
        else:
            fleet_occ = tel.fleet_occ
        sched = sched._replace(telemetry=tel._replace(
            op_hist=op_hist, lifecycle=tel.lifecycle + lc_deltas,
            esc_cause=esc_cause, occupancy=occupancy, hwm=hwm,
            tag_occ=tag_occ, fleet_occ=fleet_occ))

    return new_state, new_planes, arena, sched


def _sym_stack_update(state: StateBatch, new_state: StateBatch,
                      planes: SymPlanes, op, advanced, new_top_node
                      ) -> SymPlanes:
    """Mirror the concrete stack effect onto the node plane: drop pops, keep
    the tail, write the produced node (or 0) at the new top; DUP copies the
    source slot's node; SWAP exchanges two nodes."""
    batch, slots = planes.stack_sym.shape
    lane = jnp.arange(batch)
    stack_sym = planes.stack_sym

    is_dup = (op >= 0x80) & (op <= 0x8F)
    is_swap = (op >= 0x90) & (op <= 0x9F)
    pushes = lockstep.PUSHES_T[op]
    writes_result = (pushes >= 1) & ~is_swap

    dup_n = jnp.clip(op - 0x7F, 1, 16)
    dup_src = jnp.clip(state.sp - dup_n, 0, slots - 1)
    dup_node = stack_sym[lane, dup_src]

    top_value = jnp.where(is_dup, dup_node, new_top_node)
    write_idx = jnp.clip(new_state.sp - 1, 0, slots - 1)
    do_write = advanced & writes_result
    stack_sym = stack_sym.at[jnp.where(do_write, lane, batch),
                             write_idx].set(
        jnp.where(do_write, top_value, 0), mode="drop")

    # slots above the new sp are dead: clear so stale nodes never resurface
    j = jnp.arange(slots)[None, :]
    above = advanced[:, None] & (j >= new_state.sp[:, None])
    stack_sym = jnp.where(above, 0, stack_sym)

    # SWAPn exchanges (sp-1) and (sp-1-n)
    swap_n = jnp.clip(op - 0x8F, 1, 16)
    swap_do = advanced & is_swap
    top_idx = jnp.clip(state.sp - 1, 0, slots - 1)
    deep_idx = jnp.clip(state.sp - 1 - swap_n, 0, slots - 1)
    top_node = stack_sym[lane, top_idx]
    deep_node = stack_sym[lane, deep_idx]
    stack_sym = stack_sym.at[jnp.where(swap_do, lane, batch),
                             top_idx].set(
        jnp.where(swap_do, deep_node, 0), mode="drop")
    stack_sym = stack_sym.at[jnp.where(swap_do, lane, batch),
                             deep_idx].set(
        jnp.where(swap_do, top_node, 0), mode="drop")

    return planes._replace(stack_sym=stack_sym)


@partial(jax.jit, static_argnames=("n_steps",))
def run_chunk(state: StateBatch, planes: SymPlanes, arena: A.Arena,
              sched: DeviceScheduler, n_steps: int):
    """n_steps fused symbolic steps with the on-device scheduler engaged:
    forks claim lanes or push siblings, DEAD lanes reseed from the stack,
    escapes buffer — zero host involvement inside the chunk."""
    def body(_, carry):
        return sym_step(*carry)

    return jax.lax.fori_loop(0, n_steps, body,
                             (state, planes, arena, sched))


@partial(jax.jit, static_argnames=("n_steps",))
def sym_step_many(state: StateBatch, planes: SymPlanes, arena: A.Arena,
                  n_steps: int):
    """Legacy driver-less entry: scheduler disabled, so forking lanes
    freeze at saturation and escapes stay frozen ESCAPED (round-4
    semantics for tests / the graft entry)."""
    sched = new_scheduler(state, planes, 1, 1, disabled=True)

    def body(_, carry):
        return sym_step(*carry)

    state, planes, arena, _ = jax.lax.fori_loop(
        0, n_steps, body, (state, planes, arena, sched))
    return state, planes, arena


@partial(jax.jit, static_argnames=("n_steps",))
def sym_step_many_counted(state: StateBatch, planes: SymPlanes,
                          arena: A.Arena, n_steps: int):
    """Legacy entry plus the executed-instruction count (profiling)."""
    sched = new_scheduler(state, planes, 1, 1, disabled=True)

    def body(_, carry):
        return sym_step(*carry)

    state, planes, arena, sched = jax.lax.fori_loop(
        0, n_steps, body, (state, planes, arena, sched))
    return state, planes, arena, sched.executed


# ---- on-device state merging (veritesting) --------------------------------------
# Fork siblings that reconverged at a post-dominator pc are redundant: their
# path conditions differ ONLY in the sign of the last condition appended at
# the fork ((P & c) | (P & ~c) = P), and their machine states differ only in
# the effects the two diamond arms produced. The merge pass pairs such lanes
# and collapses each pair into ONE lane: drop the final condition, ITE-blend
# every differing stack / storage slot through the arena's internal ite node
# (op 0x0F — the host converts it to If(c, then, else), smt terms), retire
# the partner DEAD so forks and reseeds reclaim it.
#
# Pairing is sort-based (the embarrassingly-parallel shape the ISSUE names):
# every eligible lane gets a content hash over the leaves a merge must NOT
# blend (pc, sp, memory, storage keys, conds prefix, ...), lanes sort by
# (hash, last-cond sign), and adjacent (-, +) positions are verified exactly
# before merging — a hash collision can only MISS a merge, never corrupt
# one. Lanes allocate arena nodes independently, so only true fork siblings
# (row copies sharing the conds prefix by id) pair up; cousin pairs merge
# bottom-up across repeated rounds, collapsing a 2^k reconverged subtree in
# k rounds.

#: frontier.merge.ite_depth histogram buckets (blended slots per pair)
MERGE_DEPTH_LABELS = ("0", "1", "2", "3", "4-7", "8+")
N_MERGE_DEPTH = len(MERGE_DEPTH_LABELS)

#: frontier.merge.blocked_by.* counter order in the stats vector — the
#: accounting pass pairs reconverged-looking lanes that did NOT merge and
#: charges each to the first gate that refused it
MERGE_BLOCKED_LABELS = ("memory", "mem_sym", "storage_keys", "tstore",
                        "depth")

#: merge-pass stats vector layout:
#: [merges, ites, mem_blends, blocked_by[5], tag_hits[K], depth_hist]
MERGE_STATS_FIXED = 3 + len(MERGE_BLOCKED_LABELS)

_H_PRIME = 1099511628211
_H_MASK = (1 << 62) - 1


def _merge_fold(acc, leaf):
    """Fold one per-lane leaf into the lane content hash (int64 wraparound
    arithmetic; position-weighted so permuted content hashes apart)."""
    flat = leaf.reshape(leaf.shape[0], -1).astype(jnp.int64)
    mult = (jnp.arange(flat.shape[1], dtype=jnp.int64)
            * jnp.int64(2654435761) + jnp.int64(0x9E3779B9)) | jnp.int64(1)
    return acc * jnp.int64(_H_PRIME) + jnp.sum(flat * mult[None, :], axis=1)


def _rows_equal(leaf, ti, fi):
    """bool[P]: rows ti and fi of `leaf` are elementwise identical."""
    a, b = leaf[ti], leaf[fi]
    return jnp.all((a == b).reshape(a.shape[0], -1), axis=1)


def merge_pass(state: StateBatch, planes: SymPlanes, arena: A.Arena,
               merge_pcs: jnp.ndarray,
               mem_pcs: Optional[jnp.ndarray] = None,
               mem_words: Optional[jnp.ndarray] = None,
               n_rounds: int = 6
               ) -> Tuple[StateBatch, SymPlanes, A.Arena, jnp.ndarray]:
    """Collapse reconverged fork-sibling lanes; `n_rounds` greedy pairing
    rounds per invocation (each round merges one level of the fork tree).
    `merge_pcs` (i32[K] post-dominator merge points from staticanalysis/)
    attributes merge events to tags for telemetry; pairing itself keys on
    full state equality, which subsumes "reconverged at the join".

    `mem_pcs` (i32[J]) and `mem_words` (i32[J, W], -1 padded) are the
    absint join table: join pcs whose diamond arms provably confine their
    memory writes to the listed 32-byte-aligned windows. When non-empty, a
    second widened pairing phase runs at exactly those pcs with the
    identical-memory requirement relaxed: sibling pairs whose byte/plane
    diffs all land inside the windows get each differing window ITE-blended
    through a fresh symbolic word (mem_sym cells (node<<5)+j — the same
    pattern a symbolic MSTORE leaves, so MLOAD round-trips stay clean).
    The containment and blendability checks run on the live planes, so a
    wrong window table can only MISS a blend, never corrupt one.

    A final accounting pass pairs reconverged-looking lanes that did NOT
    merge and charges each to the first gate that refused it
    (MERGE_BLOCKED_LABELS order in the stats vector).

    Returns (state, planes, arena,
    stats i64[MERGE_STATS_FIXED + K + N_MERGE_DEPTH])."""
    batch = state.pc.shape[0]
    half = batch // 2
    slots = planes.stack_sym.shape[1]
    kslots = planes.storage_sym.shape[1]
    max_conds = planes.conds.shape[1]
    mem_cap = planes.mem_sym.shape[1]
    n_tags = merge_pcs.shape[0]
    lane = jnp.arange(batch)
    j32 = jnp.arange(32, dtype=I32)
    if mem_pcs is None:
        mem_pcs = jnp.zeros(0, dtype=I32)
        mem_words = jnp.zeros((0, 1), dtype=I32)
    mem_pcs = jnp.asarray(mem_pcs, dtype=I32)
    mem_words = jnp.asarray(mem_words, dtype=I32)
    n_wins = mem_words.shape[1]

    # leaves a merge must find identical (everything else is blended or
    # recomputed). Immutable template planes — code, calldata, env words,
    # gas_limit — are covered by ctx_id equality: lanes with one ctx_id
    # were row-copied from one seed template and no device op writes them.
    # Transient storage is required equal rather than blended (rare). The
    # memory planes sit in their own tuple: the widened phase relaxes
    # exactly those two while requiring everything else identical.
    eq_leaves_weak = (state.pc, state.sp, state.msize, state.code_len,
                      state.retdata_len, state.retdata,
                      state.storage_keys, state.storage_used,
                      state.tstore_keys, state.tstore_vals,
                      state.tstore_used, planes.storage_base_sym,
                      planes.symbolic_env, planes.ctx_id)
    eq_leaves_mem = (state.memory, planes.mem_sym)
    weak_h = jnp.zeros(batch, dtype=jnp.int64)
    for leaf in eq_leaves_weak:
        weak_h = _merge_fold(weak_h, leaf)
    static_h = weak_h
    for leaf in eq_leaves_mem:
        static_h = _merge_fold(static_h, leaf)

    stats0 = jnp.zeros(MERGE_STATS_FIXED + n_tags + N_MERGE_DEPTH,
                       dtype=jnp.int64)

    def make_round(widen_mem):
        def one_round(r, carry):
            state, planes, arena, stats = carry
            cc = planes.cond_count
            last_idx = jnp.clip(cc - 1, 0, max_conds - 1)
            last = planes.conds[lane, last_idx]
            sign = (last > 0).astype(jnp.int64)
            # partners share |last| — hash with the sign stripped, sort on it
            conds_abs = planes.conds.at[lane, last_idx].set(jnp.abs(last))
            eligible = (state.status == RUNNING) & (cc > 0) & (last != 0) \
                & (planes.fork_cond == 0)
            if widen_mem:
                # widened pairing happens ONLY at the proven join pcs
                at_join = state.pc[:, None] == mem_pcs[None, :]
                eligible &= jnp.any(at_join, axis=1)
                join_row = jnp.argmax(at_join, axis=1)
                base_h = weak_h
            else:
                base_h = static_h

            h = _merge_fold(base_h, conds_abs)
            h = h * jnp.int64(_H_PRIME) + cc.astype(jnp.int64)
            key = jnp.where(eligible, ((h & jnp.int64(_H_MASK)) << 1) | sign,
                            jnp.int64(0x7FFFFFFFFFFFFFFF))
            perm = jnp.argsort(key)
            # alternate pair alignment by round so an unpaired singleton can
            # never shadow the same candidate pair across every round
            perm = jnp.roll(perm, -(r % 2))
            fi = perm[0:2 * half:2]   # sorts first in a group: last cond < 0
            ti = perm[1:2 * half:2]   # last cond > 0 — the merge survivor

            ok = eligible[ti] & eligible[fi]
            last_t = last[ti]
            ok &= (last_t > 0) & (last_t == -last[fi])
            ok &= cc[ti] == cc[fi]
            ok &= jnp.all(conds_abs[ti] == conds_abs[fi], axis=1)
            for leaf in eq_leaves_weak:
                ok &= _rows_equal(leaf, ti, fi)
            if not widen_mem:
                for leaf in eq_leaves_mem:
                    ok &= _rows_equal(leaf, ti, fi)

            if widen_mem:
                # ---- memory-window containment + blendability ---------------
                # every differing byte/plane cell must fall inside a valid
                # window of the pair's join, and each differing window must
                # read back as ONE well-defined 256-bit word on both sides:
                # fully concrete (no sym marks) or a clean symbolic word.
                # Windows are non-overlapping by construction (absint
                # word_windows), so per-window diff counts add up exactly.
                wins = mem_words[join_row[ti]]              # i32[half, W]
                valid_w = (wins >= 0) & (wins + 32 <= mem_cap)
                idx = wins[:, :, None] + j32[None, None, :]  # [half, W, 32]
                safe = jnp.clip(idx, 0, mem_cap - 1).reshape(half, -1)

                def win_gather(plane, rows):
                    return jnp.take_along_axis(
                        plane[rows], safe, axis=1).reshape(half, n_wins, 32)

                mem_tg = win_gather(state.memory, ti)
                mem_fg = win_gather(state.memory, fi)
                sym_tg = win_gather(planes.mem_sym, ti)
                sym_fg = win_gather(planes.mem_sym, fi)
                mdiff_all = (state.memory[ti] != state.memory[fi]) \
                    | (planes.mem_sym[ti] != planes.mem_sym[fi])
                wdiff_cells = ((mem_tg != mem_fg) | (sym_tg != sym_fg)) \
                    & valid_w[:, :, None]
                contained = jnp.sum(mdiff_all, axis=1) \
                    == jnp.sum(wdiff_cells, axis=(1, 2))
                wdiff = jnp.any(wdiff_cells, axis=2)        # [half, W]

                def word_view(sym_g):
                    all0 = jnp.all(sym_g == 0, axis=2)
                    first = sym_g[:, :, 0]
                    clean = (first != 0) & ((first & 31) == 0) & jnp.all(
                        sym_g == first[:, :, None] + j32[None, None, :],
                        axis=2)
                    return all0, first, clean

                all0_t, first_t, clean_t = word_view(sym_tg)
                all0_f, first_f, clean_f = word_view(sym_fg)
                blendable = (all0_t | clean_t) & (all0_f | clean_f)
                ok &= contained
                ok &= jnp.all(~(wdiff & valid_w) | blendable, axis=1)
                need = wdiff & valid_w & ok[:, None]

                # per-window value nodes: the clean word's node, else a
                # fresh CONST wrapping the window's concrete bytes
                word_t = words.from_bytes(mem_tg)
                word_f = words.from_bytes(mem_fg)
                arena, mcid_t, movf1 = A.alloc_consts(
                    arena, (need & all0_t).reshape(-1),
                    word_t.reshape(half * n_wins, -1))
                arena, mcid_f, movf2 = A.alloc_consts(
                    arena, (need & all0_f).reshape(-1),
                    word_f.reshape(half * n_wins, -1))
                mnode_t = jnp.where(all0_t.reshape(-1), mcid_t,
                                    (first_t >> 5).reshape(-1))
                mnode_f = jnp.where(all0_f.reshape(-1), mcid_f,
                                    (first_f >> 5).reshape(-1))
                mcond = jnp.broadcast_to(last_t[:, None],
                                         (half, n_wins)).reshape(-1)
                mzero = jnp.zeros_like(mnode_t)
                arena, ite_m, movf3 = A.alloc_rows(
                    arena, need.reshape(-1), jnp.full_like(mnode_t, 0x0F),
                    mcond, mnode_t, mnode_f, mzero, mzero)
                mem_ovf = (movf1 | movf2 | movf3).reshape(half, n_wins)

            # ---- blend differing stack slots through ite(cond, then, else) --
            # cond is the survivor's positive last condition, so the taken
            # side's value is the `then` child (op 0x0F: a != 0 -> b else c).
            # Slots whose sym nodes agree need no blend — when nonzero the
            # sym node governs materialization and the concrete word is dead.
            sp_t = state.sp[ti]
            sym_t, sym_f = planes.stack_sym[ti], planes.stack_sym[fi]
            conc_t, conc_f = state.stack[ti], state.stack[fi]
            live = jnp.arange(slots)[None, :] < sp_t[:, None]
            sdiff = ok[:, None] & live & (
                (sym_t != sym_f)
                | ((sym_t == 0) & (sym_f == 0)
                   & jnp.any(conc_t != conc_f, axis=-1)))
            limbs = state.stack.shape[-1]
            arena, cid_t, ovf1 = A.alloc_consts(
                arena, (sdiff & (sym_t == 0)).reshape(-1),
                conc_t.reshape(half * slots, limbs))
            arena, cid_f, ovf2 = A.alloc_consts(
                arena, (sdiff & (sym_f == 0)).reshape(-1),
                conc_f.reshape(half * slots, limbs))
            node_t = jnp.where(sym_t.reshape(-1) != 0, sym_t.reshape(-1),
                               cid_t)
            node_f = jnp.where(sym_f.reshape(-1) != 0, sym_f.reshape(-1),
                               cid_f)
            cond_b = jnp.broadcast_to(last_t[:, None],
                                      (half, slots)).reshape(-1)
            zero = jnp.zeros_like(node_t)
            arena, ite_s, ovf3 = A.alloc_rows(
                arena, sdiff.reshape(-1), jnp.full_like(node_t, 0x0F),
                cond_b, node_t, node_f, zero, zero)
            stack_ovf = (ovf1 | ovf2 | ovf3).reshape(half, slots)

            # ---- blend differing storage slots (keys/used verified equal) ---
            ksym_t, ksym_f = planes.storage_sym[ti], planes.storage_sym[fi]
            kval_t, kval_f = state.storage_vals[ti], state.storage_vals[fi]
            kdiff = ok[:, None] & state.storage_used[ti] & (
                (ksym_t != ksym_f)
                | ((ksym_t == 0) & (ksym_f == 0)
                   & jnp.any(kval_t != kval_f, axis=-1)))
            arena, kid_t, ovf4 = A.alloc_consts(
                arena, (kdiff & (ksym_t == 0)).reshape(-1),
                kval_t.reshape(half * kslots, limbs))
            arena, kid_f, ovf5 = A.alloc_consts(
                arena, (kdiff & (ksym_f == 0)).reshape(-1),
                kval_f.reshape(half * kslots, limbs))
            knode_t = jnp.where(ksym_t.reshape(-1) != 0, ksym_t.reshape(-1),
                                kid_t)
            knode_f = jnp.where(ksym_f.reshape(-1) != 0, ksym_f.reshape(-1),
                                kid_f)
            kcond_b = jnp.broadcast_to(last_t[:, None],
                                       (half, kslots)).reshape(-1)
            kzero = jnp.zeros_like(knode_t)
            arena, ite_k, ovf6 = A.alloc_rows(
                arena, kdiff.reshape(-1), jnp.full_like(knode_t, 0x0F),
                kcond_b, knode_t, knode_f, kzero, kzero)
            storage_ovf = (ovf4 | ovf5 | ovf6).reshape(half, kslots)

            # arena exhaustion mid-blend: cancel the pair (both lanes keep
            # exploring — a missed merge is a perf loss, never a lost path)
            merged = ok & ~jnp.any(stack_ovf, axis=1) \
                & ~jnp.any(storage_ovf, axis=1)
            if widen_mem:
                merged &= ~jnp.any(mem_ovf, axis=1)

            # ---- apply: rewrite the survivor, retire the partner ------------
            tset = jnp.where(merged, ti, batch).astype(I32)
            fset = jnp.where(merged, fi, batch).astype(I32)
            m2 = merged[:, None]
            stack_sym = planes.stack_sym.at[tset].set(
                jnp.where(sdiff & m2, ite_s.reshape(half, slots), sym_t),
                mode="drop")
            storage_sym = planes.storage_sym.at[tset].set(
                jnp.where(kdiff & m2, ite_k.reshape(half, kslots), ksym_t),
                mode="drop")
            # either side's dirty writes must materialize from the survivor
            storage_dirty = planes.storage_dirty.at[tset].set(
                planes.storage_dirty[ti] | planes.storage_dirty[fi],
                mode="drop")
            conds = planes.conds.at[tset, last_idx[ti]].set(0, mode="drop")
            cond_count = planes.cond_count.at[tset].set(cc[ti] - 1,
                                                        mode="drop")
            # deeper side wins: host depth bounds stay conservative
            branches = planes.branches.at[tset].set(
                jnp.maximum(planes.branches[ti], planes.branches[fi]),
                mode="drop")
            status = state.status.at[fset].set(I32(DEAD), mode="drop")
            gas = state.gas_used.at[tset].set(
                jnp.maximum(state.gas_used[ti], state.gas_used[fi]),
                mode="drop")
            state = state._replace(status=status, gas_used=gas)
            mem_sym = planes.mem_sym
            if widen_mem:
                # survivor's differing windows become clean symbolic words
                # over the ITE node — the survivor's stale concrete bytes
                # are dead wherever a mark is set (MLOAD reads the node)
                blend3 = (need & merged[:, None])[:, :, None] \
                    & jnp.broadcast_to(True, idx.shape)
                cells = (ite_m.reshape(half, n_wins)[:, :, None] << 5) \
                    + j32[None, None, :]
                rows3 = jnp.broadcast_to(tset[:, None, None], idx.shape)
                cols3 = jnp.where(blend3, idx, mem_cap).astype(I32)
                mem_sym = mem_sym.at[rows3, cols3].set(cells, mode="drop")
            planes = planes._replace(
                stack_sym=stack_sym, storage_sym=storage_sym,
                storage_dirty=storage_dirty, conds=conds,
                cond_count=cond_count, branches=branches, mem_sym=mem_sym)

            # ---- stats ------------------------------------------------------
            depth = jnp.sum(sdiff & m2, axis=1) + jnp.sum(kdiff & m2, axis=1)
            if widen_mem:
                depth = depth + jnp.sum(need & m2, axis=1)
                stats = stats.at[2].add(jnp.sum(
                    merged & jnp.any(need, axis=1), dtype=jnp.int64))
            stats = stats.at[0].add(jnp.sum(merged, dtype=jnp.int64))
            stats = stats.at[1].add(jnp.sum(depth, dtype=jnp.int64))
            if n_tags:
                pc_t = state.pc[ti]
                stats = stats.at[MERGE_STATS_FIXED:
                                 MERGE_STATS_FIXED + n_tags].add(jnp.sum(
                                     merged[:, None]
                                     & (pc_t[:, None] == merge_pcs[None, :]),
                                     axis=0, dtype=jnp.int64))
            bucket = jnp.where(depth >= 8, 5, jnp.where(depth >= 4, 4,
                                                        depth))
            stats = stats.at[jnp.where(
                merged, MERGE_STATS_FIXED + n_tags + bucket,
                stats.shape[0])].add(jnp.int64(1), mode="drop")
            return state, planes, arena, stats

        return one_round

    carry = jax.lax.fori_loop(0, n_rounds, make_round(False),
                              (state, planes, arena, stats0))
    if mem_pcs.shape[0]:
        # widened phase AFTER the strict rounds: strict merges are cheaper
        # (no arena traffic for memory) and collapsing them first lets the
        # widened rounds pair the fresh survivors bottom-up too
        carry = jax.lax.fori_loop(0, n_rounds, make_round(True), carry)
    state, planes, arena, stats = carry

    # ---- blocked-by accounting ----------------------------------------------
    # pair lanes whose CORE state (pc/sp/sizes/ctx — no conds, no mutable
    # planes) matches and that still did not merge; charge each pair to the
    # first gate that refused it. Pure telemetry: no state is modified.
    cc = planes.cond_count
    last_idx = jnp.clip(cc - 1, 0, max_conds - 1)
    last = planes.conds[lane, last_idx]
    sign = (last > 0).astype(jnp.int64)
    conds_abs = planes.conds.at[lane, last_idx].set(jnp.abs(last))
    eligible = (state.status == RUNNING) & (cc > 0) & (last != 0) \
        & (planes.fork_cond == 0)
    core_h = jnp.zeros(batch, dtype=jnp.int64)
    for leaf in (state.pc, state.sp, state.msize, state.code_len,
                 state.retdata_len, state.retdata, planes.symbolic_env,
                 planes.ctx_id):
        core_h = _merge_fold(core_h, leaf)
    key = jnp.where(eligible, ((core_h & jnp.int64(_H_MASK)) << 1) | sign,
                    jnp.int64(0x7FFFFFFFFFFFFFFF))
    perm = jnp.argsort(key)
    fi = perm[0:2 * half:2]
    ti = perm[1:2 * half:2]
    cand = eligible[ti] & eligible[fi]
    for leaf in (state.pc, state.sp, state.msize, state.code_len,
                 state.retdata_len, state.retdata, planes.symbolic_env,
                 planes.ctx_id):
        cand &= _rows_equal(leaf, ti, fi)
    # gate 1: fork siblinghood — same condition prefix, opposite last sign
    sib = (last[ti] > 0) & (last[ti] == -last[fi]) & (cc[ti] == cc[fi]) \
        & jnp.all(conds_abs[ti] == conds_abs[fi], axis=1)
    blocked_depth = cand & ~sib
    rest = cand & sib
    # gate 2: storage shape (differing VALUES would have blended)
    keys_eq = _rows_equal(state.storage_keys, ti, fi) \
        & _rows_equal(state.storage_used, ti, fi) \
        & _rows_equal(planes.storage_base_sym, ti, fi)
    blocked_storage = rest & ~keys_eq
    rest &= keys_eq
    # gate 3: transient storage (required equal, never blended)
    ts_eq = _rows_equal(state.tstore_keys, ti, fi) \
        & _rows_equal(state.tstore_vals, ti, fi) \
        & _rows_equal(state.tstore_used, ti, fi)
    blocked_tstore = rest & ~ts_eq
    rest &= ts_eq
    # gate 4: the memory planes — split on whether symbolic marks differ
    msym_eq = _rows_equal(planes.mem_sym, ti, fi)
    mem_eq = _rows_equal(state.memory, ti, fi)
    blocked_mem_sym = rest & ~msym_eq
    blocked_mem = rest & msym_eq & ~mem_eq
    for slot, blocked in ((3, blocked_mem), (4, blocked_mem_sym),
                          (5, blocked_storage), (6, blocked_tstore),
                          (7, blocked_depth)):
        stats = stats.at[slot].add(jnp.sum(blocked, dtype=jnp.int64))
    return state, planes, arena, stats
