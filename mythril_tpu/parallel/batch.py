"""StateBatch: the frontier as a structure-of-arrays pytree.

This is SURVEY §7's design center: where the host engine keeps a Python
worklist of `GlobalState` objects (`core/svm.py:61`), the TPU lane keeps ONE
dense pytree whose leading axis is the lane (= state) axis. Forking, pruning
and scheduling become masked tensor ops; sharding the lane axis over a
`jax.sharding.Mesh` gives multi-chip data parallelism with zero code change to
the step function.

All capacities are static (XLA shapes): stack slots S, memory bytes M, code
bytes C, calldata D, return-data R, storage slots K. A lane that outgrows any
capacity sets status=ESCAPE and is handed back to the host oracle
(`core/instructions.py`) — the same split the reference uses between symbolic
execution and concrete host services (natives, RPC), applied to capacity.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from . import words

# lane status values
RUNNING, STOPPED, RETURNED, REVERTED, ERRORED, ESCAPED = 0, 1, 2, 3, 4, 5
# symbolic-frontier statuses: lane paused at a symbolic JUMPI waiting for the
# driver to duplicate it (FORKING); lane's path condition proved unsat (DEAD)
FORKING, DEAD = 6, 7

STATUS_NAMES = {
    RUNNING: "running", STOPPED: "stop", RETURNED: "return",
    REVERTED: "revert", ERRORED: "error", ESCAPED: "escape",
    FORKING: "forking", DEAD: "dead",
}


class StateBatch(NamedTuple):
    """All-lanes EVM machine state. Leading axis of every field is the lane axis."""

    # machine
    stack: jnp.ndarray        # uint32[B, S, 16]
    sp: jnp.ndarray           # int32[B] — number of occupied slots
    pc: jnp.ndarray           # int32[B] — byte offset into code
    gas_used: jnp.ndarray     # int64[B] — lower-bound gas accounting
    gas_limit: jnp.ndarray    # int64[B]
    status: jnp.ndarray       # int32[B]
    # memory
    memory: jnp.ndarray       # uint8[B, M]
    msize: jnp.ndarray        # int32[B] — active size in bytes (multiple of 32)
    # code
    code: jnp.ndarray         # uint8[B, C]
    code_len: jnp.ndarray     # int32[B]
    jumpdest: jnp.ndarray     # bool[B, C]
    # calldata
    calldata: jnp.ndarray     # uint8[B, D]
    calldata_len: jnp.ndarray # int32[B]
    # return buffer (RETURN/REVERT payload)
    retdata: jnp.ndarray      # uint8[B, R]
    retdata_len: jnp.ndarray  # int32[B]
    # storage: linear-probe table of (key, value) words
    storage_keys: jnp.ndarray # uint32[B, K, 16]
    storage_vals: jnp.ndarray # uint32[B, K, 16]
    storage_used: jnp.ndarray # bool[B, K]
    # transient storage (EIP-1153), same layout
    tstore_keys: jnp.ndarray  # uint32[B, T, 16]
    tstore_vals: jnp.ndarray  # uint32[B, T, 16]
    tstore_used: jnp.ndarray  # bool[B, T]
    # environment (words)
    address: jnp.ndarray
    caller: jnp.ndarray
    origin: jnp.ndarray
    callvalue: jnp.ndarray
    gasprice: jnp.ndarray
    coinbase: jnp.ndarray
    timestamp: jnp.ndarray
    number: jnp.ndarray
    prevrandao: jnp.ndarray
    block_gaslimit: jnp.ndarray
    chainid: jnp.ndarray
    basefee: jnp.ndarray
    selfbalance: jnp.ndarray

    @property
    def n_lanes(self) -> int:
        return self.stack.shape[0]


class LaneSpec:
    """Host-side description of one execution (one VMTest / one concolic replay)."""

    def __init__(self, code: bytes, calldata: bytes = b"",
                 storage: Optional[Dict[int, int]] = None,
                 gas_limit: int = 10_000_000, address: int = 0,
                 caller: int = 0, origin: int = 0, callvalue: int = 0,
                 gasprice: int = 0, coinbase: int = 0, timestamp: int = 0,
                 number: int = 0, prevrandao: int = 0,
                 block_gaslimit: int = 0, chainid: int = 1, basefee: int = 0,
                 selfbalance: int = 0):
        self.code = code
        self.calldata = calldata
        self.storage = dict(storage or {})
        self.gas_limit = gas_limit
        self.address = address
        self.caller = caller
        self.origin = origin
        self.callvalue = callvalue
        self.gasprice = gasprice
        self.coinbase = coinbase
        self.timestamp = timestamp
        self.number = number
        self.prevrandao = prevrandao
        self.block_gaslimit = block_gaslimit
        self.chainid = chainid
        self.basefee = basefee
        self.selfbalance = selfbalance


def next_pow2(value: int, floor: int = 1) -> int:
    """Smallest power of two >= max(value, floor) — the package's one shape
    bucketing helper (stable XLA signatures over exact-fit capacities)."""
    capacity = floor
    while capacity < value:
        capacity *= 2
    return capacity


def shard_count(n_lanes: int, requested: int,
                log: Optional["logging.Logger"] = None) -> int:
    """Validated logical-shard count for an `n_lanes`-wide frontier.

    `requested` comes from MYTHRIL_TPU_FLEET_SHARD (or a device count):
    the lane axis is split into that many equal contiguous blocks, so it
    must divide the lane count and be at least 2 to mean anything. An
    invalid request falls back to 1 (single-shard) with a logged reason
    instead of erroring — a mis-sized corpus should run, just unsharded."""
    if requested <= 1:
        return 1
    if n_lanes % requested:
        if log is not None:
            log.warning(
                "fleet shard: %d lanes not divisible by %d shards; "
                "falling back to single-shard", n_lanes, requested)
        return 1
    if n_lanes // requested < 1:
        if log is not None:
            log.warning(
                "fleet shard: %d shards exceed %d lanes; falling back "
                "to single-shard", requested, n_lanes)
        return 1
    return int(requested)


def _jumpdest_bitmap(code: bytes, capacity: int) -> np.ndarray:
    """Valid JUMPDEST byte offsets (0x5b outside PUSH immediates)."""
    bitmap = np.zeros(capacity, dtype=bool)
    i = 0
    while i < len(code):
        op = code[i]
        if op == 0x5B:
            bitmap[i] = True
        if 0x60 <= op <= 0x7F:
            i += op - 0x5F
        i += 1
    return bitmap


def _word_rows(values) -> np.ndarray:
    return np.stack([np.asarray(words.from_int(v)) for v in values])


def build_batch(specs, stack_slots: int = 96, memory_bytes: int = 4096,
                calldata_bytes: int = 512, retdata_bytes: int = 512,
                storage_slots: int = 64, tstore_slots: int = 8) -> StateBatch:
    """Pack host LaneSpecs into one dense StateBatch.

    code/calldata capacities are BUCKETED to powers of two (min 256):
    exact-fit capacities gave every contract its own XLA shape signature,
    so a corpus sweep recompiled the fused symbolic step per contract
    (SURVEY §7 hard part #4 — padding tiers over bucketed recompilation)."""
    n = len(specs)
    code_cap = next_pow2(max(1, max(len(s.code) for s in specs)), floor=256)
    calldata_cap = next_pow2(max(calldata_bytes,
                                 max(len(s.calldata) for s in specs)),
                             floor=256)

    code = np.zeros((n, code_cap), dtype=np.uint8)
    jumpdest = np.zeros((n, code_cap), dtype=bool)
    code_len = np.zeros(n, dtype=np.int32)
    calldata = np.zeros((n, calldata_cap), dtype=np.uint8)
    calldata_len = np.zeros(n, dtype=np.int32)
    storage_keys = np.zeros((n, storage_slots, words.NLIMBS), dtype=np.uint32)
    storage_vals = np.zeros((n, storage_slots, words.NLIMBS), dtype=np.uint32)
    storage_used = np.zeros((n, storage_slots), dtype=bool)
    gas_limit = np.zeros(n, dtype=np.int64)

    env_fields = ["address", "caller", "origin", "callvalue", "gasprice",
                  "coinbase", "timestamp", "number", "prevrandao",
                  "block_gaslimit", "chainid", "basefee", "selfbalance"]
    env = {f: np.zeros((n, words.NLIMBS), dtype=np.uint32) for f in env_fields}

    for i, spec in enumerate(specs):
        code[i, :len(spec.code)] = np.frombuffer(spec.code, dtype=np.uint8)
        code_len[i] = len(spec.code)
        jumpdest[i] = _jumpdest_bitmap(spec.code, code_cap)
        calldata[i, :len(spec.calldata)] = np.frombuffer(spec.calldata,
                                                         dtype=np.uint8)
        calldata_len[i] = len(spec.calldata)
        if len(spec.storage) > storage_slots:
            raise ValueError("initial storage exceeds storage_slots")
        for slot_index, (key, value) in enumerate(sorted(spec.storage.items())):
            storage_keys[i, slot_index] = np.asarray(words.from_int(key))
            storage_vals[i, slot_index] = np.asarray(words.from_int(value))
            storage_used[i, slot_index] = True
        gas_limit[i] = min(spec.gas_limit, 2**62)
        for field in env_fields:
            env[field][i] = np.asarray(words.from_int(getattr(spec, field)))

    return StateBatch(
        stack=jnp.zeros((n, stack_slots, words.NLIMBS), dtype=jnp.uint32),
        sp=jnp.zeros(n, dtype=jnp.int32),
        pc=jnp.zeros(n, dtype=jnp.int32),
        gas_used=jnp.zeros(n, dtype=jnp.int64),
        gas_limit=jnp.asarray(gas_limit),
        status=jnp.zeros(n, dtype=jnp.int32),
        memory=jnp.zeros((n, memory_bytes), dtype=jnp.uint8),
        msize=jnp.zeros(n, dtype=jnp.int32),
        code=jnp.asarray(code),
        code_len=jnp.asarray(code_len),
        jumpdest=jnp.asarray(jumpdest),
        calldata=jnp.asarray(calldata),
        calldata_len=jnp.asarray(calldata_len),
        retdata=jnp.zeros((n, retdata_bytes), dtype=jnp.uint8),
        retdata_len=jnp.zeros(n, dtype=jnp.int32),
        storage_keys=jnp.asarray(storage_keys),
        storage_vals=jnp.asarray(storage_vals),
        storage_used=jnp.asarray(storage_used),
        tstore_keys=jnp.zeros((n, tstore_slots, words.NLIMBS), dtype=jnp.uint32),
        tstore_vals=jnp.zeros((n, tstore_slots, words.NLIMBS), dtype=jnp.uint32),
        tstore_used=jnp.zeros((n, tstore_slots), dtype=bool),
        **{f: jnp.asarray(env[f]) for f in env_fields},
    )


def extract_storage(state: StateBatch, lane: int) -> Dict[int, int]:
    """Host-side: read one lane's storage table back into a dict."""
    used = np.asarray(state.storage_used[lane])
    keys = words.to_ints(state.storage_keys[lane])
    vals = words.to_ints(state.storage_vals[lane])
    return {int(keys[i]): int(vals[i]) for i in range(len(used)) if used[i]}


def extract_stack(state: StateBatch, lane: int):
    """Host-side: one lane's stack, bottom first."""
    depth = int(state.sp[lane])
    vals = words.to_ints(state.stack[lane, :depth])
    return [int(v) for v in np.atleast_1d(vals)] if depth else []


def extract_retdata(state: StateBatch, lane: int) -> bytes:
    length = int(state.retdata_len[lane])
    return bytes(np.asarray(state.retdata[lane, :length]).tolist())
