"""The TPU frontier driver: symbolic message-call exploration on device.

`analyze --engine tpu` routes each symbolic transaction through here instead
of the host worklist (core/transaction/symbolic.py execute_message_call).
Every open world state seeds one device lane (pc=0, symbolic calldata/env,
storage table from the world state); the batch runs fused symbolic steps
(parallel/symstep.py) until lanes pause or leave:

  - Symbolic JUMPIs fork ON DEVICE (symstep.sym_step's fork block): the
    forker takes the jump; its fall-through sibling claims a DEAD lane
    (width) or is PUSHED onto the scheduler's HBM sibling stack (depth) —
    DEAD lanes pop the deepest sibling next step, so the batch runs a DFS
    worklist entirely in HBM (symstep.DeviceScheduler). Forks are
    OPTIMISTIC end to end, exactly like the host engine's jumpi_ (and the
    reference's): no solver runs during exploration; path conditions ride
    along as arena ids and are solved only where the host engine solves
    them — at issue/witness time (MYTHRIL_TPU_CHECK_ESCAPES=1 opts back
    into escape-time pruning). Escaping lanes buffer their row in the HBM
    escape buffer and free instantly; the host bulk-drains buffered rows
    in bandwidth-sized light transfers.
  - Conditions whose taint cone (arena cls bitmask) contains tx.origin or
    block attributes are NOT forked on device: the lane escapes at the JUMPI
    so the dependence detectors see it exactly as in host-only exploration.
  - ESCAPED lanes (CALL family, SELFDESTRUCT, keccak over symbolic bytes,
    RETURN/STOP/REVERT, ...) are materialized into full host GlobalStates —
    stack/memory/storage/path conditions rebuilt as terms — and pushed onto
    the host worklist: the host executes the instruction the device could
    not, with all detector hooks firing unchanged.

The device explores the cheap, hot part of the state space (dispatch,
require-chains over calldata/env, storage guards) in lockstep; the host keeps
everything heavy. The net replaces the reference's per-state Python stepping
(mythril/laser/ethereum/svm.py:325-401) for the covered region."""

from __future__ import annotations

import logging
import os
import threading
import traceback
from contextlib import contextmanager
from copy import copy
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..analysis import module_screen
from ..core.state.annotation import StateAnnotation
from ..core.state.global_state import GlobalState
from ..exceptions import UnsatError
from ..smt.solver import cfa_screen
from ..observe import metrics, slog, trace
from ..smt import Bool, Extract, symbol_factory
from ..smt import terms as T
from ..support import tpu_config
from . import arena as A
from . import symstep
from . import words
from .batch import (DEAD, ERRORED, ESCAPED, FORKING, RUNNING, StateBatch,
                    LaneSpec, build_batch)

log = logging.getLogger(__name__)

#: stop the device phase when the arena has less head-room than this
ARENA_HEADROOM = 16_384
#: fused steps between summaries (the tunnel round-trip is ~0.1 ms but each
#: fused step at 4096 lanes is ~25 ms of device work — the chunk bounds how
#: long cold-SLOAD pauses wait for service, not dispatch overhead)
CHUNK = 64
#: hard step budget per transaction phase
MAX_STEPS = 4_096
#: device lanes (seeds + fork capacity)
DEFAULT_LANES = 128
#: per-lane path-constraint capacity (conds plane)
MAX_CONDS = 64


def _gather_rows(state, planes, index):
    """jit-bundled row gather: one XLA program per (bucket, shape
    signature) instead of ~44 individually-dispatched (and individually
    COMPILED) per-leaf gathers — those dominated profiled analyses."""
    import jax

    return jax.tree_util.tree_map(lambda leaf: leaf[index], (state, planes))


def _scatter_rows(state, planes, index, rows_state, rows_planes):
    """Inverse of _gather_rows: write row blocks back into lanes (pending-
    queue re-seeding). Padded index entries point one past the lane axis and
    are dropped."""
    import jax

    return jax.tree_util.tree_map(
        lambda leaf, rows: leaf.at[index].set(rows, mode="drop"),
        (state, planes), (rows_state, rows_planes))


def _summary(state, planes, arena, sched):
    """Everything the driver needs per chunk, packed into ONE int64 vector:
    the tunnel charges a ~30 ms FLOOR per fetched array, so a 13-leaf tuple
    costs ~400 ms while this single [13 + 2B] download costs one floor.
    Layout: [stack_top, esc_count, executed, forks, pushes, pops, arena_n,
    arena_n_const, esc_msize_max, esc_sp_max, esc_slots_max, esc_conds_max,
    batch] then status[B] then fork_cond[B] then ctx_id[B] (the fleet
    deadline drain reads lane ownership from it every chunk), then — only
    when the telemetry plane is armed — symstep.telemetry_words(
    sched.telemetry) appended at the END (the counters ride the same
    single download, zero extra host syncs). Sharded schedulers (vector
    tops) report global sums in slots 0/1 and append a trailing shard
    block [stack_top[D], esc_count[D], steals_sent[D], steals_received[D],
    steal_rows] — 4D+1 words the host slices off by its static D."""
    esc_rows = sched.esc_state.status.shape[0]
    sharded = sched.stack_top.ndim == 1
    ecount_vec = jnp.atleast_1d(sched.esc_count)
    seg_esc = esc_rows // ecount_vec.shape[0]
    live = (jnp.arange(esc_rows) % seg_esc) < jnp.repeat(ecount_vec, seg_esc)

    def live_max(column):
        return jnp.max(jnp.where(live, column, 0))

    batch = state.status.shape[0]
    scalars = jnp.stack([
        jnp.sum(sched.stack_top).astype(jnp.int64),
        jnp.sum(sched.esc_count).astype(jnp.int64),
        sched.executed, sched.forks, sched.pushes, sched.pops,
        arena.n.astype(jnp.int64), arena.n_const.astype(jnp.int64),
        live_max(sched.esc_state.msize).astype(jnp.int64),
        live_max(sched.esc_state.sp).astype(jnp.int64),
        live_max(jnp.sum(sched.esc_state.storage_used,
                         axis=1, dtype=jnp.int32)).astype(jnp.int64),
        live_max(sched.esc_planes.cond_count).astype(jnp.int64),
        jnp.asarray(batch, dtype=jnp.int64),
    ])
    packed = jnp.concatenate([scalars, state.status.astype(jnp.int64),
                              planes.fork_cond.astype(jnp.int64),
                              planes.ctx_id.astype(jnp.int64)])
    if sched.telemetry is not None:
        packed = jnp.concatenate(
            [packed, symstep.telemetry_words(sched.telemetry)])
    if sharded:
        packed = jnp.concatenate([
            packed,
            sched.stack_top.astype(jnp.int64),
            sched.esc_count.astype(jnp.int64),
            sched.steals_sent, sched.steals_received,
            sched.steal_rows[None]])
    return packed


#: _drain_light int32-section field layout: (name, per-row element count fn)
_DRAIN_I32_FIELDS = ("pc", "sp", "msize", "code_len", "cond_count",
                     "ctx_id", "last_jump", "branches")


def _pack_rows(state_like, planes_like, index, mem_b: int, sp_b: int,
               st_b: int, conds_w: int):
    """Gather `index`'s rows and pack ONLY what materialization reads
    (per-field, sliced to the callers' maxima) into THREE flat arrays
    (i32 / u8 / i64) before they cross the tunnel: full rows are ~40 KB,
    every separate array pays a ~30 ms floor, and bandwidth is ~35 MB/s —
    the one full-pytree gather this replaced cost 44 floors per call.
    Works on the lane batch and on scheduler pools alike."""
    from jax import lax

    s, p = state_like, planes_like

    def b32(x):
        return lax.bitcast_convert_type(x, jnp.int32)

    i32 = jnp.concatenate([
        s.pc[index], s.sp[index], s.msize[index], s.code_len[index],
        p.cond_count[index], p.ctx_id[index], p.last_jump[index],
        p.branches[index],
        b32(s.stack[index][:, :sp_b]).reshape(-1),
        b32(s.storage_keys[index][:, :st_b]).reshape(-1),
        b32(s.storage_vals[index][:, :st_b]).reshape(-1),
        p.stack_sym[index][:, :sp_b].reshape(-1),
        p.mem_sym[index][:, :mem_b].reshape(-1),
        p.storage_sym[index][:, :st_b].reshape(-1),
        p.conds[index][:, :conds_w].reshape(-1),
    ])
    u8 = jnp.concatenate([
        s.memory[index][:, :mem_b].reshape(-1),
        s.storage_used[index][:, :st_b].astype(jnp.uint8).reshape(-1),
        p.storage_dirty[index][:, :st_b].astype(jnp.uint8).reshape(-1),
    ])
    return i32, u8, s.gas_used[index]


def _row_maxima(state_like, planes_like, index):
    """Packed [msize_max, sp_max, used_slots_max, cond_count_max] over the
    selected rows — sizes _pack_rows' static slices in one tiny fetch."""
    return jnp.stack([
        jnp.max(state_like.msize[index]).astype(jnp.int64),
        jnp.max(state_like.sp[index]).astype(jnp.int64),
        jnp.max(jnp.sum(state_like.storage_used[index],
                        axis=1, dtype=jnp.int32)).astype(jnp.int64),
        jnp.max(planes_like.cond_count[index]).astype(jnp.int64),
    ])


def _drain_unpack(i32, u8, gas, bucket: int, mem_b: int, sp_b: int,
                  st_b: int, conds_w: int):
    """Host-side inverse of _drain_light's packing."""
    from . import words

    limbs = words.NLIMBS
    i32 = np.asarray(i32)
    u8 = np.asarray(u8)
    offset = [0]

    def cut(count, shape=None, view=None):
        part = i32[offset[0]:offset[0] + count]
        offset[0] += count
        if view is not None:
            part = part.view(view)
        return part.reshape(shape) if shape else part

    rows_state = {}
    rows_planes = {}
    for field in _DRAIN_I32_FIELDS:
        target = rows_planes if field in ("cond_count", "ctx_id",
                                          "last_jump", "branches") \
            else rows_state
        target[field] = cut(bucket)
    rows_state["stack"] = cut(bucket * sp_b * limbs,
                              (bucket, sp_b, limbs), np.uint32)
    rows_state["storage_keys"] = cut(bucket * st_b * limbs,
                                     (bucket, st_b, limbs), np.uint32)
    rows_state["storage_vals"] = cut(bucket * st_b * limbs,
                                     (bucket, st_b, limbs), np.uint32)
    rows_planes["stack_sym"] = cut(bucket * sp_b, (bucket, sp_b))
    rows_planes["mem_sym"] = cut(bucket * mem_b, (bucket, mem_b))
    rows_planes["storage_sym"] = cut(bucket * st_b, (bucket, st_b))
    rows_planes["conds"] = cut(bucket * conds_w, (bucket, conds_w))
    rows_state["memory"] = u8[:bucket * mem_b].reshape(bucket, mem_b)
    rows_state["storage_used"] = u8[
        bucket * mem_b:bucket * (mem_b + st_b)].reshape(
            bucket, st_b).astype(bool)
    rows_planes["storage_dirty"] = u8[
        bucket * (mem_b + st_b):bucket * (mem_b + 2 * st_b)].reshape(
            bucket, st_b).astype(bool)
    rows_state["gas_used"] = np.asarray(gas)
    return rows_state, rows_planes


def _reset_esc(sched):
    return sched._replace(esc_count=jnp.zeros_like(sched.esc_count))


def _pack_steal_rows(state_like, planes_like, index, mem_b: int, sp_b: int,
                     st_b: int, conds_w: int):
    """Wire format for stolen pending-pool rows: exactly the quantized
    escape-row codec (_pack_rows) plus the two freeze masks the escape
    path reads from the summary instead — `status` and `fork_cond` — as
    trailing i32 columns. A stolen row must arrive on the receiving shard
    runnable-or-frozen exactly as it left the donor."""
    i32, u8, gas = _pack_rows(state_like, planes_like, index, mem_b=mem_b,
                              sp_b=sp_b, st_b=st_b, conds_w=conds_w)
    extras = jnp.concatenate([
        state_like.status[index].astype(jnp.int32),
        planes_like.fork_cond[index].astype(jnp.int32)])
    return jnp.concatenate([i32, extras]), u8, gas


def _unpack_steal_rows(i32, u8, gas, bucket: int, mem_b: int, sp_b: int,
                       st_b: int, conds_w: int):
    """Device-side inverse of _pack_steal_rows (the host inverse of the
    shared layout is _drain_unpack): two dicts of full-width field arrays,
    keyed like StateBatch/SymPlanes fields. Bitcasts mirror _pack_rows'
    so unpack(pack(rows)) is bit-identical."""
    from jax import lax

    from . import words

    limbs = words.NLIMBS
    offset = [0]

    def cut(count, shape=None, as_u32=False):
        part = i32[offset[0]:offset[0] + count]
        offset[0] += count
        if shape is not None:
            part = part.reshape(shape)
        if as_u32:
            part = lax.bitcast_convert_type(part, jnp.uint32)
        return part

    rows_state = {}
    rows_planes = {}
    for field in _DRAIN_I32_FIELDS:
        target = rows_planes if field in ("cond_count", "ctx_id",
                                          "last_jump", "branches") \
            else rows_state
        target[field] = cut(bucket)
    rows_state["stack"] = cut(bucket * sp_b * limbs,
                              (bucket, sp_b, limbs), as_u32=True)
    rows_state["storage_keys"] = cut(bucket * st_b * limbs,
                                     (bucket, st_b, limbs), as_u32=True)
    rows_state["storage_vals"] = cut(bucket * st_b * limbs,
                                     (bucket, st_b, limbs), as_u32=True)
    rows_planes["stack_sym"] = cut(bucket * sp_b, (bucket, sp_b))
    rows_planes["mem_sym"] = cut(bucket * mem_b, (bucket, mem_b))
    rows_planes["storage_sym"] = cut(bucket * st_b, (bucket, st_b))
    rows_planes["conds"] = cut(bucket * conds_w, (bucket, conds_w))
    rows_state["status"] = cut(bucket)
    rows_planes["fork_cond"] = cut(bucket)
    rows_state["memory"] = u8[:bucket * mem_b].reshape(bucket, mem_b)
    rows_state["storage_used"] = u8[
        bucket * mem_b:bucket * (mem_b + st_b)].reshape(
            bucket, st_b).astype(bool)
    rows_planes["storage_dirty"] = u8[
        bucket * (mem_b + st_b):bucket * (mem_b + 2 * st_b)].reshape(
            bucket, st_b).astype(bool)
    rows_state["gas_used"] = gas
    return rows_state, rows_planes


def _steal_pass(state, sched, min_imbalance: int, max_rows: int):
    """Device-resident work stealing across the D pool segments of a
    sharded scheduler: rank shards by load (running lanes + pending
    rows — both already on device, so the rebalance decision never
    touches the host), pair the poorest with the richest, and move up to
    `max_rows` pending rows from each donor's stack top to its
    receiver's. Moved rows round-trip through the packed steal-row wire
    format (_pack_steal_rows/_unpack_steal_rows — identity by
    construction, asserted by the codec parity test) composed with a
    direct gather for the planes the codec does not carry (code,
    calldata, env words); donor rows above the new top are garbage by
    the pool convention, so no zeroing is needed."""
    import jax

    D = sched.stack_top.shape[0]
    batch = state.status.shape[0]
    pool_rows = sched.stack_state.status.shape[0]
    seg_pool = pool_rows // D
    mem_b = sched.stack_state.memory.shape[1]
    sp_b = sched.stack_state.stack.shape[1]
    st_b = sched.stack_state.storage_keys.shape[1]
    conds_w = sched.stack_planes.conds.shape[1]

    running = (state.status == RUNNING).reshape(D, batch // D).sum(
        axis=1, dtype=jnp.int32)
    load = running + sched.stack_top
    order = jnp.argsort(load)  # ascending: order[0] poorest

    stack_state, stack_planes = sched.stack_state, sched.stack_planes
    new_top = sched.stack_top
    sent, recv = sched.steals_sent, sched.steals_received
    moved = sched.steal_rows
    r = jnp.arange(max_rows, dtype=jnp.int32)
    for i in range(D // 2):  # disjoint pairs, statically unrolled
        poor, rich = order[i], order[D - 1 - i]
        diff = load[rich] - load[poor]
        n = jnp.minimum(jnp.minimum(diff // 2, max_rows),
                        jnp.minimum(new_top[rich],
                                    seg_pool - new_top[poor]))
        n = jnp.where(diff >= min_imbalance,
                      jnp.maximum(n, 0), 0).astype(jnp.int32)
        valid = r < n
        src = jnp.clip(rich * seg_pool + new_top[rich] - 1 - r,
                       0, pool_rows - 1).astype(jnp.int32)
        dst = jnp.where(valid, poor * seg_pool + new_top[poor] + r,
                        pool_rows).astype(jnp.int32)
        rows_state, rows_planes = jax.tree_util.tree_map(
            lambda leaf: leaf[src], (stack_state, stack_planes))
        i32, u8, gas = _pack_steal_rows(stack_state, stack_planes, src,
                                        mem_b=mem_b, sp_b=sp_b, st_b=st_b,
                                        conds_w=conds_w)
        unp_state, unp_planes = _unpack_steal_rows(
            i32, u8, gas, max_rows, mem_b=mem_b, sp_b=sp_b, st_b=st_b,
            conds_w=conds_w)
        rows_state = rows_state._replace(**unp_state)
        rows_planes = rows_planes._replace(**unp_planes)
        stack_state = StateBatch(*[
            pool_leaf.at[dst].set(row, mode="drop")
            for pool_leaf, row in zip(stack_state, rows_state)])
        stack_planes = symstep.SymPlanes(*[
            pool_leaf.at[dst].set(row, mode="drop")
            for pool_leaf, row in zip(stack_planes, rows_planes)])
        new_top = new_top.at[rich].add(-n).at[poor].add(n)
        sent = sent.at[rich].add(n.astype(jnp.int64))
        recv = recv.at[poor].add(n.astype(jnp.int64))
        moved = moved + n.astype(jnp.int64)
    return sched._replace(stack_state=stack_state,
                          stack_planes=stack_planes, stack_top=new_top,
                          steals_sent=sent, steals_received=recv,
                          steal_rows=moved)


_gather_rows_jit = None
_scatter_rows_jit = None
_summary_jit = None
_pack_rows_jit = None
_row_maxima_jit = None
_reset_esc_jit = None
_merge_jit = None
_steal_jit = None

#: greedy pairing rounds per merge invocation — each round collapses one
#: level of a reconverged fork subtree, so 6 rounds fold up to 64 sibling
#: lanes per pass (deeper trees finish on the next triggered pass)
_MERGE_ROUNDS = 6


def _gather_rows_compiled():
    global _gather_rows_jit
    if _gather_rows_jit is None:
        import jax

        _gather_rows_jit = jax.jit(_gather_rows)
    return _gather_rows_jit


def _scatter_rows_compiled():
    global _scatter_rows_jit
    if _scatter_rows_jit is None:
        import jax

        _scatter_rows_jit = jax.jit(_scatter_rows)
    return _scatter_rows_jit


def _summary_compiled():
    global _summary_jit
    if _summary_jit is None:
        import jax

        _summary_jit = jax.jit(_summary)
    return _summary_jit


def _pack_rows_compiled():
    global _pack_rows_jit
    if _pack_rows_jit is None:
        import jax

        _pack_rows_jit = jax.jit(
            _pack_rows,
            static_argnames=("mem_b", "sp_b", "st_b", "conds_w"))
    return _pack_rows_jit


def _row_maxima_compiled():
    global _row_maxima_jit
    if _row_maxima_jit is None:
        import jax

        _row_maxima_jit = jax.jit(_row_maxima)
    return _row_maxima_jit


def _reset_esc_compiled():
    global _reset_esc_jit
    if _reset_esc_jit is None:
        import jax

        _reset_esc_jit = jax.jit(_reset_esc)
    return _reset_esc_jit


def _merge_compiled():
    global _merge_jit
    if _merge_jit is None:
        import jax

        _merge_jit = jax.jit(symstep.merge_pass,
                             static_argnames=("n_rounds",))
    return _merge_jit


def _steal_compiled():
    global _steal_jit
    if _steal_jit is None:
        import jax

        _steal_jit = jax.jit(_steal_pass,
                             static_argnames=("min_imbalance", "max_rows"))
    return _steal_jit


class LaneContext(A.TxContext):
    """Seeding context: one (open world state, transaction) pair."""

    def __init__(self, tx_id: str, calldata, environment, template: GlobalState):
        super().__init__(tx_id, calldata, environment)
        self.template = template
        #: dispatcher-order function entry pcs from the taint summary
        #: (module_screen.function_order): fleet scheduling groups this
        #: contract's lanes per function from here (ROADMAP item 2)
        self.function_order: Tuple[int, ...] = ()


class MergeTagAnnotation(StateAnnotation):
    """Rides on materialized lanes whose basic block reconverges at a
    static post-dominator pc: the merge pass of ROADMAP item 3 groups
    lanes by this key (pc, not index, so it survives re-disassembly)."""

    __slots__ = ("merge_pc",)

    def __init__(self, merge_pc: int):
        self.merge_pc = merge_pc

    def __copy__(self):
        return MergeTagAnnotation(self.merge_pc)


class LoopHintAnnotation(StateAnnotation):
    """Rides on materialized lanes whose pc sits inside a natural loop
    (taint summary's per-loop-header hint tables): the bounded-unroll
    budgeter groups lanes by header pc to cap per-loop lane spend."""

    __slots__ = ("header_pc",)

    def __init__(self, header_pc: int):
        self.header_pc = header_pc

    def __copy__(self):
        return LoopHintAnnotation(self.header_pc)


def _storage_entries(storage) -> Tuple[List[Tuple[int, object]], bool]:
    """Walk the storage store-chain into ((concrete_key, BitVec_value) pairs,
    base_is_symbolic) — latest store wins. A symbolic BASE (every
    `--bin-runtime`/`-a` analysis: analysis/symbolic.py seeds
    `Array("Storage[...]")`, mirroring the reference's lazy Storage at
    mythril/laser/ethereum/state/account.py:18-76) is device-representable:
    cold SLOADs fault the slot in as Select(base, key) host-term leaves via
    the driver's pause service.

    A symbolic KEY in the chain (`mapping[msg.sender]` — every token
    contract's tx 2+) stops the walk THERE: stores above it (which shadow
    it) seed the device table; the store itself and everything below become
    the symbolic base. A device SLOAD that misses the table faults in
    `Select(full chain, key)` — the correct ITE over the symbolic-key
    store — so the whole transaction stays device-resident where round 4
    fell back to a pure host run."""
    from ..smt import BitVec

    node = storage._standard_storage.raw
    entries: Dict[int, object] = {}
    while node.op == "store":
        key, value = node.args[1], node.args[2]
        if not key.is_const:
            # concrete-key stores BELOW this point may be shadowed when the
            # symbolic key aliases them — they must stay out of the table
            # and resolve through the fault-in chain select instead
            return list(entries.items()), True
        entries.setdefault(key.value, BitVec(value))
        node = node.args[0]
    if node.op == "const_array":
        if not (node.args[0].is_const and node.args[0].value == 0):
            return list(entries.items()), True
        return list(entries.items()), False
    return list(entries.items()), True  # symbolic base: fault-in on demand


class _Frontier:
    def __init__(self, laser_evm, n_lanes: int):
        self.laser = laser_evm
        self.n_lanes = n_lanes
        self.contexts: List[LaneContext] = []
        self.arena = A.new_arena()
        self.harena: Optional[A.HostArena] = None
        self.materialized = 0
        self.forks = 0
        self.infeasible = 0
        self.faults = 0  # cold-SLOAD fault-ins serviced
        self._lane_sharding_cache = Ellipsis  # unset sentinel
        #: instruction-states executed on device (live lanes x steps) — the
        #: symbolic analogue of the host engine's executed_nodes counter
        self.lane_steps = 0
        #: escape-time solver pruning is OFF by default: the host engine's
        #: JUMPI is optimistic (core/instructions.py jumpi_ forks both sides
        #: structurally, exactly like the reference's
        #: mythril/laser/ethereum/instructions.py jumpi_), so checking each
        #: escaping lane's path conditions here did strictly MORE solver work
        #: than the host ever does — it was 85x of the round-4 bench wall.
        #: Feasibility is decided where the host decides it: at issue time.
        self.check_escapes = tpu_config.get_flag(
            "MYTHRIL_TPU_CHECK_ESCAPES")
        #: (signed cond id, ctx index) -> Bool (see _cond_bools)
        self._cond_memo: Dict[Tuple[int, int], Bool] = {}
        #: drained-but-unmaterialized row blocks: [rows_state, rows_planes,
        #: count, cursor]. The svm exec loop pulls batches on demand via
        #: make_feeder() — materialization is LAZY, so rows the budget never
        #: reaches cost nothing (host-timeout parity), and the device loop
        #: never stalls on per-row Python GlobalState construction.
        self.deferred: List[list] = []
        #: escape rows accumulate in the DEVICE buffer until this many
        #: wait, then the host drains them in one bandwidth-sized light
        #: transfer
        self.drain_batch = tpu_config.get_int(
            "MYTHRIL_TPU_DRAIN_BATCH", max(4 * n_lanes, 1024))
        #: host overflow tier: raw rows land here only when the DEVICE
        #: scheduler cannot hold them (sibling stack full at total
        #: deadlock) or on checkpoint/resume; they re-seed into DEAD lanes
        #: once the device stack is empty. Scheduling itself lives on
        #: device (symstep.DeviceScheduler) — the tunnel charges ~100 ms
        #: per host-argument upload, so per-service host decisions are
        #: poison.
        self.pending: List[Tuple[Dict[str, np.ndarray],
                                 Dict[str, np.ndarray]]] = []
        self.spilled = 0    # host-tier spills (device stack overflow)
        self.reseeded = 0   # host-tier reseeds (pending -> lanes)
        self.stack_pushes = 0  # device DFS-stack siblings pushed
        self.stack_pops = 0    # device DFS-stack siblings reseeded
        #: scheduler pool byte budgets (HBM)
        self.stack_bytes = tpu_config.get_int("MYTHRIL_TPU_STACK_BYTES")
        self.esc_bytes = tpu_config.get_int("MYTHRIL_TPU_ESC_BYTES")
        #: device-resident counter plane (symstep.Telemetry): knob AND the
        #: CLI A/B flag must both be on. Off means the counters are
        #: compiled OUT of the fused step entirely (None is a static
        #: pytree leaf), so --no-frontier-telemetry measures a genuinely
        #: telemetry-free executable
        from ..support.support_args import args as _support_args

        self.telemetry_enabled = (
            tpu_config.get_flag("MYTHRIL_TPU_FRONTIER_TELEMETRY")
            and getattr(_support_args, "frontier_telemetry", True))
        #: host-side names for the telemetry tag slots ("merge@0x..",
        #: "loop@0x..") — parallel to Telemetry.tag_pcs
        self.tag_names: List[str] = []
        #: previous chunk's raw telemetry words (device counters are
        #: cumulative within a phase; deltas are published per chunk)
        self._tel_prev: Optional[np.ndarray] = None
        #: on-device state merging (veritesting): collapse fork-sibling
        #: lanes that reconverged at a post-dominator join into one lane
        #: with ITE-blended planes (symstep.merge_pass). Knob AND the CLI
        #: A/B flag (--no-state-merge) must both be on.
        self.state_merge = (
            tpu_config.get_flag("MYTHRIL_TPU_STATE_MERGE")
            and getattr(_support_args, "state_merge", True))
        #: widened memory-plane merging: ship the absint join windows
        #: (staticanalysis/absint.py via the CFA screen) to the merge
        #: kernel so diamonds whose arms provably confine their writes
        #: can ITE-blend memory. --no-absint / MYTHRIL_TPU_ABSINT=0
        #: empty the table — the kernel compiles the widened phase out
        #: and behaves byte-identically to the identical-memory gate.
        self.absint = cfa_screen.absint_enabled()
        #: merge-tag occupancy (lane-visits per chunk at one merge point)
        #: that triggers a merge pass; the telemetry tag deltas are the
        #: trigger signal, so with telemetry off the pass falls back to a
        #: fixed chunk cadence
        self.merge_min_lanes = tpu_config.get_int(
            "MYTHRIL_TPU_MERGE_MIN_LANES", 2)
        self.merges = 0     # pairs collapsed (one lane retired each)
        #: last chunk's per-tag occupancy deltas (merge-pass trigger)
        self._last_tag_delta: Optional[np.ndarray] = None
        #: fleet packing (FleetDriver): when set, contexts carry per-member
        #: lasers, the chunk loop runs the per-contract deadline drain, and
        #: the telemetry plane grows a per-contract occupancy block
        self.fleet = None
        #: host-side names for the fleet occupancy slots (contract ids) —
        #: parallel to Telemetry.fleet_occ
        self.fleet_names: List[str] = []
        #: last chunk's per-contract occupancy deltas (frontierview feed)
        self._last_fleet_delta: Optional[np.ndarray] = None
        #: logical shard count D: the lane axis (and both scheduler pools)
        #: is split into D equal contiguous blocks, each with its own
        #: stack/escape segment and top, so a multi-device mesh can place
        #: one block per device with all of that block's planes local.
        #: MYTHRIL_TPU_FLEET_SHARD: 0 = auto (device count on real
        #: multi-device backends, else 1), N = force N logical shards
        #: (valid on a single CPU device — segmentation is physical-
        #: device-independent). Invalid requests fall back to 1 with a
        #: logged reason (batch.shard_count).
        requested = tpu_config.get_int("MYTHRIL_TPU_FLEET_SHARD", 0)
        if requested == 0:
            try:
                import jax

                devices = jax.devices()
                if len(devices) > 1 and devices[0].platform != "cpu":
                    requested = len(devices)
            except Exception:  # allowlisted in tools/check_excepts.py
                requested = 0
        from .batch import shard_count

        self.n_shards = shard_count(n_lanes, requested, log=log)
        #: steal cadence (chunks between device-resident steal passes;
        #: 0 disables) and the minimum load gap before a shard pair
        #: actually exchanges rows
        self.steal_cadence = tpu_config.get_int("MYTHRIL_TPU_STEAL_CADENCE")
        self.steal_min_imbalance = tpu_config.get_int(
            "MYTHRIL_TPU_STEAL_MIN_IMBALANCE")
        #: host copies of the last summary's shard block (per-shard tops,
        #: steal counters) — feeds the drains and frontier.shard.* metrics
        self._shard_tops: Optional[np.ndarray] = None
        self._shard_esc: Optional[np.ndarray] = None
        self._shard_steals: Optional[np.ndarray] = None  # sent,recv,rows
        self._steal_passes = 0

    def _harena(self, used=None, used_const=None) -> A.HostArena:
        """The persistent incremental host mirror of the arena (term memo
        survives across services; only newly-allocated rows transfer).
        Pass `used`/`used_const` when the driver already fetched them in
        the chunk summary — each scalar int(arena.n) is otherwise a ~30 ms
        blocking tunnel read."""
        if self.harena is None:
            self.harena = A.HostArena(self.arena, used, used_const)
        else:
            self.harena.refresh(self.arena, used, used_const)
        return self.harena

    def _new_sched(self, state: StateBatch, planes):
        """Size the on-device scheduler pools by HBM byte budget."""
        row_bytes = sum(
            int(np.dtype(leaf.dtype).itemsize) * int(np.prod(leaf.shape[1:]))
            for leaf in list(state) + list(planes))
        # bounded by HBM budget AND lane count: a 128-lane corpus analysis
        # must not allocate (and zero) gigabytes of pool per transaction —
        # the stack's worst case is ~lanes x tree depth, the escape buffer
        # a few chunks of escape bursts
        stack_rows = int(max(2 * self.n_lanes,
                             min(1 << 17, 24 * self.n_lanes,
                                 self.stack_bytes // max(row_bytes, 1))))
        esc_rows = int(max(2 * self.n_lanes,
                           min(1 << 16, 8 * self.n_lanes,
                               self.esc_bytes // max(row_bytes, 1))))
        if self.n_shards > 1:  # equal segments: round pools up to D rows
            stack_rows += (-stack_rows) % self.n_shards
            esc_rows += (-esc_rows) % self.n_shards
        # the telemetry decode converts pool high-water marks into HBM
        # byte gauges with this factor — pure host arithmetic on numbers
        # the summary download already carries
        self._row_bytes = row_bytes
        log.info("device scheduler: %d stack + %d escape rows x %d B "
                 "(%.0f MiB HBM)", stack_rows, esc_rows, row_bytes,
                 (stack_rows + esc_rows) * row_bytes / 2 ** 20)
        telemetry = None
        if self.telemetry_enabled:
            tag_pcs, self.tag_names = self._collect_tag_pcs()
            fleet_slots, self.fleet_names = self._collect_fleet_slots()
            telemetry = symstep.new_telemetry(
                tag_pcs, fleet_slots=fleet_slots,
                n_fleet=len(self.fleet_names))
            self._tel_prev = None  # device counters restart each phase
            self._last_tag_delta = None
            self._last_fleet_delta = None
        # shard block stash restarts with the device counters
        self._shard_tops = None
        self._shard_esc = None
        self._shard_steals = None
        return symstep.new_scheduler(state, planes, stack_rows, esc_rows,
                                     telemetry=telemetry,
                                     n_shards=self.n_shards)

    #: telemetry tag-occupancy slots — one B x K compare per fused step,
    #: so the table stays small; overflow is logged, never silent
    TAG_SLOTS = 32

    def _collect_tag_pcs(self) -> Tuple[List[int], List[str]]:
        """Merge-point and loop-header pcs to track lane occupancy at,
        from the CFA / taint tables seed() already warmed. Loop headers
        first (fewer, and they drive the unroll budgeter), then
        post-dominator merge points until the slot cap."""
        loops: List[Tuple[int, str]] = []
        merges: List[Tuple[int, str]] = []
        seen = set()
        for ctx in self.contexts:
            code = ctx.template.environment.code
            summary = module_screen.summary_for(code)
            if summary is not None:
                for loop in summary.loops:
                    key = ("loop", loop.header_pc)
                    if key not in seen:
                        seen.add(key)
                        loops.append((loop.header_pc,
                                      f"loop@{loop.header_pc:#x}"))
            cfa = cfa_screen.cfa_for(code)
            if cfa is not None:
                for pc in sorted(cfa.merge_points):
                    key = ("merge", pc)
                    if key not in seen:
                        seen.add(key)
                        merges.append((pc, f"merge@{pc:#x}"))
        tags = (loops + merges)[:self.TAG_SLOTS]
        dropped = len(loops) + len(merges) - len(tags)
        if dropped:
            log.info("frontier telemetry: tracking %d of %d tagged pcs "
                     "(%d merge points dropped past the %d-slot cap)",
                     len(tags), len(tags) + dropped, dropped,
                     self.TAG_SLOTS)
        return [pc for pc, _ in tags], [name for _, name in tags]

    def _collect_fleet_slots(self) -> Tuple[List[int], List[str]]:
        """Per-contract occupancy slots: map every seeding context to its
        fleet member's slot (same ≤32-slot counter mechanism as the tag
        table). Empty outside fleet mode — solo runs pay zero extra
        summary words."""
        if self.fleet is None:
            return [], []
        slots: List[int] = []
        names: List[str] = []
        index_of: Dict[str, int] = {}
        for ctx in self.contexts:
            member = getattr(ctx, "member", None)
            cid = member.contract_id if member is not None \
                else "(unowned)"
            if cid not in index_of:
                if len(names) >= self.TAG_SLOTS:
                    log.info("frontier fleet telemetry: contract %r past "
                             "the %d-slot cap, folding into last slot",
                             cid, self.TAG_SLOTS)
                    slots.append(len(names) - 1)
                    continue
                index_of[cid] = len(names)
                names.append(cid)
            slots.append(index_of[cid])
        return slots, names

    #: merge-attribution table cap (one P x K compare per merge round)
    MERGE_PC_SLOTS = 64

    def _merge_pc_table(
            self) -> Tuple[np.ndarray, List[str], np.ndarray, np.ndarray]:
        """Post-dominator merge-point pcs for merge-event attribution
        (frontier.merge.tag_merges labels). Pairing itself keys on full
        state equality, so joins past the cap still merge — they just
        land in the 'untagged' bucket.

        Also returns the widened-merge window table (mem_pcs i32[J],
        mem_words i32[J, W] window start offsets, -1 padded): join pcs
        where absint proved both diamond arms confine their memory
        writes to a small set of 32-byte windows. Empty when the absint
        screen is off — the kernel then compiles the widened phase out.
        A stale or cross-contract row can only make the kernel's
        containment check fail (missed blend), never corrupt a merge."""
        pcs: List[int] = []
        names: List[str] = []
        seen = set()
        mem_map: Dict[int, Tuple[int, ...]] = {}
        for ctx in self.contexts:
            code = ctx.template.environment.code
            cfa = cfa_screen.cfa_for(code)
            if cfa is None:
                continue
            for pc in sorted(cfa.merge_points):
                if pc not in seen:
                    seen.add(pc)
                    pcs.append(pc)
                    names.append(f"merge@{pc:#x}")
                if self.absint and pc not in mem_map:
                    windows = cfa_screen.merge_mem_windows(code, pc)
                    if windows:
                        # one row per join-block pc the fact covers: the
                        # merge cadence may run a chunk after the lanes
                        # step off the join itself
                        for row_pc in cfa_screen.merge_window_pcs(
                                code, pc):
                            mem_map.setdefault(row_pc, tuple(windows))
        pcs, names = pcs[:self.MERGE_PC_SLOTS], names[:self.MERGE_PC_SLOTS]
        mem_items = sorted(mem_map.items())[:self.MERGE_PC_SLOTS]
        if mem_items:
            width = max(len(w) for _, w in mem_items)
            mem_pcs = np.asarray([pc for pc, _ in mem_items],
                                 dtype=np.int32)
            mem_words = np.full((len(mem_items), width), -1,
                                dtype=np.int32)
            for i, (_, w) in enumerate(mem_items):
                mem_words[i, :len(w)] = w
        else:
            mem_pcs = np.zeros(0, dtype=np.int32)
            mem_words = np.zeros((0, 1), dtype=np.int32)
        return np.asarray(pcs, dtype=np.int32), names, mem_pcs, mem_words

    def _publish_merge(self, mstats: np.ndarray,
                       merge_names: List[str]) -> None:
        """Decode one merge pass's stats vector (symstep.merge_pass:
        [merges, ites, mem_blends, blocked_by[5], tag_hits[K],
        depth_hist]) into declared metrics and a Perfetto counter
        track."""
        fixed = symstep.MERGE_STATS_FIXED
        n_tags = len(merge_names)
        merges = int(mstats[0])
        metrics.inc("frontier.merge.passes")
        # the blocked-by gate accounting publishes even on a 0-merge
        # pass — "why did nothing merge" IS the 0-merge signal
        for label, count in zip(symstep.MERGE_BLOCKED_LABELS, mstats[3:8]):
            if count:
                metrics.inc("frontier.merge.blocked_by." + label,
                            int(count))
        if not merges:
            return
        self.merges += merges
        metrics.inc("frontier.merge.events", merges)
        metrics.inc("frontier.merge.lanes_retired", merges)
        metrics.inc("frontier.merge.ites", int(mstats[1]))
        if int(mstats[2]):
            metrics.inc("absint.merge.mem_blends", int(mstats[2]))
        tagged = 0
        for name, count in zip(merge_names, mstats[fixed:fixed + n_tags]):
            if count:
                tagged += int(count)
                metrics.observe("frontier.merge.tag_merges", int(count),
                                label=name)
        if merges > tagged:
            metrics.observe("frontier.merge.tag_merges", merges - tagged,
                            label="untagged")
        for name, count in zip(symstep.MERGE_DEPTH_LABELS,
                               mstats[fixed + n_tags:]):
            if count:
                metrics.observe("frontier.merge.ite_depth", int(count),
                                label=name)
        if trace.enabled():
            # per-pass deltas, like every frontier counter track (the
            # viewers sum samples into run totals)
            trace.counter("frontier.merges", merged=merges,
                          ites=int(mstats[1]))

    # -- seeding -----------------------------------------------------------------------

    def seed(self, seed_states: List[GlobalState]) -> Tuple:
        specs = []
        for template in seed_states:
            account = template.environment.active_account
            entries, base_sym = _storage_entries(account.storage)
            code_hex = template.environment.code.bytecode
            specs.append((template, entries, base_sym,
                          bytes.fromhex(code_hex[2:] if code_hex.startswith("0x")
                                        else code_hex)))

        # lane placement: identity when unsharded; block-affine when the
        # frontier is sharded (each seed's lanes land in the shard block
        # that owns its contract, so the block's planes stay device-local)
        seed_lanes = self._assign_seed_lanes(len(specs))
        spec_at = {lane: i for i, lane in enumerate(seed_lanes)}
        lane_specs = []
        for lane_i in range(self.n_lanes):
            if lane_i not in spec_at:
                lane_specs.append(LaneSpec(code=b"\x00"))  # dead filler
                continue
            template, entries, _base_sym, code = specs[spec_at[lane_i]]
            # symbolic-valued slots enter the table with a 0 placeholder so
            # the slot EXISTS — storage_sym below overlays the arena node
            # (otherwise device SLOADs would read concrete 0 for them)
            table = {key: (value.raw.value if value.raw.is_const else 0)
                     for key, value in entries}
            lane_specs.append(LaneSpec(
                code=code,
                storage=table,
                gas_limit=int(template.mstate.gas_limit),
                address=template.environment.address.raw.value,
            ))
        state = build_batch(lane_specs)
        planes = symstep.SymPlanes.empty(
            self.n_lanes, state.stack.shape[1], state.memory.shape[1],
            state.storage_keys.shape[1], MAX_CONDS)

        status = np.full(self.n_lanes, DEAD, dtype=np.int32)
        if seed_lanes:
            status[np.asarray(seed_lanes)] = RUNNING
        state = state._replace(status=np.asarray(status))

        storage_sym = np.zeros((self.n_lanes,
                                state.storage_keys.shape[1]), dtype=np.int32)
        storage_base_sym = np.zeros(self.n_lanes, dtype=bool)
        ctx_id = np.full(self.n_lanes, -1, dtype=np.int32)
        for lane, (template, entries, base_sym, _code) in zip(
                seed_lanes, specs):
            storage_base_sym[lane] = base_sym
            tx, _ = template.transaction_stack[-1]
            ctx = LaneContext(str(tx.id), template.environment.calldata,
                              template.environment, template)
            # build the CFA tables now, outside the step loop: every
            # materialized lane of this contract reads them
            cfa_screen.warm(template.environment.code)
            # same for the taint summary; the lane context carries the
            # dispatcher function order for per-function lane grouping
            module_screen.warm(template.environment.code)
            ctx.function_order = module_screen.function_order(
                template.environment.code)
            self.contexts.append(ctx)
            ctx_id[lane] = len(self.contexts) - 1
            # symbolic storage values ride in as host-term leaves
            for key, value in entries:
                if value.raw.is_const:
                    continue
                node = self._alloc_host_term(ctx, value)
                if node is None:
                    continue
                slot = self._storage_slot_of(state, lane, key)
                if slot is not None:
                    storage_sym[lane, slot] = node
        planes = planes._replace(storage_sym=np.asarray(storage_sym),
                                 storage_base_sym=np.asarray(storage_base_sym),
                                 ctx_id=np.asarray(ctx_id))
        return state, planes

    def _assign_seed_lanes(self, n_seeds: int) -> List[int]:
        """Lane index per seed. Unsharded: identity (seed i -> lane i).
        Sharded: seeds are distributed over the D lane blocks by their
        fleet owner's device index (`_seed_owner_index`, set by
        FleetDriver before seed()) or round-robin for standalone runs,
        filling each block sequentially; a full block overflows into the
        next with room — placement is an affinity hint, not a cage."""
        if self.n_shards <= 1:
            return list(range(n_seeds))
        per_block = self.n_lanes // self.n_shards
        owners = getattr(self, "_seed_owner_index", None)
        cursor = [0] * self.n_shards
        lanes: List[int] = []
        for i in range(n_seeds):
            want = (owners[i] if owners and i < len(owners)
                    else i) % self.n_shards
            blk = want
            for probe in range(self.n_shards):
                blk = (want + probe) % self.n_shards
                if cursor[blk] < per_block:
                    break
            lanes.append(blk * per_block + cursor[blk])
            cursor[blk] += 1
        return lanes

    def _alloc_host_term(self, ctx: "LaneContext", value) -> Optional[int]:
        """Park an arbitrary host BitVec as a V_HOST_TERM arena leaf; the
        leaf's taint-class bits include any detector annotations riding on
        the term (origin/predictable taint persisted through storage must
        still force a host visit at a dependent JUMPI)."""
        ctx.host_terms.append(value)
        self.arena, node, overflow = A.alloc_rows(
            self.arena,
            np.asarray([True]), np.asarray([A.VAR], dtype=np.int32),
            np.asarray([0], dtype=np.int32),
            np.asarray([0], dtype=np.int32),
            np.asarray([0], dtype=np.int32),
            np.asarray([A.V_HOST_TERM], dtype=np.int32),
            np.asarray([len(ctx.host_terms) - 1], dtype=np.int32))
        if bool(overflow[0]):
            return None
        extra_bits = self._annotation_class_bits(value)
        if extra_bits:
            node_index = int(node[0])
            self.arena = self.arena._replace(
                cls=self.arena.cls.at[node_index].set(
                    int(self.arena.cls[node_index]) | extra_bits))
        return int(node[0])

    @staticmethod
    def _annotation_class_bits(value) -> int:
        from ..analysis.modules.dependence_on_origin import OriginAnnotation
        from ..analysis.modules.dependence_on_predictable_vars import \
            PredictableValueAnnotation

        bits = 0
        for annotation in getattr(value, "annotations", ()):
            if isinstance(annotation, OriginAnnotation):
                bits |= 1 << A.V_ORIGIN
            elif isinstance(annotation, PredictableValueAnnotation):
                bits |= 1 << A.V_TIMESTAMP
        return bits

    @staticmethod
    def _storage_slot_of(state: StateBatch, lane: int, key: int
                         ) -> Optional[int]:
        from . import words

        used = np.asarray(state.storage_used[lane])
        keys = np.asarray(state.storage_keys[lane])
        for slot in range(used.shape[0]):
            if used[slot] and int(words.to_ints(keys[slot])) == key:
                return slot
        return None

    # -- host services -----------------------------------------------------------------

    def run(self, state: StateBatch, planes: symstep.SymPlanes) -> None:
        import jax

        from ..core.time_handler import time_handler

        max_steps = tpu_config.get_int("MYTHRIL_TPU_MAX_STEPS", MAX_STEPS)
        chunk = tpu_config.get_int("MYTHRIL_TPU_CHUNK", CHUNK)
        # env vars keep working; `analyze --checkpoint/--resume` rides the
        # laser's host-phase paths with a .device suffix beside the pickle
        host_ckpt = getattr(self.laser, "checkpoint_path", None)
        # NOT laser.resume_path: the host-resume logic consumes that before
        # the frontier runs (svm.execute_transactions)
        host_resume = getattr(self.laser, "_device_resume_path", None)
        checkpoint_path = tpu_config.get_str("MYTHRIL_TPU_CHECKPOINT") \
            or (f"{host_ckpt}.device" if host_ckpt else None)
        resume_path = tpu_config.get_str("MYTHRIL_TPU_RESUME") \
            or (f"{host_resume}.device" if host_resume else None)
        if self.fleet is not None and (checkpoint_path or resume_path):
            # a shared multi-contract wave must not land in ONE npz under
            # the primary's name: fleet resume rides the per-contract HOST
            # checkpoints (contract_id-stamped, support/checkpoint.py)
            log.info("fleet mode: device checkpoints disabled; per-contract "
                     "host checkpoints carry resume")
            checkpoint_path = None
            resume_path = None
        if resume_path:
            if not resume_path.endswith(".npz"):
                resume_path += ".npz"
            if os.path.exists(resume_path):
                try:
                    state, planes = self.load_checkpoint(resume_path)
                    log.info("resumed frontier from %s (%d forks so far)",
                             resume_path, self.forks)
                except Exception as error:  # corrupt file / identity mismatch
                    log.warning("cannot resume from %s (%s); starting the "
                                "device phase fresh", resume_path, error)
                tpu_config.consume("MYTHRIL_TPU_RESUME")  # consume once
                self.laser._device_resume_path = None
        # ONE jit signature: numpy rows written by host services must be
        # re-canonicalized to device arrays, or the next fused call sees a
        # host-placed argument signature and XLA recompiles the whole step
        state, planes = self._to_device(state, planes)
        # one fused chunk can allocate ~3 nodes/lane/step; the headroom
        # margin must cover a full chunk burst or symstep's overflow guard
        # kills lanes (paths dropped from the report). A config whose burst
        # cannot fit gets a LOUD host hand-over, not an unsafe margin
        headroom = max(ARENA_HEADROOM, 4 * chunk * self.n_lanes)
        if headroom > self.arena.capacity // 2:
            log.warning(
                "MYTHRIL_TPU_CHUNK (%d) x lanes (%d) allocation burst "
                "exceeds the arena safety margin (capacity %d); running "
                "this transaction on the host — lower the chunk or lane "
                "count", chunk, self.n_lanes, self.arena.capacity)
            self._hand_over_running(state, planes)
            return
        sched = self._new_sched(state, planes)
        stack_rows = sched.stack_state.status.shape[0]
        # steal width: up to one block's worth of lanes per donor/receiver
        # pair each pass, bounded by the segment size (static jit arg)
        steal_max_rows = min(max(stack_rows // max(self.n_shards, 1), 1),
                             max(16, self.n_lanes // max(self.n_shards, 1)))
        # post-dominator merge-point table (staticanalysis/ via the CFA
        # screen): attribution labels for frontier.merge.tag_merges. The
        # telemetry tag-occupancy deltas on these pcs are the trigger;
        # without them the pass runs on a fixed chunk cadence.
        merge_pc_arr, merge_names, mem_pc_arr, mem_word_arr = \
            self._merge_pc_table() if self.state_merge else \
            (np.zeros(0, np.int32), [], np.zeros(0, np.int32),
             np.zeros((0, 1), np.int32))
        merge_by_tags = self.telemetry_enabled and any(
            name.startswith("merge@") for name in self.tag_names)
        # an unsatisfiable count trigger would silently degrade every drain
        # to the frozen-ESCAPED overflow fallback
        drain_batch = min(self.drain_batch,
                          sched.esc_state.status.shape[0])
        # counters are cumulative across transactions; the scheduler's
        # device counters restart at 0 each phase
        lane_base, fork_base = self.lane_steps, self.forks
        push_base, pop_base = self.stack_pushes, self.stack_pops
        steps = 0
        status = np.asarray(state.status)
        arena_n = int(self.arena.n)
        backlog = None  # fetched escape rows awaiting materialization
        # the device may consume at most this fraction of the remaining
        # execution budget: the rest belongs to the host continuation
        # (detector hooks, deferred-row materialization, next-tx seeding)
        frac = tpu_config.get_float("MYTHRIL_TPU_DEVICE_FRAC")
        device_deadline = time_handler.time_remaining() * min(max(frac, 0.05),
                                                              1.0)
        import time as time_module

        phase_start = time_module.monotonic()
        while steps < max_steps:
            if arena_n > self.arena.capacity - headroom:
                log.warning("arena head-room exhausted; handing remaining "
                            "lanes to the host")
                break
            if time_handler.time_remaining() <= 1000:  # ms
                log.info("execution budget exhausted; ending device phase")
                break
            if (time_module.monotonic() - phase_start) * 1000 \
                    > device_deadline:
                log.info("device budget fraction (%.0f%%) consumed; the "
                         "host continuation takes over", frac * 100)
                break
            # the dispatch itself is async — the span bounds enqueue time;
            # the blocking device wait lands in the frontier.sync span below
            with trace.span("frontier.chunk", steps=chunk):
                state, planes, self.arena, sched = symstep.run_chunk(
                    state, planes, self.arena, sched, chunk)
            metrics.inc("frontier.chunks")
            steps += chunk
            # cadenced device-resident steal pass: the trigger (per-shard
            # load from running lanes + pending rows) and the row moves
            # both happen on device — the rebalance decision never touches
            # the host (the cadence itself is host-static arithmetic)
            if self.n_shards > 1 and self.steal_cadence > 0 \
                    and (steps // chunk) % self.steal_cadence == 0:
                with trace.span("frontier.steal"):
                    sched = _steal_compiled()(
                        state, sched,
                        min_imbalance=self.steal_min_imbalance,
                        max_rows=steal_max_rows)
                self._steal_passes += 1
                metrics.inc("frontier.shard.steal_passes")
            # PIPELINE: the chunk dispatch above is async — materialize the
            # previously-fetched escape rows NOW, while the device steps
            if backlog is not None:
                self._flush_backlog(backlog)
                backlog = None
            # ONE small packed download per chunk: lane status, scheduler
            # pointers/counters, arena fill, escape-row maxima. Everything
            # else stays in HBM (the tunnel: ~30 ms floor PER ARRAY +
            # ~35 MB/s down, ~100 ms floor up — per-service host decisions
            # and multi-leaf fetches are unaffordable)
            with trace.span("frontier.sync"):
                packed = np.asarray(jax.device_get(
                    _summary_compiled()(state, planes, self.arena, sched)))
            # a sharded scheduler appends [tops[D], esc[D], sent[D],
            # recv[D], moved] — peel it off the tail first (D is static
            # host knowledge; the block rides the same single download)
            shard_words = None
            if self.n_shards > 1:
                n_shard_words = 4 * self.n_shards + 1
                shard_words = packed[-n_shard_words:]
                packed = packed[:-n_shard_words]
            (stack_top, esc_count, executed, forks, pushes, pops, arena_n,
             arena_nc, esc_msize, esc_sp, esc_slots, esc_conds, _batch) = (
                 int(v) for v in packed[:13])
            status = packed[13:13 + self.n_lanes].astype(np.int32)
            fork_cond = packed[13 + self.n_lanes:
                               13 + 2 * self.n_lanes].astype(np.int32)
            lane_ctx = packed[13 + 2 * self.n_lanes:
                              13 + 3 * self.n_lanes].astype(np.int32)
            if shard_words is not None:
                self._publish_shard(shard_words, status)
            if sched.telemetry is not None:
                self._publish_telemetry(
                    packed[13 + 3 * self.n_lanes:],
                    running=int(np.sum(status == RUNNING)),
                    stack_top=stack_top, esc_count=esc_count,
                    arena_n=arena_n)
            self.lane_steps = lane_base + executed
            self.forks = fork_base + forks
            self.stack_pushes = push_base + pushes
            self.stack_pops = pop_base + pops
            dirty = False  # host mutated lane state this round?
            # per-contract deadline drain: a fleet member past its budget
            # has its live lanes killed in place — freed for reseeding by
            # the surviving contracts, NOT a global abort
            if self.fleet is not None \
                    and self.fleet.deadline_drain(self, status, lane_ctx):
                dirty = True
            # cold-SLOAD pauses need a host fault-in to progress at all
            cold = np.nonzero((status == FORKING) & (fork_cond == 0))[0]
            if len(cold):
                metrics.inc("frontier.cold_sloads", len(cold))
                with trace.span("frontier.service_cold", lanes=len(cold)):
                    harena = self._harena(arena_n, arena_nc)
                    state, planes = self._service_cold(
                        state, planes, status, [int(l) for l in cold],
                        harena)
                dirty = True
            # escape-buffer overflow: lanes frozen ESCAPED are packed off
            # to the deferred queue (lazy materialization) and freed
            frozen = np.nonzero(status == ESCAPED)[0]
            if len(frozen):
                self._harena(arena_n, arena_nc)
                self._defer_lanes(state, planes, frozen)
                status[frozen] = DEAD
                dirty = True
            # total deadlock with the sibling stack full: spill half the
            # waiting forkers to the host overflow tier
            waiting = (status == FORKING) & (fork_cond != 0)
            # sharded: a single FULL segment can wedge its block's forkers
            # even while other segments have room (pushes are segment-
            # local), so the spill trigger is the fullest segment
            if self.n_shards > 1 and self._shard_tops is not None:
                stack_full = int(np.max(self._shard_tops)) \
                    >= stack_rows // self.n_shards
            else:
                stack_full = stack_top >= stack_rows
            if waiting.any() and not (status == RUNNING).any() \
                    and not (status == DEAD).any() \
                    and stack_full:
                lanes = np.nonzero(waiting)[0]
                self._spill_host(state, planes, status,
                                 [int(l) for l in lanes[:max(1, len(lanes)
                                                             // 2)]])
                dirty = True
            # bulk-drain the escape buffer: one batched light transfer now,
            # Python materialization deferred past the next chunk dispatch
            if esc_count >= drain_batch or (
                    esc_count and stack_top == 0
                    and not (status == RUNNING).any()):
                metrics.observe("frontier.drain.rows", esc_count)
                with trace.span("frontier.fetch_escapes", rows=esc_count):
                    backlog = self._fetch_escapes(sched, esc_count,
                                                  esc_msize, esc_sp,
                                                  esc_slots, esc_conds,
                                                  arena_n, arena_nc)
                sched = _reset_esc_compiled()(sched)
                esc_count = 0
            # host overflow rows re-enter once the device stack is empty
            if self.pending and stack_top == 0 and (status == DEAD).any():
                state, planes = self._reseed_host(state, planes, status)
                dirty = True
            if dirty:
                state = state._replace(status=status)
                state, planes = self._to_device(state, planes)
            # state merging (veritesting): collapse fork-sibling lanes that
            # reconverged after their diamond. MUST run after the dirty
            # re-upload above — an earlier merge would be undone when the
            # stale host-side status resurrects the retired partner. The
            # trigger is the per-chunk merge-tag occupancy delta (>= K
            # lane-visits at one join point); runs only while >= 2 lanes
            # can actually pair
            if self.state_merge and int(np.sum(status == RUNNING)) >= 2:
                if merge_by_tags and self._last_tag_delta is not None:
                    due = any(
                        int(count) >= self.merge_min_lanes
                        for name, count in zip(self.tag_names,
                                               self._last_tag_delta)
                        if name.startswith("merge@"))
                else:  # telemetry off (or no tracked joins): fixed cadence
                    due = (steps // chunk) % 4 == 0
                if due:
                    with trace.span("frontier.merge"):
                        state, planes, self.arena, mstats = \
                            _merge_compiled()(
                                state, planes, self.arena, merge_pc_arr,
                                mem_pc_arr, mem_word_arr,
                                n_rounds=_MERGE_ROUNDS)
                        # one small vector download, on triggered chunks
                        # only (the tunnel charges a ~30 ms floor)
                        mstats = np.asarray(jax.device_get(mstats))
                    self._publish_merge(mstats, merge_names)
            if checkpoint_path and steps % (chunk * 16) == 0:
                # deferred rows live only in host RAM (neither the device
                # npz nor the host pickle covers them): materialize them
                # into the worklist first so the host checkpoint owns them
                try:
                    while self.deferred:
                        entry = self.deferred[0]
                        rows_state, rows_planes, count, _ = entry
                        self._prefetch_feasibility(rows_planes,
                                                   range(entry[3], count),
                                                   state_np=rows_state)
                        while entry[3] < count:
                            # advance the cursor in place BEFORE popping: a
                            # mid-loop exception must leave the entry (with
                            # its progress) on the list so the feeder still
                            # drains the remaining rows
                            row = entry[3]
                            self._materialize_np(rows_state, rows_planes,
                                                 self.harena, row)
                            entry[3] = row + 1
                        self.deferred.pop(0)
                    self.save_checkpoint(checkpoint_path, state, planes,
                                         sched)
                except Exception as error:  # noqa: BLE001
                    log.warning("periodic device checkpoint failed (%s); "
                                "continuing without it", error)
            if not ((status == RUNNING) | (status == FORKING)).any() \
                    and stack_top == 0 and esc_count == 0 \
                    and not self.pending:
                self._flush_backlog(backlog)
                self._discard_checkpoint(checkpoint_path)
                return
        # budget exhausted: surviving lanes + backlog continue on host.
        # Timeout parity: with no budget left, fetched-but-unmaterialized
        # rows are dropped exactly like the host's mid-worklist states
        if time_handler.time_remaining() > 1000:
            self._flush_backlog(backlog)
        self._hand_over_running(state, planes, sched)
        self._discard_checkpoint(checkpoint_path)

    def _publish_telemetry(self, tel_words, running: int, stack_top: int,
                           esc_count: int, arena_n: int) -> None:
        """Decode one chunk's telemetry words (cumulative device counters,
        already fetched in the summary — pure host numpy, zero extra
        syncs) into per-chunk deltas published as declared metrics and
        Perfetto counter ('C') tracks."""
        tel_words = np.asarray(tel_words, dtype=np.int64)
        prev = self._tel_prev
        if prev is None or prev.shape != tel_words.shape:
            prev = np.zeros_like(tel_words)
        delta = tel_words - prev
        self._tel_prev = tel_words
        n_op, n_lc = symstep.N_OP_CLASSES, symstep.N_LIFECYCLE
        n_ec = symstep.N_ESC_CAUSES
        op_d = delta[:n_op]
        lc = dict(zip(symstep.LIFECYCLE_NAMES,
                      (int(v) for v in delta[n_op:n_op + n_lc])))
        ec_d = delta[n_op + n_lc:n_op + n_lc + n_ec]
        occupancy = tel_words[n_op + n_lc + n_ec:n_op + n_lc + n_ec + 2]
        hwm = tel_words[n_op + n_lc + n_ec + 2:n_op + n_lc + n_ec + 4]
        tag_base = n_op + n_lc + n_ec + 4
        tag_d = delta[tag_base:tag_base + len(self.tag_names)]
        fleet_d = delta[tag_base + len(self.tag_names):]
        self._last_tag_delta = tag_d  # merge-pass trigger signal
        self._last_fleet_delta = fleet_d

        metrics.inc("frontier.telemetry.executed", int(np.sum(op_d)))
        metrics.inc("frontier.telemetry.forks",
                    lc["forks_claimed"] + lc["forks_pushed"]
                    + lc["forks_spilled"])
        metrics.inc("frontier.telemetry.escapes",
                    lc["esc_buffered"] + lc["esc_frozen"])
        metrics.inc("frontier.telemetry.reseeds", lc["reseeds"])
        metrics.inc("frontier.telemetry.deaths",
                    lc["err_deaths"] + lc["overflow_kills"]
                    + lc["bad_jump_deaths"])
        metrics.inc("frontier.telemetry.cold_sload_pauses",
                    lc["cold_sloads"])
        metrics.set_gauge("frontier.telemetry.stack_hwm", int(hwm[0]))
        metrics.set_gauge("frontier.telemetry.esc_hwm", int(hwm[1]))
        # device-memory accounting: high-water rows x packed row bytes,
        # arena nodes x per-node bytes — shape/dtype metadata only, no
        # extra device syncs beyond the summary download we already have
        row_bytes = getattr(self, "_row_bytes", 0)
        node_bytes = getattr(self, "_arena_node_bytes", None)
        if node_bytes is None:
            node_bytes = sum(
                int(np.dtype(leaf.dtype).itemsize) for leaf in self.arena
                if getattr(leaf, "ndim", 0) == 1
                and leaf.shape[0] == self.arena.capacity)
            self._arena_node_bytes = node_bytes
        stack_bytes = int(hwm[0]) * row_bytes
        esc_bytes = int(hwm[1]) * row_bytes
        arena_bytes = arena_n * node_bytes
        metrics.set_gauge("frontier.telemetry.stack_bytes", stack_bytes)
        metrics.set_gauge("frontier.telemetry.esc_bytes", esc_bytes)
        metrics.set_gauge("frontier.telemetry.arena_bytes", arena_bytes)
        if int(occupancy[1]):
            metrics.set_gauge("frontier.telemetry.occupancy",
                              float(occupancy[0]) / float(occupancy[1]))
        for name, count in zip(symstep.OP_CLASS_NAMES, op_d):
            if count:
                metrics.observe("frontier.telemetry.op_class", int(count),
                                label=name)
        for name, count in zip(symstep.ESC_CAUSE_NAMES, ec_d):
            if count:
                metrics.observe("frontier.telemetry.esc_cause", int(count),
                                label=name)
        for name, count in lc.items():
            if count:
                metrics.observe("frontier.telemetry.lifecycle", count,
                                label=name)
        for name, count in zip(self.tag_names, tag_d):
            if count:
                metrics.observe("frontier.telemetry.tag_occupancy",
                                int(count), label=name)
        # per-contract fleet occupancy (running-lane-steps this chunk per
        # packed contract) — the fairness signal frontierview renders
        if self.fleet_names:
            metrics.set_gauge("frontier.fleet.contracts",
                              len(self.fleet_names))
            for name, count in zip(self.fleet_names, fleet_d):
                if count:
                    metrics.observe("frontier.fleet.lane_steps",
                                    int(count), label=name)
        if slog.enabled():
            # correlated structured log line per chunk: under serve the
            # handling thread's contextvar carries the request's cid
            slog.event("frontier.chunk", running=running,
                       stack=stack_top, escaped=esc_count,
                       arena=arena_n,
                       executed=int(np.sum(op_d)),
                       stack_bytes=stack_bytes, esc_bytes=esc_bytes,
                       arena_bytes=arena_bytes)
        if trace.enabled():
            trace.counter("frontier.lanes", running=running,
                          stack=stack_top, escaped=esc_count)
            trace.counter("frontier.arena", nodes=arena_n)
            trace.counter("frontier.memory", stack_bytes=stack_bytes,
                          esc_bytes=esc_bytes, arena_bytes=arena_bytes)
            trace.counter("frontier.ops", **{
                name: int(count)
                for name, count in zip(symstep.OP_CLASS_NAMES, op_d)})
            trace.counter("frontier.causes", **{
                name: int(count)
                for name, count in zip(symstep.ESC_CAUSE_NAMES, ec_d)})
            trace.counter("frontier.lifecycle", **lc)
            if self.tag_names:
                trace.counter("frontier.tags", **{
                    name: int(count)
                    for name, count in zip(self.tag_names, tag_d)})
            if self.fleet_names:
                trace.counter("frontier.fleet", **{
                    name: int(count)
                    for name, count in zip(self.fleet_names, fleet_d)})

    def _publish_shard(self, shard_words, status) -> None:
        """Decode the summary's trailing shard block — pure host numpy on
        the single download the chunk already paid for. Publishes the
        frontier.shard.* metrics (per-shard occupancy, steal counters as
        per-chunk deltas, imbalance + Jain fairness over per-shard load)
        and a frontierview counter track, and stashes the per-shard tops
        and escape counts the segmented host drains read."""
        words = np.asarray(shard_words, dtype=np.int64)
        n = self.n_shards
        tops = words[:n]
        esc = words[n:2 * n]
        sent = words[2 * n:3 * n]
        recv = words[3 * n:4 * n]
        moved = int(words[4 * n])
        self._shard_tops = tops
        self._shard_esc = esc
        prev = self._shard_steals
        self._shard_steals = (sent, recv, moved)
        occ = (np.asarray(status) == RUNNING).reshape(n, -1).sum(axis=1)
        # load = running lanes + pending pool rows, the steal pass's own
        # ranking signal; Jain fairness of it is the balance criterion
        load = occ.astype(np.float64) + tops.astype(np.float64)
        square_sum = float(np.sum(load * load))
        fairness = (float(np.sum(load)) ** 2 / (n * square_sum)
                    if square_sum > 0 else 1.0)
        metrics.set_gauge("frontier.shard.devices", n)
        metrics.set_gauge("frontier.shard.imbalance",
                          int(load.max() - load.min()))
        metrics.set_gauge("frontier.shard.fairness", round(fairness, 4))
        for dev in range(n):
            metrics.observe("frontier.shard.occupancy", int(occ[dev]),
                            label=f"dev{dev}")
        # steal counters accumulate on device within a phase: delta here
        if prev is not None:
            d_sent, d_recv = sent - prev[0], recv - prev[1]
            d_moved = moved - prev[2]
        else:
            d_sent, d_recv, d_moved = sent, recv, moved
        for dev in range(n):
            if int(d_sent[dev]):
                metrics.observe("frontier.shard.steals_sent",
                                int(d_sent[dev]), label=f"dev{dev}")
            if int(d_recv[dev]):
                metrics.observe("frontier.shard.steals_received",
                                int(d_recv[dev]), label=f"dev{dev}")
        if d_moved:
            metrics.inc("frontier.shard.steal_rows", int(d_moved))
        if trace.enabled():
            trace.counter("frontier.shard", **{
                f"dev{dev}": int(load[dev]) for dev in range(n)})

    @staticmethod
    def _discard_checkpoint(checkpoint_path) -> None:
        """The device phase ended and its wave is fully on the host side:
        a leftover .npz would graft this wave onto a LATER transaction's
        fresh seeding on resume (same lane/context counts pass the
        identity check) — delete it (ADVICE r4 medium)."""
        if not checkpoint_path:
            return
        path = checkpoint_path if checkpoint_path.endswith(".npz") \
            else checkpoint_path + ".npz"
        try:
            if os.path.exists(path):
                os.remove(path)
        except OSError as error:
            log.warning("cannot remove completed device checkpoint %s: %s",
                        path, error)

    def _lane_sharding(self):
        if self._lane_sharding_cache is not Ellipsis:
            return self._lane_sharding_cache
        self._lane_sharding_cache = self._compute_lane_sharding()
        return self._lane_sharding_cache

    def _compute_lane_sharding(self):
        """NamedSharding over the lane axis when the process has multiple
        devices (SURVEY §2.3 'sharded frontier over devices ≡ multi-chip
        DP'). Fork-target allocation runs a cumsum over the GLOBAL lane
        axis, so a forker on one device claims dead capacity on any other —
        XLA's inserted collectives ARE the load-aware rebalance.

        Gating: MYTHRIL_TPU_SHARD=1 forces on, =0 forces off; default is
        on only for REAL accelerator meshes (the CI conftest creates 8
        virtual CPU devices for mesh tests, and paying the GSPMD compile
        of the fused step on every CPU test run is not acceptable).

        Mesh-aware plane placement: with a logically sharded frontier
        (n_shards contiguous lane blocks, each block's contract planes
        seeded block-local) the mesh size must put device boundaries ON
        block boundaries — otherwise one block straddles two devices and
        lockstep stepping gathers its planes cross-device every step. Any
        misfit (lane count not divisible, shard/device counts unaligned)
        falls back to single-device with a logged reason, never an
        error."""
        import jax

        devices = jax.devices()
        flag = tpu_config.get_raw("MYTHRIL_TPU_SHARD")
        n_dev = len(devices)
        if flag == "0" or n_dev < 2:
            return None
        if flag != "1" and devices[0].platform == "cpu":
            return None
        if self.n_lanes % n_dev:
            log.warning(
                "%d lanes do not divide across %d devices; running "
                "single-device (set MYTHRIL_TPU_LANES to a multiple of "
                "the device count)", self.n_lanes, n_dev)
            return None
        if self.n_shards > 1 and self.n_shards % n_dev \
                and n_dev % self.n_shards:
            log.warning(
                "mesh of %d devices does not align with %d logical shard "
                "blocks (device boundaries must land on block "
                "boundaries); running single-device — set "
                "MYTHRIL_TPU_FLEET_SHARD to a multiple or divisor of the "
                "device count", n_dev, self.n_shards)
            return None
        from jax.sharding import (Mesh, NamedSharding, PartitionSpec)

        mesh = Mesh(np.array(devices), ("lanes",))
        return NamedSharding(mesh, PartitionSpec("lanes"))

    def _to_device(self, state: StateBatch, planes: symstep.SymPlanes):
        import jax

        # ONE batched async transfer for the whole pytree: 40+ sequential
        # per-field puts each paid a full round-trip on the remote-TPU
        # tunnel (~12s of dead time per seeding at 512 lanes)
        sharding = self._lane_sharding()
        if sharding is None:
            return jax.device_put((state, planes))
        return jax.device_put((state, planes), jax.tree_util.tree_map(
            lambda _: sharding, (state, planes)))

    def _pack_async(self, state_like, planes_like, index, msize_m: int,
                    sp_m: int, st_m: int, conds_m: int):
        """Dispatch the quantized light pack and START its host copy; the
        returned handle unpacks later (so the multi-MB transfer streams
        while the device computes the next chunk).

        Quantized static slice sizes: every distinct (bucket, mem_b, sp_b,
        st_b, conds_w) combination is its own XLA program (compile, then a
        ~0.3 s cache read per process) — a few coarse steps beat exact
        power-of-two fits."""
        def quantize(value, steps_, cap):
            for step in steps_:
                if value <= step:
                    return min(step, cap)
            return cap

        mem_b = quantize(msize_m, (1, 32, 512),
                         planes_like.mem_sym.shape[1])
        sp_b = quantize(sp_m, (4, 16), state_like.stack.shape[1])
        st_b = quantize(st_m, (1, 8), state_like.storage_keys.shape[1])
        conds_w = quantize(conds_m, (16,), planes_like.conds.shape[1])
        i32, u8, gas = _pack_rows_compiled()(
            state_like, planes_like, np.asarray(index, dtype=np.int32),
            mem_b=mem_b, sp_b=sp_b, st_b=st_b, conds_w=conds_w)
        for leaf in (i32, u8, gas):
            try:
                leaf.copy_to_host_async()
            except AttributeError:  # numpy backend
                pass
        return i32, u8, gas, len(index), mem_b, sp_b, st_b, conds_w

    @staticmethod
    def _pack_apply(handle):
        i32, u8, gas, bucket, mem_b, sp_b, st_b, conds_w = handle
        return _drain_unpack(i32, u8, gas, bucket, mem_b, sp_b, st_b,
                             conds_w)

    def _pack_fetch(self, state_like, planes_like, index, msize_m: int,
                    sp_m: int, st_m: int, conds_m: int):
        """Synchronous pack + unpack (hand-over and fallback paths)."""
        return self._pack_apply(self._pack_async(
            state_like, planes_like, index, msize_m, sp_m, st_m, conds_m))

    def _fetch_rows(self, state_like, planes_like, index):
        """Shared maxima + light-pack fetch of selected rows: index padded
        to a power-of-two bucket (pad repeats index[0]: fetched, unused) so
        gather shapes and their XLA compiles stay bounded. Returns
        (rows_state, rows_planes, count)."""
        import jax

        from .batch import next_pow2

        index = np.asarray(index)
        count = len(index)
        if not count:
            return None, None, 0
        bucket = next_pow2(count)
        padded = np.full(bucket, index[0], dtype=np.int32)
        padded[:count] = index
        maxima = np.asarray(jax.device_get(_row_maxima_compiled()(
            state_like, planes_like, padded)))
        rows_state, rows_planes = self._pack_fetch(
            state_like, planes_like, padded, *(int(v) for v in maxima))
        return rows_state, rows_planes, count

    def _materialize_lanes(self, state: StateBatch, planes, harena,
                           lanes) -> None:
        """Batched materialization of selected lanes: one tiny maxima fetch
        sizes the light pack, one bundled download moves the rows, then
        per-row host GlobalState construction."""
        rows_state, rows_planes, count = self._fetch_rows(state, planes,
                                                          lanes)
        for row in range(count):
            self._materialize_np(rows_state, rows_planes, harena, row)

    def _defer_lanes(self, state: StateBatch, planes, lanes) -> None:
        """Pack selected lanes' rows to host RAM for lazy materialization
        (escape-buffer overflow relief)."""
        rows_state, rows_planes, count = self._fetch_rows(state, planes,
                                                          lanes)
        if count:
            self.deferred.append([rows_state, rows_planes, count, 0])

    @staticmethod
    def _pool_used_indices(counts, pool_rows: int) -> np.ndarray:
        """Host-side row index of a pool's used rows. Scalar count: the
        plain prefix [0, count). Sharded (i64[D] per-segment counts): the
        concatenation of each segment's prefix [d*seg, d*seg+counts[d])
        — used rows are segment-local prefixes, not one global prefix."""
        counts = np.atleast_1d(np.asarray(counts))
        seg = pool_rows // len(counts)
        parts = [np.arange(d * seg, d * seg + int(c), dtype=np.int64)
                 for d, c in enumerate(counts)]
        return (np.concatenate(parts) if parts
                else np.zeros(0, dtype=np.int64))

    def _materialize_pool_prefix(self, pool_state, pool_planes,
                                 used) -> None:
        """Materialize the used rows of a scheduler pool (hand-over):
        `used` is a row-index array, or a scalar meaning rows [0, used)."""
        index = np.asarray(used)
        if index.ndim == 0:
            index = np.arange(int(index))
        if not len(index):
            return
        rows_state, rows_planes, count = self._fetch_rows(
            pool_state, pool_planes, index)
        if count:
            self.deferred.append([rows_state, rows_planes, count, 0])

    def _spill_host(self, state: StateBatch, planes, status,
                    lanes: List[int]) -> None:
        """Overflow tier: gather rows to the numpy pending list (one bundled
        transfer). Only reached when the DEVICE sibling stack is full at a
        total deadlock — the scheduler handles everything else in HBM."""
        import jax

        from .batch import next_pow2

        index = np.asarray(lanes, dtype=np.int64)
        bucket = next_pow2(len(index))
        padded = np.full(bucket, index[0], dtype=np.int64)
        padded[:len(index)] = index
        rows_state, rows_planes = jax.device_get(
            _gather_rows_compiled()(state, planes, padded.astype(np.int32)))
        for row in range(len(index)):
            self.pending.append((
                {field: np.asarray(getattr(rows_state, field)[row])
                 for field in rows_state._fields},
                {field: np.asarray(getattr(rows_planes, field)[row])
                 for field in rows_planes._fields}))
        status[index] = DEAD
        self.spilled += len(index)

    def _reseed_host(self, state: StateBatch, planes, status):
        """Scatter pending overflow rows into DEAD lanes (bundled upload);
        each row resumes with its own saved status (RUNNING sibling,
        FORKING waiter, or ESCAPED row that re-buffers next chunk)."""
        from .batch import next_pow2

        count = min(int(np.sum(status == DEAD)), len(self.pending))
        if not count:
            return state, planes
        self.pending.sort(key=lambda rows: int(rows[1]["cond_count"]))
        take = [self.pending.pop() for _ in range(count)]  # deepest first
        lanes = np.nonzero(status == DEAD)[0][:count]
        bucket = next_pow2(count)
        index = np.full(bucket, self.n_lanes, dtype=np.int32)  # pad: drop
        index[:count] = lanes
        rows_state = {}
        for field in StateBatch._fields:
            rows = np.stack([rs[field] for rs, _ in take])
            rows_state[field] = rows if bucket == count else np.concatenate(
                [rows, np.zeros((bucket - count,) + rows.shape[1:],
                                dtype=rows.dtype)])
        rows_planes = {}
        for field in symstep.SymPlanes._fields:
            rows = np.stack([rp[field] for _, rp in take])
            rows_planes[field] = rows if bucket == count else np.concatenate(
                [rows, np.zeros((bucket - count,) + rows.shape[1:],
                                dtype=rows.dtype)])
        state, planes = _scatter_rows_compiled()(
            state, planes, np.asarray(index),
            StateBatch(**rows_state), symstep.SymPlanes(**rows_planes))
        for position, lane in enumerate(lanes):
            status[lane] = int(take[position][0]["status"])
        self.reseeded += count
        return state, planes

    def _fetch_escapes(self, sched, esc_count: int, esc_msize: int,
                       esc_sp: int, esc_slots: int, esc_conds: int,
                       arena_n: int, arena_nc: int):
        """Dispatch the LIGHT pack of the buffered escape rows + the arena
        mirror delta, with host copies STARTED but not awaited. The driver
        materializes the returned backlog entry after dispatching the next
        fused chunk: both the multi-MB transfers and the per-row Python
        GlobalState construction then overlap device compute."""
        from .batch import next_pow2

        if self.harena is None:
            self.harena = A.HostArena(self.arena, 1, 0)  # empty mirror
        delta_handle = self.harena.refresh_async(self.arena, arena_n,
                                                 arena_nc)
        esc_cap = sched.esc_state.status.shape[0]
        # sharded: used escape rows are per-segment prefixes — the shard
        # block parsed from this chunk's summary carries the counts, so no
        # extra device read is needed
        if self.n_shards > 1 and self._shard_esc is not None:
            pool_used = self._pool_used_indices(self._shard_esc, esc_cap)
        else:
            pool_used = np.arange(min(esc_count, esc_cap))
        count = len(pool_used)
        bucket = min(next_pow2(max(count, 1)), esc_cap)
        index = np.zeros(bucket, dtype=np.int32)
        index[:min(count, bucket)] = pool_used[:bucket]
        pack_handle = self._pack_async(
            sched.esc_state, sched.esc_planes, index, esc_msize, esc_sp,
            esc_slots, esc_conds)
        return pack_handle, delta_handle, count

    def _flush_backlog(self, backlog) -> None:
        """Land a drain's transfers in host RAM and queue the rows for
        LAZY materialization (make_feeder); nothing is built eagerly."""
        if backlog is None:
            return
        pack_handle, delta_handle, count = backlog
        with trace.span("frontier.host_drain", rows=count):
            self.harena.refresh_apply(delta_handle)
            rows_state, rows_planes = self._pack_apply(pack_handle)
            self.deferred.append([rows_state, rows_planes, count, 0])

    def make_feeder(self, batch_rows: int = 256):
        """Refill callback for the svm exec loop: materialize up to
        `batch_rows` deferred rows into the worklist; False when empty."""
        def feeder() -> bool:
            fed = 0
            while self.deferred and fed < batch_rows:
                entry = self.deferred[0]
                rows_state, rows_planes, count, cursor = entry
                take = min(count - cursor, batch_rows - fed)
                self._prefetch_feasibility(rows_planes,
                                           range(cursor, cursor + take),
                                           state_np=rows_state)
                for row in range(cursor, cursor + take):
                    self._materialize_np(rows_state, rows_planes,
                                         self.harena, row)
                entry[3] += take
                fed += take
                if entry[3] >= count:
                    self.deferred.pop(0)
            return fed > 0

        return feeder

    def _sched_rows(self, sched) -> List[Tuple[Dict[str, np.ndarray],
                                               Dict[str, np.ndarray]]]:
        """Full rows still held by the device scheduler (sibling stack +
        escape buffer), for checkpointing and hand-over. Read-only: the
        scheduler is not mutated."""
        import jax

        from .batch import next_pow2

        rows: List[Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]] = []
        for pool_state, pool_planes, counts in (
                (sched.stack_state, sched.stack_planes, sched.stack_top),
                (sched.esc_state, sched.esc_planes, sched.esc_count)):
            pool_used = self._pool_used_indices(
                np.asarray(counts), pool_state.status.shape[0])
            used = len(pool_used)
            if not used:
                continue
            bucket = min(next_pow2(used), pool_state.status.shape[0])
            index = np.zeros(bucket, dtype=np.int32)
            index[:used] = pool_used[:bucket]
            rows_state, rows_planes = jax.device_get(
                _gather_rows_compiled()(pool_state, pool_planes, index))
            for row in range(used):
                rows.append((
                    {field: np.asarray(getattr(rows_state, field)[row])
                     for field in rows_state._fields},
                    {field: np.asarray(getattr(rows_planes, field)[row])
                     for field in rows_planes._fields}))
        return rows

    def _service_cold(self, state: StateBatch, planes, status,
                      cold: List[int], harena):
        """Fault-in service for cold-SLOAD pauses, on gathered ROWS: one
        bundled gather, per-row host mutation, one bundled scatter-back.
        (The round-4 version round-tripped the ENTIRE batch through numpy
        per service — ~160 MB over the tunnel at 4096 lanes.)"""
        import jax

        from .batch import next_pow2

        index = np.asarray(cold, dtype=np.int64)
        bucket = next_pow2(len(index))
        padded = np.full(bucket, index[0], dtype=np.int64)
        padded[:len(index)] = index
        rows_state, rows_planes = jax.device_get(
            _gather_rows_compiled()(state, planes, padded.astype(np.int32)))
        state_rows = {field: np.array(getattr(rows_state, field))
                      for field in rows_state._fields}
        planes_rows = {field: np.array(getattr(rows_planes, field))
                       for field in rows_planes._fields}
        for row, lane in enumerate(cold):
            self._cold_sload_lane(state_rows, planes_rows, harena, status,
                                  int(lane), row)
        scat_index = np.full(bucket, self.n_lanes, dtype=np.int32)  # drop pad
        scat_index[:len(cold)] = cold
        return _scatter_rows_compiled()(
            state, planes, scat_index,
            StateBatch(**state_rows), symstep.SymPlanes(**planes_rows))

    def _cold_sload_lane(self, state_np, planes_np, harena, status,
                         lane: int, row: int) -> None:
        """Fault a storage slot into the device table: the lane paused AT an
        SLOAD whose concrete key misses the table on a symbolic-base storage.
        Reads the template's Storage (yielding Select(base, key) — or a known
        value the chain walk pre-seeded), parks the term as a V_HOST_TERM
        arena leaf, inserts the slot, and resumes the lane on device.
        `state_np`/`planes_np` hold gathered rows; `row` is the lane's row
        index, `lane` its global index (for the status plane)."""
        from . import words

        ctx = self.contexts[int(planes_np["ctx_id"][row])]
        sp = int(state_np["sp"][row])
        key = int(words.to_ints(state_np["stack"][row, sp - 1]))
        used = state_np["storage_used"][row]
        free = np.nonzero(~used)[0]
        if not len(free):
            # table capacity exhausted: the host owns this lane from here
            self._materialize_np(state_np, planes_np, harena, row)
            status[lane] = DEAD
            return
        slot = int(free[0])
        account = ctx.template.environment.active_account
        value = account.storage[symbol_factory.BitVecVal(key, 256)]
        state_np["storage_keys"][row, slot] = np.asarray(
            words.from_int(key))
        state_np["storage_used"][row, slot] = True
        if value.raw.is_const:
            state_np["storage_vals"][row, slot] = np.asarray(
                words.from_int(value.raw.value))
            planes_np["storage_sym"][row, slot] = 0
        else:
            node = self._alloc_host_term(ctx, value)
            if node is None:
                # arena exhausted: node id 0 would silently read as
                # "concrete" — hand the lane to the host instead
                state_np["storage_used"][row, slot] = False
                self._materialize_np(state_np, planes_np, harena, row)
                status[lane] = DEAD
                return
            planes_np["storage_sym"][row, slot] = node
        # a fault-in is a READ: dirty stays False, materialization will not
        # write Select(base, key) back over the template's storage
        planes_np["storage_dirty"][row, slot] = False
        self.faults += 1
        status[lane] = RUNNING

    def _cond_bools(self, planes_np, harena, lane: int) -> List[Bool]:
        """Signed condition ids -> Bools, memoized per (id, context): tree
        siblings share long condition prefixes, so across a drain of N
        lanes most conds repeat — the memo turns the drain's dominant cost
        (profiled at ~0.7 ms/lane) into dict hits."""
        ctx_index = int(planes_np["ctx_id"][lane])
        ctx = self.contexts[ctx_index]
        memo = self._cond_memo
        bools: List[Bool] = []
        for position in range(int(planes_np["cond_count"][lane])):
            signed = int(planes_np["conds"][lane, position])
            key = (signed, ctx_index)
            cached = memo.get(key)
            if cached is None:
                word = harena.to_term(abs(signed), ctx)
                is_zero = T.bv_cmp("eq", word.raw, T.bv_const(0, 256))
                cached = Bool(T.bool_not(is_zero) if signed > 0
                              else is_zero)
                memo[key] = cached
            bools.append(cached)
        return bools

    def _prefetch_feasibility(self, planes_np, rows, state_np=None) -> None:
        """Escape-time pruning prefetch (MYTHRIL_TPU_CHECK_ESCAPES=1 +
        `--solver jax`): queue the feasibility queries of a whole slab of
        deferred rows on the solver's batch dispatch queue before
        _materialize_np walks them one at a time — the first row's
        _feasible() then flushes the slab as ONE device batch instead of
        paying a launch per lane. Best-effort: any trouble here just means
        the rows solve individually, exactly as before.

        When the caller threads `state_np` in, rows parked on a
        statically-dead pc (CFA dead-code mask) are skipped: their
        feasibility query is wasted solver work by construction."""
        if not self.check_escapes:
            return
        from ..core.state.constraints import Constraints
        from ..support.model import prefetch_models

        # fleet mode: group rows per owning member so each group's queries
        # build under that member's keccak axioms / symbol namespace and
        # carry its contract id as the dispatch query origin. Every group
        # still lands on the SAME dispatch queue before any flush — mixed
        # fleets produce genuinely shared solver batches.
        groups: List[Tuple[object, list]] = []
        by_member: Dict[int, list] = {}
        for row in rows:
            if int(planes_np["cond_count"][row]) <= 0:
                continue
            ctx = self.contexts[int(planes_np["ctx_id"][row])]
            member = getattr(ctx, "member", None)
            if member is not None and member.abandoned:
                continue  # deadline-drained: its rows never materialize
            if state_np is not None and cfa_screen.statically_dead(
                    ctx.template.environment.code,
                    int(state_np["pc"][row])):
                metrics.inc("cfa.frontier.prefetch_skipped")
                continue
            key = id(member)
            if key not in by_member:
                by_member[key] = []
                groups.append((member, by_member[key]))
            by_member[key].append((ctx, row))
        for member, group_rows in groups:
            sets = []
            with _member_env(self.fleet, member):
                for ctx, row in group_rows:
                    constraints = Constraints(
                        list(ctx.template.world_state.constraints)
                        + self._cond_bools(planes_np, self.harena, row))
                    sets.append(tuple(constraints.get_all_constraints()))
                if not sets:
                    continue
                try:
                    prefetch_models(sets)
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as error:
                    log.debug("feasibility prefetch failed (%r) — rows "
                              "solve individually", error)

    def _feasible(self, planes_np, harena, lane: int) -> bool:
        from ..core.state.constraints import Constraints
        from ..exceptions import SolverTimeOutException
        from ..support.model import get_model

        ctx = self.contexts[int(planes_np["ctx_id"][lane])]
        with _member_env(self.fleet, getattr(ctx, "member", None)):
            constraints = Constraints(
                list(ctx.template.world_state.constraints)
                + self._cond_bools(planes_np, harena, lane))
            try:
                get_model(tuple(constraints.get_all_constraints()))
                return True
            except SolverTimeOutException:
                # budget exhaustion is NOT infeasibility (it subclasses
                # UnsatError): keep the lane, the host re-checks at issue
                # time
                return True
            except UnsatError:
                return False
            except Exception:
                return True  # any other solver trouble: keep exploring

    # -- materialization ---------------------------------------------------------------

    def _materialize_np(self, state_np, planes_np, harena, lane: int):

        ctx = self.contexts[int(planes_np["ctx_id"][lane])]
        member = getattr(ctx, "member", None)
        if member is not None and member.abandoned:
            # the owning contract hit its budget: its escaped rows drop
            # exactly like the host's mid-worklist states on timeout
            member.count_dropped(1)
            return
        # OPTIMISTIC by default, matching the host engine's JUMPI exactly
        # (core/instructions.py jumpi_ forks both sides with no solver call;
        # the reference does the same — feasibility is decided at issue
        # time). MYTHRIL_TPU_CHECK_ESCAPES=1 re-enables escape-time pruning:
        # it trades one CDCL solve per escaping lane for a smaller host
        # worklist — measured 85x slower than the host engine on the
        # 2^16-path bench when it was the default (BENCH_r04).
        if self.check_escapes and int(planes_np["cond_count"][lane]) > 0 \
                and not self._feasible(planes_np, harena, lane):
            self.infeasible += 1
            return
        template = ctx.template
        global_state = copy(template)
        mstate = global_state.mstate

        # program counter: byte offset -> instruction index
        byte_pc = int(state_np["pc"][lane])
        disassembly = global_state.environment.code
        index = disassembly.index_of_address(byte_pc)
        if index is None:
            if byte_pc >= int(state_np["code_len"][lane]):
                # running off the code end: the host's fetch treats an
                # out-of-range pc as STOP (core/svm.py execute_state)
                index = len(disassembly.instruction_list)
            else:
                log.warning("materialize: pc %d not on an instruction "
                            "boundary", byte_pc)
                return
        mstate.pc = index

        # stack
        sp = int(state_np["sp"][lane])
        mstate.stack.clear()
        for slot in range(sp):
            node = int(planes_np["stack_sym"][lane, slot])
            if node:
                mstate.stack.append(harena.to_term(node, ctx))
            else:
                value = int(words.to_ints(state_np["stack"][lane, slot]))
                mstate.stack.append(symbol_factory.BitVecVal(value, 256))

        # memory — touch only the bytes that need a term (symbolic markers
        # and nonzero concrete bytes): a per-byte Python loop over msize was
        # a profiled hot spot of round-4 materialization
        msize = int(state_np["msize"][lane])
        if msize:
            mstate.mem_extend(0, msize)
            mem = state_np["memory"][lane][:msize]
            mem_sym = planes_np["mem_sym"][lane][:msize]
            for offset in np.nonzero(mem_sym)[0]:
                marker = int(mem_sym[offset])
                node, byte_index = marker >> 5, marker & 31
                word = harena.to_term(node, ctx)
                high = 255 - 8 * byte_index
                mstate.memory[int(offset)] = Extract(high, high - 7, word)
            for offset in np.nonzero((mem_sym == 0) & (mem != 0))[0]:
                mstate.memory[int(offset)] = symbol_factory.BitVecVal(
                    int(mem[offset]), 8)

        # storage writes made on device (dirty slots only: seeds and
        # faulted-in reads are already present in the template's storage)
        account = global_state.environment.active_account
        used = state_np["storage_used"][lane]
        dirty = planes_np["storage_dirty"][lane]
        sink_values = []  # integer-detector sink harvest (SSTORE/JUMPI)
        for slot in range(used.shape[0]):
            if not used[slot] or not dirty[slot]:
                continue
            key = int(words.to_ints(state_np["storage_keys"][lane, slot]))
            node = int(planes_np["storage_sym"][lane, slot])
            if node:
                value = harena.to_term(node, ctx)
                sink_values.append(value)
            else:
                value = symbol_factory.BitVecVal(
                    int(words.to_ints(state_np["storage_vals"][lane, slot])),
                    256)
            account.storage[symbol_factory.BitVecVal(key, 256)] = value

        # path conditions
        for condition in self._cond_bools(planes_np, harena, lane):
            global_state.world_state.constraints.append(condition)
        for position in range(int(planes_np["cond_count"][lane])):
            signed = int(planes_np["conds"][lane, position])
            sink_values.append(harena.to_term(abs(signed), ctx))

        # the integer detector's SSTORE/JUMPI sink hooks fire on host
        # execution; for instructions the device executed, harvest their
        # overflow markers here with identical semantics
        if sink_values:
            from ..analysis.modules.integer import harvest_values

            harvest_values(global_state, sink_values)

        # last JUMP taken on device: the exceptions detector keys its
        # dedup cache and source location on this annotation — without it
        # every materialized INVALID after the first was cache-swallowed
        last_jump = int(planes_np["last_jump"][lane]) \
            if "last_jump" in planes_np else 0
        if last_jump:
            from ..analysis.modules.exceptions import LastJumpAnnotation

            global_state.annotate(LastJumpAnnotation(last_jump))

        # CFA merge tagging: lanes whose block reconverges at a static
        # post-dominator pc carry the merge key, so the on-device merge
        # pass (ROADMAP item 3) can group them without re-deriving the CFG
        merge_pc = cfa_screen.merge_point_at(disassembly, byte_pc)
        if merge_pc is not None:
            global_state.annotate(MergeTagAnnotation(merge_pc))
            metrics.inc("cfa.frontier.merge_tagged")

        # loop tagging: lanes inside a natural loop carry the innermost
        # header pc, so bounded-unroll budgeting can cap lane spend per
        # loop instead of per contract
        loop_header = module_screen.loop_header_at(disassembly, byte_pc)
        if loop_header is not None:
            global_state.annotate(LoopHintAnnotation(loop_header))
            metrics.inc("taint.frontier.loop_tagged")

        # gas accounting (device tracks the lower-bound model)
        gas_used = int(state_np["gas_used"][lane])
        mstate.min_gas_used += gas_used
        mstate.max_gas_used += gas_used
        # depth parity: the device counts every JUMPI branch it took
        # (concrete-condition branches included), exactly like host jumpi_
        if "branches" in planes_np:
            mstate.depth += int(planes_np["branches"][lane])
        else:
            mstate.depth += int(planes_np["cond_count"][lane])

        self.materialized += 1
        # fleet demux: rows re-enter their OWN contract's engine worklist,
        # not the frontier owner's — detections stay per-contract
        laser = getattr(ctx, "laser", None) or self.laser
        if getattr(laser, "requires_statespace", False) and \
                global_state.node is None:
            global_state.node = template.node
        laser.work_list.append(global_state)

    # -- checkpointing -----------------------------------------------------------------

    def save_checkpoint(self, path: str, state: StateBatch,
                        planes: symstep.SymPlanes, sched=None) -> None:
        """Dense-array frontier checkpoint (SURVEY §5: 'dense arrays
        serialize trivially'): one .npz holding the device phase —
        StateBatch planes, symbolic planes, the USED prefix of the
        expression arena, and lane bookkeeping. Written crash-safe
        (tmp + fsync + os.replace, support/checkpoint.py fsync_replace) so
        preemption or power loss mid-write never corrupts the only
        checkpoint. Scope: the device phase only — states already
        materialized onto the host worklist are drained by the host
        continuation and are not re-created on resume."""
        if not path.endswith(".npz"):
            path += ".npz"  # np.savez appends it; keep save/resume agreeing
        # scheduler-held rows (sibling stack + escape buffer) serialize as
        # pending rows; the live scheduler is NOT mutated — on resume they
        # re-enter through the host reseed path with their saved statuses
        pending_rows = list(self.pending)
        if sched is not None:
            pending_rows += self._sched_rows(sched)
        arrays = {}
        for field in state._fields:
            arrays[f"state_{field}"] = np.asarray(getattr(state, field))
        for field in planes._fields:
            arrays[f"planes_{field}"] = np.asarray(getattr(planes, field))
        used = int(self.arena.n)
        used_const = int(self.arena.n_const)
        for field in ("op", "a", "b", "c", "imm", "imm2", "cls"):
            arrays[f"arena_{field}"] = np.asarray(
                getattr(self.arena, field))[:used]
        arrays["arena_const_vals"] = np.asarray(
            self.arena.const_vals)[:used_const]
        arrays["arena_caps"] = np.asarray(
            [self.arena.capacity, self.arena.const_vals.shape[0],
             used, used_const])
        arrays["counters"] = np.asarray(
            [self.forks, self.infeasible, self.materialized, self.lane_steps,
             self.spilled, self.reseeded])
        if pending_rows:
            for field in StateBatch._fields:
                arrays[f"pend_state_{field}"] = np.stack(
                    [rs[field] for rs, _ in pending_rows])
            for field in symstep.SymPlanes._fields:
                arrays[f"pend_planes_{field}"] = np.stack(
                    [rp[field] for _, rp in pending_rows])
        arrays["identity"] = np.asarray(
            [self.n_lanes, len(self.contexts)])
        # tx stamp: n_lanes/n_contexts are env-fixed, so a wave saved during
        # an EARLIER transaction would otherwise pass the identity check on
        # resume and graft stale machine states onto fresh seeds (ADVICE r4)
        arrays["tx_index"] = np.asarray(
            [int(getattr(self.laser, "_current_tx_index", 0))])
        # V_HOST_TERM leaves index into per-context host_terms lists that
        # GROW after seeding (cold-SLOAD fault-ins); a resume that rebuilt
        # only the seed-time lists would resolve checkpointed nodes against
        # wrong terms. Terms pickle exactly (smt/terms.py Term.__reduce__),
        # but deep Select chains can exceed the default recursion limit —
        # guard like support/checkpoint.py, and never let the periodic save
        # crash the analysis it exists to protect (ADVICE r4).
        import pickle
        import sys as sys_module

        limit = sys_module.getrecursionlimit()
        sys_module.setrecursionlimit(max(limit, 200_000))
        try:
            arrays["host_terms"] = np.frombuffer(
                pickle.dumps([ctx.host_terms for ctx in self.contexts]),
                dtype=np.uint8)
        finally:
            sys_module.setrecursionlimit(limit)
        # per-context contract namespace: a killed FLEET run must resume
        # per-contract — lane counts alone would let contract A's wave
        # graft onto contract B's fresh seeding (same lane/context shape)
        arrays["contract_ids"] = np.frombuffer(
            pickle.dumps([_ctx_contract_id(ctx) for ctx in self.contexts]),
            dtype=np.uint8)
        from ..support.checkpoint import fsync_replace

        import time as time_module

        started = time_module.perf_counter()
        with trace.span("checkpoint.save", kind="device",
                        pending_rows=len(pending_rows)):
            tmp = f"{path}.tmp"
            with open(tmp, "wb") as handle:
                np.savez_compressed(handle, **arrays)
            fsync_replace(tmp, path)
        metrics.inc("checkpoint.saves")
        metrics.observe("checkpoint.write_ms",
                        (time_module.perf_counter() - started) * 1000.0)

    def load_checkpoint(self, path: str):
        """Restore (state, planes) saved by save_checkpoint; the arena and
        counters are restored onto this frontier in place. Raises ValueError
        on an identity mismatch (checkpoint from a different seeding)."""
        if not path.endswith(".npz"):
            path += ".npz"
        with trace.span("checkpoint.load", kind="device"):
            data = np.load(path)
        n_lanes, n_contexts = (int(v) for v in data["identity"])
        if n_lanes != self.n_lanes or n_contexts != len(self.contexts):
            raise ValueError(
                f"checkpoint identity mismatch: saved {n_lanes} lanes / "
                f"{n_contexts} contexts, this frontier has {self.n_lanes} / "
                f"{len(self.contexts)}")
        if "tx_index" in data:
            saved_tx = int(data["tx_index"][0])
            current_tx = int(getattr(self.laser, "_current_tx_index", 0))
            if saved_tx != current_tx:
                raise ValueError(
                    f"checkpoint is for transaction {saved_tx}, the "
                    f"analysis is at transaction {current_tx}")
        if "contract_ids" in data:
            import pickle

            saved_ids = pickle.loads(data["contract_ids"].tobytes())
            current_ids = [_ctx_contract_id(ctx) for ctx in self.contexts]
            if saved_ids != current_ids:
                raise ValueError(
                    f"checkpoint contract namespace mismatch: saved "
                    f"{saved_ids}, this seeding has {current_ids}")
        if "host_terms" in data:
            import pickle

            for ctx, saved_terms in zip(
                    self.contexts,
                    pickle.loads(data["host_terms"].tobytes())):
                ctx.host_terms = saved_terms
        else:
            raise ValueError("checkpoint predates host_terms serialization; "
                             "V_HOST_TERM leaves would resolve wrongly")
        state = StateBatch(**{f: data[f"state_{f}"]
                              for f in StateBatch._fields})
        planes = symstep.SymPlanes(**{f: data[f"planes_{f}"]
                                      for f in symstep.SymPlanes._fields})
        cap, const_cap, used, used_const = (int(v)
                                            for v in data["arena_caps"])
        arena = A.new_arena(capacity=cap, const_capacity=const_cap)
        fields = {}
        for field in ("op", "a", "b", "c", "imm", "imm2", "cls"):
            full = np.zeros(cap, dtype=np.int32)
            full[:used] = data[f"arena_{field}"]
            fields[field] = full
        const_vals = np.zeros_like(np.asarray(arena.const_vals))
        const_vals[:used_const] = data["arena_const_vals"]
        self.arena = arena._replace(
            n=np.int32(used), n_const=np.int32(used_const),
            const_vals=const_vals, **fields)
        self.harena = None  # mirror of the replaced arena is invalid
        counters = [int(v) for v in data["counters"]]
        (self.forks, self.infeasible, self.materialized,
         self.lane_steps) = counters[:4]
        if len(counters) >= 6:
            self.spilled, self.reseeded = counters[4:6]
        self.pending = []
        if "pend_state_status" in data:
            n_pending = data["pend_state_status"].shape[0]
            for row in range(n_pending):
                self.pending.append((
                    {field: data[f"pend_state_{field}"][row]
                     for field in StateBatch._fields},
                    {field: data[f"pend_planes_{field}"][row]
                     for field in symstep.SymPlanes._fields}))
        return state, planes

    def _hand_over_running(self, state: StateBatch, planes,
                           sched=None) -> None:
        from ..core.time_handler import time_handler

        status = np.asarray(state.status)
        # frozen ESCAPED lanes (buffer overflow) continue on the host like
        # live lanes; the scheduler's stack + escape buffer are the backlog
        live = np.nonzero((status == RUNNING) | (status == FORKING)
                          | (status == ESCAPED))[0]
        sched_backlog = 0
        if sched is not None:
            sched_backlog = int(np.sum(np.asarray(sched.stack_top))) \
                + int(np.sum(np.asarray(sched.esc_count)))
        backlog = len(self.pending) + sched_backlog
        if time_handler.time_remaining() <= 1000 and (len(live) or backlog):
            # execution budget exhausted: the host could not explore these
            # states either (its own timeout drops mid-worklist states the
            # same way)
            log.info("execution budget exhausted with %d live lanes + %d "
                     "backlog rows; dropping them (host-timeout parity)",
                     len(live), backlog)
            # graceful-drain accounting: the partial report's coverage
            # stats count these alongside the host's own dropped states
            if self.fleet is not None:
                self._drop_fleet_lanes(planes, sched, live)
            else:
                self.laser.timed_out = True
                self.laser.dropped_states = getattr(
                    self.laser, "dropped_states", 0) + len(live) + backlog
            return
        if not len(live) and not backlog:
            return
        trace.instant("frontier.hand_over", live_lanes=len(live),
                      backlog_rows=backlog)
        harena = self._harena()
        if len(live):
            self._materialize_lanes(state, planes, harena, live)
        # backlog rows never made it back onto the device: the host explores
        # them from their saved positions. Scheduler pools drain through the
        # LIGHT pack path — the full 44-leaf gather paid a ~30 ms tunnel
        # floor per leaf and moved whole 40 KB rows
        if sched is not None:
            self._materialize_pool_prefix(
                sched.stack_state, sched.stack_planes,
                self._pool_used_indices(
                    np.asarray(sched.stack_top),
                    sched.stack_state.status.shape[0]))
            self._materialize_pool_prefix(
                sched.esc_state, sched.esc_planes,
                self._pool_used_indices(
                    np.asarray(sched.esc_count),
                    sched.esc_state.status.shape[0]))
        for row_state, row_planes in self.pending:
            self.deferred.append([
                {field: value[None] for field, value in row_state.items()},
                {field: value[None] for field, value in row_planes.items()},
                1, 0])
        del self.pending[:]

    def _drop_fleet_lanes(self, planes, sched, live) -> None:
        """Global-budget exhaustion in fleet mode: attribute every dropped
        lane / backlog row to the contract that owned it, so each member's
        partial report carries ITS dropped-state count (host-timeout
        parity per contract, not a pooled number on the primary)."""
        ctx_ids = [int(c) for c in np.asarray(planes.ctx_id)[live]]
        if sched is not None:
            stack_ids = np.asarray(sched.stack_planes.ctx_id)
            esc_ids = np.asarray(sched.esc_planes.ctx_id)
            ctx_ids += [int(c) for c in stack_ids[self._pool_used_indices(
                np.asarray(sched.stack_top), len(stack_ids))]]
            ctx_ids += [int(c) for c in esc_ids[self._pool_used_indices(
                np.asarray(sched.esc_count), len(esc_ids))]]
        for _, row_planes in self.pending:
            ctx_ids.append(int(np.asarray(row_planes["ctx_id"]).flat[0]))
        for cid in ctx_ids:
            ctx = self.contexts[cid] if 0 <= cid < len(self.contexts) \
                else None
            member = getattr(ctx, "member", None) if ctx else None
            if member is not None:
                member.count_dropped(1)
            else:
                self.laser.timed_out = True
                self.laser.dropped_states = getattr(
                    self.laser, "dropped_states", 0) + 1


def build_seed_templates(laser_evm, callee_address,
                         func_hashes=None) -> List[GlobalState]:
    """Consume the laser's open states into frontier seed templates — one
    pending MessageCallTransaction GlobalState per open world state, with
    the ACTORS caller constraint and the 4-byte selector restriction
    applied exactly as on the host path. Shared by the solo device path
    (execute_message_call_tpu) and the fleet gate, so both seed
    identically."""
    from ..core.transaction.symbolic import (ACTORS,
                                             generate_function_constraints)
    from ..core.state.calldata import SymbolicCalldata
    from ..core.transaction.transaction_models import (
        MessageCallTransaction, get_next_transaction_id)
    from ..smt import Or

    open_states = laser_evm.open_states[:]
    del laser_evm.open_states[:]
    seeds: List[GlobalState] = []
    for open_world_state in open_states:
        if open_world_state[callee_address].deleted:
            continue
        next_transaction_id = get_next_transaction_id()
        external_sender = symbol_factory.BitVecSym(
            f"sender_{next_transaction_id}", 256)
        calldata = SymbolicCalldata(next_transaction_id)
        transaction = MessageCallTransaction(
            world_state=open_world_state,
            identifier=next_transaction_id,
            gas_price=symbol_factory.BitVecSym(
                f"gas_price{next_transaction_id}", 256),
            gas_limit=8000000,
            origin=external_sender,
            caller=external_sender,
            callee_account=open_world_state[callee_address],
            call_data=calldata,
            call_value=symbol_factory.BitVecSym(
                f"call_value{next_transaction_id}", 256),
        )
        template = transaction.initial_global_state()
        template.transaction_stack.append((transaction, None))
        template.world_state.constraints.append(
            Or(*[transaction.caller == actor
                 for actor in ACTORS.addresses.values()]))
        if func_hashes:
            for constraint in generate_function_constraints(calldata,
                                                            func_hashes):
                template.world_state.constraints.append(constraint)
        if getattr(laser_evm, "requires_statespace", False):
            laser_evm.new_node_for_transaction(template, transaction)
        seeds.append(template)
    return seeds


def execute_message_call_tpu(laser_evm, callee_address,
                             func_hashes=None) -> None:
    """Drop-in for core/transaction/symbolic.py execute_message_call: seed the
    device frontier from every open state, explore, and drain the escaped
    states through the host engine (detectors run there unchanged).
    `func_hashes` restricts the tx's 4-byte selector exactly as on the host
    path (generate_function_constraints) so `--transaction-sequences` and the
    tx prioritizer behave identically under both engines."""
    seeds = build_seed_templates(laser_evm, callee_address, func_hashes)

    if not seeds:
        laser_evm.exec()
        return

    import os

    lane_budget = tpu_config.get_int("MYTHRIL_TPU_LANES", DEFAULT_LANES)
    frontier = _Frontier(laser_evm,
                         n_lanes=max(lane_budget, 2 * len(seeds)))
    with trace.span("frontier.seed", seeds=len(seeds)):
        state, planes = frontier.seed(seeds)
    with trace.span("frontier.device_phase", lanes=frontier.n_lanes) as ph:
        frontier.run(state, planes)
        ph.set(forks=frontier.forks, lane_steps=frontier.lane_steps)
    log.info("frontier: %d forks, %d storage fault-ins, %d infeasible "
             "pruned, %d states materialized + %d deferred for the host "
             "(arena nodes: %d, stack pushes/pops %d/%d, host "
             "spills/reseeds %d/%d)",
             frontier.forks, frontier.faults, frontier.infeasible,
             frontier.materialized,
             sum(entry[2] - entry[3] for entry in frontier.deferred),
             int(frontier.arena.n),
             frontier.stack_pushes, frontier.stack_pops,
             frontier.spilled, frontier.reseeded)
    # cumulative counters for benchmarking/diagnostics (bench.py)
    laser_evm.frontier_lane_steps = getattr(
        laser_evm, "frontier_lane_steps", 0) + frontier.lane_steps
    laser_evm.frontier_forks = getattr(
        laser_evm, "frontier_forks", 0) + frontier.forks
    if tpu_config.get_flag("MYTHRIL_TPU_SKIP_HOST_DRAIN"):
        # warm-up aid (bench.py): compile/load the device executable without
        # paying a full host continuation of the materialized states
        del laser_evm.work_list[:]
        del frontier.deferred[:]
        return
    # deferred escape rows materialize lazily as the exec loop drains the
    # worklist dry — rows the budget never reaches are dropped with zero
    # cost, exactly like the host engine's own states at timeout
    laser_evm.frontier_feeder = frontier.make_feeder()
    try:
        with trace.span("frontier.host_continuation"):
            laser_evm.exec()
    finally:
        laser_evm.frontier_feeder = None
        if frontier.deferred:
            dropped = sum(entry[2] - entry[3] for entry in frontier.deferred)
            log.info("execution budget exhausted with %d deferred frontier "
                     "rows unmaterialized; dropping them (host-timeout "
                     "parity)", dropped)
            laser_evm.timed_out = True
            laser_evm.dropped_states = getattr(
                laser_evm, "dropped_states", 0) + dropped
            del frontier.deferred[:]


# -- fleet packing ---------------------------------------------------------------------
#
# FleetDriver runs N independent contract analyses as ONE device workload:
# every member's per-transaction seeds land in a single shared _Frontier
# (per-lane ctx_id keeps ownership; merge_pass already refuses cross-ctx
# pairs), the fused stepper runs once for everyone, and escaped rows demux
# back into each member's OWN engine worklist. Host turns stay strictly
# serialized — one member holds the token at a time, and the process-global
# singletons the engine leans on (tx id counter, keccak axioms, detector
# issue/cache state) are SWAPPED per turn so every member sees exactly the
# namespace a solo run would: detections come out byte-identical to N
# sequential runs, while the device and the solver dispatch queue see the
# union of everyone's work.


def _ctx_contract_id(ctx) -> str:
    """Stable contract namespace for a seeding context (checkpoint
    validation): the owning fleet member's id, else the contract name."""
    member = getattr(ctx, "member", None)
    if member is not None:
        return member.contract_id
    account = ctx.template.environment.active_account
    return getattr(account, "contract_name", "") or ""


@contextmanager
def _member_env(fleet, member):
    """Solver-side view swap: run the body under `member`'s symbol
    namespace (tx id counter + keccak axioms) with its contract id as the
    dispatch query origin. No-op outside fleet mode."""
    if fleet is None or member is None:
        yield
        return
    with fleet.member_env(member):
        yield


class FleetMember:
    """One contract's analysis job inside a fleet."""

    def __init__(self, index: int, contract_id: str, work=None,
                 execution_timeout: int = 0, preempt=None):
        self.index = index
        self.contract_id = contract_id
        #: the whole per-contract analysis (SymExecWrapper + detector
        #: harvest), supplied by the analyzer; runs on this member's thread
        self.work = work
        self.execution_timeout = execution_timeout
        #: optional threading.Event: when set (e.g. by the serve batcher
        #: on an interactive arrival), this member's budget reads as
        #: exhausted and the next deadline_drain sweep abandons it — it
        #: checkpoints what it has and yields the device (QoS preemption)
        self.preempt = preempt
        self.driver: Optional["FleetDriver"] = None
        self.laser = None        # set by SymExecWrapper(fleet=member)
        self.gate_laser = None   # laser parked at the device gate
        self.gate_seeds: Optional[List[GlobalState]] = None
        self.result = None       # work()'s return (the member's issues)
        self.error: Optional[BaseException] = None
        self.traceback_str = ""
        self.done = False
        #: deadline-drained on device: lanes freed, rows skipped+counted
        self.abandoned = False
        self._pending_feeder = None
        self.thread: Optional[threading.Thread] = None
        self._grant = threading.Event()
        self._yield = threading.Event()
        # per-member snapshots of the process-global singletons (installed
        # by FleetDriver._swap_in, captured back by _swap_out)
        self.tx_counter = 0
        self.keccak_state: Dict[str, object] = {}
        self.module_state: Dict[str, Dict[str, object]] = {}

    def install(self, laser_evm) -> None:
        """Attach this member to its freshly-built laser (called from
        SymExecWrapper construction on the member's thread)."""
        self.laser = laser_evm
        laser_evm.contract_id = self.contract_id
        laser_evm.fleet_gate = self._gate

    def _gate(self, laser_evm, callee_address, func_hashes=None) -> None:
        self.driver.gate(self, laser_evm, callee_address, func_hashes)

    def budget_remaining(self) -> float:
        """Seconds left in this member's own execution budget (inf when
        untimed, 0 when preempted). Mirrors svm._exec_pass: total wall
        since the member's transaction phase began."""
        if self.preempt is not None and self.preempt.is_set():
            return 0.0
        laser = self.gate_laser or self.laser
        timeout = getattr(laser, "execution_timeout", 0) if laser \
            else self.execution_timeout
        if not timeout:
            return float("inf")
        started = getattr(laser, "time", None)
        if started is None:
            return float(timeout)
        from datetime import datetime

        return timeout - (datetime.now() - started).total_seconds()

    def count_dropped(self, n: int) -> None:
        """Host-timeout parity accounting: `n` of this member's states
        were dropped (deadline drain / skipped materialization)."""
        laser = self.gate_laser or self.laser
        if laser is None or not n:
            return
        laser.timed_out = True
        laser.dropped_states = getattr(laser, "dropped_states", 0) + n


class FleetDriver:
    """Seed, step, merge, and drain N contracts in one jit program.

    Protocol: every member runs its UNCHANGED engine loop on its own
    thread, but only one thread holds the execution token at a time. A
    member's turn ends when it parks at the device gate (seeds built for
    its next transaction) or finishes. When every live member is parked,
    the coordinator packs all parked seeds into one _Frontier, runs the
    device phase once, then hands each member a shared feeder and resumes
    the turns. A member that exhausts its budget mid-phase is deadline-
    drained on device — its lanes free for the others, its report comes
    out `incomplete` — never a global abort."""

    def __init__(self, members: List[FleetMember], modules=None):
        self.members = members
        for member in members:
            member.driver = self
        self.modules = modules
        self.aborted = False
        self.frontier: Optional[_Frontier] = None
        #: cumulative device counters across phases (bench/logs)
        self.lane_steps = 0
        self.forks = 0
        self.phases = 0
        self._active: Optional[FleetMember] = None
        self._all_modules = None

    # -- singleton swap ----------------------------------------------------------------

    def _module_list(self):
        if self._all_modules is None:
            from ..analysis.module import ModuleLoader
            from ..analysis.module.base import EntryPoint

            loader = ModuleLoader()
            self._all_modules = (
                loader.get_detection_modules(entry_point=EntryPoint.CALLBACK)
                + loader.get_detection_modules(entry_point=EntryPoint.POST))
        return self._all_modules

    def _swap_in(self, member: FleetMember) -> None:
        """Install `member`'s view of the process-global singletons: the
        tx id counter, the keccak function manager, every detection
        module's issues + dedup cache, and the dispatch query origin. Each
        member's snapshots descend from a FRESH reset, so symbol names and
        issue caches match a solo run of that contract exactly."""
        from ..core.function_managers import keccak_function_manager
        from ..core.transaction.transaction_models import tx_id_manager
        from ..smt.solver import dispatch

        tx_id_manager.set_counter(member.tx_counter)
        if not member.keccak_state:
            fresh = type(keccak_function_manager)()
            member.keccak_state = dict(fresh.__dict__)
        keccak_function_manager.__dict__.clear()
        keccak_function_manager.__dict__.update(member.keccak_state)
        for module in self._module_list():
            saved = member.module_state.setdefault(
                module.name, {"issues": [], "cache": set()})
            module.issues = saved["issues"]
            module.cache = saved["cache"]
        dispatch.set_query_origin(member.contract_id)
        self._active = member

    def _swap_out(self, member: FleetMember) -> None:
        from ..core.function_managers import keccak_function_manager
        from ..core.transaction.transaction_models import tx_id_manager
        from ..smt.solver import dispatch

        member.tx_counter = tx_id_manager._next_transaction_id
        member.keccak_state = dict(keccak_function_manager.__dict__)
        for module in self._module_list():
            member.module_state[module.name] = {
                "issues": module.issues, "cache": module.cache}
        dispatch.set_query_origin(None)
        self._active = None

    @contextmanager
    def member_env(self, member: FleetMember):
        """Temporary solver-side swap (feasibility checks and prefetch
        batches during a device phase): `member`'s symbol namespace and
        query origin, restored on exit. A no-op when the member already
        holds the token — its LIVE singleton state must not be clobbered
        by its own stale snapshot."""
        if member is self._active:
            yield
            return
        from ..core.function_managers import keccak_function_manager
        from ..core.transaction.transaction_models import tx_id_manager
        from ..smt.solver import dispatch

        saved_tx = tx_id_manager._next_transaction_id
        saved_keccak = dict(keccak_function_manager.__dict__)
        saved_origin = dispatch.get_query_origin()
        tx_id_manager.set_counter(member.tx_counter)
        if not member.keccak_state:
            fresh = type(keccak_function_manager)()
            member.keccak_state = dict(fresh.__dict__)
        keccak_function_manager.__dict__.clear()
        keccak_function_manager.__dict__.update(member.keccak_state)
        dispatch.set_query_origin(member.contract_id)
        try:
            yield
        finally:
            member.tx_counter = tx_id_manager._next_transaction_id
            member.keccak_state = dict(keccak_function_manager.__dict__)
            keccak_function_manager.__dict__.clear()
            keccak_function_manager.__dict__.update(saved_keccak)
            tx_id_manager.set_counter(saved_tx)
            dispatch.set_query_origin(saved_origin)

    # -- token / clock -----------------------------------------------------------------

    def _arm_clock(self, seconds: float) -> None:
        from ..core.time_handler import time_handler

        if seconds == float("inf"):
            time_handler.reset()
        else:
            time_handler.start_execution(max(int(seconds), 1))

    def _run_turn(self, member: FleetMember) -> None:
        """Grant the token: the member runs until its next gate park or
        completion. The global clock is re-armed with ITS remaining
        budget first (the member re-arms itself at each transaction-phase
        start, exactly like a solo run)."""
        self._swap_in(member)
        self._arm_clock(member.budget_remaining())
        member._yield.clear()
        member._grant.set()
        member._yield.wait()
        self._swap_out(member)

    # -- member-thread side ------------------------------------------------------------

    def _member_main(self, member: FleetMember) -> None:
        member._grant.wait()
        member._grant.clear()
        try:
            member.result = member.work()
        except BaseException as error:  # noqa: BLE001 — reported per member
            member.error = error
            member.traceback_str = traceback.format_exc()
            log.warning("fleet member %r failed: %r", member.contract_id,
                        error)
        finally:
            member.done = True
            member._yield.set()

    def gate(self, member: FleetMember, laser_evm, callee_address,
             func_hashes=None) -> None:
        """The per-transaction device gate (replaces
        execute_message_call_tpu for fleet members): build this member's
        seeds, park until the coordinator has run the shared device phase,
        then drain the shared feeder through this member's own exec loop."""
        seeds = build_seed_templates(laser_evm, callee_address, func_hashes)
        if not seeds:
            laser_evm.exec()
            return
        member.gate_seeds = seeds
        member.gate_laser = laser_evm
        member._yield.set()
        member._grant.wait()
        member._grant.clear()
        if self.aborted:
            raise RuntimeError("fleet driver aborted")
        member.gate_seeds = None
        feeder = member._pending_feeder
        member._pending_feeder = None
        laser_evm.frontier_feeder = feeder
        try:
            with trace.span("frontier.host_continuation"):
                laser_evm.exec()
        finally:
            laser_evm.frontier_feeder = None

    # -- coordinator -------------------------------------------------------------------

    def run(self) -> List[FleetMember]:
        for member in self.members:
            member.thread = threading.Thread(
                target=self._member_main, args=(member,),
                name=f"fleet-{member.index}", daemon=True)
            member.thread.start()
        try:
            # first turns: construction + creation tx, up to the first gate
            for member in self.members:
                if not member.done:
                    self._run_turn(member)
            while True:
                gated = [m for m in self.members
                         if not m.done and m.gate_seeds is not None]
                if not gated:
                    break
                self._device_phase(gated)
                for member in gated:
                    if not member.done:
                        self._run_turn(member)
        except BaseException:
            self.aborted = True
            for member in self.members:
                member._grant.set()  # release parked threads to fail out
            raise
        finally:
            self._drain_frontier()
            from ..core.time_handler import time_handler

            time_handler.reset()
            for member in self.members:
                if member.thread is not None:
                    member.thread.join(timeout=60)
        return self.members

    def _drain_frontier(self) -> None:
        """Materialize every row still deferred on the previous phase's
        frontier into its owner's worklist (abandoned members' rows are
        skipped and counted): a member's exec turn that timed out must not
        strand OTHER members' rows."""
        frontier, self.frontier = self.frontier, None
        if frontier is None:
            return
        try:
            feeder = frontier.make_feeder(batch_rows=1024)
            while feeder():
                pass
        except Exception as error:  # noqa: BLE001
            log.warning("fleet: draining leftover deferred rows failed "
                        "(%r)", error)

    def _device_phase(self, gated: List[FleetMember]) -> None:
        """Pack every parked member's seeds into ONE frontier and run the
        fused device loop once for all of them."""
        self._drain_frontier()
        seeds: List[GlobalState] = []
        owners: List[FleetMember] = []
        for member in gated:
            seeds.extend(member.gate_seeds)
            owners.extend([member] * len(member.gate_seeds))
        primary = gated[0].gate_laser
        lane_budget = tpu_config.get_int("MYTHRIL_TPU_FLEET_LANES", 0) \
            or tpu_config.get_int("MYTHRIL_TPU_LANES", DEFAULT_LANES)
        frontier = _Frontier(primary, n_lanes=max(lane_budget,
                                                  2 * len(seeds)))
        frontier.fleet = self
        # per-shard member affinity: seed() places each member's lanes in
        # the shard block matching its index, so a block's contract
        # planes are local to the device that steps it
        frontier._seed_owner_index = [
            gated.index(owner) for owner in owners]
        with trace.span("frontier.fleet.seed", seeds=len(seeds),
                        contracts=len(gated)):
            state, planes = frontier.seed(seeds)
        ctx_of: Dict[int, List[int]] = {}
        for index, (ctx, owner) in enumerate(zip(frontier.contexts,
                                                 owners)):
            ctx.member = owner
            ctx.laser = owner.gate_laser
            ctx_of.setdefault(id(owner), []).append(index)
        frontier._fleet_ctx_of = ctx_of
        self._arm_clock(max(m.budget_remaining() for m in gated))
        self.phases += 1
        metrics.inc("frontier.fleet.phases")
        if slog.enabled():
            slog.event("fleet.phase", contracts=len(gated),
                       seeds=len(seeds), lanes=frontier.n_lanes)
        with trace.span("frontier.fleet.device_phase",
                        lanes=frontier.n_lanes,
                        contracts=len(gated)) as phase:
            frontier.run(state, planes)
            phase.set(forks=frontier.forks, lane_steps=frontier.lane_steps)
        self.lane_steps += frontier.lane_steps
        self.forks += frontier.forks
        self.frontier = frontier
        feeder = frontier.make_feeder()
        for member in gated:
            member._pending_feeder = feeder

    def deadline_drain(self, frontier: "_Frontier", status: np.ndarray,
                       lane_ctx: np.ndarray) -> bool:
        """Per-contract deadline drain, called once per chunk from the
        frontier loop: members past their budget have their live lanes
        killed IN PLACE (freed for reseeding by the others) and every
        dropped lane counted on their own laser. Returns True when lane
        state changed (the caller re-uploads)."""
        changed = False
        live = ((status == RUNNING) | (status == FORKING)
                | (status == ESCAPED))
        ctx_of = getattr(frontier, "_fleet_ctx_of", {})
        for member in self.members:
            if not member.abandoned:
                if member.budget_remaining() > 1.0:
                    continue
                member.abandoned = True
                log.info("fleet member %r exhausted its budget; draining "
                         "its lanes (others continue)", member.contract_id)
                if slog.enabled():
                    slog.event("fleet.deadline_drain",
                               contract=member.contract_id)
            indices = ctx_of.get(id(member))
            if not indices:
                continue
            mask = live & np.isin(lane_ctx, indices)
            count = int(np.sum(mask))
            if count:
                status[mask] = DEAD
                member.count_dropped(count)
                metrics.inc("frontier.fleet.drained", count)
                changed = True
        return changed
